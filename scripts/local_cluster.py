#!/usr/bin/env python
"""Local cluster runner (parity with the reference's process-compose.yaml:
discovery store + marshal + 2 brokers + an echo client, each a real OS
process over TCP; SQLite stands in for KeyDB).

    python scripts/local_cluster.py [--duration 30]

Exits nonzero if any component dies early or the client fails to echo.
"""

from __future__ import annotations

import argparse
import os
import signal
import subprocess
import sys
import tempfile
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


sys.path.insert(0, REPO)
from pushcdn_tpu.bin.common import spawn_binary  # noqa: E402


def spawn(name: str, *args: str, env_extra=None) -> subprocess.Popen:
    proc = spawn_binary(name, *args, env_extra=env_extra)
    print(f"[cluster] {name} up (pid {proc.pid})")
    return proc


def check_trace_chain(trace_dir: str, wait_s: float = 5.0) -> bool:
    """Assemble the per-process JSONL span logs and verify at least one
    trace id produced the COMPLETE lifecycle chain: auth (marshal) +
    publish → ingress → plan → egress (broker) → delivery (client).
    Retries briefly: the broker's egress span lands microseconds after
    the client prints its echo, and we read the files right then."""
    import glob
    import json as json_mod
    need = {"auth", "publish", "ingress", "plan", "egress", "delivery"}
    deadline = time.time() + wait_s
    hops_by_id: dict = {}
    while True:
        hops_by_id = {}
        for path in glob.glob(os.path.join(trace_dir, "*.jsonl")):
            with open(path) as fh:
                for line in fh:
                    try:
                        rec = json_mod.loads(line)
                    except ValueError:
                        continue
                    hops_by_id.setdefault(rec["trace_id"],
                                          set()).add(rec["hop"])
        for tid, hops in hops_by_id.items():
            if need <= hops:
                print(f"[cluster] trace chain complete: id={tid:x} "
                      f"hops={sorted(hops)}")
                return True
        if time.time() >= deadline:
            break
        time.sleep(0.2)
    print(f"[cluster] FAIL: no complete trace chain "
          f"(saw {[(hex(t), sorted(h)) for t, h in hops_by_id.items()]})")
    return False


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--duration", type=float, default=20.0)
    ap.add_argument("--base-port", type=int, default=21700,
                    help="0 picks a free contiguous range (CI runs that "
                         "must not collide with other suites)")
    ap.add_argument("--device-plane", action="store_true",
                    help="brokers route eligible traffic on the attached "
                         "device (single-shard planes)")
    ap.add_argument("--trace-log", metavar="DIR", default=None,
                    help="write per-process lifecycle-trace span JSONL "
                         "under DIR and verify one complete span chain "
                         "(publish -> auth -> ingress -> plan -> egress "
                         "-> delivery)")
    args = ap.parse_args()

    if args.trace_log:
        os.makedirs(args.trace_log, exist_ok=True)

    def trace_env(name: str):
        if not args.trace_log:
            return None
        return {"PUSHCDN_TRACE_LOG":
                os.path.join(args.trace_log, f"{name}.jsonl")}

    db = os.path.join(tempfile.mkdtemp(prefix="pushcdn-cluster-"), "cdn.sqlite")
    bp = args.base_port
    if bp == 0:
        # bind one free port and take the following ~100 as the range —
        # racy in principle, but ephemeral allocations are sparse and the
        # components fail loudly on a collision
        import socket
        with socket.socket() as s:
            s.bind(("127.0.0.1", 0))
            bp = min(s.getsockname()[1], 65000 - 200)
    procs: list[tuple[str, subprocess.Popen]] = []
    try:
        for i in range(2):
            procs.append((f"broker{i}", spawn(
                "broker",
                "--discovery-endpoint", db,
                "--public-advertise-endpoint", f"127.0.0.1:{bp + i * 2}",
                "--public-bind-endpoint", f"127.0.0.1:{bp + i * 2}",
                "--private-advertise-endpoint", f"127.0.0.1:{bp + i * 2 + 1}",
                "--private-bind-endpoint", f"127.0.0.1:{bp + i * 2 + 1}",
                "--user-transport", "tcp",   # plain tcp for the local demo
                "--metrics-bind-endpoint", f"127.0.0.1:{bp + 100 + i}",
                *(["--device-plane"] if args.device_plane else []),
                env_extra=trace_env(f"broker{i}"))))
        time.sleep(1.5)  # brokers register + mesh up
        procs.append(("marshal", spawn(
            "marshal",
            "--discovery-endpoint", db,
            "--bind-endpoint", f"127.0.0.1:{bp + 50}",
            "--user-transport", "tcp",
            env_extra=trace_env("marshal"))))
        time.sleep(1.0)
        procs.append(("client", spawn(
            "client",
            "--marshal-endpoint", f"127.0.0.1:{bp + 50}",
            "--transport", "tcp",
            "--interval", "1.0", "--key-seed", "7",
            env_extra=trace_env("client"))))

        deadline = time.time() + args.duration
        echoed = False
        client = procs[-1][1]
        while time.time() < deadline:
            for name, proc in procs[:-1]:
                if proc.poll() is not None:
                    print(f"[cluster] FAIL: {name} died early")
                    print(proc.stdout.read()[-2000:])
                    return 1
            line = client.stdout.readline()
            if line:
                sys.stdout.write(f"[client] {line}")
                if "recv direct" in line:
                    echoed = True
                    break
        if not echoed:
            print("[cluster] FAIL: client never echoed")
            return 1
        if args.trace_log and not check_trace_chain(args.trace_log):
            return 1
        print("[cluster] OK: end-to-end echo through real processes")
        return 0
    finally:
        for _name, proc in procs:
            if proc.poll() is None:
                proc.send_signal(signal.SIGINT)
        time.sleep(0.5)
        for _name, proc in procs:
            if proc.poll() is None:
                proc.kill()


if __name__ == "__main__":
    sys.exit(main())
