// LD_PRELOAD syscall-attribution interposer for the io-impl A/B bench.
//
// Counts the data-plane syscalls a process issues (write/send*/read/recv*/
// epoll_wait and io_uring_enter via the glibc syscall() wrapper) by
// interposing the libc PLT symbols. strace is absent from the bench
// container and /proc/self/io does not count socket ops, so this is the
// honest per-message attribution source: the bench child loads this very
// library via ctypes (dlopen of an already-LD_PRELOADed DSO returns the
// same mapping) and reads counter deltas around the measured loop.
//
// Only calls that cross a PLT are seen (glibc-internal calls bypass
// interposition) — exactly the set CPython and our native shim issue.
//
// Build: g++ -O2 -shared -fPIC -o libpushcdn_syscount.so syscount.cpp -ldl

#define _GNU_SOURCE 1
#include <dlfcn.h>
#include <stdarg.h>
#include <stddef.h>
#include <sys/epoll.h>
#include <sys/socket.h>
#include <sys/syscall.h>
#include <sys/types.h>
#include <sys/uio.h>

extern "C" {

enum {
    C_WRITE = 0, C_WRITEV, C_SEND, C_SENDTO, C_SENDMSG,
    C_READ, C_RECV, C_RECVFROM, C_RECVMSG,
    C_EPOLL_WAIT, C_EPOLL_PWAIT, C_URING_ENTER,
    C_COUNT
};

static unsigned long long g_counts[C_COUNT];

static inline void bump(int idx) {
    __atomic_fetch_add(&g_counts[idx], 1ull, __ATOMIC_RELAXED);
}

// counter access for the in-process reader (ctypes)
unsigned long long pcu_syscount(int idx) {
    if (idx < 0 || idx >= C_COUNT) return 0;
    return __atomic_load_n(&g_counts[idx], __ATOMIC_RELAXED);
}

int pcu_syscount_n(void) { return C_COUNT; }

#define REAL(name, ret, ...)                                              \
    typedef ret (*name##_fn)(__VA_ARGS__);                                \
    static name##_fn real_##name;                                         \
    static name##_fn get_##name(void) {                                   \
        if (!real_##name)                                                 \
            real_##name = (name##_fn)dlsym(RTLD_NEXT, #name);             \
        return real_##name;                                               \
    }

REAL(write, ssize_t, int, const void *, size_t)
REAL(writev, ssize_t, int, const struct iovec *, int)
REAL(send, ssize_t, int, const void *, size_t, int)
REAL(sendto, ssize_t, int, const void *, size_t, int,
     const struct sockaddr *, socklen_t)
REAL(sendmsg, ssize_t, int, const struct msghdr *, int)
REAL(read, ssize_t, int, void *, size_t)
REAL(recv, ssize_t, int, void *, size_t, int)
REAL(recvfrom, ssize_t, int, void *, size_t, int, struct sockaddr *,
     socklen_t *)
REAL(recvmsg, ssize_t, int, struct msghdr *, int)
REAL(epoll_wait, int, int, struct epoll_event *, int, int)
REAL(epoll_pwait, int, int, struct epoll_event *, int, int,
     const sigset_t *)
REAL(syscall, long, long, ...)

ssize_t write(int fd, const void *buf, size_t n) {
    bump(C_WRITE);
    return get_write()(fd, buf, n);
}

ssize_t writev(int fd, const struct iovec *iov, int cnt) {
    bump(C_WRITEV);
    return get_writev()(fd, iov, cnt);
}

ssize_t send(int fd, const void *buf, size_t n, int flags) {
    bump(C_SEND);
    return get_send()(fd, buf, n, flags);
}

ssize_t sendto(int fd, const void *buf, size_t n, int flags,
               const struct sockaddr *addr, socklen_t alen) {
    bump(C_SENDTO);
    return get_sendto()(fd, buf, n, flags, addr, alen);
}

ssize_t sendmsg(int fd, const struct msghdr *msg, int flags) {
    bump(C_SENDMSG);
    return get_sendmsg()(fd, msg, flags);
}

ssize_t read(int fd, void *buf, size_t n) {
    bump(C_READ);
    return get_read()(fd, buf, n);
}

ssize_t recv(int fd, void *buf, size_t n, int flags) {
    bump(C_RECV);
    return get_recv()(fd, buf, n, flags);
}

ssize_t recvfrom(int fd, void *buf, size_t n, int flags,
                 struct sockaddr *addr, socklen_t *alen) {
    bump(C_RECVFROM);
    return get_recvfrom()(fd, buf, n, flags, addr, alen);
}

ssize_t recvmsg(int fd, struct msghdr *msg, int flags) {
    bump(C_RECVMSG);
    return get_recvmsg()(fd, msg, flags);
}

int epoll_wait(int epfd, struct epoll_event *ev, int max, int timeout) {
    bump(C_EPOLL_WAIT);
    return get_epoll_wait()(epfd, ev, max, timeout);
}

int epoll_pwait(int epfd, struct epoll_event *ev, int max, int timeout,
                const sigset_t *sig) {
    bump(C_EPOLL_PWAIT);
    return get_epoll_pwait()(epfd, ev, max, timeout, sig);
}

#ifndef SYS_io_uring_enter
#define SYS_io_uring_enter 426
#endif

// The native uring shim issues io_uring_enter through glibc's variadic
// syscall() wrapper; forwarding six longs matches the SysV ABI for every
// syscall shape.
long syscall(long number, ...) {
    if (number == SYS_io_uring_enter) bump(C_URING_ENTER);
    va_list ap;
    va_start(ap, number);
    long a = va_arg(ap, long);
    long b = va_arg(ap, long);
    long c = va_arg(ap, long);
    long d = va_arg(ap, long);
    long e = va_arg(ap, long);
    long f = va_arg(ap, long);
    va_end(ap);
    return get_syscall()(number, a, b, c, d, e, f);
}

}  // extern "C"
