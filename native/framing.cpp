// Native frame plumbing: the socket⇄HBM pump's hot loops.
//
// The reference's native-performance-critical layer is its Rust transport +
// framing stack (cdn-proto/src/connection/protocols/mod.rs:309-394 —
// length-delimited u32 frames — and the per-message buffer handling). Here
// the equivalent C++ sits at exactly that seam (SURVEY.md §7 design stance,
// seam (a)): batch packing of variable-length payloads into the fixed-shape
// frame tensors the device router consumes, and batch scanning/encoding of
// length-delimited byte streams for the TCP edge.
//
// Exposed as a plain C ABI for ctypes (no pybind11 in this image).
//
// Build: g++ -O3 -march=native -shared -fPIC framing.cpp -o libpushcdn_framing.so

#include <cstdint>
#include <cstring>

extern "C" {

// Pack n variable-length payloads (concatenated in `blob`, located by
// offsets/lengths) into a [capacity, frame_bytes] frame tensor + aligned
// metadata columns. Returns the number of frames packed (stops at capacity
// or at a payload that exceeds frame_bytes — the host path handles those).
// Topic masks are [n, topic_words] / [capacity, topic_words] u32 rows
// (topic_words=1 is the compact ≤32-topic layout; 8 covers the full u8
// topic space).
int32_t pushcdn_pack_frames(
    const uint8_t* blob, const int64_t* offsets, const int32_t* lengths,
    const int32_t* kinds, const uint32_t* tmasks, const int32_t* dests,
    int32_t n, int32_t capacity, int32_t frame_bytes, int32_t topic_words,
    uint8_t* out_frames, int32_t* out_kind, int32_t* out_len,
    uint32_t* out_tmask, int32_t* out_dest, uint8_t* out_valid) {
  int32_t packed = 0;
  for (int32_t i = 0; i < n && packed < capacity; ++i) {
    const int32_t len = lengths[i];
    if (len < 0 || len > frame_bytes) return packed;  // caller handles
    uint8_t* slot = out_frames + (int64_t)packed * frame_bytes;
    std::memcpy(slot, blob + offsets[i], (size_t)len);
    if (len < frame_bytes) std::memset(slot + len, 0, (size_t)(frame_bytes - len));
    out_kind[packed] = kinds[i];
    out_len[packed] = len;
    std::memcpy(out_tmask + (int64_t)packed * topic_words,
                tmasks + (int64_t)i * topic_words,
                (size_t)topic_words * sizeof(uint32_t));
    out_dest[packed] = dests[i];
    out_valid[packed] = 1;
    ++packed;
  }
  return packed;
}

// Scan a received byte stream for complete length-delimited frames
// (u32 big-endian length prefix; parity protocols/mod.rs:309-351).
// Writes (offset, length) of each complete frame; returns the number of
// bytes consumed (start of the first incomplete frame). Frames longer than
// max_frame_len abort the scan with *error = 1 (peer violation).
int64_t pushcdn_scan_frames(
    const uint8_t* buf, int64_t len, uint32_t max_frame_len,
    int64_t* out_offsets, int32_t* out_lengths, int32_t max_frames,
    int32_t* num_frames, int32_t* error) {
  int64_t pos = 0;
  int32_t count = 0;
  *error = 0;
  while (count < max_frames && len - pos >= 4) {
    const uint32_t flen = ((uint32_t)buf[pos] << 24) | ((uint32_t)buf[pos + 1] << 16) |
                          ((uint32_t)buf[pos + 2] << 8) | (uint32_t)buf[pos + 3];
    if (flen > max_frame_len) {
      *error = 1;
      break;
    }
    if (len - pos - 4 < (int64_t)flen) break;  // incomplete
    out_offsets[count] = pos + 4;
    out_lengths[count] = (int32_t)flen;
    ++count;
    pos += 4 + (int64_t)flen;
  }
  *num_frames = count;
  return pos;
}

// Encode n payloads into one contiguous length-delimited byte stream
// (u32 BE prefix per frame) — the writer-side batch: one buffer, one
// syscall. Returns total bytes written, or -1 if out_capacity is too small.
int64_t pushcdn_encode_frames(
    const uint8_t* blob, const int64_t* offsets, const int32_t* lengths,
    int32_t n, uint8_t* out, int64_t out_capacity) {
  int64_t pos = 0;
  for (int32_t i = 0; i < n; ++i) {
    const int32_t len = lengths[i];
    if (pos + 4 + (int64_t)len > out_capacity) return -1;
    out[pos] = (uint8_t)((uint32_t)len >> 24);
    out[pos + 1] = (uint8_t)((uint32_t)len >> 16);
    out[pos + 2] = (uint8_t)((uint32_t)len >> 8);
    out[pos + 3] = (uint8_t)len;
    std::memcpy(out + pos + 4, blob + offsets[i], (size_t)len);
    pos += 4 + (int64_t)len;
  }
  return pos;
}

// Same encode, but the payloads arrive as an array of pointers (ctypes
// c_char_p array built from the Python bytes objects — zero join, zero
// intermediate blob). The single copy is straight into `out`.
int64_t pushcdn_encode_frames_ptrs(
    const uint8_t* const* payloads, const int32_t* lengths,
    int32_t n, uint8_t* out, int64_t out_capacity) {
  int64_t pos = 0;
  for (int32_t i = 0; i < n; ++i) {
    const int32_t len = lengths[i];
    if (pos + 4 + (int64_t)len > out_capacity) return -1;
    out[pos] = (uint8_t)((uint32_t)len >> 24);
    out[pos + 1] = (uint8_t)((uint32_t)len >> 16);
    out[pos + 2] = (uint8_t)((uint32_t)len >> 8);
    out[pos + 3] = (uint8_t)len;
    std::memcpy(out + pos + 4, payloads[i], (size_t)len);
    pos += 4 + (int64_t)len;
  }
  return pos;
}

// ---------------------------------------------------------------------------
// Device-plane egress engine (SURVEY.md §7 stage 8; the socket side of the
// socket⇄HBM pump). The router's delivery matrix says which (user, frame)
// pairs deliver; these two passes turn a whole step's matrix into per-user
// length-delimited byte streams with zero per-frame Python:
//
//   pass 1 (count):  per-user bytes + message totals,
//   pass 2 (fill):   one contiguous stream per user at caller-computed
//                    offsets (prefix sum over pass 1), each frame encoded
//                    as u32-BE length ‖ payload — exactly what the wire
//                    writer sends, so the stream is handed to the
//                    connection's writer as-is (one flush per user).
//
// The matrix rows are scanned 8 bytes at a time (numpy bool_ is one byte
// per cell; a zero uint64 word skips 8 frames), so sparse matrices cost
// ~N/8 loads per user. Frame payloads live in `nb` equally-shaped blocks
// (the per-shard host ring snapshots, in gather order): frame n is row
// (n % rows_per_block) of block (n / rows_per_block) — egress reads the
// SAME host buffers the step's H2D copy read, no device round-trip of
// frame bytes (the delivery decision, not the payload, is what crosses
// the mesh on the single-host topology).

static inline uint64_t load_u64(const uint8_t* p) {
  uint64_t w;
  std::memcpy(&w, p, 8);
  return w;
}

// Pass 1: per-user delivered bytes (4-byte prefix included) and counts.
void pushcdn_egress_count(
    const uint8_t* deliver,  // [U, N] row-major (numpy bool_)
    int32_t U, int32_t N,
    const int32_t* lengths,  // [N] frame payload lengths
    int64_t* out_bytes,      // [U]
    int32_t* out_msgs) {     // [U]
  const int32_t nwords = N / 8;
  for (int32_t u = 0; u < U; ++u) {
    const uint8_t* row = deliver + (int64_t)u * N;
    int64_t bytes = 0;
    int32_t msgs = 0;
    int32_t n = 0;
    for (int32_t w = 0; w < nwords; ++w, n += 8) {
      if (load_u64(row + n) == 0) continue;
      for (int32_t k = 0; k < 8; ++k) {
        if (row[n + k]) {
          bytes += 4 + (int64_t)lengths[n + k];
          ++msgs;
        }
      }
    }
    for (; n < N; ++n) {
      if (row[n]) {
        bytes += 4 + (int64_t)lengths[n];
        ++msgs;
      }
    }
    out_bytes[u] = bytes;
    out_msgs[u] = msgs;
  }
}

// Fused single-pass variant: count + prefix-sum + fill in ONE walk over the
// delivery matrix, into a caller-recycled buffer (the egress pool in
// pushcdn_tpu/native). Writes per-user offsets/bytes/msgs as it goes and
// returns total bytes written, or -1 when the buffer is too small — the
// caller then sizes it with pushcdn_egress_count and retries; with a
// grow-only pooled buffer the retry happens once per high-water mark, so
// the steady state pays a single matrix walk and zero page faults.
int64_t pushcdn_egress_encode_fused(
    const uint8_t* deliver, int32_t U, int32_t N, const int32_t* lengths,
    const uint8_t* const* blocks, int32_t nb, int32_t rows_per_block,
    int64_t frame_stride,
    int64_t* out_offsets,  // [U] written: stream start per user
    int64_t* out_bytes,    // [U] written: stream size per user
    int32_t* out_msgs,     // [U] written: delivered count per user
    uint8_t* out, int64_t out_capacity) {
  const int32_t nwords = N / 8;
  int64_t pos = 0;
  for (int32_t u = 0; u < U; ++u) {
    const uint8_t* row = deliver + (int64_t)u * N;
    const int64_t start = pos;
    int32_t msgs = 0;
    int32_t n = 0;
    for (int32_t w = 0; w < nwords; ++w, n += 8) {
      if (load_u64(row + n) == 0) continue;
      for (int32_t k = 0; k < 8; ++k) {
        const int32_t f = n + k;
        if (!row[f]) continue;
        const int32_t len = lengths[f];
        if (pos + 4 + (int64_t)len > out_capacity) return -1;
        out[pos] = (uint8_t)((uint32_t)len >> 24);
        out[pos + 1] = (uint8_t)((uint32_t)len >> 16);
        out[pos + 2] = (uint8_t)((uint32_t)len >> 8);
        out[pos + 3] = (uint8_t)len;
        const uint8_t* src = blocks[f / rows_per_block] +
                             (int64_t)(f % rows_per_block) * frame_stride;
        std::memcpy(out + pos + 4, src, (size_t)len);
        pos += 4 + (int64_t)len;
        ++msgs;
      }
    }
    for (; n < N; ++n) {
      if (!row[n]) continue;
      const int32_t len = lengths[n];
      if (pos + 4 + (int64_t)len > out_capacity) return -1;
      out[pos] = (uint8_t)((uint32_t)len >> 24);
      out[pos + 1] = (uint8_t)((uint32_t)len >> 16);
      out[pos + 2] = (uint8_t)((uint32_t)len >> 8);
      out[pos + 3] = (uint8_t)len;
      const uint8_t* src = blocks[n / rows_per_block] +
                           (int64_t)(n % rows_per_block) * frame_stride;
      std::memcpy(out + pos + 4, src, (size_t)len);
      pos += 4 + (int64_t)len;
      ++msgs;
    }
    out_offsets[u] = start;
    out_bytes[u] = pos - start;
    out_msgs[u] = msgs;
  }
  return pos;
}

// Pass 2: fill per-user streams. Returns total bytes written, or -1 if any
// user's stream would overrun out_capacity (callers size `out` from pass 1,
// so -1 means the matrix changed between passes — it can't, both run on one
// snapshot, but the guard keeps the ABI memory-safe regardless).
int64_t pushcdn_egress_fill(
    const uint8_t* deliver, int32_t U, int32_t N, const int32_t* lengths,
    const uint8_t* const* blocks, int32_t nb, int32_t rows_per_block,
    int64_t frame_stride,
    const int64_t* offsets,  // [U] stream start offsets (prefix sum)
    uint8_t* out, int64_t out_capacity) {
  const int32_t nwords = N / 8;
  int64_t total = 0;
  for (int32_t u = 0; u < U; ++u) {
    const uint8_t* row = deliver + (int64_t)u * N;
    int64_t pos = offsets[u];
    int32_t n = 0;
    for (int32_t w = 0; w < nwords; ++w, n += 8) {
      if (load_u64(row + n) == 0) continue;
      for (int32_t k = 0; k < 8; ++k) {
        const int32_t f = n + k;
        if (!row[f]) continue;
        const int32_t len = lengths[f];
        if (pos + 4 + (int64_t)len > out_capacity) return -1;
        out[pos] = (uint8_t)((uint32_t)len >> 24);
        out[pos + 1] = (uint8_t)((uint32_t)len >> 16);
        out[pos + 2] = (uint8_t)((uint32_t)len >> 8);
        out[pos + 3] = (uint8_t)len;
        const uint8_t* src =
            blocks[f / rows_per_block] +
            (int64_t)(f % rows_per_block) * frame_stride;
        std::memcpy(out + pos + 4, src, (size_t)len);
        pos += 4 + (int64_t)len;
        total += 4 + (int64_t)len;
      }
    }
    for (; n < N; ++n) {
      if (!row[n]) continue;
      const int32_t len = lengths[n];
      if (pos + 4 + (int64_t)len > out_capacity) return -1;
      out[pos] = (uint8_t)((uint32_t)len >> 24);
      out[pos + 1] = (uint8_t)((uint32_t)len >> 16);
      out[pos + 2] = (uint8_t)((uint32_t)len >> 8);
      out[pos + 3] = (uint8_t)len;
      const uint8_t* src =
          blocks[n / rows_per_block] +
          (int64_t)(n % rows_per_block) * frame_stride;
      std::memcpy(out + pos + 4, src, (size_t)len);
      pos += 4 + (int64_t)len;
      total += 4 + (int64_t)len;
    }
  }
  return total;
}

}  // extern "C"
