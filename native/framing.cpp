// Native frame plumbing: the socket⇄HBM pump's hot loops.
//
// The reference's native-performance-critical layer is its Rust transport +
// framing stack (cdn-proto/src/connection/protocols/mod.rs:309-394 —
// length-delimited u32 frames — and the per-message buffer handling). Here
// the equivalent C++ sits at exactly that seam (SURVEY.md §7 design stance,
// seam (a)): batch packing of variable-length payloads into the fixed-shape
// frame tensors the device router consumes, and batch scanning/encoding of
// length-delimited byte streams for the TCP edge.
//
// Exposed as a plain C ABI for ctypes (no pybind11 in this image).
//
// Build: g++ -O3 -march=native -shared -fPIC framing.cpp -o libpushcdn_framing.so

#include <cstdint>
#include <cstring>

extern "C" {

// Pack n variable-length payloads (concatenated in `blob`, located by
// offsets/lengths) into a [capacity, frame_bytes] frame tensor + aligned
// metadata columns. Returns the number of frames packed (stops at capacity
// or at a payload that exceeds frame_bytes — the host path handles those).
// Topic masks are [n, topic_words] / [capacity, topic_words] u32 rows
// (topic_words=1 is the compact ≤32-topic layout; 8 covers the full u8
// topic space).
int32_t pushcdn_pack_frames(
    const uint8_t* blob, const int64_t* offsets, const int32_t* lengths,
    const int32_t* kinds, const uint32_t* tmasks, const int32_t* dests,
    int32_t n, int32_t capacity, int32_t frame_bytes, int32_t topic_words,
    uint8_t* out_frames, int32_t* out_kind, int32_t* out_len,
    uint32_t* out_tmask, int32_t* out_dest, uint8_t* out_valid) {
  int32_t packed = 0;
  for (int32_t i = 0; i < n && packed < capacity; ++i) {
    const int32_t len = lengths[i];
    if (len < 0 || len > frame_bytes) return packed;  // caller handles
    uint8_t* slot = out_frames + (int64_t)packed * frame_bytes;
    std::memcpy(slot, blob + offsets[i], (size_t)len);
    if (len < frame_bytes) std::memset(slot + len, 0, (size_t)(frame_bytes - len));
    out_kind[packed] = kinds[i];
    out_len[packed] = len;
    std::memcpy(out_tmask + (int64_t)packed * topic_words,
                tmasks + (int64_t)i * topic_words,
                (size_t)topic_words * sizeof(uint32_t));
    out_dest[packed] = dests[i];
    out_valid[packed] = 1;
    ++packed;
  }
  return packed;
}

// Scan a received byte stream for complete length-delimited frames
// (u32 big-endian length prefix; parity protocols/mod.rs:309-351).
// Writes (offset, length) of each complete frame; returns the number of
// bytes consumed (start of the first incomplete frame). Frames longer than
// max_frame_len abort the scan with *error = 1 (peer violation).
int64_t pushcdn_scan_frames(
    const uint8_t* buf, int64_t len, uint32_t max_frame_len,
    int64_t* out_offsets, int32_t* out_lengths, int32_t max_frames,
    int32_t* num_frames, int32_t* error) {
  int64_t pos = 0;
  int32_t count = 0;
  *error = 0;
  while (count < max_frames && len - pos >= 4) {
    const uint32_t flen = ((uint32_t)buf[pos] << 24) | ((uint32_t)buf[pos + 1] << 16) |
                          ((uint32_t)buf[pos + 2] << 8) | (uint32_t)buf[pos + 3];
    if (flen > max_frame_len) {
      *error = 1;
      break;
    }
    if (len - pos - 4 < (int64_t)flen) break;  // incomplete
    out_offsets[count] = pos + 4;
    out_lengths[count] = (int32_t)flen;
    ++count;
    pos += 4 + (int64_t)flen;
  }
  *num_frames = count;
  return pos;
}

// Encode n payloads into one contiguous length-delimited byte stream
// (u32 BE prefix per frame) — the writer-side batch: one buffer, one
// syscall. Returns total bytes written, or -1 if out_capacity is too small.
int64_t pushcdn_encode_frames(
    const uint8_t* blob, const int64_t* offsets, const int32_t* lengths,
    int32_t n, uint8_t* out, int64_t out_capacity) {
  int64_t pos = 0;
  for (int32_t i = 0; i < n; ++i) {
    const int32_t len = lengths[i];
    if (pos + 4 + (int64_t)len > out_capacity) return -1;
    out[pos] = (uint8_t)((uint32_t)len >> 24);
    out[pos + 1] = (uint8_t)((uint32_t)len >> 16);
    out[pos + 2] = (uint8_t)((uint32_t)len >> 8);
    out[pos + 3] = (uint8_t)len;
    std::memcpy(out + pos + 4, blob + offsets[i], (size_t)len);
    pos += 4 + (int64_t)len;
  }
  return pos;
}

// Same encode, but the payloads arrive as an array of pointers (ctypes
// c_char_p array built from the Python bytes objects — zero join, zero
// intermediate blob). The single copy is straight into `out`.
int64_t pushcdn_encode_frames_ptrs(
    const uint8_t* const* payloads, const int32_t* lengths,
    int32_t n, uint8_t* out, int64_t out_capacity) {
  int64_t pos = 0;
  for (int32_t i = 0; i < n; ++i) {
    const int32_t len = lengths[i];
    if (pos + 4 + (int64_t)len > out_capacity) return -1;
    out[pos] = (uint8_t)((uint32_t)len >> 24);
    out[pos + 1] = (uint8_t)((uint32_t)len >> 16);
    out[pos + 2] = (uint8_t)((uint32_t)len >> 8);
    out[pos + 3] = (uint8_t)len;
    std::memcpy(out + pos + 4, payloads[i], (size_t)len);
    pos += 4 + (int64_t)len;
  }
  return pos;
}

}  // extern "C"
