// Raw io_uring submission/completion shim for the host data plane.
//
// Deliberately liburing-free: the three syscalls (io_uring_setup /
// io_uring_enter / io_uring_register) are invoked directly and every
// uapi struct is declared here, so the wheel carries zero native
// dependencies and builds on any glibc that can mmap. The Python side
// (pushcdn_tpu/native/uring.py) drives this through ctypes; the ABI is
// plain C. One pcu_ring per event loop / shard worker.
//
// Responsibilities kept in C (everything the hot path touches per
// SQE/CQE): SQ tail/CQ head ring arithmetic with acquire/release
// ordering, SQE field layout, the provided-buffer ring (recv buffers
// the kernel picks from), and CQE batch extraction into flat arrays.
// Policy — what to submit, lifetime of buffers, ordering contracts —
// stays in Python where the writer queue lives.

#include <cerrno>
#include <cstdint>
#include <cstdlib>
#include <cstring>
#include <ctime>
#include <new>
#include <sys/mman.h>
#include <sys/syscall.h>
#include <unistd.h>

namespace {

using u8 = uint8_t;
using u16 = uint16_t;
using u32 = uint32_t;
using u64 = uint64_t;
using s32 = int32_t;

// ---- uapi mirror (linux/io_uring.h) ----------------------------------------

struct io_sqring_offsets {
    u32 head, tail, ring_mask, ring_entries, flags, dropped, array, resv1;
    u64 user_addr;
};
struct io_cqring_offsets {
    u32 head, tail, ring_mask, ring_entries, overflow, cqes, flags, resv1;
    u64 user_addr;
};
struct io_uring_params {
    u32 sq_entries, cq_entries, flags, sq_thread_cpu, sq_thread_idle;
    u32 features, wq_fd, resv[3];
    struct io_sqring_offsets sq_off;
    struct io_cqring_offsets cq_off;
};

struct io_uring_sqe {
    u8 opcode;
    u8 flags;
    u16 ioprio;
    s32 fd;
    union { u64 off; u64 addr2; };
    union { u64 addr; u64 splice_off_in; };
    u32 len;
    union {
        u32 rw_flags; u32 msg_flags; u32 accept_flags; u32 cancel_flags;
        u32 fsync_flags; u32 timeout_flags; u32 open_flags; u32 splice_flags;
    };
    u64 user_data;
    union { u16 buf_index; u16 buf_group; } __attribute__((packed));
    u16 personality;
    union { s32 splice_fd_in; u32 file_index; };
    u64 addr3;
    u64 __pad2[1];
};
static_assert(sizeof(io_uring_sqe) == 64, "sqe ABI drift");

struct io_uring_cqe {
    u64 user_data;
    s32 res;
    u32 flags;
};
static_assert(sizeof(io_uring_cqe) == 16, "cqe ABI drift");

struct io_uring_buf {
    u64 addr;
    u32 len;
    u16 bid;
    u16 resv;
};
// The pbuf ring is an array of io_uring_buf; the kernel-visible tail
// lives in the resv slot of entry 0 (uapi io_uring_buf_ring union).
struct io_uring_buf_reg {
    u64 ring_addr;
    u32 ring_entries;
    u16 bgid;
    u16 flags;
    u64 resv[3];
};
struct io_uring_rsrc_register {
    u32 nr;
    u32 flags;
    u64 resv2;
    u64 data;
    u64 tags;
};
struct io_uring_rsrc_update2 {
    u32 offset;
    u32 resv;
    u64 data;
    u64 tags;
    u32 nr;
    u32 resv2;
};
struct io_uring_probe_op {
    u8 op;
    u8 resv;
    u16 flags;  // IO_URING_OP_SUPPORTED
    u32 resv2;
};
struct io_uring_probe {
    u8 last_op;
    u8 ops_len;
    u16 resv;
    u32 resv2[3];
    struct io_uring_probe_op ops[64];
};

enum {
    IORING_OP_WRITE_FIXED = 5,
    IORING_OP_ACCEPT = 13,
    IORING_OP_ASYNC_CANCEL = 14,
    IORING_OP_SEND = 26,
    IORING_OP_RECV = 27,
    IORING_OP_SHUTDOWN = 34,
    IORING_OP_SEND_ZC = 47,
};
enum {
    IORING_SETUP_SQPOLL = 1u << 1,
    IORING_SETUP_CLAMP = 1u << 4,
};
enum {
    IORING_ENTER_GETEVENTS = 1u << 0,
    IORING_ENTER_SQ_WAKEUP = 1u << 1,
};
enum {
    IORING_SQ_NEED_WAKEUP = 1u << 0,
    IORING_SQ_CQ_OVERFLOW = 1u << 1,
};
enum {
    IORING_FEAT_SINGLE_MMAP = 1u << 0,
    IORING_FEAT_NODROP = 1u << 1,
};
enum {
    IOSQE_IO_LINK = 1u << 2,
    IOSQE_BUFFER_SELECT = 1u << 5,
};
enum {
    IORING_CQE_F_BUFFER = 1u << 0,
    IORING_CQE_F_MORE = 1u << 1,
    IORING_CQE_F_NOTIF = 1u << 3,
};
enum {
    IORING_RECVSEND_FIXED_BUF = 1u << 2,
    IORING_RECV_MULTISHOT = 1u << 1,
    IORING_ACCEPT_MULTISHOT = 1u << 0,
};
enum {
    IORING_REGISTER_BUFFERS2 = 15,
    IORING_REGISTER_BUFFERS_UPDATE = 16,
    IORING_REGISTER_PROBE = 8,
    IORING_REGISTER_EVENTFD = 4,
    IORING_REGISTER_EVENTFD_ASYNC = 7,
    IORING_UNREGISTER_EVENTFD = 5,
    IORING_REGISTER_PBUF_RING = 22,
    IORING_UNREGISTER_PBUF_RING = 23,
};
enum { IORING_RSRC_REGISTER_SPARSE = 1u << 0 };
enum { IO_URING_OP_SUPPORTED = 1u << 0 };

constexpr u64 IORING_OFF_SQ_RING = 0ULL;
constexpr u64 IORING_OFF_CQ_RING = 0x8000000ULL;
constexpr u64 IORING_OFF_SQES = 0x10000000ULL;

#ifndef __NR_io_uring_setup
#define __NR_io_uring_setup 425
#define __NR_io_uring_enter 426
#define __NR_io_uring_register 427
#endif

static int sys_setup(unsigned entries, struct io_uring_params *p) {
    int r = (int)syscall(__NR_io_uring_setup, entries, p);
    return r < 0 ? -errno : r;
}
static long sys_enter(int fd, unsigned to_submit, unsigned min_complete,
                      unsigned flags) {
    long r = syscall(__NR_io_uring_enter, fd, to_submit, min_complete,
                     flags, nullptr, 0);
    return r < 0 ? -errno : r;
}
static int sys_register(int fd, unsigned opcode, void *arg, unsigned nr) {
    int r = (int)syscall(__NR_io_uring_register, fd, opcode, arg, nr);
    return r < 0 ? -errno : r;
}

#define LOAD_ACQ(p) __atomic_load_n((p), __ATOMIC_ACQUIRE)
#define STORE_REL(p, v) __atomic_store_n((p), (v), __ATOMIC_RELEASE)

}  // namespace

// ---- native telemetry block (ISSUE 19) --------------------------------------
//
// A shared-memory stats block the data plane stamps with CLOCK_MONOTONIC
// (vdso — no syscall) at each stage boundary: recv-CQE -> plan-done ->
// SQE-submit -> send-CQE. Values accumulate into log2-ns bucket
// histograms + per-class / per-peer counters; a single sequence word
// makes whole-block snapshots torn-read-safe (the same commit-word
// scheme as the shard handoff ring): the writer bumps it to odd around
// every update, the reader retries until it observes the same even
// value on both sides of its copy. Single writer (the ring's event-loop
// thread), any number of snapshot readers.

enum {
    PCU_TM_BUCKETS = 64,  // bucket k counts durations in [2^(k-1), 2^k) ns
    PCU_TM_STAGES = 4,    // 0=plan 1=submit 2=wire 3=total
    PCU_TM_CHAIN = 2,     // 0=enter (io_uring_enter wall) 1=chain (submit->quiesce)
    PCU_TM_CLASSES = 4,   // 0=control 1=consensus 2=live 3=bulk
    PCU_TM_PEERS = 64,    // bounded per-peer counter table (fd-keyed)
};

struct pcu_hist {
    u64 count;
    u64 sum_ns;
    u64 bucket[PCU_TM_BUCKETS];
};

struct pcu_telem {
    u64 seq;  // seqlock commit word (odd = write in progress)
    // everything below `seq` is the snapshot payload, flat u64s
    pcu_hist stage[PCU_TM_STAGES];
    pcu_hist chain[PCU_TM_CHAIN];
    pcu_hist class_delay[PCU_TM_CLASSES];  // recv->send-CQE, per frame
    u64 class_frames[PCU_TM_CLASSES];      // pumped deliveries (dir=out)
    u64 class_bytes[PCU_TM_CLASSES];
    u64 peer_fd[PCU_TM_PEERS];
    u64 peer_frames[PCU_TM_PEERS];
    u64 peer_bytes[PCU_TM_PEERS];
    u64 peer_used;
    // frame-fate ledger (ISSUE 20): pumped frames DROPPED in C (peer
    // poisoned / send error / chain teardown), per class — appended at
    // the end so every prior snapshot offset stays stable
    u64 fate_drop_frames[PCU_TM_CLASSES];
};

static inline u64 pcu_now_ns(void) {
    struct timespec ts;
    clock_gettime(CLOCK_MONOTONIC, &ts);
    return (u64)ts.tv_sec * 1000000000ull + (u64)ts.tv_nsec;
}

// write_seqcount_begin/end: the fences are store-store barriers so the
// payload stores can never be observed outside the odd window
static inline void pcu_tm_begin(pcu_telem *t) {
    __atomic_store_n(&t->seq, t->seq + 1, __ATOMIC_RELAXED);
    __atomic_thread_fence(__ATOMIC_RELEASE);
}

static inline void pcu_tm_end(pcu_telem *t) {
    __atomic_store_n(&t->seq, t->seq + 1, __ATOMIC_RELEASE);
}

static inline int pcu_log2_bucket(u64 ns) {
    if (!ns) return 0;
    int b = 64 - __builtin_clzll(ns);
    return b >= PCU_TM_BUCKETS ? PCU_TM_BUCKETS - 1 : b;
}

// one observation: 2 sequence bumps + 3 plain adds (no lock, no syscall)
static inline void pcu_tm_observe(pcu_telem *t, pcu_hist *h, u64 ns) {
    pcu_tm_begin(t);
    h->count++;
    h->sum_ns += ns;
    h->bucket[pcu_log2_bucket(ns)]++;
    pcu_tm_end(t);
}

// weighted observation (per-class delay: one duration covers n frames)
static inline void pcu_tm_observe_n(pcu_telem *t, pcu_hist *h, u64 ns,
                                    u64 n) {
    if (!n) return;
    pcu_tm_begin(t);
    h->count += n;
    h->sum_ns += ns * n;
    h->bucket[pcu_log2_bucket(ns)] += n;
    pcu_tm_end(t);
}

struct pcu_ring {
    int fd = -1;
    unsigned sq_entries = 0, cq_entries = 0;
    unsigned features = 0, setup_flags = 0;

    void *sq_ptr = nullptr, *cq_ptr = nullptr;
    size_t sq_sz = 0, cq_sz = 0;
    io_uring_sqe *sqes = nullptr;
    size_t sqes_sz = 0;

    u32 *sq_khead = nullptr, *sq_ktail = nullptr, *sq_kflags = nullptr;
    u32 *sq_array = nullptr;
    u32 sq_mask = 0;
    u32 *cq_khead = nullptr, *cq_ktail = nullptr, *cq_koverflow = nullptr;
    io_uring_cqe *cqes = nullptr;
    u32 cq_mask = 0;

    u32 local_tail = 0;       // SQEs prepped
    u32 local_submitted = 0;  // SQEs handed to the kernel

    // provided-buffer ring (recv buffers), bgid 0
    io_uring_buf *pbuf_ring = nullptr;
    u8 *pbuf_slab = nullptr;
    unsigned pbuf_entries = 0, pbuf_len = 0;
    u16 *pbuf_tail = nullptr;

    // native telemetry block (null = telemetry off: one branch per site)
    pcu_telem *telem = nullptr;
};

extern "C" {

// One-shot capability probe: can this kernel/seccomp profile set up a
// ring at all, and does it speak the opcodes the data plane uses?
// Returns a bitmask (>0) on success: bit0 always, bit1 SEND_ZC
// supported. Returns -errno (ENOSYS under old kernels, EPERM under
// seccomp/sysctl io_uring_disabled) when denied.
long pcu_probe(void) {
    struct io_uring_params p;
    memset(&p, 0, sizeof(p));
    int fd = sys_setup(4, &p);
    if (fd < 0)
        return fd;
    long out = 1;
    struct io_uring_probe pr;
    memset(&pr, 0, sizeof(pr));
    if (sys_register(fd, IORING_REGISTER_PROBE, &pr, 64) == 0) {
        bool base_ok = true;
        const u8 need[] = {IORING_OP_SEND, IORING_OP_RECV, IORING_OP_ACCEPT,
                           IORING_OP_ASYNC_CANCEL, IORING_OP_WRITE_FIXED};
        for (u8 op : need)
            if (op > pr.last_op || !(pr.ops[op].flags & IO_URING_OP_SUPPORTED))
                base_ok = false;
        if (!base_ok) {
            close(fd);
            return -ENOSYS;
        }
        if (IORING_OP_SEND_ZC <= pr.last_op &&
            (pr.ops[IORING_OP_SEND_ZC].flags & IO_URING_OP_SUPPORTED))
            out |= 2;
    }
    close(fd);
    return out;
}

pcu_ring *pcu_create(unsigned entries, unsigned sqpoll,
                     unsigned sq_thread_idle_ms, int *err_out) {
    pcu_ring *r = new (std::nothrow) pcu_ring();
    if (!r) {
        if (err_out) *err_out = -ENOMEM;
        return nullptr;
    }
    struct io_uring_params p;
    memset(&p, 0, sizeof(p));
    p.flags = IORING_SETUP_CLAMP;
    if (sqpoll) {
        p.flags |= IORING_SETUP_SQPOLL;
        p.sq_thread_idle = sq_thread_idle_ms ? sq_thread_idle_ms : 50;
    }
    int fd = sys_setup(entries, &p);
    if (fd < 0) {
        if (err_out) *err_out = fd;
        delete r;
        return nullptr;
    }
    r->fd = fd;
    r->sq_entries = p.sq_entries;
    r->cq_entries = p.cq_entries;
    r->features = p.features;
    r->setup_flags = p.flags;

    r->sq_sz = p.sq_off.array + p.sq_entries * sizeof(u32);
    r->cq_sz = p.cq_off.cqes + p.cq_entries * sizeof(io_uring_cqe);
    if (p.features & IORING_FEAT_SINGLE_MMAP) {
        if (r->cq_sz > r->sq_sz) r->sq_sz = r->cq_sz;
        r->cq_sz = r->sq_sz;
    }
    r->sq_ptr = mmap(nullptr, r->sq_sz, PROT_READ | PROT_WRITE,
                     MAP_SHARED | MAP_POPULATE, fd, IORING_OFF_SQ_RING);
    if (r->sq_ptr == MAP_FAILED) goto fail;
    if (p.features & IORING_FEAT_SINGLE_MMAP) {
        r->cq_ptr = r->sq_ptr;
    } else {
        r->cq_ptr = mmap(nullptr, r->cq_sz, PROT_READ | PROT_WRITE,
                         MAP_SHARED | MAP_POPULATE, fd, IORING_OFF_CQ_RING);
        if (r->cq_ptr == MAP_FAILED) { r->cq_ptr = nullptr; goto fail; }
    }
    r->sqes_sz = p.sq_entries * sizeof(io_uring_sqe);
    r->sqes = (io_uring_sqe *)mmap(nullptr, r->sqes_sz,
                                   PROT_READ | PROT_WRITE,
                                   MAP_SHARED | MAP_POPULATE, fd,
                                   IORING_OFF_SQES);
    if (r->sqes == MAP_FAILED) { r->sqes = nullptr; goto fail; }

    {
        u8 *sq = (u8 *)r->sq_ptr;
        r->sq_khead = (u32 *)(sq + p.sq_off.head);
        r->sq_ktail = (u32 *)(sq + p.sq_off.tail);
        r->sq_kflags = (u32 *)(sq + p.sq_off.flags);
        r->sq_array = (u32 *)(sq + p.sq_off.array);
        r->sq_mask = *(u32 *)(sq + p.sq_off.ring_mask);
        u8 *cq = (u8 *)r->cq_ptr;
        r->cq_khead = (u32 *)(cq + p.cq_off.head);
        r->cq_ktail = (u32 *)(cq + p.cq_off.tail);
        r->cq_koverflow = (u32 *)(cq + p.cq_off.overflow);
        r->cqes = (io_uring_cqe *)(cq + p.cq_off.cqes);
        r->cq_mask = *(u32 *)(cq + p.cq_off.ring_mask);
        // identity SQ index array: slot i always points at SQE i
        for (u32 i = 0; i <= r->sq_mask; i++) r->sq_array[i] = i;
    }
    if (err_out) *err_out = 0;
    return r;

fail:
    if (err_out) *err_out = -errno;
    if (r->sqes) munmap(r->sqes, r->sqes_sz);
    if (r->cq_ptr && r->cq_ptr != r->sq_ptr) munmap(r->cq_ptr, r->cq_sz);
    if (r->sq_ptr) munmap(r->sq_ptr, r->sq_sz);
    close(fd);
    delete r;
    return nullptr;
}

void pcu_destroy(pcu_ring *r) {
    if (!r) return;
    if (r->sqes) munmap(r->sqes, r->sqes_sz);
    if (r->cq_ptr && r->cq_ptr != r->sq_ptr) munmap(r->cq_ptr, r->cq_sz);
    if (r->sq_ptr) munmap(r->sq_ptr, r->sq_sz);
    if (r->fd >= 0) close(r->fd);
    free(r->pbuf_ring);
    free(r->pbuf_slab);
    if (r->telem) munmap(r->telem, sizeof(pcu_telem));
    delete r;
}

// ---- telemetry ABI ----------------------------------------------------------

// Allocate + attach the shm telemetry block (idempotent). MAP_SHARED |
// MAP_ANONYMOUS: same address space here, but the mapping survives a
// fork and is the natural substrate should a sibling process ever map
// it — and it is page-aligned and zero-filled by the kernel.
int pcu_telem_enable(pcu_ring *r) {
    if (r->telem) return 0;
    void *p = mmap(nullptr, sizeof(pcu_telem), PROT_READ | PROT_WRITE,
                   MAP_SHARED | MAP_ANONYMOUS, -1, 0);
    if (p == MAP_FAILED) return -errno;
    r->telem = (pcu_telem *)p;
    return 0;
}

int pcu_telem_enabled(pcu_ring *r) { return r->telem ? 1 : 0; }

// Snapshot payload size in u64 words (everything after the seq word).
long pcu_telem_words(void) {
    return (long)((sizeof(pcu_telem) - sizeof(u64)) / sizeof(u64));
}

// Torn-read-safe whole-block copy: retry until the sequence word reads
// the same even value on both sides. Returns words copied, 0 when
// telemetry is off, -1 on a too-small buffer, -2 if the writer never
// went quiet (callers keep their previous snapshot).
long pcu_telem_snapshot(pcu_ring *r, unsigned long long *out, long cap) {
    pcu_telem *t = r->telem;
    if (!t) return 0;
    const long words = pcu_telem_words();
    if (cap < words) return -1;
    for (int attempt = 0; attempt < 1000; attempt++) {
        u64 s1 = __atomic_load_n(&t->seq, __ATOMIC_ACQUIRE);
        if (s1 & 1) continue;
        memcpy(out, (const u8 *)t + sizeof(u64),
               (size_t)words * sizeof(u64));
        __atomic_thread_fence(__ATOMIC_ACQUIRE);
        u64 s2 = __atomic_load_n(&t->seq, __ATOMIC_RELAXED);
        if (s1 == s2) return words;
    }
    return -2;
}

// Test hook: drive one observation into a chosen histogram from Python
// (kind 0 = stage, 1 = chain, 2 = class_delay) so the seqlock and the
// log2 bucketing are testable without a live pumped ring.
int pcu_telem_test_observe(pcu_ring *r, int kind, int idx,
                           unsigned long long ns, unsigned long long n) {
    pcu_telem *t = r->telem;
    if (!t) return -1;
    pcu_hist *h;
    if (kind == 0 && idx >= 0 && idx < PCU_TM_STAGES) h = &t->stage[idx];
    else if (kind == 1 && idx >= 0 && idx < PCU_TM_CHAIN) h = &t->chain[idx];
    else if (kind == 2 && idx >= 0 && idx < PCU_TM_CLASSES)
        h = &t->class_delay[idx];
    else return -2;
    pcu_tm_observe_n(t, h, ns, n ? n : 1);
    return 0;
}

// Test hook: bump the flat per-class counters (which 0 = class_frames,
// 1 = fate_drop_frames) so the conservation-ledger fold in metrics.py
// is testable without a live pumped ring.
int pcu_telem_test_count(pcu_ring *r, int which, int idx,
                         unsigned long long n) {
    pcu_telem *t = r->telem;
    if (!t) return -1;
    if (idx < 0 || idx >= PCU_TM_CLASSES || which < 0 || which > 1)
        return -2;
    pcu_tm_begin(t);
    if (which == 0) t->class_frames[idx] += n;
    else t->fate_drop_frames[idx] += n;
    pcu_tm_end(t);
    return 0;
}

int pcu_ring_fd(pcu_ring *r) { return r->fd; }
unsigned pcu_sq_entries(pcu_ring *r) { return r->sq_entries; }

int pcu_register_eventfd(pcu_ring *r, int efd, int async_only) {
    unsigned op = async_only ? IORING_REGISTER_EVENTFD_ASYNC
                             : IORING_REGISTER_EVENTFD;
    int rc = sys_register(r->fd, op, &efd, 1);
    if (rc < 0 && async_only)  // pre-5.1-ASYNC kernels: plain eventfd
        rc = sys_register(r->fd, IORING_REGISTER_EVENTFD, &efd, 1);
    return rc;
}

// Sparse fixed-buffer table; individual slots are filled later as the
// egress pool hands buffers over (registration is a page-pinning
// operation — done once per pooled buffer, not per send).
int pcu_register_buf_table(pcu_ring *r, unsigned nslots) {
    struct io_uring_rsrc_register rr;
    memset(&rr, 0, sizeof(rr));
    rr.nr = nslots;
    rr.flags = IORING_RSRC_REGISTER_SPARSE;
    return sys_register(r->fd, IORING_REGISTER_BUFFERS2, &rr, sizeof(rr));
}

int pcu_update_buf(pcu_ring *r, unsigned slot, void *addr,
                   unsigned long len) {
    struct iovec { void *iov_base; size_t iov_len; } iov = {addr, len};
    u64 tag = 0;
    struct io_uring_rsrc_update2 up;
    memset(&up, 0, sizeof(up));
    up.offset = slot;
    up.data = (u64)(uintptr_t)&iov;
    up.tags = (u64)(uintptr_t)&tag;
    up.nr = 1;
    return sys_register(r->fd, IORING_REGISTER_BUFFERS_UPDATE, &up,
                        sizeof(up));
}

// Provided-buffer ring (bgid 0): the kernel picks a free buffer per
// multishot-recv completion; Python copies the payload out and recycles
// the bid immediately, so the slab is sized for in-flight CQEs only.
int pcu_pbuf_setup(pcu_ring *r, unsigned entries, unsigned buflen,
                   unsigned long long *base_out) {
    if (r->pbuf_ring) return -EEXIST;
    if (entries & (entries - 1)) return -EINVAL;
    io_uring_buf *ring = (io_uring_buf *)aligned_alloc(
        4096, entries * sizeof(io_uring_buf));
    u8 *slab = (u8 *)malloc((size_t)entries * buflen);
    if (!ring || !slab) { free(ring); free(slab); return -ENOMEM; }
    memset(ring, 0, entries * sizeof(io_uring_buf));
    struct io_uring_buf_reg reg;
    memset(&reg, 0, sizeof(reg));
    reg.ring_addr = (u64)(uintptr_t)ring;
    reg.ring_entries = entries;
    reg.bgid = 0;
    int rc = sys_register(r->fd, IORING_REGISTER_PBUF_RING, &reg, 1);
    if (rc < 0) { free(ring); free(slab); return rc; }
    r->pbuf_ring = ring;
    r->pbuf_slab = slab;
    r->pbuf_entries = entries;
    r->pbuf_len = buflen;
    r->pbuf_tail = &ring[0].resv;  // uapi: tail overlays entry 0's resv
    u16 tail = 0;
    for (unsigned i = 0; i < entries; i++) {
        io_uring_buf *e = &ring[tail & (entries - 1)];
        e->addr = (u64)(uintptr_t)(slab + (size_t)i * buflen);
        e->len = buflen;
        e->bid = (u16)i;
        tail++;
    }
    STORE_REL(r->pbuf_tail, tail);
    if (base_out) *base_out = (unsigned long long)(uintptr_t)slab;
    return 0;
}

void pcu_pbuf_recycle(pcu_ring *r, unsigned short bid) {
    u16 tail = *r->pbuf_tail;
    io_uring_buf *e = &r->pbuf_ring[tail & (r->pbuf_entries - 1)];
    e->addr = (u64)(uintptr_t)(r->pbuf_slab + (size_t)bid * r->pbuf_len);
    e->len = r->pbuf_len;
    e->bid = bid;
    STORE_REL(r->pbuf_tail, (u16)(tail + 1));
}

unsigned pcu_pbuf_buflen(pcu_ring *r) { return r->pbuf_len; }

// ---- SQE prep --------------------------------------------------------------

static io_uring_sqe *next_sqe(pcu_ring *r) {
    u32 head = LOAD_ACQ(r->sq_khead);
    if (r->local_tail - head >= r->sq_entries)
        return nullptr;  // SQ full: caller must submit first
    io_uring_sqe *sqe = &r->sqes[r->local_tail & r->sq_mask];
    memset(sqe, 0, sizeof(*sqe));
    r->local_tail++;
    return sqe;
}

int pcu_sq_space(pcu_ring *r) {
    u32 head = LOAD_ACQ(r->sq_khead);
    return (int)(r->sq_entries - (r->local_tail - head));
}

int pcu_prep_send(pcu_ring *r, int fd, unsigned long long addr, unsigned len,
                  unsigned long long ud, unsigned sqe_flags,
                  unsigned msg_flags) {
    io_uring_sqe *sqe = next_sqe(r);
    if (!sqe) return -EBUSY;
    sqe->opcode = IORING_OP_SEND;
    sqe->flags = (u8)sqe_flags;
    sqe->fd = fd;
    sqe->addr = addr;
    sqe->len = len;
    sqe->msg_flags = msg_flags;
    sqe->user_data = ud;
    return 0;
}

// MSG_ZEROCOPY send: posts the normal CQE (res = bytes, F_MORE) and a
// later F_NOTIF CQE once the kernel is done with the pages; buf_index
// >= 0 selects a registered fixed buffer.
int pcu_prep_send_zc(pcu_ring *r, int fd, unsigned long long addr,
                     unsigned len, unsigned long long ud,
                     unsigned sqe_flags, unsigned msg_flags, int buf_index) {
    io_uring_sqe *sqe = next_sqe(r);
    if (!sqe) return -EBUSY;
    sqe->opcode = IORING_OP_SEND_ZC;
    sqe->flags = (u8)sqe_flags;
    sqe->fd = fd;
    sqe->addr = addr;
    sqe->len = len;
    sqe->msg_flags = msg_flags;
    sqe->user_data = ud;
    if (buf_index >= 0) {
        sqe->ioprio = IORING_RECVSEND_FIXED_BUF;
        sqe->buf_index = (u16)buf_index;
    }
    return 0;
}

int pcu_prep_write_fixed(pcu_ring *r, int fd, unsigned long long addr,
                         unsigned len, int buf_index, unsigned long long ud,
                         unsigned sqe_flags) {
    io_uring_sqe *sqe = next_sqe(r);
    if (!sqe) return -EBUSY;
    sqe->opcode = IORING_OP_WRITE_FIXED;
    sqe->flags = (u8)sqe_flags;
    sqe->fd = fd;
    sqe->addr = addr;
    sqe->len = len;
    sqe->buf_index = (u16)buf_index;
    sqe->user_data = ud;
    return 0;
}

int pcu_prep_recv_multishot(pcu_ring *r, int fd, unsigned long long ud) {
    io_uring_sqe *sqe = next_sqe(r);
    if (!sqe) return -EBUSY;
    sqe->opcode = IORING_OP_RECV;
    sqe->flags = IOSQE_BUFFER_SELECT;
    sqe->ioprio = IORING_RECV_MULTISHOT;
    sqe->fd = fd;
    sqe->buf_group = 0;
    sqe->user_data = ud;
    return 0;
}

int pcu_prep_recv(pcu_ring *r, int fd, unsigned long long addr, unsigned len,
                  unsigned long long ud) {
    io_uring_sqe *sqe = next_sqe(r);
    if (!sqe) return -EBUSY;
    sqe->opcode = IORING_OP_RECV;
    sqe->fd = fd;
    sqe->addr = addr;
    sqe->len = len;
    sqe->user_data = ud;
    return 0;
}

int pcu_prep_accept_multishot(pcu_ring *r, int fd, unsigned long long ud) {
    io_uring_sqe *sqe = next_sqe(r);
    if (!sqe) return -EBUSY;
    sqe->opcode = IORING_OP_ACCEPT;
    sqe->ioprio = IORING_ACCEPT_MULTISHOT;
    sqe->fd = fd;
    sqe->user_data = ud;
    return 0;
}

int pcu_prep_cancel(pcu_ring *r, unsigned long long target_ud,
                    unsigned long long ud) {
    io_uring_sqe *sqe = next_sqe(r);
    if (!sqe) return -EBUSY;
    sqe->opcode = IORING_OP_ASYNC_CANCEL;
    sqe->addr = target_ud;
    sqe->fd = -1;
    sqe->user_data = ud;
    return 0;
}

int pcu_prep_shutdown(pcu_ring *r, int fd, int how, unsigned long long ud) {
    io_uring_sqe *sqe = next_sqe(r);
    if (!sqe) return -EBUSY;
    sqe->opcode = IORING_OP_SHUTDOWN;
    sqe->fd = fd;
    sqe->len = (u32)how;
    sqe->user_data = ud;
    return 0;
}

// ---- submit / complete -----------------------------------------------------

// Publish prepped SQEs. Non-SQPOLL: one io_uring_enter covering every
// SQE prepped since the last submit (the whole point — one syscall per
// loop tick, not per flush). SQPOLL: zero syscalls unless the poller
// thread went idle and needs a wakeup. Returns number consumed, or
// -errno.
long pcu_submit(pcu_ring *r, unsigned wait_nr) {
    u32 to_submit = r->local_tail - r->local_submitted;
    STORE_REL(r->sq_ktail, r->local_tail);
    if (r->setup_flags & IORING_SETUP_SQPOLL) {
        r->local_submitted = r->local_tail;
        unsigned flags = 0;
        if (LOAD_ACQ(r->sq_kflags) & IORING_SQ_NEED_WAKEUP)
            flags |= IORING_ENTER_SQ_WAKEUP;
        if (wait_nr) flags |= IORING_ENTER_GETEVENTS;
        if (!flags) return to_submit;  // poller awake: zero-syscall submit
        u64 t0 = r->telem ? pcu_now_ns() : 0;
        long rc = sys_enter(r->fd, 0, wait_nr, flags);
        if (r->telem)
            pcu_tm_observe(r->telem, &r->telem->chain[0],
                           pcu_now_ns() - t0);
        return rc < 0 ? rc : (long)to_submit;
    }
    if (!to_submit && !wait_nr) return 0;
    unsigned flags = wait_nr ? IORING_ENTER_GETEVENTS : 0;
    u64 t0 = r->telem ? pcu_now_ns() : 0;
    long rc = sys_enter(r->fd, to_submit, wait_nr, flags);
    if (r->telem)
        pcu_tm_observe(r->telem, &r->telem->chain[0], pcu_now_ns() - t0);
    if (rc < 0) return rc;
    r->local_submitted += (u32)rc;
    return rc;
}

int pcu_cq_overflowed(pcu_ring *r) {
    return (LOAD_ACQ(r->sq_kflags) & IORING_SQ_CQ_OVERFLOW) ? 1 : 0;
}

// Flush kernel-side overflowed CQEs back into the ring (NODROP path).
long pcu_flush_overflow(pcu_ring *r) {
    return sys_enter(r->fd, 0, 0, IORING_ENTER_GETEVENTS);
}

// Drain up to max CQEs into flat arrays (one ctypes call per drain, not
// per completion).
int pcu_peek_cqes(pcu_ring *r, unsigned long long *uds, int *ress,
                  unsigned *flagss, int max) {
    u32 head = *r->cq_khead;
    u32 tail = LOAD_ACQ(r->cq_ktail);
    int n = 0;
    while (head != tail && n < max) {
        io_uring_cqe *cqe = &r->cqes[head & r->cq_mask];
        uds[n] = cqe->user_data;
        ress[n] = cqe->res;
        flagss[n] = cqe->flags;
        n++;
        head++;
    }
    if (n) STORE_REL(r->cq_khead, head);
    return n;
}

}  // extern "C"
