// Batch frame -> Message decoder using the CPython API.
//
// The fan-out drain's decoded-delivery rate is bound by per-message Python
// work: decode_frames (proto/message.py) spends ~750 ns/msg on byte
// indexing, payload slicing, and Broadcast/Direct construction. This
// translation unit does the same work in C — one call per FrameChunk —
// constructing the SAME Python classes (passed in from message.py) via
// tp_alloc + direct slot writes, bypassing the interpreter loop and
// __init__.  Parity note: this accelerates the hot half of the decode path
// that mirrors the reference's per-frame deserialize in its receive loop
// (cdn-broker/src/tasks/broker/handler.rs:240-272); cold kinds and
// malformed frames go through the Python fallback callable so error
// semantics (Error(DESERIALIZE)) are byte-identical.
//
// Loaded via ctypes.PyDLL (GIL held for the whole call). Compiled
// separately from framing.cpp, which is a plain-C-ABI CDLL whose calls
// release the GIL — mixing the two conventions in one library would make
// it too easy to call a Python-API function GIL-free.

#define PY_SSIZE_T_CLEAN
#include <Python.h>

#include <cstdint>

#ifndef Py_T_OBJECT_EX  // pre-3.12 spelling
#define Py_T_OBJECT_EX T_OBJECT_EX
#include <structmember.h>
#endif

namespace {

constexpr uint8_t KIND_DIRECT = 4;
constexpr uint8_t KIND_BROADCAST = 5;

// Resolved once per process (the message classes are module-level
// singletons); offset 0 means "not resolved / unusable".
struct SlotOffsets {
  Py_ssize_t bc_topics = 0, bc_message = 0;
  Py_ssize_t di_recipient = 0, di_message = 0;
  PyTypeObject* bc_type = nullptr;
  PyTypeObject* di_type = nullptr;
  bool ready = false;
};
SlotOffsets g_slots;

// Find the byte offset of a __slots__ member descriptor on `type`.
// Returns 0 on any surprise (caller then refuses the fast path).
Py_ssize_t slot_offset(PyTypeObject* type, const char* name) {
  PyObject* descr = PyDict_GetItemString(type->tp_dict, name);  // borrowed
  if (descr == nullptr) return 0;
  if (Py_TYPE(descr) != &PyMemberDescr_Type) return 0;
  PyMemberDef* m = ((PyMemberDescrObject*)descr)->d_member;
  if (m == nullptr || m->type != Py_T_OBJECT_EX || m->offset <= 0) return 0;
  return m->offset;
}

bool resolve_types(PyObject* broadcast_type, PyObject* direct_type) {
  if (!PyType_Check(broadcast_type) || !PyType_Check(direct_type))
    return false;
  PyTypeObject* bt = (PyTypeObject*)broadcast_type;
  PyTypeObject* dt = (PyTypeObject*)direct_type;
  SlotOffsets s;
  s.bc_topics = slot_offset(bt, "topics");
  s.bc_message = slot_offset(bt, "message");
  s.di_recipient = slot_offset(dt, "recipient");
  s.di_message = slot_offset(dt, "message");
  if (!s.bc_topics || !s.bc_message || !s.di_recipient || !s.di_message)
    return false;
  // the types outlive the process (module globals); borrow, no incref
  s.bc_type = bt;
  s.di_type = dt;
  s.ready = true;
  g_slots = s;
  return true;
}

// a and b are STOLEN on success; freed on failure.
PyObject* alloc_with_slots(PyTypeObject* type, Py_ssize_t off_a,
                           PyObject* a, Py_ssize_t off_b, PyObject* b) {
  PyObject* obj = type->tp_alloc(type, 0);
  if (obj == nullptr) {
    Py_DECREF(a);
    Py_DECREF(b);
    return nullptr;
  }
  *(PyObject**)((char*)obj + off_a) = a;
  *(PyObject**)((char*)obj + off_b) = b;
  return obj;
}

// A zero-copy payload: a slice of `master` (a memoryview over the chunk
// buffer — the buffer stays alive through the view's reference chain).
// Returns a new reference, or NULL with an exception set.
PyObject* slice_view(PyObject* master, Py_ssize_t start, Py_ssize_t stop) {
  PyObject* lo = PyLong_FromSsize_t(start);
  PyObject* hi = PyLong_FromSsize_t(stop);
  if (lo == nullptr || hi == nullptr) {
    Py_XDECREF(lo);
    Py_XDECREF(hi);
    return nullptr;
  }
  PyObject* sl = PySlice_New(lo, hi, nullptr);
  Py_DECREF(lo);
  Py_DECREF(hi);
  if (sl == nullptr) return nullptr;
  PyObject* out = PyObject_GetItem(master, sl);
  Py_DECREF(sl);
  return out;
}

// Decode one frame at data[o : o+n]. Returns a new message object, or
// NULL with an exception set. With `master` non-NULL (a memoryview of
// the whole buffer), hot payloads of at least `zc_min` bytes come back
// as zero-copy views; smaller ones stay owned copies (message.py
// ZERO_COPY_MIN rationale: the copy is cheaper than the view object AND
// a retained view pins its whole chunk after the permit returns).
PyObject* decode_one(const uint8_t* data, Py_ssize_t o, Py_ssize_t n,
                     PyObject* fallback, PyObject* master,
                     Py_ssize_t zc_min) {
  if (n >= 3) {
    const uint8_t kind = data[o];
    if (kind == KIND_BROADCAST) {
      const Py_ssize_t nt =
          (Py_ssize_t)data[o + 1] | ((Py_ssize_t)data[o + 2] << 8);
      if (3 + nt <= n) {
        PyObject* topics = PyTuple_New(nt);
        if (topics == nullptr) return nullptr;
        for (Py_ssize_t t = 0; t < nt; t++)
          PyTuple_SET_ITEM(topics, t, PyLong_FromLong(data[o + 3 + t]));
        PyObject* msg =
            master != nullptr && n - 3 - nt >= zc_min
                ? slice_view(master, o + 3 + nt, o + n)
                : PyBytes_FromStringAndSize((const char*)data + o + 3 + nt,
                                            n - 3 - nt);
        if (msg == nullptr) {
          Py_DECREF(topics);
          return nullptr;
        }
        return alloc_with_slots(g_slots.bc_type, g_slots.bc_topics, topics,
                                g_slots.bc_message, msg);
      }
    } else if (kind == KIND_DIRECT && n >= 5) {
      const Py_ssize_t rlen = (Py_ssize_t)data[o + 1] |
                              ((Py_ssize_t)data[o + 2] << 8) |
                              ((Py_ssize_t)data[o + 3] << 16) |
                              ((Py_ssize_t)data[o + 4] << 24);
      if (5 + rlen <= n) {
        // the recipient stays an owned bytes copy: it is small and used
        // as a dict key (hashable) by every consumer
        PyObject* rcpt =
            PyBytes_FromStringAndSize((const char*)data + o + 5, rlen);
        if (rcpt == nullptr) return nullptr;
        PyObject* msg =
            master != nullptr && n - 5 - rlen >= zc_min
                ? slice_view(master, o + 5 + rlen, o + n)
                : PyBytes_FromStringAndSize((const char*)data + o + 5 + rlen,
                                            n - 5 - rlen);
        if (msg == nullptr) {
          Py_DECREF(rcpt);
          return nullptr;
        }
        return alloc_with_slots(g_slots.di_type, g_slots.di_recipient, rcpt,
                                g_slots.di_message, msg);
      }
    }
  }
  // cold kind or malformed hot frame: Python fallback keeps the
  // Error(DESERIALIZE) semantics (and may raise — propagate)
  PyObject* frame = PyBytes_FromStringAndSize((const char*)data + o, n);
  if (frame == nullptr) return nullptr;
  PyObject* item = PyObject_CallFunctionObjArgs(fallback, frame, nullptr);
  Py_DECREF(frame);
  return item;
}

}  // namespace

extern "C" {

// Decode frames [start, len(offs)) of one chunk into a list of message
// objects. With zero_copy_min > 0, Broadcast/Direct payloads of at least
// that many bytes are memoryview slices over `buf` (one master view per
// call; the buffer lives as long as any view). Returns:
//   - new list on success;
//   - Py_None (new ref) when inputs don't fit the fast path (caller falls
//     back to the Python decoder);
//   - NULL with an exception set when decoding failed.
PyObject* pushcdn_decode_frames_py(PyObject* buf, PyObject* offs,
                                   PyObject* lens, Py_ssize_t start,
                                   PyObject* broadcast_type,
                                   PyObject* direct_type,
                                   PyObject* fallback,
                                   Py_ssize_t zero_copy_min) {
  // (re)resolve when first called OR when the caller's classes changed
  // (module reload): constructing stale types would silently break
  // type() checks downstream, and a GC'd old type would dangle.
  if ((!g_slots.ready ||
       (PyObject*)g_slots.bc_type != broadcast_type ||
       (PyObject*)g_slots.di_type != direct_type) &&
      !resolve_types(broadcast_type, direct_type))
    Py_RETURN_NONE;
  if (!PyBytes_Check(buf) || !PyList_Check(offs) || !PyList_Check(lens))
    Py_RETURN_NONE;
  const uint8_t* data = (const uint8_t*)PyBytes_AS_STRING(buf);
  const Py_ssize_t buf_len = PyBytes_GET_SIZE(buf);
  const Py_ssize_t count = PyList_GET_SIZE(offs);
  if (PyList_GET_SIZE(lens) != count || start < 0 || start > count)
    Py_RETURN_NONE;

  PyObject* master = nullptr;
  if (zero_copy_min > 0) {
    master = PyMemoryView_FromObject(buf);
    if (master == nullptr) return nullptr;
  }
  PyObject* out = PyList_New(count - start);
  if (out == nullptr) {
    Py_XDECREF(master);
    return nullptr;
  }

  for (Py_ssize_t i = start; i < count; i++) {
    const Py_ssize_t o = PyLong_AsSsize_t(PyList_GET_ITEM(offs, i));
    const Py_ssize_t n = PyLong_AsSsize_t(PyList_GET_ITEM(lens, i));
    if (o < 0 || n < 0 || o + n > buf_len) {
      // non-int or out-of-range offs/lens: delegate the WHOLE batch to
      // the Python loop so both implementations behave identically on
      // degenerate inputs (Python slicing truncates; we must not invent
      // a third behavior here)
      PyErr_Clear();
      Py_DECREF(out);
      Py_XDECREF(master);
      Py_RETURN_NONE;
    }
    PyObject* item = decode_one(data, o, n, fallback, master,
                                zero_copy_min);
    if (item == nullptr) {
      Py_DECREF(out);
      Py_XDECREF(master);
      return nullptr;
    }
    PyList_SET_ITEM(out, i - start, item);
  }
  Py_XDECREF(master);
  return out;
}

}  // extern "C"
