// Fused data-plane pump: recv'd chunk -> route plan -> linked send SQEs
// in one native pass (ISSUE 15).
//
// This TU *includes* the two layers it composes so it shares their types
// and helpers: the pcu_ring struct + SQE prep from io_uring.cpp and the
// RouteTable + pushcdn_route_plan kernel from route_plan.cpp. The pump
// library operates on handles CREATED BY the other libraries (the
// engine's pcu_ring*, the planner's RouteTable*): the structs hold all
// state (no file-scope globals), every .so is compiled from the same
// sources with the same flags, and malloc/free share libc — so the
// layouts interoperate across the dlopen boundary.
//
// Data model:
//   - pushcdn_pump_route_chunk runs the EXISTING plan kernel over the
//     chunk, then partitions the (peer, frame) pairs: peers mapped to an
//     engaged pump slot get per-peer zero-copy RUNS (maximal contiguous
//     frame spans of the pooled chunk — the wire bytes verbatim) queued
//     and submitted as one linked chain of plain SEND SQEs per peer;
//     everything else (unengaged peers, fenced peers, cross-shard peers
//     left unmapped by Python) is compacted into residual pair arrays
//     for the existing Python _send_plan, in frame order.
//   - A chunk with at least one staged run takes one CHUNK SLOT whose
//     refcount is one per run; Python parks the chunk's pool lease under
//     that slot and drops it when the slot shows up in
//     pushcdn_pump_take_released — batch-wise lease accounting
//     reconciled against proto/limiter.py. Released slots accumulate in
//     a bounded internal list (each slot releases exactly once per
//     in_use cycle), so a burst can never overflow them away.
//   - pushcdn_pump_drain replaces the engine's raw CQE peek: pump-tagged
//     CQEs (bit 63 of user_data) are accounted here, mirroring
//     UringStream._on_send_cqe exactly (WAITALL re-pump on a short lone
//     tail, poison on a short mid-chain link, EPIPE on zero-with-
//     remaining); everything else is compacted out for the Python
//     dispatcher. Peer state transitions (idle / error / quiesced)
//     return as flat int64 triples.
//
// Sends are plain IORING_OP_SEND (MSG_NOSIGNAL|MSG_WAITALL), not
// SEND_ZC: the run already points at the pooled chunk, so userspace
// copies are zero either way; the kernel copy to the socket buffer
// matches the non-ZC engine path this replaces.

#include "io_uring.cpp"
#include "route_plan.cpp"

namespace {

constexpr unsigned long long PUMP_UD_TAG = 1ull << 63;
constexpr int PUMP_CHAIN_MAX = 64;

#ifndef MSG_NOSIGNAL
#define MSG_NOSIGNAL 0x4000
#endif
#ifndef MSG_WAITALL
#define MSG_WAITALL 0x100
#endif
constexpr unsigned PUMP_MSG_FLAGS = MSG_NOSIGNAL | MSG_WAITALL;

struct PumpRun {
  unsigned long long addr;
  u32 len;
  u32 sent;
  s32 chunk_slot;
  // telemetry (ISSUE 19): stage stamps ride the run so completion can
  // observe recv->send latency with zero Python. All zero when the
  // ring's telemetry block is off.
  u64 t_recv = 0;    // recv-CQE stamp (drain wakeup that carried the chunk)
  u64 t_ready = 0;   // plan-done stamp (run queued, eligible to submit)
  u64 t_submit = 0;  // SQE-submit stamp (prep_chain staged the send)
  u32 cls_frames[PCU_TM_CLASSES] = {0, 0, 0, 0};
  u32 cls_bytes[PCU_TM_CLASSES] = {0, 0, 0, 0};
};

struct PumpPeer {
  int fd = -1;
  bool in_use = false;
  bool fenced = false;
  bool dead = false;   // drop_peer'd: slot frees on quiesce
  int err = 0;         // positive errno once the peer failed
  PumpRun *q = nullptr;
  u32 q_cap = 0, q_head = 0, q_len = 0;  // live runs: q[q_head .. +q_len)
  u32 inflight = 0;    // CQEs outstanding for the current chain
  // per-route_chunk staging (frame-ordered pair list indices)
  s32 stage_head = -1, stage_tail = -1;
  // telemetry: chain start stamp + claimed row in the bounded per-peer
  // counter table (-1 = unclaimed / table full)
  u64 chain_t0 = 0;
  s32 tm_row = -1;
};

struct ChunkSlot {
  u32 refs = 0;
  bool in_use = false;
};

struct Pump {
  pcu_ring *ring = nullptr;
  PumpPeer *peers = nullptr;
  u32 max_peers = 0;
  s32 *slot_map = nullptr;   // route peer slot -> pump id (or -1)
  u32 slot_n = 0, slot_cap = 0;
  ChunkSlot *chunks = nullptr;
  s32 *chunk_free = nullptr;
  u32 n_chunks = 0, n_chunk_free = 0;
  s32 *released = nullptr;   // slots whose refs hit 0, pending Python
  u32 n_released = 0;
  u32 sq_reserve = 0;        // SQ entries kept back for the Python engine
  // plan + staging scratch
  s32 *pr_peer = nullptr, *pr_frame = nullptr, *pr_next = nullptr;
  s32 *touched = nullptr;
  long pair_cap = 0;
  // stats
  u64 st_runs = 0, st_chains = 0, st_sqes = 0, st_cqes = 0;
  u64 st_bytes = 0, st_frames = 0, st_errors = 0, st_short_repump = 0;
  u64 st_ev_lost = 0;
  // telemetry: recv-CQE stamp from the last drain wakeup (one vdso clock
  // read per drain, shared by every route_chunk the wakeup fans into)
  u64 last_recv_ns = 0;
};

struct EvBuf {
  long long *ev;
  long cap, n;
};

enum { EV_PEER_IDLE = 1, EV_PEER_ERROR = 2, EV_PEER_QUIESCED = 3 };

void emit(Pump *p, EvBuf *eb, long long type, long long a, long long b) {
  if (eb == nullptr || eb->n + 3 > eb->cap) {
    p->st_ev_lost++;
    return;
  }
  eb->ev[eb->n] = type;
  eb->ev[eb->n + 1] = a;
  eb->ev[eb->n + 2] = b;
  eb->n += 3;
}

void chunk_decref(Pump *p, s32 slot) {
  if (slot < 0 || (u32)slot >= p->n_chunks) return;
  ChunkSlot &c = p->chunks[slot];
  if (!c.in_use || c.refs == 0) return;
  if (--c.refs == 0) {
    c.in_use = false;
    p->chunk_free[p->n_chunk_free++] = slot;
    p->released[p->n_released++] = slot;  // bounded: once per use cycle
  }
}

void pop_run(Pump *p, PumpPeer &pp) {
  chunk_decref(p, pp.q[pp.q_head].chunk_slot);
  pp.q_head++;
  pp.q_len--;
  if (pp.q_len == 0) pp.q_head = 0;
}

// Terminal fate for an abandoned run: fold its per-class frame counts
// into the shared fate_drop_frames block so the conservation ledger
// accounts pump drops with zero Python on the frame path. cls_frames is
// valid from enqueue time (unlike the t_* stamps), so runs dropped
// before submit — or queued while telemetry was off — still count.
void run_dropped(Pump *p, const PumpRun &r) {
  pcu_telem *tm = p->ring->telem;
  if (tm == nullptr) return;
  pcu_tm_begin(tm);
  for (int c = 0; c < PCU_TM_CLASSES; ++c)
    tm->fate_drop_frames[c] += r.cls_frames[c];
  pcu_tm_end(tm);
}

// Drop every queued-but-not-inflight run (peer failed or dropped). The
// inflight ones keep their refs until their CQEs drain.
void drop_tail_runs(Pump *p, PumpPeer &pp) {
  while (pp.q_len > pp.inflight) {
    const PumpRun &r = pp.q[pp.q_head + pp.q_len - 1];
    run_dropped(p, r);
    chunk_decref(p, r.chunk_slot);
    pp.q_len--;
  }
  if (pp.q_len == 0) pp.q_head = 0;
}

void free_peer_slot(Pump *p, u32 id) {
  PumpPeer &pp = p->peers[id];
  std::free(pp.q);
  pp = PumpPeer();
}

// Unlocked histogram add — callers batch several of these inside one
// pcu_tm_begin/pcu_tm_end seqlock section.
inline void tm_hist_add(pcu_hist *h, u64 ns, u64 n) {
  h->count += n;
  h->sum_ns += ns * n;
  h->bucket[pcu_log2_bucket(ns)] += n;
}

// Telemetry on a fully-delivered run: wire + total stage latencies,
// per-class delay/frames/bytes, bounded per-peer counters. One seqlock
// section per delivered run. Runs queued before telemetry was enabled
// carry zero stamps and are skipped.
void run_delivered(Pump *p, PumpPeer &pp, const PumpRun &r, u64 t_done) {
  pcu_telem *tm = p->ring->telem;
  if (tm == nullptr || r.t_submit == 0) return;
  const u64 wire = t_done > r.t_submit ? t_done - r.t_submit : 0;
  const u64 total =
      (r.t_recv != 0 && t_done > r.t_recv) ? t_done - r.t_recv : 0;
  u64 frames = 0;
  for (int c = 0; c < PCU_TM_CLASSES; ++c) frames += r.cls_frames[c];
  if (pp.tm_row < 0) {
    // claim (or rejoin, after re-engage) a row in the bounded per-peer
    // table, keyed by fd; table full -> stays unattributed (-1)
    for (u32 i = 0; i < tm->peer_used; ++i)
      if (tm->peer_fd[i] == (u64)pp.fd) { pp.tm_row = (s32)i; break; }
    if (pp.tm_row < 0 && tm->peer_used < (u64)PCU_TM_PEERS) {
      pp.tm_row = (s32)tm->peer_used;
      pcu_tm_begin(tm);
      tm->peer_fd[pp.tm_row] = (u64)pp.fd;
      tm->peer_used++;
      pcu_tm_end(tm);
    }
  }
  pcu_tm_begin(tm);
  tm_hist_add(&tm->stage[2], wire, 1);
  tm_hist_add(&tm->stage[3], total, 1);
  for (int c = 0; c < PCU_TM_CLASSES; ++c) {
    if (r.cls_frames[c] == 0) continue;
    tm_hist_add(&tm->class_delay[c], total, r.cls_frames[c]);
    tm->class_frames[c] += r.cls_frames[c];
    tm->class_bytes[c] += r.cls_bytes[c];
  }
  if (pp.tm_row >= 0) {
    tm->peer_frames[pp.tm_row] += frames;
    tm->peer_bytes[pp.tm_row] += r.len;
  }
  pcu_tm_end(tm);
}

void peer_fail(Pump *p, u32 id, int neg_errno, EvBuf *eb) {
  PumpPeer &pp = p->peers[id];
  if (pp.err == 0) {
    pp.err = -neg_errno;
    p->st_errors++;
    emit(p, eb, EV_PEER_ERROR, id, neg_errno);
  }
  drop_tail_runs(p, pp);
}

// Prep one linked chain for a peer whose previous chain finished.
// Returns SQEs prepped (0 when the SQ is too full to respect the
// engine's reserve — the drain sweep retries).
int prep_chain(Pump *p, u32 id) {
  PumpPeer &pp = p->peers[id];
  if (pp.inflight != 0 || pp.q_len == 0 || pp.err != 0 || pp.dead)
    return 0;
  int space = pcu_sq_space(p->ring) - (int)p->sq_reserve;
  if (space <= 0) return 0;
  u32 n = pp.q_len;
  if (n > (u32)PUMP_CHAIN_MAX) n = PUMP_CHAIN_MAX;
  if (n > (u32)space) n = (u32)space;
  const unsigned long long ud = PUMP_UD_TAG | id;
  u32 done = 0;
  for (u32 i = 0; i < n; ++i) {
    const PumpRun &r = pp.q[pp.q_head + i];
    const unsigned flags = (i + 1 < n) ? IOSQE_IO_LINK : 0;
    if (pcu_prep_send(p->ring, pp.fd, r.addr + r.sent, r.len - r.sent,
                      ud, flags, PUMP_MSG_FLAGS) != 0)
      break;  // SQ refused after the space check (defensive)
    done = i + 1;
  }
  if (done == 0) return 0;
  if (done < n) {
    // truncated: the previously prepped SQE carries IOSQE_IO_LINK and
    // would chain into an unrelated later SQE — clear it so the partial
    // chain stays well-formed
    pcu_ring *r = p->ring;
    r->sqes[(r->local_tail - 1) & r->sq_mask].flags &=
        (u8)~IOSQE_IO_LINK;
  }
  pp.inflight = done;
  p->st_chains++;
  p->st_sqes += done;
  if (p->ring->telem != nullptr) {
    pcu_telem *tm = p->ring->telem;
    const u64 t_sub = pcu_now_ns();
    pp.chain_t0 = t_sub;
    pcu_tm_begin(tm);
    for (u32 i = 0; i < done; ++i) {
      PumpRun &qr = pp.q[pp.q_head + i];
      qr.t_submit = t_sub;  // re-preps (ECANCELED requeue) restamp
      const u64 d =
          (qr.t_ready != 0 && t_sub > qr.t_ready) ? t_sub - qr.t_ready : 0;
      tm_hist_add(&tm->stage[1], d, 1);
    }
    pcu_tm_end(tm);
  }
  return (int)done;
}

// One CQE against a peer's head run — mirrors UringStream._on_send_cqe.
void pump_on_cqe(Pump *p, u32 id, int res, EvBuf *eb) {
  if (id >= p->max_peers) return;
  PumpPeer &pp = p->peers[id];
  if (!pp.in_use || pp.inflight == 0) return;  // stale/aborted
  pp.inflight--;
  p->st_cqes++;
  if (pp.inflight == 0 && pp.chain_t0 != 0 && p->ring->telem != nullptr) {
    // submit -> quiesce wall time for the chain that just finished
    const u64 now = pcu_now_ns();
    pcu_tm_observe(p->ring->telem, &p->ring->telem->chain[1],
                   now > pp.chain_t0 ? now - pp.chain_t0 : 0);
    pp.chain_t0 = 0;
  }
  if (pp.err != 0) {
    // draining a failed peer: every trailing CQE frees one head run
    if (pp.q_len > 0) {
      run_dropped(p, pp.q[pp.q_head]);
      pop_run(p, pp);
    }
  } else if (res < 0) {
    if (res == -ECANCELED) {
      // entry stays queued; a later chain re-sends it
    } else {
      peer_fail(p, id, res, eb);
      if (pp.q_len > 0) {
        run_dropped(p, pp.q[pp.q_head]);  // the failed head
        pop_run(p, pp);
      }
      drop_tail_runs(p, pp);
    }
  } else {
    PumpRun &r = pp.q[pp.q_head];
    if (res == 0 && r.sent < r.len) {
      peer_fail(p, id, -EPIPE, eb);
      if (pp.q_len > 0) {
        run_dropped(p, r);
        pop_run(p, pp);
      }
      drop_tail_runs(p, pp);
    } else {
      r.sent += (u32)res;
      if (r.sent >= r.len) {
        if (p->ring->telem != nullptr)
          run_delivered(p, pp, r, pcu_now_ns());
        pop_run(p, pp);
      } else if (pp.inflight > 0) {
        // short link mid-chain: later links already wrote past the gap
        // — the wire holds a torn frame; poison, never re-frame
        peer_fail(p, id, -EIO, eb);
        if (pp.q_len > 0) {
          run_dropped(p, pp.q[pp.q_head]);
          pop_run(p, pp);
        }
        drop_tail_runs(p, pp);
      } else {
        p->st_short_repump++;  // lone short tail: re-pump the residue
      }
    }
  }
  if (pp.inflight == 0 && pp.q_len == 0) {
    if (pp.err != 0 || pp.dead) {
      const bool was_dead = pp.dead;
      emit(p, eb, EV_PEER_QUIESCED, id, was_dead ? 1 : 0);
      if (was_dead) free_peer_slot(p, id);
    } else {
      emit(p, eb, EV_PEER_IDLE, id, 0);
    }
  }
  // inflight == 0 with q_len > 0 (re-pump / ECANCELED requeue) is
  // handled by the drain's chain sweep
}

}  // namespace

extern "C" {

void *pushcdn_pump_create(void *ring_handle, int max_peers, int chunk_slots,
                          int sq_reserve, long pair_cap) {
  if (ring_handle == nullptr || max_peers <= 0 || chunk_slots <= 0 ||
      pair_cap <= 0)
    return nullptr;
  Pump *p = new (std::nothrow) Pump();
  if (p == nullptr) return nullptr;
  p->ring = (pcu_ring *)ring_handle;
  p->max_peers = (u32)max_peers;
  p->n_chunks = (u32)chunk_slots;
  p->sq_reserve = sq_reserve > 0 ? (u32)sq_reserve : 0;
  p->pair_cap = pair_cap;
  p->peers = new (std::nothrow) PumpPeer[max_peers]();
  p->chunks = new (std::nothrow) ChunkSlot[chunk_slots]();
  p->chunk_free = (s32 *)std::malloc(sizeof(s32) * chunk_slots);
  p->released = (s32 *)std::malloc(sizeof(s32) * chunk_slots);
  p->pr_peer = (s32 *)std::malloc(sizeof(s32) * pair_cap);
  p->pr_frame = (s32 *)std::malloc(sizeof(s32) * pair_cap);
  p->pr_next = (s32 *)std::malloc(sizeof(s32) * pair_cap);
  p->touched = (s32 *)std::malloc(sizeof(s32) * max_peers);
  if (p->peers == nullptr || p->chunks == nullptr ||
      p->chunk_free == nullptr || p->released == nullptr ||
      p->pr_peer == nullptr || p->pr_frame == nullptr ||
      p->pr_next == nullptr || p->touched == nullptr) {
    delete[] p->peers;
    delete[] p->chunks;
    std::free(p->chunk_free);
    std::free(p->released);
    std::free(p->pr_peer);
    std::free(p->pr_frame);
    std::free(p->pr_next);
    std::free(p->touched);
    delete p;
    return nullptr;
  }
  for (int i = 0; i < chunk_slots; ++i)
    p->chunk_free[i] = chunk_slots - 1 - i;
  p->n_chunk_free = (u32)chunk_slots;
  return p;
}

void pushcdn_pump_destroy(void *handle) {
  Pump *p = (Pump *)handle;
  if (p == nullptr) return;
  for (u32 i = 0; i < p->max_peers; ++i) std::free(p->peers[i].q);
  delete[] p->peers;
  delete[] p->chunks;
  std::free(p->chunk_free);
  std::free(p->released);
  std::free(p->slot_map);
  std::free(p->pr_peer);
  std::free(p->pr_frame);
  std::free(p->pr_next);
  std::free(p->touched);
  delete p;
}

// Engage a connection: returns the pump id, or -1 when the table is full.
int pushcdn_pump_add_peer(void *handle, int fd) {
  Pump *p = (Pump *)handle;
  if (p == nullptr || fd < 0) return -1;
  for (u32 i = 0; i < p->max_peers; ++i) {
    PumpPeer &pp = p->peers[i];
    if (!pp.in_use) {
      pp = PumpPeer();
      pp.in_use = true;
      pp.fd = fd;
      return (int)i;
    }
  }
  return -1;
}

void pushcdn_pump_set_fence(void *handle, int id, int fenced) {
  Pump *p = (Pump *)handle;
  if (p == nullptr || id < 0 || (u32)id >= p->max_peers) return;
  p->peers[id].fenced = fenced != 0;
}

// Runs still owed to the wire (queued + inflight). 0 == fully drained.
long pushcdn_pump_peer_pending(void *handle, int id) {
  Pump *p = (Pump *)handle;
  if (p == nullptr || id < 0 || (u32)id >= p->max_peers) return 0;
  PumpPeer &pp = p->peers[id];
  return pp.in_use ? (long)pp.q_len : 0;
}

void pushcdn_pump_peer_stats(void *handle, int id, long long *out) {
  // out[6]: q_len, inflight, fenced, err, dead, in_use
  Pump *p = (Pump *)handle;
  std::memset(out, 0, 6 * sizeof(long long));
  if (p == nullptr || id < 0 || (u32)id >= p->max_peers) return;
  PumpPeer &pp = p->peers[id];
  out[0] = pp.q_len;
  out[1] = pp.inflight;
  out[2] = pp.fenced;
  out[3] = pp.err;
  out[4] = pp.dead;
  out[5] = pp.in_use;
}

// Disengage: drop queued-but-not-inflight runs NOW (their chunk refs
// land in take_released), mark the peer dead so trailing CQEs drain the
// rest, free the slot immediately when already quiesced. Returns 1 when
// the slot was freed synchronously, 0 when it frees on quiesce, -1 on a
// bad id.
int pushcdn_pump_drop_peer(void *handle, int id) {
  Pump *p = (Pump *)handle;
  if (p == nullptr || id < 0 || (u32)id >= p->max_peers) return -1;
  PumpPeer &pp = p->peers[id];
  if (!pp.in_use) return -1;
  drop_tail_runs(p, pp);
  pp.dead = true;
  if (pp.inflight == 0 && pp.q_len == 0) {
    free_peer_slot(p, (u32)id);
    return 1;
  }
  return 0;
}

// Chunk slots whose refcount hit zero since the last call: Python drops
// the parked pool leases. MUST be drained after every call that can
// release (drain / inject / drop_peer) and before the next route_chunk,
// or a reused slot would alias a fresh lease.
long pushcdn_pump_take_released(void *handle, int *out, long cap) {
  Pump *p = (Pump *)handle;
  if (p == nullptr) return 0;
  long n = (long)p->n_released;
  if (n > cap) n = cap;
  for (long i = 0; i < n; ++i) out[i] = p->released[i];
  if ((u32)n < p->n_released) {
    std::memmove(p->released, p->released + n,
                 sizeof(s32) * (p->n_released - (u32)n));
    p->n_released -= (u32)n;
  } else {
    p->n_released = 0;
  }
  return n;
}

// Replace the route-slot -> pump-id map (Python rebuilds it whenever the
// snapshot version moves; -1 = not pumped).
int pushcdn_pump_set_slots(void *handle, const int *slots, long n) {
  Pump *p = (Pump *)handle;
  if (p == nullptr || n < 0) return -1;
  if ((u32)n > p->slot_cap) {
    s32 *grown = (s32 *)std::realloc(p->slot_map, sizeof(s32) * n);
    if (grown == nullptr) return -1;
    p->slot_map = grown;
    p->slot_cap = (u32)n;
  }
  if (n) std::memcpy(p->slot_map, slots, sizeof(s32) * n);
  p->slot_n = (u32)n;
  return 0;
}

// out_meta (int64[16]):
//  0 consumed        1 stop            2 n_resid        3 chunk_slot (-1)
//  4 refs_added      5 sqes_prepped    6 pumped_pairs   7 pumped_user_pairs
//  8 pumped_broker_pairs  9 resid_unmapped  10 resid_fenced
// 11 resid_error    12 no_chunk_slot  13 pumped_runs   14 plan_pairs
int64_t pushcdn_pump_route_chunk(
    void *handle, void *table_handle, const unsigned char *buf,
    int64_t buf_len, const int64_t *offs, const int64_t *lens,
    int64_t start, int64_t count, int mode, int *resid_peer,
    int *resid_frame, int64_t resid_cap, int64_t *out_meta,
    unsigned char *out_class) {
  Pump *p = (Pump *)handle;
  RouteTable *t = (RouteTable *)table_handle;
  std::memset(out_meta, 0, 16 * sizeof(int64_t));
  out_meta[3] = -1;
  if (p == nullptr || t == nullptr) {
    out_meta[1] = 1;  // STOP_RESIDUAL: caller falls back
    return 0;
  }
  pcu_telem *tm = p->ring->telem;
  u64 t_recv = 0;
  if (tm != nullptr) {
    // recv stamp comes from the drain wakeup that delivered the chunk;
    // a stale stamp (cold start, >100ms old) falls back to "now" so an
    // idle gap never masquerades as plan latency
    const u64 now = pcu_now_ns();
    t_recv = (p->last_recv_ns != 0 && now >= p->last_recv_ns &&
              now - p->last_recv_ns < 100000000ull)
                 ? p->last_recv_ns
                 : now;
  }
  int64_t n_pairs = 0;
  int32_t stop = 0;
  int64_t consumed = pushcdn_route_plan(
      table_handle, buf, buf_len, offs, lens, start, count, mode,
      p->pr_peer, p->pr_frame, p->pair_cap, &n_pairs, &stop, out_class);
  if (consumed < 0) {
    out_meta[1] = 1;
    return 0;
  }
  u64 t_plan = 0;
  if (tm != nullptr && consumed > 0) {
    t_plan = pcu_now_ns();
    pcu_tm_observe(tm, &tm->stage[0], t_plan > t_recv ? t_plan - t_recv : 0);
  }
  out_meta[0] = consumed;
  out_meta[1] = stop;
  out_meta[14] = n_pairs;
  if (n_pairs == 0) return consumed;

  const bool have_chunk_slot = p->n_chunk_free > 0;
  if (!have_chunk_slot) out_meta[12] = 1;
  s32 chunk_slot = -1;
  u32 refs = 0;
  long n_touched = 0;
  int64_t n_resid = 0;
  const int n_users = t->n_users;

  // partition pairs: engaged peers stage onto per-peer frame-ordered
  // lists; everything else compacts into the residual arrays in frame
  // order (pairs already arrive frame-ordered from the plan)
  for (int64_t k = 0; k < n_pairs; ++k) {
    const s32 peer = p->pr_peer[k];
    s32 id = -1;
    if (have_chunk_slot && peer >= 0 && (u32)peer < p->slot_n)
      id = p->slot_map[peer];
    PumpPeer *pp = nullptr;
    if (id >= 0 && (u32)id < p->max_peers) {
      pp = &p->peers[id];
      if (!pp->in_use || pp->dead || pp->err != 0) {
        out_meta[11]++;
        pp = nullptr;
      } else if (pp->fenced) {
        out_meta[10]++;
        pp = nullptr;
      }
    } else if (have_chunk_slot) {
      out_meta[9]++;
    }
    if (pp != nullptr && pp->stage_head < 0) {
      // first pair for this peer this call: compact the queue to offset
      // 0 and make sure it can absorb the worst case (one run per
      // consumed frame) up front, so a failed realloc cleanly demotes
      // the peer to residual before any run is appended. Moving the
      // structs is safe mid-chain: the SQEs hold copies of addr/len and
      // accounting goes through q[q_head], which moves with them.
      if (pp->q_head > 0) {
        std::memmove(pp->q, pp->q + pp->q_head,
                     sizeof(PumpRun) * pp->q_len);
        pp->q_head = 0;
      }
      const u32 need = pp->q_len + (u32)consumed;
      if (need > pp->q_cap) {
        u32 cap = pp->q_cap ? pp->q_cap : 64;
        while (cap < need) cap *= 2;
        PumpRun *grown =
            (PumpRun *)std::realloc(pp->q, sizeof(PumpRun) * cap);
        if (grown == nullptr) {
          out_meta[11]++;
          pp = nullptr;
        } else {
          pp->q = grown;
          pp->q_cap = cap;
        }
      }
      if (pp != nullptr) {
        p->touched[n_touched++] = id;
        pp->stage_head = (s32)k;
        pp->stage_tail = (s32)k;
        p->pr_next[k] = -1;
      }
    } else if (pp != nullptr) {
      p->pr_next[pp->stage_tail] = (s32)k;
      p->pr_next[k] = -1;
      pp->stage_tail = (s32)k;
    }
    if (pp == nullptr) {
      if (n_resid < resid_cap) {
        resid_peer[n_resid] = peer;
        resid_frame[n_resid] = p->pr_frame[k];
        n_resid++;
      }
      continue;
    }
    out_meta[6]++;
    if (peer < n_users) out_meta[7]++; else out_meta[8]++;
  }
  out_meta[2] = n_resid;

  // build per-peer zero-copy runs (maximal contiguous frame spans) and
  // chain-submit for peers whose previous chain is idle
  int64_t prepped = 0, n_runs = 0;
  for (long i = 0; i < n_touched; ++i) {
    const u32 id = (u32)p->touched[i];
    PumpPeer &pp = p->peers[id];
    s32 k = pp.stage_head;
    while (k >= 0) {
      const s32 first = p->pr_frame[k];
      s32 last = first;
      s32 nk = p->pr_next[k];
      while (nk >= 0 && p->pr_frame[nk] == last + 1) {
        last = p->pr_frame[nk];
        nk = p->pr_next[nk];
      }
      if (chunk_slot < 0) {
        chunk_slot = p->chunk_free[--p->n_chunk_free];
        p->chunks[chunk_slot].in_use = true;
        p->chunks[chunk_slot].refs = 0;
        out_meta[3] = chunk_slot;
      }
      const int64_t a = offs[first] - 4;
      const int64_t b = offs[last] + lens[last];
      PumpRun &r = pp.q[pp.q_head + pp.q_len];
      r.addr = (unsigned long long)(uintptr_t)buf + (unsigned long long)a;
      r.len = (u32)(b - a);
      r.sent = 0;
      r.chunk_slot = chunk_slot;
      // the queue comes from realloc: always reset the telemetry fields
      // so a run queued while telemetry is off can't replay stale stamps
      // after a later enable
      r.t_recv = t_recv;
      r.t_ready = t_plan;
      r.t_submit = 0;
      for (int c = 0; c < PCU_TM_CLASSES; ++c) {
        r.cls_frames[c] = 0;
        r.cls_bytes[c] = 0;
      }
      if (out_class != nullptr) {
        for (s32 f = first; f <= last; ++f) {
          const int c = out_class[f] & (PCU_TM_CLASSES - 1);
          r.cls_frames[c]++;
          r.cls_bytes[c] += (u32)(lens[f] + 4);
        }
      }
      pp.q_len++;
      p->chunks[chunk_slot].refs++;
      refs++;
      n_runs++;
      p->st_bytes += (u64)(b - a);
      k = nk;
    }
    pp.stage_head = pp.stage_tail = -1;
    prepped += prep_chain(p, id);
  }
  p->st_runs += (u64)n_runs;
  p->st_frames += (u64)out_meta[6];
  out_meta[4] = refs;
  out_meta[5] = prepped;
  out_meta[13] = n_runs;
  return consumed;
}

// Drain the CQ: pump-tagged CQEs are accounted natively; the rest are
// compacted into (uds, ress, flagss) for the Python engine. Appends flat
// (type, a, b) event triples to `events`. Returns the count of non-pump
// CQEs; *n_prepped reports SQEs prepped by the post-drain chain sweep
// (the caller must schedule a submit when > 0). *n_events is the int64
// count written (triples * 3).
int pushcdn_pump_drain(void *handle, unsigned long long *uds, int *ress,
                       unsigned *flagss, int max, long long *events,
                       long ev_cap, long *n_events, long *n_prepped) {
  Pump *p = (Pump *)handle;
  *n_events = 0;
  *n_prepped = 0;
  if (p == nullptr) return 0;
  EvBuf eb{events, ev_cap, 0};
  pcu_ring *r = p->ring;
  if (r->telem != nullptr) p->last_recv_ns = pcu_now_ns();
  u32 head = *r->cq_khead;
  const u32 tail = LOAD_ACQ(r->cq_ktail);
  int n_out = 0;
  while (head != tail && n_out < max) {
    io_uring_cqe *cqe = &r->cqes[head & r->cq_mask];
    if (cqe->user_data & PUMP_UD_TAG) {
      pump_on_cqe(p, (u32)(cqe->user_data & 0xffffffffu), cqe->res, &eb);
    } else {
      uds[n_out] = cqe->user_data;
      ress[n_out] = cqe->res;
      flagss[n_out] = cqe->flags;
      n_out++;
    }
    head++;
  }
  STORE_REL(r->cq_khead, head);
  // chain sweep: any engaged peer with queued runs and an idle chain
  // (SQ was full at route_chunk time, a short-tail re-pump, ECANCELED
  // requeues) gets its next chain prepped now
  long prepped = 0;
  for (u32 i = 0; i < p->max_peers; ++i) {
    PumpPeer &pp = p->peers[i];
    if (pp.in_use && pp.err == 0 && !pp.dead && pp.inflight == 0 &&
        pp.q_len > 0)
      prepped += prep_chain(p, i);
  }
  *n_prepped = prepped;
  *n_events = eb.n;
  return n_out;
}

// Test hook: feed one synthetic completion through the pump's CQE
// accounting (the C twin of tests driving UringStream._on_send_cqe
// directly) — deterministic short-write / reset / mid-chain fault
// injection without a cooperating kernel.
int pushcdn_pump_inject_cqe(void *handle, int id, int res,
                            long long *events, long ev_cap,
                            long *n_events) {
  Pump *p = (Pump *)handle;
  *n_events = 0;
  if (p == nullptr || id < 0 || (u32)id >= p->max_peers) return -1;
  EvBuf eb{events, ev_cap, 0};
  pump_on_cqe(p, (u32)id, res, &eb);
  *n_events = eb.n;
  return 0;
}

void pushcdn_pump_stats(void *handle, unsigned long long *out) {
  // out[16]: runs, chains, sqes, cqes, bytes, frames, errors,
  //          short_repump, engaged, fenced, chunk_slots_free,
  //          queued_runs, ev_lost (rest reserved)
  Pump *p = (Pump *)handle;
  std::memset(out, 0, 16 * sizeof(unsigned long long));
  if (p == nullptr) return;
  out[0] = p->st_runs;
  out[1] = p->st_chains;
  out[2] = p->st_sqes;
  out[3] = p->st_cqes;
  out[4] = p->st_bytes;
  out[5] = p->st_frames;
  out[6] = p->st_errors;
  out[7] = p->st_short_repump;
  u64 engaged = 0, fenced = 0, queued = 0;
  for (u32 i = 0; i < p->max_peers; ++i) {
    PumpPeer &pp = p->peers[i];
    if (pp.in_use) {
      engaged++;
      if (pp.fenced) fenced++;
      queued += pp.q_len;
    }
  }
  out[8] = engaged;
  out[9] = fenced;
  out[10] = p->n_chunk_free;
  out[11] = queued;
  out[12] = p->st_ev_lost;
}

}  // extern "C"
