// Batch route-plan kernel: the broker data-plane's cut-through core.
//
// PR 1 committed the finding that the broker's forwarding floor is
// per-message Python, not the wire: transports deliver whole FrameChunk
// batches and egress is vectorized, but the receive loops still peeled one
// frame at a time (deserialize -> hook -> route_*), materializing a Python
// message object per frame. This translation unit removes that: ONE call
// scans a chunk's frame headers in place (kind tag, topic words, dest key,
// length/offset), matches Broadcast topic bitmasks against a snapshot of
// the broker's interest table and Direct dest keys against a DirectMap
// hash snapshot, and returns a flat (peer, frame) fan-out pair list. The
// caller groups pairs per peer (stable sort keeps per-(sender->receiver)
// frame order identical to the scalar path) and hands the chunk's byte
// ranges straight to egress — payload bytes never become Python objects.
//
// Control frames (Subscribe/Sync/auth/malformed) STOP the plan at their
// index: the scalar path applies them (they mutate routing state, which
// invalidates this snapshot), then planning resumes. This is what keeps
// batch-vs-scalar semantics identical for mixes like
// [Subscribe(t), Broadcast(t)] arriving in one chunk.
//
// ISSUE 7 (million-user control plane): the table is INCREMENTALLY
// maintainable. pushcdn_route_table_apply takes a batch of typed deltas —
// absolute per-peer interest masks plus DirectMap upserts/removes — and
// applies them in place, O(delta) not O(users):
//
//   - per-peer masks are STORED, so an interest update diffs old vs new
//     and touches only the changed topics' lists;
//   - the inverted index is 256 per-topic dynamic arrays with LAZY
//     deletion: an unsubscribe just clears the stored mask bit (O(1));
//     plan() skips entries whose mask bit is gone, and the stamp dedupe
//     already tolerates the duplicate entries a re-subscribe appends.
//     Garbage is bounded by the caller's compaction policy (a full
//     rebuild when list_entries outgrows live_subs — see
//     pushcdn_route_table_stats);
//   - the DirectMap hash supports tombstoned removal and in-place
//     upsert, rehashing itself when load gets high; key bytes append to
//     a growable blob whose garbage is likewise compacted by rebuild.
//
// Peer indices are SLOTS: the caller manages a free-list so a connected
// peer keeps its index for its lifetime; n_users/n_brokers passed to
// build() are slot CAPACITIES (dead slots have zero masks and no dmap
// entries, so they can never be planned).
//
// Same discipline as the reference's "deserialize once per hop, forward
// raw bytes" rule (cdn-broker handler.rs hot path); plain C ABI for
// ctypes like framing.cpp (no pybind11 in the image).

#include <cstdint>
#include <cstdlib>
#include <cstring>
#include <new>

namespace {

constexpr uint8_t KIND_DIRECT = 4;
constexpr uint8_t KIND_BROADCAST = 5;
// Kind-tag high bit: "16-byte trace block follows" (proto/message.py
// TRACE_FLAG). Traced frames take the instrumented scalar path so span
// emission lives OFF the batch plan — the plan stops at them exactly like
// it stops at control frames, and the rest of the chunk stays batched.
constexpr uint8_t KIND_TRACE_FLAG = 0x80;

constexpr int MASK_WORDS = 4;  // 4 x u64 = the full u8 topic space
constexpr int N_TOPICS = 256;

struct DirectSlot {
  uint64_t hash;     // 0 = never used (hash is forced non-zero)
  int64_t key_off;   // into keys blob
  int32_t key_len;   // -1 = tombstone (probing continues past it)
  int32_t peer;      // user peer slot, or >= n_users for a broker slot
};

struct RouteTable {
  int32_t n_users = 0;    // user slot CAPACITY
  int32_t n_brokers = 0;  // broker slot capacity
  uint64_t valid_mask[MASK_WORDS] = {0, 0, 0, 0};

  // stored per-peer interest masks — the diff base for incremental apply
  // and the liveness test for lazily-deleted index entries
  uint64_t* peer_masks = nullptr;  // [n_peers * MASK_WORDS]

  // inverted interest index: topic t -> dynamic array of peer slots
  // (users and brokers in one space: users [0, n_users), brokers
  // [n_users, n_users + n_brokers)). Entries may be stale (mask bit
  // cleared) or duplicated (re-subscribe after lazy delete) — plan()
  // filters on the stored mask and dedupes per frame via stamps.
  int32_t* topic_list[N_TOPICS] = {};
  int32_t topic_len[N_TOPICS] = {};
  int32_t topic_cap[N_TOPICS] = {};
  int64_t live_subs = 0;     // popcount over peer_masks (valid topics only)
  int64_t list_entries = 0;  // sum of topic_len (live + garbage + dups)

  // DirectMap snapshot: open-addressed hash of recipient key -> peer,
  // with tombstoned removal for in-place maintenance
  DirectSlot* dmap = nullptr;
  uint64_t dmap_mask = 0;  // table size - 1 (power of two)
  int64_t dmap_live = 0;
  int64_t dmap_tombstones = 0;
  uint8_t* keys_blob = nullptr;
  int64_t keys_blob_len = 0;  // bytes used
  int64_t keys_blob_cap = 0;
  int64_t blob_garbage = 0;   // bytes owned by removed/stale entries

  // per-frame dedupe stamps for broadcast fan-out (u64: a u32 would wrap
  // within hours at sustained multi-M frames/s on a stable deployment
  // that never rebuilds, and a wrapped stamp silently skips a peer)
  uint64_t* stamp = nullptr;
  uint64_t stamp_cur = 0;

  // topic byte -> flow class (0=control 1=consensus 2=live 3=bulk) for
  // per-class accounting (ISSUE 19). Survives rebuild/apply: the taxonomy
  // is deployment config, not routing state. Defaults to live.
  uint8_t topic_class[N_TOPICS];
};

uint64_t fnv1a(const uint8_t* data, int32_t len) {
  uint64_t h = 1469598103934665603ull;
  for (int32_t i = 0; i < len; ++i) {
    h ^= data[i];
    h *= 1099511628211ull;
  }
  return h ? h : 1ull;  // 0 is the empty-slot marker
}

void free_table_storage(RouteTable* t) {
  std::free(t->peer_masks);
  for (int i = 0; i < N_TOPICS; ++i) {
    std::free(t->topic_list[i]);
    t->topic_list[i] = nullptr;
    t->topic_len[i] = 0;
    t->topic_cap[i] = 0;
  }
  std::free(t->dmap);
  std::free(t->keys_blob);
  std::free(t->stamp);
  t->peer_masks = nullptr;
  t->dmap = nullptr;
  t->keys_blob = nullptr;
  t->stamp = nullptr;
  t->live_subs = t->list_entries = 0;
  t->dmap_live = t->dmap_tombstones = 0;
  t->keys_blob_len = t->keys_blob_cap = t->blob_garbage = 0;
}

bool topic_push(RouteTable* t, int tt, int32_t peer) {
  if (t->topic_len[tt] == t->topic_cap[tt]) {
    int32_t cap = t->topic_cap[tt] ? t->topic_cap[tt] * 2 : 8;
    int32_t* grown =
        (int32_t*)std::realloc(t->topic_list[tt], cap * sizeof(int32_t));
    if (grown == nullptr) return false;
    t->topic_list[tt] = grown;
    t->topic_cap[tt] = cap;
  }
  t->topic_list[tt][t->topic_len[tt]++] = peer;
  ++t->list_entries;
  return true;
}

// find the slot holding `key` (or ~first-insertable-slot if absent).
int64_t dmap_find(const RouteTable* t, const uint8_t* key, int32_t klen,
                  uint64_t h) {
  uint64_t slot = h & t->dmap_mask;
  int64_t first_free = -1;
  while (true) {
    const DirectSlot& s = t->dmap[slot];
    if (s.hash == 0) {
      return ~(first_free >= 0 ? first_free : (int64_t)slot);
    }
    if (s.key_len < 0) {  // tombstone: insertable, keep probing
      if (first_free < 0) first_free = (int64_t)slot;
    } else if (s.hash == h && s.key_len == klen &&
               std::memcmp(t->keys_blob + s.key_off, key, (size_t)klen)
                   == 0) {
      return (int64_t)slot;
    }
    slot = (slot + 1) & t->dmap_mask;
  }
}

bool dmap_rehash(RouteTable* t, uint64_t new_cap) {
  DirectSlot* fresh = (DirectSlot*)std::calloc(new_cap, sizeof(DirectSlot));
  if (fresh == nullptr) return false;
  DirectSlot* old = t->dmap;
  const uint64_t old_cap = t->dmap_mask + 1;
  const uint64_t mask = new_cap - 1;
  for (uint64_t i = 0; i < old_cap; ++i) {
    const DirectSlot& s = old[i];
    if (s.hash == 0 || s.key_len < 0) continue;
    uint64_t slot = s.hash & mask;
    while (fresh[slot].hash != 0) slot = (slot + 1) & mask;
    fresh[slot] = s;
  }
  std::free(old);
  t->dmap = fresh;
  t->dmap_mask = mask;
  t->dmap_tombstones = 0;
  return true;
}

bool blob_append(RouteTable* t, const uint8_t* key, int32_t klen,
                 int64_t* off_out) {
  if (t->keys_blob_len + klen > t->keys_blob_cap) {
    int64_t cap = t->keys_blob_cap ? t->keys_blob_cap : 256;
    while (cap < t->keys_blob_len + klen) cap *= 2;
    uint8_t* grown = (uint8_t*)std::realloc(t->keys_blob, (size_t)cap);
    if (grown == nullptr) return false;
    t->keys_blob = grown;
    t->keys_blob_cap = cap;
  }
  *off_out = t->keys_blob_len;
  std::memcpy(t->keys_blob + t->keys_blob_len, key, (size_t)klen);
  t->keys_blob_len += klen;
  return true;
}

}  // namespace

extern "C" {

void* pushcdn_route_table_create() {
  RouteTable* t = new (std::nothrow) RouteTable();
  if (t != nullptr) std::memset(t->topic_class, 2, N_TOPICS);  // live
  return t;
}

// Replace the topic -> flow-class map (256 bytes, values 0..3; higher
// bits are masked off at plan time). Returns 0, or -1 on a bad handle.
int32_t pushcdn_route_table_set_classes(void* handle,
                                        const uint8_t* classes) {
  RouteTable* t = (RouteTable*)handle;
  if (t == nullptr || classes == nullptr) return -1;
  std::memcpy(t->topic_class, classes, N_TOPICS);
  return 0;
}

void pushcdn_route_table_destroy(void* handle) {
  RouteTable* t = (RouteTable*)handle;
  if (t == nullptr) return;
  free_table_storage(t);
  delete t;
}

// (Re)build the routing snapshot from scratch (first build, version-gap /
// delta-overflow fallback, and COMPACTION — a rebuild purges the lazy
// deletions, duplicate index entries, dmap tombstones, and blob garbage
// the incremental path accrues).
//   n_users / n_brokers: slot CAPACITIES (free slots carry zero masks)
//   peer_masks:  [ (n_users + n_brokers) * 4 ] u64 interest bitmasks
//   valid_mask:  [4] u64 — the deployment's valid-topic set
//   dkeys_blob / dkey_offs / dkey_lens / dkey_owner: DirectMap entries
//     whose owner resolves to a CONNECTED peer (local user -> that user's
//     peer slot; remote owner -> its broker peer slot). Unresolvable
//     owners are omitted by the caller — a plan miss is a drop, exactly
//     like the scalar flush finding no connection.
// Returns 0 on success, -1 on allocation failure (table left empty; the
// caller must fall back to the scalar path).
int32_t pushcdn_route_table_build(
    void* handle, int32_t n_users, int32_t n_brokers,
    const uint64_t* valid_mask, const uint64_t* peer_masks,
    const uint8_t* dkeys_blob, const int64_t* dkey_offs,
    const int32_t* dkey_lens, const int32_t* dkey_owner, int32_t n_dkeys) {
  RouteTable* t = (RouteTable*)handle;
  if (t == nullptr || n_users < 0 || n_brokers < 0 || n_dkeys < 0) return -1;
  free_table_storage(t);
  t->n_users = n_users;
  t->n_brokers = n_brokers;
  t->stamp_cur = 0;
  std::memcpy(t->valid_mask, valid_mask, sizeof(t->valid_mask));
  const int64_t n_peers = (int64_t)n_users + n_brokers;

  // stored masks (the incremental-apply diff base)
  const int64_t mask_words = (n_peers ? n_peers : 1) * MASK_WORDS;
  t->peer_masks = (uint64_t*)std::malloc(mask_words * sizeof(uint64_t));
  if (t->peer_masks == nullptr) return -1;
  std::memcpy(t->peer_masks, peer_masks,
              (size_t)n_peers * MASK_WORDS * sizeof(uint64_t));

  // inverted index: count pass, then exact-size per-topic arrays
  int32_t counts[N_TOPICS] = {};
  int64_t total = 0;
  for (int64_t p = 0; p < n_peers; ++p) {
    const uint64_t* m = peer_masks + p * MASK_WORDS;
    for (int w = 0; w < MASK_WORDS; ++w)
      for (uint64_t bits = m[w]; bits; bits &= bits - 1) {
        ++counts[w * 64 + __builtin_ctzll(bits)];
        ++total;
      }
  }
  for (int tt = 0; tt < N_TOPICS; ++tt) {
    if (counts[tt] == 0) continue;
    t->topic_list[tt] = (int32_t*)std::malloc(counts[tt] * sizeof(int32_t));
    if (t->topic_list[tt] == nullptr) { free_table_storage(t); return -1; }
    t->topic_cap[tt] = counts[tt];
  }
  for (int64_t p = 0; p < n_peers; ++p) {
    const uint64_t* m = peer_masks + p * MASK_WORDS;
    for (int w = 0; w < MASK_WORDS; ++w)
      for (uint64_t bits = m[w]; bits; bits &= bits - 1) {
        const int tt = w * 64 + __builtin_ctzll(bits);
        t->topic_list[tt][t->topic_len[tt]++] = (int32_t)p;
      }
  }
  t->live_subs = total;
  t->list_entries = total;

  // direct-map hash (open addressing, power-of-two, 2x load headroom)
  uint64_t cap = 16;
  while (cap < (uint64_t)n_dkeys * 2 + 1) cap <<= 1;
  t->dmap = (DirectSlot*)std::calloc(cap, sizeof(DirectSlot));
  if (t->dmap == nullptr) { free_table_storage(t); return -1; }
  t->dmap_mask = cap - 1;
  int64_t blob_len = 0;
  for (int32_t i = 0; i < n_dkeys; ++i) blob_len += dkey_lens[i];
  t->keys_blob_cap = blob_len ? blob_len : 256;
  t->keys_blob = (uint8_t*)std::malloc((size_t)t->keys_blob_cap);
  if (t->keys_blob == nullptr) { free_table_storage(t); return -1; }
  for (int32_t i = 0; i < n_dkeys; ++i) {
    const uint8_t* key = dkeys_blob + dkey_offs[i];
    const int32_t klen = dkey_lens[i];
    const uint64_t h = fnv1a(key, klen);
    int64_t slot = dmap_find(t, key, klen, h);
    if (slot >= 0) {
      // duplicate key: last entry wins (caller emits each once); the
      // earlier copy's blob bytes become garbage
      t->blob_garbage += t->dmap[slot].key_len;
      int64_t off;
      if (!blob_append(t, key, klen, &off)) {
        free_table_storage(t);
        return -1;
      }
      t->dmap[slot].key_off = off;
      t->dmap[slot].key_len = klen;
      t->dmap[slot].peer = dkey_owner[i];
      continue;
    }
    slot = ~slot;
    int64_t off;
    if (!blob_append(t, key, klen, &off)) {
      free_table_storage(t);
      return -1;
    }
    DirectSlot& s = t->dmap[slot];
    s.hash = h;
    s.key_off = off;
    s.key_len = klen;
    s.peer = dkey_owner[i];
    ++t->dmap_live;
  }

  t->stamp = (uint64_t*)std::calloc(n_peers ? n_peers : 1, sizeof(uint64_t));
  if (t->stamp == nullptr) { free_table_storage(t); return -1; }
  return 0;
}

// Apply one batch of typed deltas IN PLACE (ISSUE 7) — O(delta), never
// O(users):
//   upd_peer[i] / upd_masks[i*4..]: peer slot i's NEW absolute interest
//     mask (diffed against the stored mask; a freed slot passes zeros)
//   dkeys_* / dkey_owner: DirectMap upserts; owner == -1 removes the key
//     (tombstone), owner >= 0 sets/overwrites it
// Returns 0 on success, -1 on allocation failure or out-of-range peer
// (the caller must fall back to a full rebuild; the table stays usable
// in the sense that no partial write corrupts invariants — a half-applied
// batch is superseded by the rebuild anyway).
int32_t pushcdn_route_table_apply(
    void* handle, const int32_t* upd_peer, const uint64_t* upd_masks,
    int32_t n_upd, const uint8_t* dkeys_blob, const int64_t* dkey_offs,
    const int32_t* dkey_lens, const int32_t* dkey_owner, int32_t n_dkeys) {
  RouteTable* t = (RouteTable*)handle;
  if (t == nullptr || t->peer_masks == nullptr || n_upd < 0 || n_dkeys < 0)
    return -1;
  const int64_t n_peers = (int64_t)t->n_users + t->n_brokers;

  for (int32_t i = 0; i < n_upd; ++i) {
    const int64_t peer = upd_peer[i];
    if (peer < 0 || peer >= n_peers) return -1;
    uint64_t* stored = t->peer_masks + peer * MASK_WORDS;
    const uint64_t* fresh = upd_masks + (int64_t)i * MASK_WORDS;
    for (int w = 0; w < MASK_WORDS; ++w) {
      const uint64_t nw = fresh[w] & t->valid_mask[w];
      const uint64_t ow = stored[w];
      if (nw == ow) continue;
      for (uint64_t bits = nw & ~ow; bits; bits &= bits - 1) {
        // newly subscribed: append (a stale duplicate may already sit in
        // the list — the stamp dedupe makes that harmless)
        if (!topic_push(t, w * 64 + __builtin_ctzll(bits), (int32_t)peer))
          return -1;
        ++t->live_subs;
      }
      for (uint64_t bits = ow & ~nw; bits; bits &= bits - 1) {
        // lazy delete: the cleared mask bit is the deletion; the list
        // entry becomes garbage the next compaction rebuild purges
        --t->live_subs;
        (void)bits;
      }
      stored[w] = nw;
    }
  }

  for (int32_t i = 0; i < n_dkeys; ++i) {
    const uint8_t* key = dkeys_blob + dkey_offs[i];
    const int32_t klen = dkey_lens[i];
    const int32_t owner = dkey_owner[i];
    const uint64_t h = fnv1a(key, klen);
    int64_t slot = dmap_find(t, key, klen, h);
    if (owner < 0) {
      if (slot >= 0) {
        t->blob_garbage += t->dmap[slot].key_len;
        t->dmap[slot].key_len = -1;  // tombstone (hash stays for probing)
        --t->dmap_live;
        ++t->dmap_tombstones;
      }
      continue;
    }
    if (slot >= 0) {
      if (owner >= n_peers) return -1;
      t->dmap[slot].peer = owner;
      continue;
    }
    if (owner >= n_peers) return -1;
    // insert: keep load (live + tombstones) under half the table; a
    // rehash also purges tombstones
    const uint64_t cap = t->dmap_mask + 1;
    if ((uint64_t)(t->dmap_live + t->dmap_tombstones + 1) * 2 > cap) {
      uint64_t want = cap;
      while ((uint64_t)(t->dmap_live + 1) * 2 > want) want <<= 1;
      if (!dmap_rehash(t, want)) return -1;
      slot = dmap_find(t, key, klen, h);
      if (slot >= 0) return -1;  // can't happen: key was absent
    }
    slot = ~slot;
    int64_t off;
    if (!blob_append(t, key, klen, &off)) return -1;
    DirectSlot& s = t->dmap[slot];
    if (s.hash != 0) --t->dmap_tombstones;  // reusing a tombstoned slot
    s.hash = h;
    s.key_off = off;
    s.key_len = klen;
    s.peer = owner;
    ++t->dmap_live;
  }
  return 0;
}

// Occupancy/garbage counters for the caller's compaction policy:
// out[0..7] = {n_users, n_brokers, live_subs, list_entries, dmap_live,
//              dmap_tombstones, keys_blob_len, blob_garbage}.
void pushcdn_route_table_stats(void* handle, int64_t* out) {
  RouteTable* t = (RouteTable*)handle;
  if (t == nullptr) {
    std::memset(out, 0, 8 * sizeof(int64_t));
    return;
  }
  out[0] = t->n_users;
  out[1] = t->n_brokers;
  out[2] = t->live_subs;
  out[3] = t->list_entries;
  out[4] = t->dmap_live;
  out[5] = t->dmap_tombstones;
  out[6] = t->keys_blob_len;
  out[7] = t->blob_garbage;
}

// Plan frames [start, start+count) of one chunk.
//   mode 0: user-origin  (Direct forwards anywhere; Broadcast reaches
//           interested users AND brokers) — handler.rs user path
//   mode 1: broker-origin (Direct to OUR user only; Broadcast to local
//           users only — loop prevention) — handler.rs broker path
// Emits (peer, frame-index) pairs in frame order. Stops at the first
// frame that is not a well-formed Direct/Broadcast (*stop_reason = 1:
// the scalar path owns it) or when the pair buffer cannot be guaranteed
// to hold the next frame's worst-case fan-out (*stop_reason = 2: call
// again from the returned index). *stop_reason = 0 means the whole range
// was planned. Returns the number of frames consumed, or -1 on bad args.
//
// out_class (nullable): per-frame flow class, indexed by ABSOLUTE frame
// index — Broadcast takes the class of its FIRST topic byte, Direct is
// live, and 255 marks a consumed frame that reached no one (pruned-empty
// broadcast / unknown-recipient drop), excluded from ingress accounting.
// Only indices [start, start+consumed) are meaningful.
int64_t pushcdn_route_plan(
    void* handle, const uint8_t* buf, int64_t buf_len,
    const int64_t* offs, const int64_t* lens, int64_t start, int64_t count,
    int32_t mode, int32_t* out_peer, int32_t* out_frame, int64_t pair_cap,
    int64_t* n_pairs, int32_t* stop_reason, uint8_t* out_class) {
  RouteTable* t = (RouteTable*)handle;
  *n_pairs = 0;
  *stop_reason = 0;
  if (t == nullptr || t->peer_masks == nullptr || start < 0 || count < 0)
    return -1;
  int64_t pairs = 0;
  int64_t i = start;
  const int64_t end = start + count;
  for (; i < end; ++i) {
    const int64_t o = offs[i];
    const int64_t n = lens[i];
    if (o < 0 || n < 1 || o + n > buf_len) { *stop_reason = 1; break; }
    // Capacity is enforced EXACTLY, per emitted pair, with a rollback of
    // the current frame on overflow — the previous conservative guard
    // (reserve worst-case n_peers pairs per frame) collapsed batching to
    // one frame per plan call as soon as the peer table outgrew the pair
    // buffer (8K+ users), which is precisely the regime ISSUE 7 targets.
    // The caller keeps pair_cap >= n_peers + 1, so a lone frame always
    // fits and STOP_CAPACITY can always make progress on retry.
    // (Stamps touched by a rolled-back frame are harmless: the retry
    // plans it under a fresh stamp value.)
    const int64_t frame_pairs = pairs;
    const uint8_t kind = buf[o];
    if (kind & KIND_TRACE_FLAG) { *stop_reason = 1; break; }  // traced: scalar
    if (kind == KIND_BROADCAST && n >= 3) {
      const int64_t nt = (int64_t)buf[o + 1] | ((int64_t)buf[o + 2] << 8);
      if (3 + nt > n) { *stop_reason = 1; break; }  // malformed: scalar
      uint64_t mask[MASK_WORDS] = {0, 0, 0, 0};
      for (int64_t k = 0; k < nt; ++k) {
        const uint8_t topic = buf[o + 3 + k];
        mask[topic >> 6] |= 1ull << (topic & 63);
      }
      bool any = false;
      for (int w = 0; w < MASK_WORDS; ++w) {
        mask[w] &= t->valid_mask[w];
        any |= mask[w] != 0;
      }
      if (out_class != nullptr)
        out_class[i] = any ? (uint8_t)(t->topic_class[buf[o + 3]] & 3)
                           : (uint8_t)255;
      if (!any) continue;  // pruned empty: drop (scalar parity)
      const uint64_t st = ++t->stamp_cur;
      bool overflow = false;
      for (int w = 0; w < MASK_WORDS && !overflow; ++w)
        for (uint64_t bits = mask[w]; bits && !overflow; bits &= bits - 1) {
          const int tt = w * 64 + __builtin_ctzll(bits);
          const int32_t hi = t->topic_len[tt];
          const int32_t* lst = t->topic_list[tt];
          for (int32_t k = 0; k < hi; ++k) {
            const int32_t peer = lst[k];
            // lazy-deletion filter: the stored mask is the truth — an
            // unsubscribed (or freed-slot) entry is garbage awaiting
            // compaction
            if (!(t->peer_masks[(int64_t)peer * MASK_WORDS + w] >> (tt & 63)
                  & 1ull))
              continue;
            if (mode == 1 && peer >= t->n_users) continue;  // users only
            if (t->stamp[peer] == st) continue;  // already gets this frame
            if (pairs == pair_cap) { overflow = true; break; }
            t->stamp[peer] = st;
            out_peer[pairs] = peer;
            out_frame[pairs] = (int32_t)i;
            ++pairs;
          }
        }
      if (overflow) {
        pairs = frame_pairs;  // roll this frame back; retry next call
        *stop_reason = 2;
        break;
      }
    } else if (kind == KIND_DIRECT && n >= 5) {
      const int64_t rlen = (int64_t)buf[o + 1] | ((int64_t)buf[o + 2] << 8) |
                           ((int64_t)buf[o + 3] << 16) |
                           ((int64_t)buf[o + 4] << 24);
      if (5 + rlen > n) { *stop_reason = 1; break; }  // malformed: scalar
      const uint8_t* key = buf + o + 5;
      const int64_t slot = dmap_find(t, key, (int32_t)rlen,
                                     fnv1a(key, (int32_t)rlen));
      if (out_class != nullptr)
        out_class[i] = slot < 0 ? (uint8_t)255 : (uint8_t)2;  // Direct: live
      if (slot < 0) continue;  // unknown recipient: drop
      const int32_t peer = t->dmap[slot].peer;
      if (mode == 1 && peer >= t->n_users) {
        // Broker-origin direct whose DirectMap owner is another broker:
        // the one-hop rule forbids re-forwarding, but the frame may
        // still be deliverable over a local `parting` connection (a
        // migration eviction raced the sender's stale DirectMap
        // replica). Rare by construction — hand it to the scalar path
        // (which chases parting) instead of silently dropping.
        *stop_reason = 1;
        break;
      }
      if (pairs == pair_cap) { *stop_reason = 2; break; }
      out_peer[pairs] = peer;
      out_frame[pairs] = (int32_t)i;
      ++pairs;
    } else {
      // control kind, short frame, or unknown tag: the scalar path owns
      // this frame (and everything after it until the caller re-plans)
      *stop_reason = 1;
      break;
    }
  }
  *n_pairs = pairs;
  return i - start;
}

// Gather a peer's fan-out into one wire-ready buffer: for each listed
// frame, write [u32 BE length][payload] — byte-identical to the transport
// framing the chunk arrived with. Returns bytes written, or -1 when `out`
// is too small / an index is out of range.
int64_t pushcdn_route_gather(
    const uint8_t* buf, int64_t buf_len, const int64_t* offs,
    const int64_t* lens, const int32_t* frame_idx, int64_t n_idx,
    uint8_t* out, int64_t out_cap) {
  int64_t pos = 0;
  for (int64_t k = 0; k < n_idx; ++k) {
    const int64_t i = frame_idx[k];
    const int64_t o = offs[i];
    const int64_t n = lens[i];
    if (o < 0 || n < 0 || o + n > buf_len || pos + 4 + n > out_cap) return -1;
    out[pos] = (uint8_t)(n >> 24);
    out[pos + 1] = (uint8_t)(n >> 16);
    out[pos + 2] = (uint8_t)(n >> 8);
    out[pos + 3] = (uint8_t)n;
    std::memcpy(out + pos + 4, buf + o, (size_t)n);
    pos += 4 + n;
  }
  return pos;
}

}  // extern "C"
