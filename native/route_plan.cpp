// Batch route-plan kernel: the broker data-plane's cut-through core.
//
// PR 1 committed the finding that the broker's forwarding floor is
// per-message Python, not the wire: transports deliver whole FrameChunk
// batches and egress is vectorized, but the receive loops still peeled one
// frame at a time (deserialize -> hook -> route_*), materializing a Python
// message object per frame. This translation unit removes that: ONE call
// scans a chunk's frame headers in place (kind tag, topic words, dest key,
// length/offset), matches Broadcast topic bitmasks against a snapshot of
// the broker's interest table and Direct dest keys against a DirectMap
// hash snapshot, and returns a flat (peer, frame) fan-out pair list. The
// caller groups pairs per peer (stable sort keeps per-(sender->receiver)
// frame order identical to the scalar path) and hands the chunk's byte
// ranges straight to egress — payload bytes never become Python objects.
//
// Control frames (Subscribe/Sync/auth/malformed) STOP the plan at their
// index: the scalar path applies them (they mutate routing state, which
// invalidates this snapshot), then planning resumes. This is what keeps
// batch-vs-scalar semantics identical for mixes like
// [Subscribe(t), Broadcast(t)] arriving in one chunk.
//
// Same discipline as the reference's "deserialize once per hop, forward
// raw bytes" rule (cdn-broker handler.rs hot path); plain C ABI for
// ctypes like framing.cpp (no pybind11 in the image).

#include <cstdint>
#include <cstdlib>
#include <cstring>
#include <new>

namespace {

constexpr uint8_t KIND_DIRECT = 4;
constexpr uint8_t KIND_BROADCAST = 5;
// Kind-tag high bit: "16-byte trace block follows" (proto/message.py
// TRACE_FLAG). Traced frames take the instrumented scalar path so span
// emission lives OFF the batch plan — the plan stops at them exactly like
// it stops at control frames, and the rest of the chunk stays batched.
constexpr uint8_t KIND_TRACE_FLAG = 0x80;

constexpr int MASK_WORDS = 4;  // 4 x u64 = the full u8 topic space

struct DirectSlot {
  uint64_t hash;     // 0 = empty (hash is forced non-zero)
  int64_t key_off;   // into keys blob
  int32_t key_len;
  int32_t peer;      // user peer index, or >= n_users for a broker peer
};

struct RouteTable {
  int32_t n_users = 0;
  int32_t n_brokers = 0;
  uint64_t valid_mask[MASK_WORDS] = {0, 0, 0, 0};

  // inverted interest index: topic t -> peer indices subscribed to t
  // (users and brokers in one space: users [0, n_users), brokers
  // [n_users, n_users + n_brokers))
  int32_t* topic_offsets = nullptr;  // [257] CSR starts
  int32_t* topic_peers = nullptr;    // flattened peer lists

  // DirectMap snapshot: open-addressed hash of recipient key -> peer
  DirectSlot* dmap = nullptr;
  uint64_t dmap_mask = 0;  // table size - 1 (power of two)
  uint8_t* keys_blob = nullptr;
  int64_t keys_blob_len = 0;

  // per-frame dedupe stamps for broadcast fan-out (u64: a u32 would wrap
  // within hours at sustained multi-M frames/s on a stable deployment
  // that never rebuilds, and a wrapped stamp silently skips a peer)
  uint64_t* stamp = nullptr;
  uint64_t stamp_cur = 0;
};

uint64_t fnv1a(const uint8_t* data, int32_t len) {
  uint64_t h = 1469598103934665603ull;
  for (int32_t i = 0; i < len; ++i) {
    h ^= data[i];
    h *= 1099511628211ull;
  }
  return h ? h : 1ull;  // 0 is the empty-slot marker
}

void free_table_storage(RouteTable* t) {
  std::free(t->topic_offsets);
  std::free(t->topic_peers);
  std::free(t->dmap);
  std::free(t->keys_blob);
  std::free(t->stamp);
  t->topic_offsets = nullptr;
  t->topic_peers = nullptr;
  t->dmap = nullptr;
  t->keys_blob = nullptr;
  t->stamp = nullptr;
}

}  // namespace

extern "C" {

void* pushcdn_route_table_create() {
  return new (std::nothrow) RouteTable();
}

void pushcdn_route_table_destroy(void* handle) {
  RouteTable* t = (RouteTable*)handle;
  if (t == nullptr) return;
  free_table_storage(t);
  delete t;
}

// (Re)build the routing snapshot.
//   peer_masks:  [ (n_users + n_brokers) * 4 ] u64 interest bitmasks
//   valid_mask:  [4] u64 — the deployment's valid-topic set
//   dkeys_blob / dkey_offs / dkey_lens / dkey_owner: DirectMap entries
//     whose owner resolves to a CONNECTED peer (local user -> that user's
//     peer index; remote owner -> its broker peer index). Unresolvable
//     owners are omitted by the caller — a plan miss is a drop, exactly
//     like the scalar flush finding no connection.
// Returns 0 on success, -1 on allocation failure (table left empty; the
// caller must fall back to the scalar path).
int32_t pushcdn_route_table_build(
    void* handle, int32_t n_users, int32_t n_brokers,
    const uint64_t* valid_mask, const uint64_t* peer_masks,
    const uint8_t* dkeys_blob, const int64_t* dkey_offs,
    const int32_t* dkey_lens, const int32_t* dkey_owner, int32_t n_dkeys) {
  RouteTable* t = (RouteTable*)handle;
  if (t == nullptr || n_users < 0 || n_brokers < 0 || n_dkeys < 0) return -1;
  free_table_storage(t);
  t->n_users = n_users;
  t->n_brokers = n_brokers;
  t->stamp_cur = 0;
  std::memcpy(t->valid_mask, valid_mask, sizeof(t->valid_mask));
  const int64_t n_peers = (int64_t)n_users + n_brokers;

  // inverted index: two passes over the peer masks
  t->topic_offsets = (int32_t*)std::calloc(257, sizeof(int32_t));
  if (t->topic_offsets == nullptr) return -1;
  int64_t total = 0;
  for (int64_t p = 0; p < n_peers; ++p) {
    const uint64_t* m = peer_masks + p * MASK_WORDS;
    for (int w = 0; w < MASK_WORDS; ++w)
      for (uint64_t bits = m[w]; bits; bits &= bits - 1) {
        ++t->topic_offsets[w * 64 + __builtin_ctzll(bits) + 1];
        ++total;
      }
  }
  for (int tt = 0; tt < 256; ++tt)
    t->topic_offsets[tt + 1] += t->topic_offsets[tt];
  t->topic_peers = (int32_t*)std::malloc(
      (total ? total : 1) * sizeof(int32_t));
  if (t->topic_peers == nullptr) { free_table_storage(t); return -1; }
  int32_t* cursor = (int32_t*)std::calloc(256, sizeof(int32_t));
  if (cursor == nullptr) { free_table_storage(t); return -1; }
  for (int64_t p = 0; p < n_peers; ++p) {
    const uint64_t* m = peer_masks + p * MASK_WORDS;
    for (int w = 0; w < MASK_WORDS; ++w)
      for (uint64_t bits = m[w]; bits; bits &= bits - 1) {
        const int tt = w * 64 + __builtin_ctzll(bits);
        t->topic_peers[t->topic_offsets[tt] + cursor[tt]++] = (int32_t)p;
      }
  }
  std::free(cursor);

  // direct-map hash (open addressing, power-of-two, 2x load headroom)
  uint64_t cap = 16;
  while (cap < (uint64_t)n_dkeys * 2 + 1) cap <<= 1;
  t->dmap = (DirectSlot*)std::calloc(cap, sizeof(DirectSlot));
  if (t->dmap == nullptr) { free_table_storage(t); return -1; }
  t->dmap_mask = cap - 1;
  int64_t blob_len = 0;
  for (int32_t i = 0; i < n_dkeys; ++i) blob_len += dkey_lens[i];
  t->keys_blob = (uint8_t*)std::malloc(blob_len ? blob_len : 1);
  if (t->keys_blob == nullptr) { free_table_storage(t); return -1; }
  t->keys_blob_len = blob_len;
  int64_t pos = 0;
  for (int32_t i = 0; i < n_dkeys; ++i) {
    const uint8_t* key = dkeys_blob + dkey_offs[i];
    const int32_t klen = dkey_lens[i];
    std::memcpy(t->keys_blob + pos, key, (size_t)klen);
    const uint64_t h = fnv1a(key, klen);
    uint64_t slot = h & t->dmap_mask;
    while (t->dmap[slot].hash != 0) {
      DirectSlot& s = t->dmap[slot];
      if (s.hash == h && s.key_len == klen &&
          std::memcmp(t->keys_blob + s.key_off, key, (size_t)klen) == 0) {
        break;  // duplicate key: last entry wins (caller emits each once)
      }
      slot = (slot + 1) & t->dmap_mask;
    }
    DirectSlot& s = t->dmap[slot];
    s.hash = h;
    s.key_off = pos;
    s.key_len = klen;
    s.peer = dkey_owner[i];
    pos += klen;
  }

  t->stamp = (uint64_t*)std::calloc(n_peers ? n_peers : 1, sizeof(uint64_t));
  if (t->stamp == nullptr) { free_table_storage(t); return -1; }
  return 0;
}

// Plan frames [start, start+count) of one chunk.
//   mode 0: user-origin  (Direct forwards anywhere; Broadcast reaches
//           interested users AND brokers) — handler.rs user path
//   mode 1: broker-origin (Direct to OUR user only; Broadcast to local
//           users only — loop prevention) — handler.rs broker path
// Emits (peer, frame-index) pairs in frame order. Stops at the first
// frame that is not a well-formed Direct/Broadcast (*stop_reason = 1:
// the scalar path owns it) or when the pair buffer cannot be guaranteed
// to hold the next frame's worst-case fan-out (*stop_reason = 2: call
// again from the returned index). *stop_reason = 0 means the whole range
// was planned. Returns the number of frames consumed, or -1 on bad args.
int64_t pushcdn_route_plan(
    void* handle, const uint8_t* buf, int64_t buf_len,
    const int64_t* offs, const int64_t* lens, int64_t start, int64_t count,
    int32_t mode, int32_t* out_peer, int32_t* out_frame, int64_t pair_cap,
    int64_t* n_pairs, int32_t* stop_reason) {
  RouteTable* t = (RouteTable*)handle;
  *n_pairs = 0;
  *stop_reason = 0;
  if (t == nullptr || start < 0 || count < 0) return -1;
  const int64_t n_peers = (int64_t)t->n_users + t->n_brokers;
  int64_t pairs = 0;
  int64_t i = start;
  const int64_t end = start + count;
  for (; i < end; ++i) {
    const int64_t o = offs[i];
    const int64_t n = lens[i];
    if (o < 0 || n < 1 || o + n > buf_len) { *stop_reason = 1; break; }
    if (pair_cap - pairs < n_peers) { *stop_reason = 2; break; }
    const uint8_t kind = buf[o];
    if (kind & KIND_TRACE_FLAG) { *stop_reason = 1; break; }  // traced: scalar
    if (kind == KIND_BROADCAST && n >= 3) {
      const int64_t nt = (int64_t)buf[o + 1] | ((int64_t)buf[o + 2] << 8);
      if (3 + nt > n) { *stop_reason = 1; break; }  // malformed: scalar
      uint64_t mask[MASK_WORDS] = {0, 0, 0, 0};
      for (int64_t k = 0; k < nt; ++k) {
        const uint8_t topic = buf[o + 3 + k];
        mask[topic >> 6] |= 1ull << (topic & 63);
      }
      bool any = false;
      for (int w = 0; w < MASK_WORDS; ++w) {
        mask[w] &= t->valid_mask[w];
        any |= mask[w] != 0;
      }
      if (!any) continue;  // pruned empty: drop (scalar parity)
      const uint64_t st = ++t->stamp_cur;
      for (int w = 0; w < MASK_WORDS; ++w)
        for (uint64_t bits = mask[w]; bits; bits &= bits - 1) {
          const int tt = w * 64 + __builtin_ctzll(bits);
          const int32_t lo = t->topic_offsets[tt];
          const int32_t hi = t->topic_offsets[tt + 1];
          for (int32_t k = lo; k < hi; ++k) {
            const int32_t peer = t->topic_peers[k];
            if (mode == 1 && peer >= t->n_users) continue;  // users only
            if (t->stamp[peer] == st) continue;  // already gets this frame
            t->stamp[peer] = st;
            out_peer[pairs] = peer;
            out_frame[pairs] = (int32_t)i;
            ++pairs;
          }
        }
    } else if (kind == KIND_DIRECT && n >= 5) {
      const int64_t rlen = (int64_t)buf[o + 1] | ((int64_t)buf[o + 2] << 8) |
                           ((int64_t)buf[o + 3] << 16) |
                           ((int64_t)buf[o + 4] << 24);
      if (5 + rlen > n) { *stop_reason = 1; break; }  // malformed: scalar
      const uint8_t* key = buf + o + 5;
      const uint64_t h = fnv1a(key, (int32_t)rlen);
      uint64_t slot = h & t->dmap_mask;
      int32_t peer = -1;
      while (t->dmap[slot].hash != 0) {
        const DirectSlot& s = t->dmap[slot];
        if (s.hash == h && s.key_len == (int32_t)rlen &&
            std::memcmp(t->keys_blob + s.key_off, key, (size_t)rlen) == 0) {
          peer = s.peer;
          break;
        }
        slot = (slot + 1) & t->dmap_mask;
      }
      if (peer < 0) continue;  // unknown recipient: drop
      if (mode == 1 && peer >= t->n_users) continue;  // to_user_only
      out_peer[pairs] = peer;
      out_frame[pairs] = (int32_t)i;
      ++pairs;
    } else {
      // control kind, short frame, or unknown tag: the scalar path owns
      // this frame (and everything after it until the caller re-plans)
      *stop_reason = 1;
      break;
    }
  }
  *n_pairs = pairs;
  return i - start;
}

// Gather a peer's fan-out into one wire-ready buffer: for each listed
// frame, write [u32 BE length][payload] — byte-identical to the transport
// framing the chunk arrived with. Returns bytes written, or -1 when `out`
// is too small / an index is out of range.
int64_t pushcdn_route_gather(
    const uint8_t* buf, int64_t buf_len, const int64_t* offs,
    const int64_t* lens, const int32_t* frame_idx, int64_t n_idx,
    uint8_t* out, int64_t out_cap) {
  int64_t pos = 0;
  for (int64_t k = 0; k < n_idx; ++k) {
    const int64_t i = frame_idx[k];
    const int64_t o = offs[i];
    const int64_t n = lens[i];
    if (o < 0 || n < 0 || o + n > buf_len || pos + 4 + n > out_cap) return -1;
    out[pos] = (uint8_t)(n >> 24);
    out[pos + 1] = (uint8_t)(n >> 16);
    out[pos + 2] = (uint8_t)(n >> 8);
    out[pos + 3] = (uint8_t)n;
    std::memcpy(out + pos + 4, buf + o, (size_t)n);
    pos += 4 + n;
  }
  return pos;
}

}  // extern "C"
