"""Benchmark: device-router broadcast throughput on one chip.

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline"}.

The metric is the BASELINE.json north star, **broadcast msgs/sec/chip**:
ingress messages fully routed per second by the device data plane — each
step packs S frames, runs the jitted routing step (CRDT merge + topic-mask
+ direct-match delivery over HBM-resident frame tensors; Pallas delivery
kernel on TPU), and surfaces the delivery matrix. ``vs_baseline`` is the
ratio against the 1M msgs/sec target (v5e-16 mesh target, measured here on
a single chip — per-chip parity at 1/16 of the fleet target means
vs_baseline ≈ 1/16 at target performance; >1 beats the full-mesh target on
one chip).

The reference publishes no numbers (BASELINE.md): its criterion harnesses
measure broadcast routing latency on an in-memory transport; this bench is
the same shape — deterministic in-process routing work, no NIC — scaled to
tensor batches.
"""

from __future__ import annotations

import argparse
import json
import sys
import time

import numpy as np

# The platform is NOT forced here — the driver runs this on the real TPU
# chip — EXCEPT when the pre-flight accelerator probe fails, in which case
# main() falls back to the CPU platform with an explicit note in the JSON.
import jax
import jax.numpy as jnp

from pushcdn_tpu.parallel.crdt import ABSENT, CrdtState
from pushcdn_tpu.parallel.router import (
    IngressBatch,
    RouterState,
    routing_step,
    routing_step_single,
)
from pushcdn_tpu.proto.message import KIND_BROADCAST

U = 1024        # user slots on this broker shard
S = 65536       # ingress frames per step (a ~2 ms coalescing window at
                # the measured rate; throughput scales with S until HBM
                # binds — see BASELINE.md scaling data)
F = 1024        # frame slot bytes (10 KB-class messages live on 10 slots;
                # the reference's routing benches use 10 KB)
TOPICS = 8
TARGET_MSGS_PER_SEC = 1_000_000.0  # BASELINE.json v5e-16 fleet target


def build_inputs(seed: int = 0):
    rng = np.random.default_rng(seed)
    owners = np.zeros((U,), np.int32)             # all users local (broker 0)
    versions = np.ones((U,), np.uint32)
    ids = np.zeros((U,), np.int32)
    masks = rng.integers(1, 2**TOPICS, U).astype(np.uint32)  # ≥1 topic each
    state = RouterState(
        CrdtState(jnp.asarray(owners), jnp.asarray(versions), jnp.asarray(ids)),
        jnp.asarray(masks))

    frame_bytes = rng.integers(0, 256, (S, F)).astype(np.uint8)
    kind = np.full(S, KIND_BROADCAST, np.int32)
    length = np.full(S, F, np.int32)
    topic_mask = (1 << rng.integers(0, TOPICS, S)).astype(np.uint32)
    dest = np.full(S, -1, np.int32)
    valid = np.ones(S, bool)
    batch = IngressBatch(
        jnp.asarray(frame_bytes), jnp.asarray(kind), jnp.asarray(length),
        jnp.asarray(topic_mask), jnp.asarray(dest), jnp.asarray(valid))
    return state, batch


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--profile", metavar="DIR", default=None,
                    help="capture a JAX/XLA device trace of the timed loop "
                         "into DIR (view with TensorBoard / xprof) — the "
                         "flamegraph analog of the reference's pprof-in-"
                         "criterion integration")
    ap.add_argument("--delivery-impl",
                    choices=["auto", "pallas", "jnp", "ragged"],
                    default="auto",
                    help="delivery implementation: 'pallas' forces the "
                         "dense Pallas kernel (interpreter off-TPU), "
                         "'jnp' forces the dense XLA reference, 'ragged' "
                         "routes through the paged walk "
                         "(ops.ragged_delivery — per-step work scales "
                         "with fan-out, not U x N) — the one-command "
                         "delivery A/B for the moment the TPU tunnel "
                         "returns; 'auto' (default) picks the dense "
                         "Pallas kernel on real TPU only")
    ap.add_argument("--route-impl", choices=["auto", "native", "python"],
                    default="auto",
                    help="routing plane for the host_route_msgs_s "
                         "companion row (decoded broker forwarding): "
                         "'native' = the cut-through route-plan kernel, "
                         "'python' = the scalar receive loops — the "
                         "--delivery-impl analog for the broker data "
                         "plane (benches/route_bench.py runs the full "
                         "native-vs-python A/B)")
    args = ap.parse_args()

    # flip the router's module-level switch BEFORE any routing_step jit
    # trace reads it (trace-time capture, one value per bench process)
    from pushcdn_tpu.parallel import router as _router
    _router.set_delivery_impl(args.delivery_impl)

    # A wedged accelerator tunnel hangs jax init in-process where no
    # timeout can reach it: probe device init + a real transfer in a
    # subprocess first, and fall back to the CPU platform (honestly
    # labeled in the JSON) rather than hanging the driver's bench run.
    from pushcdn_tpu.testing.accel_probe import force_cpu_if_unreachable
    why = force_cpu_if_unreachable("bench.py")
    platform_note = None if why is None else (
        f"accelerator unreachable ({why}); CPU-platform fallback — NOT a "
        "TPU measurement")

    state, batch = build_inputs()

    ragged = args.delivery_impl == "ragged"
    if ragged:
        # the paged-walk inputs: a steady-state interest index over the
        # same uniform 8-topic masks, packed once (the batch is identical
        # every step, exactly like the dense scan's reuse)
        from pushcdn_tpu.ops.ragged_delivery import RaggedInterest
        from pushcdn_tpu.parallel.router import (
            routing_step_ragged,
            routing_step_ragged_single,
        )
        ri = RaggedInterest(TOPICS, max_pages=8192)
        host_masks = np.asarray(state.topic_masks)
        for u in range(U):
            ri.set_mask(u, int(host_masks[u]))
        walk = ri.pack(np.asarray(batch.kind), np.asarray(batch.topic_mask),
                       np.asarray(batch.dest), np.asarray(batch.valid))
        assert not walk.spilled, "bench page pool must hold the batch"
        pages_d = jnp.asarray(walk.pages)
        wp_d = jnp.asarray(walk.walk_page)
        wf_d = jnp.asarray(walk.walk_frame)

    # warmup / compile one plain step, then carry the merged CRDT so the
    # timed steps run at the converged steady state
    result = routing_step_single(state, batch)
    jax.block_until_ready(result.deliver)
    state = result.state

    # DELIBERATE host readbacks before timing — do not remove. The
    # tunneled backend has a deferred-execution mode in which
    # block_until_ready returns BEFORE the work runs: round 4 measured a
    # "1.5B msgs/s" headline whose timed loop finished in milliseconds
    # while the first later readback stalled for seconds paying for every
    # step (the tell was an implied frame-byte rate ABOVE the chip's HBM
    # spec). Any pre-timing readback pins the session to eager execution;
    # the timed region below ALSO ends with a readback, so timing can
    # never close before the work is real. These per-step scalars double
    # as the exact-count honesty baseline.
    # int32 accumulators wrap mod 2^32 (the Pallas kernel cannot compile
    # under global x64); modular sums are order-independent, so the
    # exact-count asserts below compare deltas mod 2^32
    M32 = 1 << 32
    result = routing_step_single(state, batch)
    per_step_count = int(result.deliver.sum(dtype=jnp.int32)) % M32
    delivered = result.deliver.any(axis=0)
    per_step_bytes = int(jnp.where(delivered[:, None], batch.frame_bytes,
                                   0).sum(dtype=jnp.int32)) % M32
    state = result.state
    if ragged:
        # equivalence-as-honesty: the ragged walk's counted decisions must
        # equal the dense reference's, or the timed loop below measures a
        # different workload
        rres = routing_step_ragged_single(state, batch, pages_d, wp_d,
                                          wf_d)
        ragged_count = int(rres.counts.sum(dtype=jnp.int32)) % M32
        if ragged_count != per_step_count:
            raise SystemExit(
                f"ragged delivery count {ragged_count} != dense "
                f"{per_step_count} — the paged walk dropped pairs")
        state = rres.state

    # Many steps per jit call via lax.scan: intermediates (the [S, U]
    # delivery matrix, gathered bytes) stay on device across the whole
    # call, so the tunnel ships only the carried state + one scalar —
    # per-call transfer overhead amortizes across K real steps instead of
    # shipping ~70 MB of internal buffers per step (the eager-mode cost
    # that made the old one-step-per-call structure measure the tunnel,
    # not the chip).
    K = 500         # steps per scan call (amortizes the per-call tunnel
                    # round trip, measured below and reported separately)
    repeats = 5     # best-of: the tunneled chip is noisy

    if ragged:
        # the same scan harness over the paged walk: counted decisions
        # replace the delivery-matrix sum (same modular honesty asserts),
        # and the byte pass scatters per-frame counts to rebuild the
        # delivered-frame mask for the byte forcing
        @jax.jit
        def scan_decision(state, batch, acc):
            def body(carry, _):
                st, a = carry
                r = routing_step_ragged(st, batch, pages_d, wp_d, wf_d,
                                        jnp.int32(0))
                return (r.state, a + r.counts.sum(dtype=jnp.int32)), None
            (st, a), _ = jax.lax.scan(body, (state, acc), None, length=K)
            return st, a

        @jax.jit
        def scan_bytes(state, batch, acc):
            def body(carry, _):
                st, a = carry
                r = routing_step_ragged(st, batch, pages_d, wp_d, wf_d,
                                        jnp.int32(0))
                d = jnp.zeros(S, jnp.int32).at[wf_d].add(r.counts) > 0
                masked = jnp.where(d[:, None], batch.frame_bytes, 0)
                a = a + r.counts.sum(dtype=jnp.int32) \
                    + masked.sum(dtype=jnp.int32)
                return (r.state, a), None
            (st, a), _ = jax.lax.scan(body, (state, acc), None, length=K)
            return st, a
    else:
        @jax.jit
        def scan_decision(state, batch, acc):
            def body(carry, _):
                st, a = carry
                r = routing_step(st, batch, jnp.int32(0), axis_name=None)
                return (r.state, a + r.deliver.sum(dtype=jnp.int32)), None
            (st, a), _ = jax.lax.scan(body, (state, acc), None, length=K)
            return st, a

        @jax.jit
        def scan_bytes(state, batch, acc):
            def body(carry, _):
                st, a = carry
                r = routing_step(st, batch, jnp.int32(0), axis_name=None)
                d = r.deliver.any(axis=0)                       # [S]
                masked = jnp.where(d[:, None], batch.frame_bytes, 0)
                # BYTE-TRUE forcing: every delivered frame's payload bytes
                # enter the accumulator's dependency cone
                a = a + r.deliver.sum(dtype=jnp.int32) \
                    + masked.sum(dtype=jnp.int32)
                return (r.state, a), None
            (st, a), _ = jax.lax.scan(body, (state, acc), None, length=K)
            return st, a

    # calibrate the per-call overhead with a trivial scan of the same
    # length: on the tunneled backend one eager jit call costs ~70-80 ms
    # regardless of content; reporting it separately decomposes the
    # inclusive rate below into tunnel tax vs real routing work
    @jax.jit
    def trivial(acc):
        def body(a, _):
            return a + 1, None
        a, _ = jax.lax.scan(body, acc, None, length=K)
        return a

    tacc = jnp.zeros((), jnp.int32)
    tacc = trivial(tacc)
    _ = int(tacc)
    call_overhead_s = float("inf")
    for _ in range(3):
        t0 = time.perf_counter()
        tacc = trivial(tacc)
        _ = int(tacc)
        call_overhead_s = min(call_overhead_s, time.perf_counter() - t0)

    acc = jnp.zeros((), jnp.int32)
    state, acc = scan_decision(state, batch, acc)       # compile
    acc_val = int(acc) % M32                            # eager + baseline
    accb = jnp.zeros((), jnp.int32)
    state, accb = scan_bytes(state, batch, accb)        # compile
    accb_val = int(accb) % M32

    if args.profile:  # start AFTER warm-up so the trace is steady-state
        jax.profiler.start_trace(args.profile)
        print(f"# tracing to {args.profile}", file=sys.stderr)

    # pass 1: routing-decision rate (metadata only — the historical number)
    best_decision = float("inf")
    for _ in range(repeats):
        t0 = time.perf_counter()
        state, acc = scan_decision(state, batch, acc)
        new_val = int(acc) % M32  # readback INSIDE the timed window: the
        best_decision = min(best_decision, time.perf_counter() - t0)
        # work cannot defer past it; delta checked exactly (mod 2^32)
        if (new_val - acc_val) % M32 != (K * per_step_count) % M32:
            raise SystemExit(
                f"decision-count mismatch: +{(new_val - acc_val) % M32}, "
                f"expected {(K * per_step_count) % M32} — the timed cone "
                "was not forced")
        acc_val = new_val

    # pass 2: byte-true rate — same steps, with every delivered frame's
    # bytes materialized into the accumulator's dependency cone
    best_bytes = float("inf")
    for _ in range(repeats):
        t0 = time.perf_counter()
        state, accb = scan_bytes(state, batch, accb)
        new_val = int(accb) % M32
        best_bytes = min(best_bytes, time.perf_counter() - t0)
        if (new_val - accb_val) % M32 != \
                (K * (per_step_count + per_step_bytes)) % M32:
            raise SystemExit(
                f"byte-sum mismatch: +{(new_val - accb_val) % M32}, "
                f"expected {(K * (per_step_count + per_step_bytes)) % M32}")
        accb_val = new_val

    if args.profile:
        jax.profiler.stop_trace()

    # host egress engine rate (native/framing.cpp): encode a bounded-fan-
    # out delivery matrix (16 receivers x 16K frames) into per-user wire
    # streams — the socket side of the pump, measured off-device
    egress_rate = None
    try:
        from pushcdn_tpu import native
        S_e = 16384
        rng = np.random.default_rng(1)
        deliver_e = np.zeros((U, S_e), bool)
        for f in range(S_e):
            deliver_e[rng.integers(0, U, 16), f] = True
        lengths_e = np.full(S_e, F, np.int32)
        blocks_e = [np.asarray(batch.frame_bytes)[:S_e]]
        streams = native.egress_encode(deliver_e, lengths_e, blocks_e)
        if streams is not None:
            total_msgs = streams.total_msgs
            rates = []
            for _ in range(3):
                del streams  # return the pooled buffer before re-encoding
                t0 = time.perf_counter()
                streams = native.egress_encode(deliver_e, lengths_e,
                                               blocks_e)
                rates.append(total_msgs / (time.perf_counter() - t0))
            rates.sort()
            egress_rate = rates[1]  # median of 3: the shared core's cgroup
            #                         throttling makes single shots lie
    except Exception:
        pass

    # companion host row: decoded broker-forwarding through the routing
    # plane selected by --route-impl (same measurement loop as the
    # route_bench/configs_bench rows, pushcdn_tpu.testing.routebench;
    # None = native requested but kernel unavailable — row omitted,
    # never mislabeled)
    route_rate = None
    try:
        import asyncio as _asyncio

        from pushcdn_tpu.testing.routebench import forward_rate
        _res = _asyncio.run(forward_rate(args.route_impl, msgs=2_000,
                                         trials=3))
        if _res is not None:
            route_rate = _res["median"]
    except Exception:
        pass

    msgs_per_sec = K * S / best_bytes               # headline: byte-true
    decision_rate = K * S / best_decision
    byte_rate = K * S * F / best_bytes              # delivered bytes in cone
    # tunnel-overhead-free estimate (the rate a locally-attached chip
    # would sustain): subtract the calibrated per-call floor
    overhead_free = K * S / max(best_bytes - call_overhead_s,
                                best_bytes * 0.05)
    kind = jax.devices()[0].device_kind
    # known per-chip HBM bandwidths (GB/s); the implied-fraction row is
    # informative only when the kind is recognized
    hbm_spec = {"TPU v4": 1228, "TPU v5 lite": 819, "TPU v5e": 819,
                "TPU v5p": 2765, "TPU v6 lite": 1638, "TPU v6e": 1638}
    spec = next((v for k, v in hbm_spec.items() if k in kind), None)
    row = {
        "metric": "broadcast msgs/sec/chip",
        "value": round(msgs_per_sec, 1),
        "unit": "msgs/s",
        "vs_baseline": round(msgs_per_sec / TARGET_MSGS_PER_SEC, 4),
        # byte-true companion numbers; elision-proofing: every step's
        # delivery matrix and delivered bytes are in the on-device
        # accumulator's cone, the timed window ends with a host readback
        # (deferred execution cannot escape it), and the per-call count
        # deltas are asserted against eagerly-measured per-step values.
        # NOTE the byte forcing is hoistable algebra (XLA may reduce it
        # to a precomputed per-frame row-sum dotted with the delivered
        # mask each step), so frame_byte_rate is an in-cone figure, not
        # a bandwidth measurement; the delivery MATRIX itself cannot be
        # hoisted (the carried CRDT state threads through every step)
        "decision_rate_msgs_s": round(decision_rate, 1),
        "frame_byte_rate_GBps": round(byte_rate / 1e9, 2),
        "device_kind": kind,
        "delivery_impl": args.delivery_impl,
        "route_impl": args.route_impl,
    }
    if platform_note:
        row["note"] = platform_note
    row["per_call_overhead_ms"] = round(call_overhead_s * 1e3, 1)
    row["overhead_free_msgs_s_est"] = round(overhead_free, 1)
    if spec:
        row["hbm_frac_of_spec"] = round(byte_rate / (spec * 1e9), 4)
    if egress_rate is not None:
        row["host_egress_msgs_s"] = round(egress_rate, 1)
    if route_rate is not None:
        row["host_route_msgs_s"] = round(route_rate, 1)
    from pushcdn_tpu.testing.provenance import provenance
    row["provenance"] = provenance()
    print(json.dumps(row))


if __name__ == "__main__":
    main()
