"""Benchmark: device-router broadcast throughput on one chip.

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline"}.

The metric is the BASELINE.json north star, **broadcast msgs/sec/chip**:
ingress messages fully routed per second by the device data plane — each
step packs S frames, runs the jitted routing step (CRDT merge + topic-mask
+ direct-match delivery over HBM-resident frame tensors; Pallas delivery
kernel on TPU), and surfaces the delivery matrix. ``vs_baseline`` is the
ratio against the 1M msgs/sec target (v5e-16 mesh target, measured here on
a single chip — per-chip parity at 1/16 of the fleet target means
vs_baseline ≈ 1/16 at target performance; >1 beats the full-mesh target on
one chip).

The reference publishes no numbers (BASELINE.md): its criterion harnesses
measure broadcast routing latency on an in-memory transport; this bench is
the same shape — deterministic in-process routing work, no NIC — scaled to
tensor batches.
"""

from __future__ import annotations

import argparse
import json
import sys
import time

import numpy as np

# Do NOT force a platform: the driver runs this on the real TPU chip.
import jax
import jax.numpy as jnp

from pushcdn_tpu.parallel.crdt import ABSENT, CrdtState
from pushcdn_tpu.parallel.router import (
    IngressBatch,
    RouterState,
    routing_step_single,
)
from pushcdn_tpu.proto.message import KIND_BROADCAST

U = 1024        # user slots on this broker shard
S = 65536       # ingress frames per step (a ~2 ms coalescing window at
                # the measured rate; throughput scales with S until HBM
                # binds — see BASELINE.md scaling data)
F = 1024        # frame slot bytes (10 KB-class messages live on 10 slots;
                # the reference's routing benches use 10 KB)
TOPICS = 8
TARGET_MSGS_PER_SEC = 1_000_000.0  # BASELINE.json v5e-16 fleet target


def build_inputs(seed: int = 0):
    rng = np.random.default_rng(seed)
    owners = np.zeros((U,), np.int32)             # all users local (broker 0)
    versions = np.ones((U,), np.uint32)
    ids = np.zeros((U,), np.int32)
    masks = rng.integers(1, 2**TOPICS, U).astype(np.uint32)  # ≥1 topic each
    state = RouterState(
        CrdtState(jnp.asarray(owners), jnp.asarray(versions), jnp.asarray(ids)),
        jnp.asarray(masks))

    frame_bytes = rng.integers(0, 256, (S, F)).astype(np.uint8)
    kind = np.full(S, KIND_BROADCAST, np.int32)
    length = np.full(S, F, np.int32)
    topic_mask = (1 << rng.integers(0, TOPICS, S)).astype(np.uint32)
    dest = np.full(S, -1, np.int32)
    valid = np.ones(S, bool)
    batch = IngressBatch(
        jnp.asarray(frame_bytes), jnp.asarray(kind), jnp.asarray(length),
        jnp.asarray(topic_mask), jnp.asarray(dest), jnp.asarray(valid))
    return state, batch


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--profile", metavar="DIR", default=None,
                    help="capture a JAX/XLA device trace of the timed loop "
                         "into DIR (view with TensorBoard / xprof) — the "
                         "flamegraph analog of the reference's pprof-in-"
                         "criterion integration")
    args = ap.parse_args()

    state, batch = build_inputs()

    # warmup / compile
    result = routing_step_single(state, batch)
    jax.block_until_ready(result.deliver)
    state = result.state  # carry the merged CRDT like a real steady state

    # Every step's delivery matrix is CONSUMED on device (folded into an
    # accumulator): blocking only on the final step would let a lazy
    # remote-chip backend elide intermediate steps' work and overstate
    # throughput. best-of-N repeats because tunnel dispatch is noisy.
    @jax.jit
    def consume(acc, deliver):
        # full on-device reduction: the whole matrix is in acc's
        # dependency cone, so no backend can elide any of it
        return acc + deliver.sum(dtype=jnp.int32)

    steps, repeats = 50, 3
    best_dt = float("inf")
    acc = jnp.zeros((), jnp.int32)
    acc = consume(acc, result.deliver)  # compile consume before timing
    jax.block_until_ready(acc)
    if args.profile:  # start AFTER warm-up so the trace is steady-state
        jax.profiler.start_trace(args.profile)
        print(f"# tracing to {args.profile}", file=sys.stderr)
    for _ in range(repeats):
        t0 = time.perf_counter()
        for _ in range(steps):
            result = routing_step_single(state, batch)
            state = result.state
            acc = consume(acc, result.deliver)
        jax.block_until_ready(acc)
        best_dt = min(best_dt, time.perf_counter() - t0)
    if args.profile:
        jax.profiler.stop_trace()

    msgs_per_sec = steps * S / best_dt
    print(json.dumps({
        "metric": "broadcast msgs/sec/chip",
        "value": round(msgs_per_sec, 1),
        "unit": "msgs/s",
        "vs_baseline": round(msgs_per_sec / TARGET_MSGS_PER_SEC, 4),
    }))


if __name__ == "__main__":
    main()
