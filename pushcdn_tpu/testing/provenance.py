"""Host/build provenance for bench artifacts (ISSUE 11).

BENCH_r*.json rows become a cross-round *series* (scripts/bench_series.py),
which is only honest if each row is attributable to the host it ran on —
a regression caused by moving from a 16-core runner to a 1-core container
must be readable as such. Every bench section therefore stamps this dict.
"""

from __future__ import annotations

import os
import platform
import subprocess
import sys


def git_sha(repo_root: str = None) -> str:
    """Current commit (short), or "unknown" outside a git checkout."""
    root = repo_root or os.path.dirname(os.path.dirname(
        os.path.dirname(os.path.abspath(__file__))))
    try:
        out = subprocess.run(
            ["git", "rev-parse", "--short", "HEAD"], cwd=root,
            capture_output=True, text=True, timeout=10)
        if out.returncode == 0 and out.stdout.strip():
            return out.stdout.strip()
    except (OSError, subprocess.SubprocessError):
        pass
    return "unknown"


def provenance() -> dict:
    """cpus / git sha / python + jax versions / platform — cheap enough
    to stamp into every bench section."""
    try:
        import jax
        jax_version = jax.__version__
    except Exception:
        jax_version = None
    return {
        "cpus": os.cpu_count(),
        "git_sha": git_sha(),
        "python": platform.python_version(),
        "jax": jax_version,
        "platform": platform.platform(),
        "argv0": os.path.basename(sys.argv[0]) if sys.argv else None,
    }
