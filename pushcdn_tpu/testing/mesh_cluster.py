"""MeshCluster — N broker shards on the device mesh + a marshal, users
over the Memory transport. The shared harness for mesh-group tests AND
the device-mesh configs bench (the same test/bench split the reference
serves with its non-cfg(test) harness, cdn-broker/src/tests/mod.rs:7-9).

Brokers are registered in discovery WITHOUT dialing (external handles),
so mesh-only scenarios can prove traffic crosses shards with zero host
broker links; ``start(form_host_mesh=True)`` dials the host links as the
backup plane instead.
"""

from __future__ import annotations

import asyncio
import itertools
import os
import tempfile

from pushcdn_tpu.broker.broker import Broker, BrokerConfig
from pushcdn_tpu.broker.mesh_group import MeshBrokerGroup, MeshGroupConfig
from pushcdn_tpu.broker.tasks.heartbeat import heartbeat_once
from pushcdn_tpu.client import Client, ClientConfig
from pushcdn_tpu.marshal import Marshal, MarshalConfig
from pushcdn_tpu.parallel.mesh import make_broker_mesh
from pushcdn_tpu.proto.crypto.signature import DEFAULT_SCHEME
from pushcdn_tpu.proto.def_ import testing_run_def
from pushcdn_tpu.proto.discovery.base import BrokerIdentifier
from pushcdn_tpu.proto.discovery.embedded import Embedded
from pushcdn_tpu.proto.transport.memory import Memory
from pushcdn_tpu.testing.cluster import wait_until

_UID = itertools.count()


class MeshCluster:
    def __init__(self, num_shards: int = 4, extra_lanes: tuple = (),
                 ring_slots: int = 32, frame_bytes: int = 1024,
                 num_user_slots: int = 64, batch_window_s: float = 0.002,
                 devices=None, prefix: str = "mg",
                 gather_frame_bytes: bool = False):
        self.uid = next(_UID)
        self.num_shards = num_shards
        self.extra_lanes = extra_lanes
        self.ring_slots = ring_slots
        self.frame_bytes = frame_bytes
        self.num_user_slots = num_user_slots
        self.batch_window_s = batch_window_s
        self.gather_frame_bytes = gather_frame_bytes
        self.devices = devices
        self.prefix = f"{prefix}{self.uid}"
        self.db = os.path.join(tempfile.mkdtemp(prefix="pushcdn-mesh-"),
                               "d.sqlite")
        self.run_def = testing_run_def()
        self.keypair = DEFAULT_SCHEME.generate_keypair(seed=40_000 + self.uid)
        self.brokers: list[Broker] = []
        self.group: MeshBrokerGroup = None
        self.marshal: Marshal = None

    def _ident(self, i: int) -> BrokerIdentifier:
        return BrokerIdentifier(f"{self.prefix}-b{i}-pub",
                                f"{self.prefix}-b{i}-priv")

    async def start(self, form_host_mesh: bool = False) -> "MeshCluster":
        mesh = make_broker_mesh(self.num_shards, devices=self.devices)
        self.group = MeshBrokerGroup(mesh, MeshGroupConfig(
            num_user_slots=self.num_user_slots, ring_slots=self.ring_slots,
            frame_bytes=self.frame_bytes, extra_lanes=self.extra_lanes,
            batch_window_s=self.batch_window_s,
            gather_frame_bytes=self.gather_frame_bytes))
        for i in range(self.num_shards):
            ident = self._ident(i)
            b = await Broker.new(BrokerConfig(
                run_def=self.run_def, keypair=self.keypair,
                discovery_endpoint=self.db,
                public_advertise_endpoint=ident.public_advertise_endpoint,
                public_bind_endpoint=ident.public_advertise_endpoint,
                private_advertise_endpoint=ident.private_advertise_endpoint,
                private_bind_endpoint=ident.private_advertise_endpoint,
                heartbeat_interval_s=3600, sync_interval_s=3600,
                whitelist_interval_s=3600,
                form_mesh=form_host_mesh))
            self.group.attach(b, i)
            await b.start()
            self.brokers.append(b)
        # register in discovery WITHOUT dialing (external handles), so the
        # mesh-only tests prove traffic crosses shards with zero host links
        for i in range(self.num_shards):
            h = await Embedded.new(self.db, identity=self._ident(i))
            await h.perform_heartbeat(0, 60.0)
            await h.close()
        if form_host_mesh:
            for b in self.brokers:
                await heartbeat_once(b)  # dial host links as backup plane
            await asyncio.sleep(0.2)
        self.marshal = await Marshal.new(MarshalConfig(
            run_def=self.run_def, discovery_endpoint=self.db,
            bind_endpoint=f"{self.prefix}-marshal"))
        await self.marshal.start()
        return self

    async def place_client(self, seed: int, shard: int, topics) -> Client:
        """Steer the marshal so this client lands on ``shard``."""
        for i in range(self.num_shards):
            h = await Embedded.new(self.db, identity=self._ident(i))
            await h.perform_heartbeat(0 if i == shard else 100, 60.0)
            await h.close()
        c = Client(ClientConfig(
            marshal_endpoint=f"{self.prefix}-marshal",
            keypair=DEFAULT_SCHEME.generate_keypair(seed=seed),
            protocol=Memory, subscribed_topics=set(topics)))
        await c.ensure_initialized()
        await wait_until(
            lambda: self.brokers[shard].connections.has_user(c.public_key))
        return c

    async def stop(self) -> None:
        if self.marshal:
            await self.marshal.stop()
        for b in self.brokers:
            await b.stop()
