"""Out-of-process accelerator reachability probe.

A wedged accelerator tunnel hangs ``jax.devices()`` (and even device
enumeration can succeed on a runtime that then dies at ``device_put`` —
a libtpu client/terminal version mismatch does exactly that), and an
in-process hang cannot be timed out. Benches probe in a SUBPROCESS
before touching the device in-process, and degrade with a recorded
reason instead of hanging the run.
"""

from __future__ import annotations

import os
import signal
import subprocess
import sys
from typing import Tuple

_PROBE = ("import jax; d = jax.devices()[0]; "
          "jax.device_put(0, d).block_until_ready()")


def accelerator_reachable(timeout_s: float = 120.0) -> Tuple[bool, str]:
    """Return ``(ok, reason)``; ``reason`` is empty when reachable.

    The probe runs in its own session so that on timeout the WHOLE
    process group is killed — a wedged jax runtime can fork helpers that
    inherit the output pipes, and killing only the direct child would
    leave ``subprocess.run``'s final ``communicate()`` blocked on pipe
    EOF forever (the exact hang this probe exists to prevent).
    """
    proc = None
    try:
        proc = subprocess.Popen(
            [sys.executable, "-c", _PROBE],
            stdout=subprocess.DEVNULL, stderr=subprocess.PIPE,
            start_new_session=True)
        _, stderr = proc.communicate(timeout=timeout_s)
        if proc.returncode == 0:
            return True, ""
        tail = stderr.decode(errors="replace").strip().splitlines()
        return False, ("probe exited %d: %s"
                       % (proc.returncode, tail[-1] if tail else ""))[:300]
    except subprocess.TimeoutExpired:
        try:
            os.killpg(proc.pid, signal.SIGKILL)
        except (ProcessLookupError, PermissionError, OSError):
            pass
        proc.wait()
        return False, (f"probe timed out after {timeout_s:.0f}s "
                       "(wedged accelerator tunnel?)")
    except (subprocess.SubprocessError, OSError) as exc:
        return False, f"probe failed to launch: {exc!r}"[:300]
