"""Out-of-process accelerator reachability probe.

A wedged accelerator tunnel hangs ``jax.devices()`` (and even device
enumeration can succeed on a runtime that then dies at ``device_put`` —
a libtpu client/terminal version mismatch does exactly that), and an
in-process hang cannot be timed out. Benches probe in a SUBPROCESS
before touching the device in-process, and degrade with a recorded
reason instead of hanging the run.
"""

from __future__ import annotations

import os
import signal
import subprocess
import sys
from typing import Tuple

_PROBE = ("import jax; d = jax.devices()[0]; "
          "jax.device_put(0, d).block_until_ready()")

_MEMO: "Tuple[bool, str] | None" = None


def accelerator_reachable(timeout_s: float = 120.0,
                          use_cache: bool = True) -> Tuple[bool, str]:
    """Return ``(ok, reason)``; ``reason`` is empty when reachable.

    The result is memoized per process (``use_cache=False`` re-probes):
    the probe costs a full jax-import subprocess — and the whole wedge
    timeout when the tunnel is dead — so callers that consult it more
    than once (entry() then dryrun, or bench setup) pay once.

    The probe runs in its own session so that on timeout the WHOLE
    process group is killed — a wedged jax runtime can fork helpers that
    inherit the output pipes, and killing only the direct child would
    leave ``subprocess.run``'s final ``communicate()`` blocked on pipe
    EOF forever (the exact hang this probe exists to prevent).
    """
    global _MEMO
    if use_cache and _MEMO is not None:
        return _MEMO
    proc = None
    try:
        proc = subprocess.Popen(
            [sys.executable, "-c", _PROBE],
            stdout=subprocess.DEVNULL, stderr=subprocess.PIPE,
            start_new_session=True)
        _, stderr = proc.communicate(timeout=timeout_s)
        if proc.returncode == 0:
            result = True, ""
        else:
            tail = stderr.decode(errors="replace").strip().splitlines()
            result = False, ("probe exited %d: %s"
                             % (proc.returncode,
                                tail[-1] if tail else ""))[:300]
    except subprocess.TimeoutExpired:
        try:
            os.killpg(proc.pid, signal.SIGKILL)
        except (ProcessLookupError, PermissionError, OSError):
            try:
                proc.kill()  # fall back to the direct child
            except OSError:
                pass  # this path must degrade to a report, never raise
        try:
            proc.wait(timeout=10.0)
        except subprocess.TimeoutExpired:
            # Unkillable child (e.g. stuck in uninterruptible IO on the
            # tunnel fd): report rather than hang — the zombie is leaked
            # deliberately, the alternative is blocking forever.
            pass
        result = False, (f"probe timed out after {timeout_s:.0f}s "
                         "(wedged accelerator tunnel?)")
    except (subprocess.SubprocessError, OSError) as exc:
        result = False, f"probe failed to launch: {exc!r}"[:300]
    _MEMO = result
    return result


def force_cpu_if_unreachable(label: str):
    """Probe once (memoized); when the accelerator is unreachable, force
    the CPU platform and return the reason string (``None`` when
    reachable). Call BEFORE anything initializes jax backends — the
    ``jax_platforms`` config is read at first backend init; if a backend
    already exists, a best-effort ``clear_backends()`` makes the switch
    take effect anyway."""
    ok, why = accelerator_reachable()
    if ok:
        return None
    import jax
    jax.config.update("jax_platforms", "cpu")
    try:
        jax.extend.backend.clear_backends()
    except Exception:
        pass  # best-effort: no backend initialized yet is the normal case
    print(f"{label}: accelerator unreachable ({why}); CPU-platform fallback")
    return why
