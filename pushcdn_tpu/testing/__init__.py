"""In-process whole-system fixtures shared by tests *and* benches.

The reference keeps its deterministic harness outside ``cfg(test)`` exactly
so criterion benches can reuse it (cdn-broker/src/tests/mod.rs:7-9); this
package plays the same role for the full-cluster fixture used by the
integration tests and ``benches/configs_bench.py``.
"""

from pushcdn_tpu.testing.cluster import Cluster, wait_mesh_interest, wait_until

__all__ = ["Cluster", "wait_mesh_interest", "wait_until"]
