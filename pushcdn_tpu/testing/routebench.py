"""Shared decoded-broker-forwarding measurement (ISSUE 3 A/B).

One injected broker (test harness, Memory transport), one publisher
fanning Broadcast batches to N subscribed receivers, counted at the
receivers' transport drain. Kept here — like :class:`Cluster` — so the
three consumers (`benches/route_bench.py`, `benches/configs_bench.py`'s
headline row, and `bench.py`'s companion host row) measure the SAME loop
instead of drifting copies.
"""

from __future__ import annotations

import asyncio
import os
import statistics
import time
from typing import Optional


async def forward_rate(impl: str, receivers: int = 8, msgs: int = 2_000,
                       trials: int = 3, payload: int = 512,
                       batch: int = 64,
                       trace_every: int = 0) -> Optional[dict]:
    """Measure broker forwarding msgs/s with the routing plane forced to
    ``impl`` (``auto``/``native``/``python``). Returns ``None`` when
    ``impl == "native"`` but the kernel is unavailable (callers emit a
    skipped row — never a mislabeled A/B), else a dict with the median,
    all trials, and the delivered rate.

    ``trace_every > 0`` stamps every Nth sent frame with a lifecycle-trace
    context (proto.trace wire flag), exactly what a client publishing at
    ``PUSHCDN_TRACE_SAMPLE=N`` produces — the trace-overhead A/B row."""
    from pushcdn_tpu.broker.tasks import cutthrough
    from pushcdn_tpu.broker.test_harness import TestDefinition
    from pushcdn_tpu.native import routeplan
    from pushcdn_tpu.proto import trace as trace_lib
    from pushcdn_tpu.proto.message import Broadcast, serialize
    from pushcdn_tpu.proto.transport.base import FrameChunk
    from pushcdn_tpu.proto.transport.memory import Memory

    if impl == "native" and not routeplan.available():
        return None
    # the global-state restore must survive a failing harness start OR a
    # failing shutdown: callers swallow exceptions, and a leaked forced
    # impl / widened duplex window would distort every later row (and
    # cross-contaminate tests) in the same process
    prev_impl = cutthrough.ROUTE_IMPL
    prev_win = Memory.set_duplex_window(256 * 1024)
    try:
        cutthrough.ROUTE_IMPL = impl
        run = await TestDefinition(
            connected_users=[[]] + [[0]] * receivers).run()
        try:
            frame = serialize(Broadcast([0], os.urandom(payload)))
            traced_frame = trace_lib.stamp_frame(
                frame, trace_lib.new_trace()) if trace_every else None
            sender = run.user(0).remote
            msgs = max(batch, (msgs // batch) * batch)

            async def drain(conn, n):
                got = 0
                async with asyncio.timeout(120):
                    while got < n:
                        for item in await conn.recv_frames(n - got):
                            got += item.remaining \
                                if type(item) is FrameChunk else 1
                            item.release()

            rates = []
            sent = 0
            for _ in range(trials):
                t0 = time.perf_counter()
                drains = [asyncio.create_task(
                    drain(run.user(1 + r).remote, msgs))
                    for r in range(receivers)]
                for _ in range(msgs // batch):
                    if trace_every:
                        # deterministic 1-in-N mix: the exact wire a
                        # sampled publisher produces
                        frames = []
                        for _i in range(batch):
                            sent += 1
                            frames.append(traced_frame
                                          if sent % trace_every == 0
                                          else frame)
                        await sender.send_raw_many(frames)
                    else:
                        await sender.send_raw_many([frame] * batch)
                    await asyncio.sleep(0)
                await asyncio.gather(*drains)
                rates.append(msgs / (time.perf_counter() - t0))
            med = statistics.median(rates)
            return {"median": med, "trials": rates, "msgs": msgs,
                    "receivers": receivers, "payload": payload,
                    "delivered": med * receivers}
        finally:
            await run.shutdown()
    finally:
        cutthrough.ROUTE_IMPL = prev_impl
        Memory.set_duplex_window(prev_win)
