"""Shared decoded-broker-forwarding measurement (ISSUE 3 A/B).

One injected broker (test harness, Memory transport), one publisher
fanning Broadcast batches to N subscribed receivers, counted at the
receivers' transport drain. Kept here — like :class:`Cluster` — so the
three consumers (`benches/route_bench.py`, `benches/configs_bench.py`'s
headline row, and `bench.py`'s companion host row) measure the SAME loop
instead of drifting copies.
"""

from __future__ import annotations

import asyncio
import os
import statistics
import time
from typing import Optional

import numpy as np


async def forward_rate(impl: str, receivers: int = 8, msgs: int = 2_000,
                       trials: int = 3, payload: int = 512,
                       batch: int = 64,
                       trace_every: int = 0,
                       deliver_spans: bool = False,
                       parked_users: int = 0,
                       churn: bool = False,
                       incremental: Optional[bool] = None,
                       client_decode: bool = False
                       ) -> Optional[dict]:
    """Measure broker forwarding msgs/s with the routing plane forced to
    ``impl`` (``auto``/``native``/``python``). Returns ``None`` when
    ``impl == "native"`` but the kernel is unavailable (callers emit a
    skipped row — never a mislabeled A/B), else a dict with the median,
    all trials, and the delivered rate.

    ``trace_every > 0`` stamps every Nth sent frame with a lifecycle-trace
    context (proto.trace wire flag), exactly what a client publishing at
    ``PUSHCDN_TRACE_SAMPLE=N`` produces — the trace-overhead A/B row.
    ``deliver_spans=True`` makes receivers additionally do what a real
    client does with a traced frame: emit the ``delivery`` span (feeding
    ``cdn_e2e_latency_seconds``); the result dict then carries
    ``e2e_lat_s``, the raw publish→delivery latencies, for bench-side
    p50/p99. Kept opt-in because these receivers skip frame decode (a
    real client pays it anyway), so the flag-scan is bench-side cost that
    must not pollute the broker-side trace-overhead A/B.

    ISSUE 7 knobs — the sustained-churn A/B: ``parked_users`` injects
    that many extra users subscribed to an untrafficked topic (a big
    interest table, so a snapshot rebuild has a real O(users) cost);
    ``churn=True`` runs a concurrent churner connection flooding
    Subscribe/Unsubscribe during the measurement (every mutation
    invalidates the snapshot mid-traffic; the result carries
    ``churn_ops_s``); ``incremental`` forces the native maintenance mode
    (True = in-place deltas, False = the rebuild-guard baseline,
    None = leave as configured).

    ``client_decode=True`` drains receivers through the REAL client batch
    decode (``client.decode_received`` — exactly what
    ``Client.receive_messages`` runs, zero-copy payload views included)
    instead of counting raw frames at the transport: the delivered/s
    figure then includes full message decode, the honest application-
    visible rate (ISSUE 8 client-receive-residue row)."""
    from pushcdn_tpu.broker.tasks import cutthrough
    from pushcdn_tpu.broker.test_harness import TestDefinition
    from pushcdn_tpu.native import routeplan
    from pushcdn_tpu.proto import trace as trace_lib
    from pushcdn_tpu.proto.message import Broadcast, serialize
    from pushcdn_tpu.proto.transport.base import FrameChunk
    from pushcdn_tpu.proto.transport.memory import Memory

    if impl == "native" and not routeplan.available():
        return None
    # the global-state restore must survive a failing harness start OR a
    # failing shutdown: callers swallow exceptions, and a leaked forced
    # impl / widened duplex window would distort every later row (and
    # cross-contaminate tests) in the same process
    from pushcdn_tpu.proto.message import Subscribe, Unsubscribe

    prev_impl = cutthrough.ROUTE_IMPL
    prev_inc = cutthrough.ROUTE_INCREMENTAL
    prev_win = Memory.set_duplex_window(256 * 1024)
    try:
        cutthrough.ROUTE_IMPL = impl
        if incremental is not None:
            cutthrough.ROUTE_INCREMENTAL = incremental
        # user 0 = sender, 1..receivers = receivers on topic 0, then the
        # churner (topicless), then the parked herd on topic 1 (table
        # size without fan-out traffic)
        run = await TestDefinition(
            connected_users=[[]] + [[0]] * receivers + [[]]
            + [[1]] * parked_users).run()
        try:
            frame = serialize(Broadcast([0], os.urandom(payload)))
            sender = run.user(0).remote
            churner = run.user(1 + receivers).remote
            sub_frame = serialize(Subscribe([1]))
            unsub_frame = serialize(Unsubscribe([1]))
            churn_ops = 0
            churn_stop = False

            churn_batch = [sub_frame, unsub_frame] * 4

            async def churn_loop():
                # sustained subscribe/unsubscribe churn riding the same
                # broker while forwarding is measured: each op bumps
                # interest_version, so every following plan call pays the
                # maintenance cost under test (delta vs rebuild)
                nonlocal churn_ops
                while not churn_stop:
                    try:
                        await churner.send_raw_many(churn_batch,
                                                    flush=True)
                    except Exception:
                        return
                    churn_ops += len(churn_batch)
                    await asyncio.sleep(0)

            msgs = max(batch, (msgs // batch) * batch)
            e2e_lat_s: list = []

            def _note_delivery(data) -> None:
                # the real client's per-traced-frame work: strip the
                # trace block + emit the delivery span (the e2e SLO seam)
                _, tr = trace_lib.strip_frame(bytes(data))
                if tr is not None:
                    trace_lib.emit("delivery", tr)
                    e2e_lat_s.append(max(time.time_ns() - tr[1], 0) / 1e9)

            async def drain_decoded(conn, n):
                # the client-API drain: recv_frames + the exact decode
                # Client.receive_messages runs (zero-copy views) — every
                # counted message is a decoded Message object
                from pushcdn_tpu.client.client import decode_received
                got = 0
                async with asyncio.timeout(120):
                    while got < n:
                        got += len(decode_received(
                            await conn.recv_frames(n - got)))

            async def drain(conn, n):
                if client_decode:
                    return await drain_decoded(conn, n)
                got = 0
                async with asyncio.timeout(120):
                    while got < n:
                        for item in await conn.recv_frames(n - got):
                            if type(item) is FrameChunk:
                                got += item.remaining
                                if deliver_spans and trace_every:
                                    # vectorized flag scan: one fancy-index
                                    # per chunk, per-frame work only for
                                    # the 1-in-N actually-traced frames (a
                                    # scalar Python loop here costs ~14%
                                    # of the forwarding rate and would
                                    # dominate the A/B it exists to serve)
                                    offs_a = np.asarray(item.offs, np.int64)
                                    firsts = np.frombuffer(
                                        item.buf, np.uint8)[offs_a]
                                    hits = np.nonzero(
                                        firsts & trace_lib.TRACE_FLAG)[0]
                                    for i in hits.tolist():
                                        o = int(offs_a[i])
                                        ln = int(item.lens[i])
                                        _note_delivery(
                                            memoryview(item.buf)[o:o + ln])
                            else:
                                got += 1
                                if deliver_spans and trace_every \
                                        and len(item.data) \
                                        and item.data[0] & trace_lib.TRACE_FLAG:
                                    _note_delivery(item.data)
                            item.release()

            rates = []
            sent = 0
            churn_task = asyncio.create_task(churn_loop()) if churn \
                else None
            churn_t0 = time.perf_counter()
            for _ in range(trials):
                t0 = time.perf_counter()
                drains = [asyncio.create_task(
                    drain(run.user(1 + r).remote, msgs))
                    for r in range(receivers)]
                for _ in range(msgs // batch):
                    if trace_every:
                        # deterministic 1-in-N mix: the exact wire a
                        # sampled publisher produces (stamped fresh per
                        # traced frame — real origins, so the delivery
                        # side's e2e latencies are meaningful)
                        frames = []
                        for _i in range(batch):
                            sent += 1
                            frames.append(
                                trace_lib.stamp_frame(frame,
                                                      trace_lib.new_trace())
                                if sent % trace_every == 0 else frame)
                        await sender.send_raw_many(frames)
                    else:
                        await sender.send_raw_many([frame] * batch)
                    await asyncio.sleep(0)
                await asyncio.gather(*drains)
                rates.append(msgs / (time.perf_counter() - t0))
            churn_dt = time.perf_counter() - churn_t0
            if churn_task is not None:
                churn_stop = True
                await churn_task
            med = statistics.median(rates)
            out = {"median": med, "trials": rates, "msgs": msgs,
                   "receivers": receivers, "payload": payload,
                   "delivered": med * receivers,
                   "e2e_lat_s": e2e_lat_s}
            if churn:
                out["churn_ops"] = churn_ops
                out["churn_ops_s"] = churn_ops / churn_dt if churn_dt \
                    else 0.0
                state = getattr(run.broker, "_route_state", None)
                if state is not None:
                    out["route_summary"] = state.summary()
            return out
        finally:
            await run.shutdown()
    finally:
        cutthrough.ROUTE_IMPL = prev_impl
        cutthrough.ROUTE_INCREMENTAL = prev_inc
        Memory.set_duplex_window(prev_win)


async def forward_rate_tcp(io_impl: str, route_impl: str = "auto",
                           receivers: int = 4, msgs: int = 2_000,
                           trials: int = 3, payload: int = 512,
                           batch: int = 64, pump: str = "off",
                           count_transitions: bool = False
                           ) -> Optional[dict]:
    """The :func:`forward_rate` loop with user links over REAL loopback
    TCP — the io-impl (asyncio vs io_uring) A/B seam. ``io_impl`` is
    ``asyncio`` or ``uring``; returns None when ``uring`` is requested
    but the kernel denies io_uring (callers emit a skipped row, never a
    mislabeled one).

    When this process runs under the syscall-attribution preload
    (``native.syscount``), the result carries per-syscall counter deltas
    for the measured section and ``syscalls_per_msg`` — counted write +
    sendto/sendmsg + epoll_wait + io_uring_enter per DELIVERED message.

    ``pump`` controls the ISSUE 17 fused data-plane pump for this run
    (``off``/``auto``) INDEPENDENTLY of the process environment, so the
    r15 io-impl rows keep measuring the io engine alone and the pump A/B
    flips exactly one variable. ``pump="auto"`` returns None when the
    composition can't engage (the caller emits a skipped row, never an
    unlabeled python-path run sold as a pump run); an engaged run
    carries the route-plane ``pump`` summary (pump-hit vs escalation
    counts) and runs one unmeasured warmup wave first — engagement
    completes at the first TX-idle transition, so without the warmup
    trial 1 would silently measure the residual path.

    ``count_transitions=True`` appends one extra UNMEASURED wave run
    under a ``sys.setprofile`` hook and reports
    ``transitions_per_kmsg`` — Python-interpreter call transitions per
    1k delivered messages across the whole process (broker AND bench
    clients; the hook costs ~3x in rate, which is why it never overlaps
    the timed trials)."""
    from pushcdn_tpu.broker.tasks import cutthrough
    from pushcdn_tpu.broker.test_harness import TestDefinition
    from pushcdn_tpu.native import routeplan, syscount
    from pushcdn_tpu.native import uring as nuring
    from pushcdn_tpu.proto.message import Broadcast, serialize
    from pushcdn_tpu.proto.transport.base import FrameChunk
    from pushcdn_tpu.proto.transport import pump as pump_mod
    from pushcdn_tpu.proto.transport import uring as uring_mod

    if io_impl == "uring" and not nuring.available():
        return None
    if route_impl == "native" and not routeplan.available():
        return None
    if pump != "off" and (io_impl != "uring"
                          or not routeplan.available()):
        return None
    prev_impl = cutthrough.ROUTE_IMPL
    prev_env = os.environ.get("PUSHCDN_IO_IMPL")
    prev_pump = pump_mod.PUMP_IMPL
    try:
        cutthrough.ROUTE_IMPL = route_impl
        uring_mod.set_io_impl(io_impl)
        pump_mod.set_pump_impl(pump)
        run = await TestDefinition(
            connected_users=[[]] + [[0]] * receivers, tcp_users=True).run()
        try:
            frame = serialize(Broadcast([0], os.urandom(payload)))
            sender = run.user(0).remote
            msgs = max(batch, (msgs // batch) * batch)

            async def drain(conn, n):
                got = 0
                async with asyncio.timeout(120):
                    while got < n:
                        for item in await conn.recv_frames(n - got):
                            got += item.remaining \
                                if type(item) is FrameChunk else 1
                            item.release()

            async def wave(n):
                drains = [asyncio.create_task(
                    drain(run.user(1 + r).remote, n))
                    for r in range(receivers)]
                for _ in range(n // batch):
                    await sender.send_raw_many([frame] * batch)
                    await asyncio.sleep(0)
                await asyncio.gather(*drains)

            if pump != "off":
                # unmeasured warmup: pump engagement completes at each
                # receiver stream's first TX-idle transition, which only
                # happens after a wave drains — run one so the timed
                # trials measure the engaged path, not the residual one
                await wave(max(batch, min(msgs, 4 * batch)))
                await asyncio.sleep(0.05)

            rates = []
            counts_before = syscount.snapshot()
            t_all0 = time.perf_counter()
            for _ in range(trials):
                t0 = time.perf_counter()
                await wave(msgs)
                rates.append(msgs / (time.perf_counter() - t0))
            wall_s = time.perf_counter() - t_all0
            counts_after = syscount.snapshot()
            med = statistics.median(rates)
            out = {"median": med, "trials": rates, "msgs": msgs,
                   "receivers": receivers, "payload": payload,
                   "delivered": med * receivers,
                   "io_impl": io_impl, "pump": pump, "wall_s": wall_s}
            if counts_after:
                delta = syscount.delta(counts_before, counts_after)
                delivered_total = trials * msgs * receivers
                data_calls = sum(delta.get(k, 0) for k in (
                    "write", "writev", "send", "sendto", "sendmsg",
                    "epoll_wait", "epoll_pwait", "io_uring_enter"))
                out["syscalls"] = delta
                out["syscalls_per_msg"] = data_calls / delivered_total
            if count_transitions:
                import sys as _sys
                n_calls = [0]

                def _hook(frame_, event, arg, _n=n_calls):
                    if event == "call":
                        _n[0] += 1

                _sys.setprofile(_hook)
                try:
                    await wave(msgs)
                finally:
                    _sys.setprofile(None)
                out["transitions_per_kmsg"] = \
                    n_calls[0] / (msgs * receivers) * 1e3
            state = getattr(run.broker, "_route_state", None)
            ps = getattr(state, "_pump_state", None)
            if ps is not None and not ps.closed:
                out["pump_summary"] = ps.summary()
            if pump != "off" and (ps is None or ps.closed
                                  or not ps.summary()["pump_frames"]):
                # the composition never engaged (or never pumped a
                # frame): a "pump" row from this run would be the
                # residual path mislabeled — refuse to report it
                return None
            return out
        finally:
            await run.shutdown()
    finally:
        cutthrough.ROUTE_IMPL = prev_impl
        pump_mod.set_pump_impl(prev_pump)
        if prev_env is None:
            os.environ.pop("PUSHCDN_IO_IMPL", None)
            uring_mod._resolved = None
        else:
            uring_mod.set_io_impl(prev_env)


async def stream_rate(io_impl: str, total_mb: int = 256,
                      wsize: int = 256 * 1024,
                      trials: int = 3) -> Optional[dict]:
    """Raw data-plane throughput A/B: one loopback connection, one
    producer streaming ``total_mb`` MiB in ``wsize`` writes straight at
    the :class:`RawStream` layer, one consumer draining ``read_some``.
    No broker, no framing — this isolates the byte path itself (where
    the io engine's submission batching and completion coalescing live)
    from the CPython routing work that dominates ``forward_rate_tcp``.
    Returns None when ``uring`` is requested but unavailable."""
    import socket

    from pushcdn_tpu.native import uring as nuring
    from pushcdn_tpu.proto.transport import uring as uring_mod

    if io_impl == "uring" and not nuring.available():
        return None
    total = total_mb * 1024 * 1024
    payload = bytes(wsize)
    loop = asyncio.get_running_loop()
    rates = []
    for _ in range(trials):
        if io_impl == "uring":
            eng = uring_mod.UringEngine.current()
            lst = uring_mod.uring_bind("127.0.0.1", 0)
            accept_t = asyncio.create_task(lst.accept())
            cs = socket.socket()
            cs.setblocking(False)
            await loop.sock_connect(cs, ("127.0.0.1", lst.bound_port))
            cs.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
            tx = uring_mod.UringStream(cs, eng)
            rx_s = uring_mod.UringStream((await accept_t)._sock, eng)
        else:
            conn_fut = loop.create_future()
            server = await asyncio.start_server(
                lambda r, w: conn_fut.set_result((r, w)),
                "127.0.0.1", 0)
            port = server.sockets[0].getsockname()[1]
            r1, w1 = await asyncio.open_connection("127.0.0.1", port)
            r2, _w2 = await conn_fut

        async def rx_uring():
            got = 0
            while got < total:
                got += len(await rx_s.read_some(1 << 20))

        async def rx_aio():
            got = 0
            while got < total:
                got += len(await r2.read(1 << 20))

        t0 = time.perf_counter()
        rt = asyncio.create_task(
            rx_uring() if io_impl == "uring" else rx_aio())
        sent = 0
        if io_impl == "uring":
            while sent < total:
                await tx.write(payload)
                sent += wsize
        else:
            while sent < total:
                w1.write(payload)
                await w1.drain()
                sent += wsize
        await rt
        rates.append(total / (time.perf_counter() - t0) / 1e6)
        if io_impl == "uring":
            await tx.close()
            await rx_s.close()
            await lst.close()
        else:
            w1.close()
            _w2.close()
            server.close()
            await server.wait_closed()
        await asyncio.sleep(0.02)
    return {"median": statistics.median(rates), "trials": rates,
            "total_mb": total_mb, "write_size": wsize,
            "io_impl": io_impl, "unit": "MB/s"}


def _main() -> None:
    """Subprocess entry for the syscall-attribution bench row: the parent
    re-execs ``python -m pushcdn_tpu.testing.routebench`` with
    ``LD_PRELOAD`` pointing at the interposer and reads one JSON blob
    from stdout."""
    import argparse
    import json
    import sys

    ap = argparse.ArgumentParser(description=_main.__doc__)
    ap.add_argument("--io-impl", default="asyncio",
                    choices=("asyncio", "uring"))
    ap.add_argument("--route-impl", default="auto")
    ap.add_argument("--pump", default="off", choices=("off", "auto"),
                    help="ISSUE 17 fused data-plane pump for this run "
                         "(independent of the process environment)")
    ap.add_argument("--transitions", action="store_true",
                    help="append an unmeasured sys.setprofile wave and "
                         "report interpreter transitions per kmsg")
    ap.add_argument("--receivers", type=int, default=4)
    ap.add_argument("--msgs", type=int, default=2000)
    ap.add_argument("--trials", type=int, default=3)
    ap.add_argument("--payload", type=int, default=512)
    ap.add_argument("--batch", type=int, default=64)
    ap.add_argument("--stream", action="store_true",
                    help="run the raw stream-throughput tier instead of "
                         "broker forwarding")
    ap.add_argument("--stream-mb", type=int, default=256)
    args = ap.parse_args()
    if args.stream:
        out = asyncio.run(stream_rate(
            args.io_impl, total_mb=args.stream_mb, trials=args.trials))
    else:
        out = asyncio.run(forward_rate_tcp(
            args.io_impl, route_impl=args.route_impl,
            receivers=args.receivers, msgs=args.msgs, trials=args.trials,
            payload=args.payload, batch=args.batch, pump=args.pump,
            count_transitions=args.transitions))
    json.dump(out, sys.stdout)
    sys.stdout.write("\n")


if __name__ == "__main__":
    _main()
