"""Client-pack worker for the swarm soak (ISSUE 12).

One OS process hosting many REAL clients on a single asyncio loop —
`benches/swarm_bench.py` spawns several of these so tens of thousands of
TCP connections spread across process (and fd-budget) boundaries instead
of wedging one loop. Two modes:

- ``soak``: connect N clients, subscribe each to ``seed % topics``, and
  run a receive loop per client. Every broadcast payload carries a
  4-byte big-endian per-topic sequence number; the client library's own
  LIVE gap detector (``Client.gap_detector``, armed via
  ``ClientConfig.seq_extractor``) accounts every arrival as it lands —
  holes opened (``cdn_client_gap_events``), holes healed by late
  arrivals, duplicates — so the parent's wrap-up loss check reads the
  detector's residual instead of diffing delivery logs post-hoc
  (duplicates stay legal, at-least-once). Re-home latencies come from
  ``Client.rehome_ms``; ``--metrics-endpoint`` exposes the gap counters
  on a live /metrics scrape.

- ``storm``: a pool of M clients performs Q full reconnect cycles
  (marshal auth -> broker permit redemption over real TCP) as fast as
  the backoff policy allows — the >=10K reconnect storm. Reports
  attempts/sheds and connect-latency percentiles.

Protocol with the parent: JSON lines on stdout (``ready`` once every
client is connected, periodic ``stats``, ``mark``/``result`` replies);
single-word commands on stdin (``mark`` -> snapshot re-home + liveness
state, ``finish`` -> settle, close everything, emit ``result``, exit).
"""

from __future__ import annotations

import argparse
import asyncio
import json
import sys
import time
from typing import List, Optional

from pushcdn_tpu.client.client import Client, ClientConfig, backoff_delay
from pushcdn_tpu.proto import metrics as metrics_mod
from pushcdn_tpu.proto.crypto.signature import DEFAULT_SCHEME
from pushcdn_tpu.proto.error import Error, ErrorKind
from pushcdn_tpu.proto.message import Broadcast, Direct
from pushcdn_tpu.proto.transport import Tcp


def emit(event: str, **fields) -> None:
    print(json.dumps({"event": event, **fields}), flush=True)


def _pctile(sorted_vals: List[float], q: float) -> Optional[float]:
    if not sorted_vals:
        return None
    idx = min(len(sorted_vals) - 1, max(0, round(q * (len(sorted_vals) - 1))))
    return sorted_vals[idx]


def _seq(payload) -> int:
    return int.from_bytes(bytes(payload)[:4], "big")


def make_seq_extractor(topic: int):
    """The soak's ``ClientConfig.seq_extractor``: every Broadcast/Direct
    payload opens with a 4-byte big-endian per-topic sequence number;
    control frames carry no sequence."""
    def extract(m):
        if isinstance(m, (Broadcast, Direct)):
            return (topic, _seq(m.message))
        return None
    return extract


class SoakClient:
    """One subscriber: drains deliveries and rides out errors
    elastically. Loss accounting lives in the client library's LIVE
    gap detector — this wrapper only reads it out."""

    def __init__(self, client: Client, topic: int):
        self.client = client
        self.topic = topic
        self.hard_reconnects = 0    # non-migration connection losses

    @property
    def delivered(self) -> int:
        det = self.client.gap_detector
        return det.unique + det.duplicates

    @property
    def unique(self) -> int:
        return self.client.gap_detector.unique

    @property
    def gaps(self) -> int:
        """Residual loss as the live detector sees it RIGHT NOW —
        holes opened and never healed by a late arrival."""
        return self.client.gap_detector.open_gaps

    @property
    def reorders(self) -> int:
        """Healed holes: a frame arrived after a later one (legal for
        at-least-once delivery, but the soak's elastic invariant
        requires zero)."""
        return self.client.gap_detector.healed

    async def run(self, stop: asyncio.Event) -> None:
        while not stop.is_set():
            try:
                # the client's armed gap detector observes every
                # delivery inside receive_messages — nothing to do here
                await self.client.receive_messages()
            except asyncio.CancelledError:
                raise
            except Error:
                # broker loss outside a planned migration: the next
                # receive re-dials through the marshal (with backoff);
                # messages published meanwhile are legitimately missed,
                # so the parent treats hard_reconnects > 0 as tainting
                # the loss figure rather than a harness bug
                self.hard_reconnects += 1
                await asyncio.sleep(backoff_delay(0))
                continue


async def _read_commands(queue: "asyncio.Queue[str]") -> None:
    loop = asyncio.get_running_loop()
    while True:
        line = await loop.run_in_executor(None, sys.stdin.readline)
        if not line:
            await queue.put("finish")  # parent went away
            return
        cmd = line.strip()
        if cmd:
            await queue.put(cmd)
        if cmd == "finish":
            return


def _soak_snapshot(packs: List[SoakClient]) -> dict:
    rehome_ms = sorted(
        ms for p in packs for ms in p.client.rehome_ms)
    live = sum(1 for p in packs
               if p.client._connection is not None
               and not p.client._connection.is_closed)
    return {
        "clients": len(packs),
        "live": live,
        "rehomed": sum(1 for p in packs if p.client.rehome_ms),
        "delivered": sum(p.delivered for p in packs),
        "unique": sum(p.unique for p in packs),
        "gaps": sum(p.gaps for p in packs),
        "reorders": sum(p.reorders for p in packs),
        "hard_reconnects": sum(p.hard_reconnects for p in packs),
        "rehome_ms": rehome_ms,
        # process-wide live counters — the same numbers a /metrics
        # scrape of this worker shows (cdn_client_gap_*)
        "gap_events": metrics_mod.CLIENT_GAP_EVENTS.value,
        "gap_healed": metrics_mod.CLIENT_GAP_HEALED.value,
    }


async def run_soak(args) -> int:
    metrics_server = None
    if args.metrics_endpoint:
        metrics_server = await metrics_mod.serve_metrics(
            args.metrics_endpoint)
    packs: List[SoakClient] = []
    for i in range(args.clients):
        topic = i % args.topics
        client = Client(ClientConfig(
            marshal_endpoint=args.marshal_endpoint,
            keypair=DEFAULT_SCHEME.generate_keypair(seed=args.seed_base + i),
            protocol=Tcp,
            subscribed_topics={topic},
            seq_extractor=make_seq_extractor(topic),
        ))
        packs.append(SoakClient(client, topic))

    sem = asyncio.Semaphore(args.connect_concurrency)

    async def connect(p: SoakClient):
        async with sem:
            await p.client.ensure_initialized()

    await asyncio.gather(*(connect(p) for p in packs))
    emit("ready", clients=len(packs))

    stop = asyncio.Event()
    receivers = [asyncio.create_task(p.run(stop)) for p in packs]
    commands: asyncio.Queue = asyncio.Queue()
    reader = asyncio.create_task(_read_commands(commands))

    last_delivered = 0
    last_t = time.monotonic()
    try:
        while True:
            try:
                cmd = await asyncio.wait_for(commands.get(),
                                             args.report_every_s)
            except asyncio.TimeoutError:
                now = time.monotonic()
                delivered = sum(p.delivered for p in packs)
                emit("stats", delivered=delivered,
                     delivered_per_s=round(
                         (delivered - last_delivered) / (now - last_t), 1),
                     live=sum(1 for p in packs
                              if p.client._connection is not None
                              and not p.client._connection.is_closed))
                last_delivered, last_t = delivered, now
                continue
            if cmd == "mark":
                emit("mark", **_soak_snapshot(packs))
            elif cmd == "finish":
                break
    finally:
        reader.cancel()

    await asyncio.sleep(args.settle_s)   # let in-flight deliveries land
    stop.set()
    for t in receivers:
        t.cancel()
    await asyncio.gather(*receivers, return_exceptions=True)
    snap = _soak_snapshot(packs)
    for p in packs:
        p.client.close()
    if metrics_server is not None:
        metrics_server.close()
    emit("result", mode="soak", **snap)
    return 0


async def run_storm(args) -> int:
    """Q reconnect cycles over a pool of real users: every cycle is the
    full marshal-auth + broker-permit dance on a fresh TCP connection,
    retried under the production backoff policy when shed/refused."""
    clients = [Client(ClientConfig(
        marshal_endpoint=args.marshal_endpoint,
        keypair=DEFAULT_SCHEME.generate_keypair(seed=args.seed_base + i),
        protocol=Tcp,
    )) for i in range(args.clients)]

    established = 0
    attempts = 0
    sheds = 0
    conn_ms: List[float] = []
    quota = args.storm_connections
    next_cycle = 0
    lock = asyncio.Lock()
    t_start = time.monotonic()

    async def one_cycle(client: Client) -> None:
        nonlocal established, attempts, sheds
        attempt = 0
        while True:
            t0 = time.monotonic()
            attempts += 1
            try:
                async with asyncio.timeout(30.0):
                    conn = await client._connect_once()
            except asyncio.CancelledError:
                raise
            except Error as exc:
                if exc.kind == ErrorKind.SHED:
                    sheds += 1
                delay = backoff_delay(attempt,
                                      getattr(exc, "retry_after_s", None))
                attempt += 1
                await asyncio.sleep(delay)
                continue
            except Exception:
                attempt += 1
                await asyncio.sleep(backoff_delay(attempt))
                continue
            conn_ms.append((time.monotonic() - t0) * 1000.0)
            established += 1
            await asyncio.sleep(args.hold_ms / 1000.0)
            conn.close()
            return

    gate = asyncio.Semaphore(args.connect_concurrency)

    async def worker(client: Client) -> None:
        nonlocal next_cycle
        while True:
            async with lock:
                if next_cycle >= quota:
                    return
                next_cycle += 1
            # each pool client reconnects back-to-back, which IS the
            # storm; capping in-flight dials keeps the marshal queue
            # bounded the way real jittered backoff spreads arrivals
            async with gate:
                await one_cycle(client)
            if established % 500 == 0:
                emit("stats", established=established, attempts=attempts,
                     sheds=sheds)

    await asyncio.gather(*(asyncio.create_task(worker(c))
                           for c in clients))
    duration = time.monotonic() - t_start
    conn_ms.sort()
    emit("result", mode="storm", established=established, attempts=attempts,
         sheds=sheds, duration_s=round(duration, 2),
         conns_per_s=round(established / duration, 1) if duration else 0.0,
         conn_p50_ms=round(_pctile(conn_ms, 0.50) or 0.0, 2),
         conn_p99_ms=round(_pctile(conn_ms, 0.99) or 0.0, 2))
    for c in clients:
        c.close()
    return 0


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(prog="clientpack", description=__doc__)
    p.add_argument("--marshal-endpoint", required=True)
    p.add_argument("--mode", choices=("soak", "storm"), default="soak")
    p.add_argument("--clients", type=int, default=100)
    p.add_argument("--seed-base", type=int, required=True)
    p.add_argument("--topics", type=int, default=8)
    p.add_argument("--connect-concurrency", type=int, default=25)
    p.add_argument("--metrics-endpoint", default="",
                   help="soak mode: serve /metrics here so the live "
                        "cdn_client_gap_* counters are scrapeable")
    p.add_argument("--report-every-s", type=float, default=2.0)
    p.add_argument("--settle-s", type=float, default=2.0)
    p.add_argument("--storm-connections", type=int, default=1000,
                   help="storm mode: total reconnect cycles this worker "
                        "performs across its client pool")
    p.add_argument("--hold-ms", type=float, default=50.0,
                   help="storm mode: how long each established "
                        "connection is held before the next cycle")
    return p


def main() -> None:
    args = build_parser().parse_args()
    runner = run_soak if args.mode == "soak" else run_storm
    try:
        sys.exit(asyncio.run(runner(args)))
    except KeyboardInterrupt:
        pass


if __name__ == "__main__":
    main()
