"""View-driven consensus workload (ISSUE 11): the traffic shape Push-CDN
actually serves (PAPER.md — HotShot consensus), as a reusable driver for
benches and chaos tests.

Per view ``v``: the leader (``nodes[v % n]``) broadcasts a proposal on the
proposal topic; every node that receives it sends a vote Direct back to
the leader; the view *closes* when the leader holds a quorum of votes
(default ``2n//3 + 1``) and *times out* otherwise. This is the
view-synchronized burst + long-tail fan-in pattern: N-way broadcast out,
N-way direct in, latency gated by the slowest quorum member.

Geography rides the transport, not the driver: each node's client can use
a :func:`~pushcdn_tpu.proto.transport.memory.shaped_memory` protocol whose
latency follows a zipf tail (a few far/slow nodes, most near), so quorum
formation sees realistic stragglers while the driver stays pure logic.

Every message is traced (1-in-1 sampling) and tagged with its u32 view
number (:data:`~pushcdn_tpu.proto.message.TRACE_VIEW_FLAG`), so
``scripts/trace_report.py`` can aggregate per-view SLOs from the span log
the run leaves behind. Chaos is injected via the ``chaos`` hook map —
``{view: async callable}`` fired right after that view's proposal is
published, i.e. genuinely mid-view.
"""

from __future__ import annotations

import asyncio
import struct
import time
from dataclasses import dataclass, field
from typing import Awaitable, Callable, Dict, List, Optional

from pushcdn_tpu.proto import trace as trace_mod
from pushcdn_tpu.proto.error import Error, ErrorKind
from pushcdn_tpu.proto.message import Broadcast, Direct, Retained
from pushcdn_tpu.proto.transport.memory import (LinkShape, Memory,
                                                shaped_memory)

_U32 = struct.Struct("<I")
_VOTE = struct.Struct("<IH")  # (view, node_index)


@dataclass
class ConsensusConfig:
    """Knobs for one consensus-shaped run."""

    num_nodes: int = 4
    num_views: int = 10
    view_timeout_s: float = 5.0
    quorum: Optional[int] = None          # default 2n//3 + 1
    proposal_bytes: int = 256
    vote_bytes: int = 64
    topic: int = 0
    # zipf-tailed geography: node i's one-way latency is
    #   base_latency_s + tail_latency_s / (i + 1) ** zipf_alpha
    # (node 0 slowest; the tail decays zipf-like toward base). All zero →
    # plain unshaped Memory links.
    base_latency_s: float = 0.0
    tail_latency_s: float = 0.0
    zipf_alpha: float = 1.0
    jitter_s: float = 0.0
    loss: float = 0.0
    rto_s: float = 0.05
    seed: int = 0
    trace: bool = True                    # 1-in-1 sampled, view-tagged
    client_seed_base: int = 40_000

    def effective_quorum(self) -> int:
        q = self.quorum if self.quorum is not None else \
            (2 * self.num_nodes) // 3 + 1
        return min(q, self.num_nodes)

    def node_latency_s(self, i: int) -> float:
        if self.base_latency_s == 0.0 and self.tail_latency_s == 0.0:
            return 0.0
        return (self.base_latency_s
                + self.tail_latency_s / (i + 1) ** self.zipf_alpha)

    def node_protocol(self, i: int):
        lat = self.node_latency_s(i)
        if lat == 0.0 and self.jitter_s == 0.0 and self.loss == 0.0:
            return Memory
        return shaped_memory(LinkShape(
            latency_s=lat, jitter_s=self.jitter_s, loss=self.loss,
            rto_s=self.rto_s, seed=self.seed + i))


@dataclass
class ViewStat:
    view: int
    leader: int
    started_ns: int
    completed_ns: Optional[int] = None    # quorum reached at the leader
    votes: int = 0
    timed_out: bool = False

    @property
    def completion_s(self) -> Optional[float]:
        if self.completed_ns is None:
            return None
        return (self.completed_ns - self.started_ns) / 1e9


@dataclass
class ConsensusRun:
    """Everything a bench row or an SLO gate needs from one run."""

    views: List[ViewStat] = field(default_factory=list)
    proposal_delivery_s: List[float] = field(default_factory=list)
    vote_delivery_s: List[float] = field(default_factory=list)
    proposals_sent: int = 0
    votes_sent: int = 0
    sheds: int = 0
    replayed_proposals: int = 0   # Retained catch-up frames (ISSUE 14)

    @property
    def completed(self) -> int:
        return sum(1 for v in self.views if v.completed_ns is not None)

    @property
    def timeouts(self) -> int:
        return sum(1 for v in self.views if v.timed_out)

    def completion_percentiles(self) -> Dict[str, Optional[float]]:
        samples = sorted(v.completion_s for v in self.views
                         if v.completion_s is not None)
        return {"p50": percentile(samples, 0.50),
                "p95": percentile(samples, 0.95),
                "p99": percentile(samples, 0.99)}

    def delivery_percentiles(self) -> Dict[str, Optional[float]]:
        samples = sorted(self.proposal_delivery_s + self.vote_delivery_s)
        return {"p50": percentile(samples, 0.50),
                "p95": percentile(samples, 0.95),
                "p99": percentile(samples, 0.99)}


def percentile(sorted_samples: List[float], q: float) -> Optional[float]:
    if not sorted_samples:
        return None
    idx = max(0, min(len(sorted_samples) - 1,
                     int(q * len(sorted_samples) + 0.5) - 1))
    return sorted_samples[idx]


def encode_proposal(view: int, size: int) -> bytes:
    body = b"P" + _U32.pack(view)
    return body + b"\x00" * max(0, size - len(body))


def encode_vote(view: int, node: int, size: int) -> bytes:
    body = b"V" + _VOTE.pack(view, node)
    return body + b"\x00" * max(0, size - len(body))


ChaosHook = Callable[[int], Awaitable[None]]


class ConsensusDriver:
    """Runs the view loop over a :class:`~pushcdn_tpu.testing.cluster.
    Cluster`'s clients. One driver = one run; call :meth:`start`, then
    :meth:`run`, then :meth:`stop` (or use :func:`run_consensus`)."""

    def __init__(self, cluster, config: ConsensusConfig,
                 chaos: Optional[Dict[int, ChaosHook]] = None):
        self.cluster = cluster
        self.cfg = config
        self.chaos = chaos or {}
        self.result = ConsensusRun()
        self.clients = []
        self._loops: List[asyncio.Task] = []
        self._votes: Dict[int, set] = {}
        self._quorum_events: Dict[int, asyncio.Event] = {}
        self._view_sent_ns: Dict[int, int] = {}
        self._stopping = False
        # node index -> highest view whose proposal the node has seen
        # LIVE (replay_catchup chaos drops a node only once it has voted
        # the current view, so the drop never orphans a traced frame)
        self.last_view_seen: Dict[int, int] = {}

    # -- lifecycle ------------------------------------------------------

    async def start(self) -> "ConsensusDriver":
        cfg = self.cfg
        for i in range(cfg.num_nodes):
            c = self.cluster.client(seed=cfg.client_seed_base + i,
                                    topics=[cfg.topic],
                                    protocol=cfg.node_protocol(i))
            if cfg.trace:
                c._sampler.every = 1    # trace every consensus message
            else:
                c._sampler.every = 0
            await c.ensure_initialized()
            self.clients.append(c)
        # the subscribe rides the handshake; wait until every broker sees
        # its share of users before the first proposal flies
        from pushcdn_tpu.testing.cluster import wait_until
        await wait_until(
            lambda: sum(b.connections.num_users
                        for b in self.cluster.brokers) >= cfg.num_nodes,
            timeout=15.0)
        for i, c in enumerate(self.clients):
            self._loops.append(asyncio.ensure_future(self._node_loop(i, c)))
        return self

    async def stop(self) -> None:
        self._stopping = True
        for t in self._loops:
            t.cancel()
        for t in self._loops:
            try:
                await t
            except (asyncio.CancelledError, Exception):
                pass
        for c in self.clients:
            c.close()

    async def drop_node(self, i: int) -> None:
        """Hard-drop node ``i`` mid-run (replay_catchup chaos): cancel
        its loop and close its client — no elastic re-dial. The node
        stops receiving and voting until :meth:`rejoin_node`."""
        t = self._loops[i]
        t.cancel()
        try:
            await t
        except (asyncio.CancelledError, Exception):
            pass
        self.clients[i].close()

    async def rejoin_node(self, i: int, from_seq: int = 1) -> None:
        """Re-home node ``i`` on a FRESH client and catch it up through
        the durable replay path (ISSUE 14): ``subscribe_from(topic,
        from_seq)`` replays every retained proposal as ``Retained``
        frames, then live delivery splices in gap-free — so a view in
        flight at rejoin time can still reach quorum on the rejoined
        nodes' replayed votes. Requires the serving broker to retain
        ``cfg.topic`` (``PUSHCDN_RETAIN_TOPICS``)."""
        cfg = self.cfg
        c = self.cluster.client(seed=cfg.client_seed_base + i, topics=[],
                                protocol=cfg.node_protocol(i))
        c._sampler.every = 1 if cfg.trace else 0
        await c.ensure_initialized()
        await c.subscribe_from(cfg.topic, from_seq)
        self.clients[i] = c
        self._loops[i] = asyncio.ensure_future(self._node_loop(i, c))

    # -- the view loop --------------------------------------------------

    def leader_of(self, view: int) -> int:
        return view % self.cfg.num_nodes

    async def run(self) -> ConsensusRun:
        for v in range(self.cfg.num_views):
            await self._run_view(v)
        return self.result

    async def _run_view(self, view: int) -> None:
        cfg = self.cfg
        leader_idx = self.leader_of(view)
        leader = self.clients[leader_idx]
        self._votes[view] = set()
        event = self._quorum_events[view] = asyncio.Event()
        stat = ViewStat(view=view, leader=leader_idx,
                        started_ns=time.time_ns())
        self.result.views.append(stat)

        # view-tag every message this view produces (sequential views:
        # the samplers are only touched from this loop and the node loops
        # reacting to THIS view's proposal)
        for c in self.clients:
            c._sampler.view = view

        self._view_sent_ns[view] = time.time_ns()
        await leader.send_broadcast_message(
            [cfg.topic], encode_proposal(view, cfg.proposal_bytes))
        self.result.proposals_sent += 1

        hook = self.chaos.get(view)
        if hook is not None:
            await hook(view)            # chaos lands mid-view

        try:
            await asyncio.wait_for(event.wait(), cfg.view_timeout_s)
            stat.completed_ns = time.time_ns()
        except asyncio.TimeoutError:
            stat.timed_out = True
        stat.votes = len(self._votes[view])

    # -- node behavior --------------------------------------------------

    async def _node_loop(self, idx: int, client) -> None:
        cfg = self.cfg
        while not self._stopping:
            try:
                msgs = await client.receive_messages()
            except asyncio.CancelledError:
                raise
            except Error as exc:
                if exc.kind == ErrorKind.SHED:
                    self.result.sheds += 1
                    continue
                if self._stopping:
                    return
                continue            # elastic client re-dials on next call
            except Exception:
                if self._stopping:
                    return
                continue
            now = time.time_ns()
            for m in msgs:
                body = m.payload if isinstance(m, Retained) else m.message
                data = bytes(body) if body is not None else b""
                if isinstance(m, (Broadcast, Retained)) and \
                        data[:1] == b"P":
                    (view,) = _U32.unpack_from(data, 1)
                    if isinstance(m, Retained):
                        # replayed catch-up: vote (a view in flight at
                        # rejoin completes on these), but keep the live
                        # delivery SLO samples honest
                        self.result.replayed_proposals += 1
                    else:
                        sent = self._view_sent_ns.get(view)
                        if sent is not None:
                            self.result.proposal_delivery_s.append(
                                (now - sent) / 1e9)
                        self.last_view_seen[idx] = max(
                            view, self.last_view_seen.get(idx, -1))
                    await self._send_vote(idx, client, view)
                elif isinstance(m, Direct) and data[:1] == b"V":
                    view, node = _VOTE.unpack_from(data, 1)
                    sent = self._view_sent_ns.get(view)
                    if sent is not None:
                        self.result.vote_delivery_s.append(
                            (now - sent) / 1e9)
                    votes = self._votes.get(view)
                    if votes is None:
                        continue
                    votes.add(node)
                    if (len(votes) >= cfg.effective_quorum()
                            and view in self._quorum_events):
                        self._quorum_events[view].set()

    async def _send_vote(self, idx: int, client, view: int) -> None:
        cfg = self.cfg
        leader = self.clients[self.leader_of(view)]
        client._sampler.view = view
        try:
            await client.send_direct_message(
                leader.public_key, encode_vote(view, idx, cfg.vote_bytes))
            self.result.votes_sent += 1
        except Error as exc:
            if exc.kind == ErrorKind.SHED:
                self.result.sheds += 1
            # any other send error: the elastic client already tore the
            # connection down; the vote for this view is simply lost
            # (that IS the consensus failure mode chaos is probing)


async def run_consensus(cluster, config: ConsensusConfig,
                        chaos: Optional[Dict[int, ChaosHook]] = None,
                        drain_s: float = 2.0,
                        driver_chaos=None) -> ConsensusRun:
    """start → run → drain → stop, returning the run stats. The drain
    waits (bounded) for in-flight traced messages to finish delivering so
    the span log closes every chain — ``trace_report --strict``'s
    zero-orphan gate needs quiescence, not a mid-flight teardown.

    ``driver_chaos`` is the driver-aware twin of ``chaos``: a factory
    ``fn(driver) -> {view: hook}`` for chaos that manipulates the nodes
    themselves (drop/rejoin) rather than the cluster."""
    driver = ConsensusDriver(cluster, config, chaos=chaos)
    if driver_chaos is not None:
        driver.chaos = dict(driver.chaos)
        driver.chaos.update(driver_chaos(driver))
    await driver.start()
    try:
        result = await driver.run()
        deadline = asyncio.get_running_loop().time() + drain_s
        want_proposals = result.proposals_sent * config.num_nodes
        while asyncio.get_running_loop().time() < deadline:
            # every delivered proposal triggers exactly one vote, so
            # quiescence = all proposals landed AND votes caught up
            if (len(result.proposal_delivery_s) >= want_proposals
                    and len(result.vote_delivery_s)
                    >= len(result.proposal_delivery_s)):
                break
            await asyncio.sleep(0.02)
        return result
    finally:
        await driver.stop()
