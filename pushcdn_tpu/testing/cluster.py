"""Marshal + N brokers + shared discovery, all in one process.

Parity with the reference's ``tests`` crate fixture
(tests/src/tests/mod.rs:62-143): the Memory protocol's global listener
registry stands in for the network and a shared SQLite file stands in for
KeyDB, so multi-node behavior runs on a laptop with no cluster
(SURVEY.md §4 tier 3). Load steering mirrors double_connect.rs:100-121.
"""

from __future__ import annotations

import asyncio
import itertools
import os
import tempfile
from typing import Optional, Type

from pushcdn_tpu.broker.broker import Broker, BrokerConfig
from pushcdn_tpu.broker.tasks.heartbeat import heartbeat_once
from pushcdn_tpu.client import Client, ClientConfig
from pushcdn_tpu.marshal import Marshal, MarshalConfig
from pushcdn_tpu.proto.crypto.signature import DEFAULT_SCHEME, SignatureScheme
from pushcdn_tpu.proto.def_ import testing_run_def as make_testing_run_def
from pushcdn_tpu.proto.discovery.base import BrokerIdentifier
from pushcdn_tpu.proto.discovery.embedded import Embedded
from pushcdn_tpu.proto.topic import TopicSpace
from pushcdn_tpu.proto.transport.memory import Memory

_UNIQUE = itertools.count()


async def wait_until(predicate, timeout: float = 5.0, interval: float = 0.02):
    """Poll until ``predicate()`` is truthy (handshake completion on the
    broker side lags the client's return by a few event-loop ticks)."""
    deadline = asyncio.get_running_loop().time() + timeout
    while True:
        if predicate():
            return
        if asyncio.get_running_loop().time() > deadline:
            raise AssertionError(f"condition never became true: {predicate}")
        await asyncio.sleep(interval)


async def wait_mesh_interest(cluster: "Cluster", topic: int, links: int,
                             timeout: float = 60.0):
    """Wait until every broker holds ``links`` mesh links AND sees all of
    them as interested in ``topic`` (full interest propagation). Messages
    sent before a link exists are simply not forwarded (sender.rs
    failure-is-removal semantics), and BLS broker↔broker auth takes
    hundreds of ms — so tests and benches must wait explicitly, never
    sleep."""
    await wait_until(
        lambda: all(b.connections.num_brokers == links
                    for b in cluster.brokers), timeout)
    await wait_until(
        lambda: all(
            len(b.connections.get_interested_by_topic([topic], False)[1])
            == links
            for b in cluster.brokers), timeout)


class Cluster:
    """Marshal + N brokers + shared discovery, all in-process."""

    def __init__(self, num_brokers: int = 1, device_plane=None,
                 scheme: Type[SignatureScheme] = DEFAULT_SCHEME,
                 topics: Optional[TopicSpace] = None):
        self.uid = next(_UNIQUE)
        self.num_brokers = num_brokers
        self.device_plane = device_plane
        self.scheme = scheme
        self.db = os.path.join(tempfile.mkdtemp(prefix="pushcdn-it-"),
                               "discovery.sqlite")
        self.run_def = make_testing_run_def(scheme=scheme, topics=topics)
        self.broker_keypair = scheme.generate_keypair(seed=10_000 + self.uid)
        self.brokers: list[Broker] = []
        self.marshal: Marshal = None

    def broker_endpoints(self, i: int):
        return (f"it{self.uid}-b{i}-pub", f"it{self.uid}-b{i}-priv")

    @property
    def marshal_endpoint(self) -> str:
        return f"it{self.uid}-marshal"

    async def start(self):
        for i in range(self.num_brokers):
            pub, priv = self.broker_endpoints(i)
            broker = await Broker.new(BrokerConfig(
                run_def=self.run_def,
                keypair=self.broker_keypair,  # one deployment key (same-key check)
                discovery_endpoint=self.db,
                public_advertise_endpoint=pub, public_bind_endpoint=pub,
                private_advertise_endpoint=priv, private_bind_endpoint=priv,
                # deterministic: we drive heartbeats/syncs manually
                heartbeat_interval_s=3600, sync_interval_s=3600,
                whitelist_interval_s=3600,
                device_plane=self.device_plane,
            ))
            await broker.start()
            self.brokers.append(broker)
        # two heartbeat rounds: all register, then dial each other
        for b in self.brokers:
            await heartbeat_once(b)
        for b in self.brokers:
            await heartbeat_once(b)
        await asyncio.sleep(0.1)  # let mesh links finish auth + full sync

        self.marshal = await Marshal.new(MarshalConfig(
            run_def=self.run_def,
            discovery_endpoint=self.db,
            bind_endpoint=self.marshal_endpoint,
        ))
        await self.marshal.start()
        return self

    def client(self, seed: int, topics=(), protocol: Type = Memory) -> Client:
        """``protocol`` lets a caller shape this client's link (e.g.
        ``shaped_memory(LinkShape(...))`` for geo-shaped consensus nodes);
        the default is the plain in-process transport."""
        return Client(ClientConfig(
            marshal_endpoint=self.marshal_endpoint,
            keypair=self.scheme.generate_keypair(seed=seed),
            protocol=protocol,
            scheme=self.scheme,
            subscribed_topics=set(topics),
        ))

    async def restart_broker(self, broker_index: int) -> "Broker":
        """Start a replacement broker under broker_index's identity
        (same endpoints + deployment keypair, same config shape as
        ``start`` — single source of truth for restart tests). The old
        instance must already be stopped."""
        pub, priv = self.broker_endpoints(broker_index)
        broker = await Broker.new(BrokerConfig(
            run_def=self.run_def,
            keypair=self.broker_keypair,
            discovery_endpoint=self.db,
            public_advertise_endpoint=pub, public_bind_endpoint=pub,
            private_advertise_endpoint=priv, private_bind_endpoint=priv,
            heartbeat_interval_s=3600, sync_interval_s=3600,
            whitelist_interval_s=3600,
            device_plane=self.device_plane,
        ))
        await broker.start()
        self.brokers[broker_index] = broker
        return broker

    async def restart_marshal(self) -> "Marshal":
        """Start a replacement marshal on the same endpoint (chaos tests:
        marshal loss mid-view). The old instance must already be
        stopped."""
        self.marshal = await Marshal.new(MarshalConfig(
            run_def=self.run_def,
            discovery_endpoint=self.db,
            bind_endpoint=self.marshal_endpoint,
        ))
        await self.marshal.start()
        return self.marshal

    async def steer_load(self, broker_index: int, load: int):
        """Fake a broker's advertised load to steer marshal placement
        (parity double_connect.rs:100-121)."""
        pub, priv = self.broker_endpoints(broker_index)
        handle = await Embedded.new(self.db,
                                    identity=BrokerIdentifier(pub, priv))
        await handle.perform_heartbeat(load, 60.0)
        await handle.close()

    async def place_on(self, broker_index: int):
        """Steer the next client onto one broker: everyone else looks busy."""
        for i in range(self.num_brokers):
            await self.steer_load(i, 0 if i == broker_index else 10_000)

    async def stop(self):
        if self.marshal:
            await self.marshal.stop()
        for b in self.brokers:
            await b.stop()
