"""In-process sharded-broker harness (ISSUE 6 equivalence suite).

Builds N real ``Broker`` instances on ONE event loop wired as worker
shards of a single broker identity: real shared-memory handoff rings +
notify sockets (``broker.shardring``), a ``LocalBus`` control plane
(synchronous total-order delta relay — the in-process stand-in for the
parent hub), users injected per shard exactly like
``broker.test_harness.TestDefinition`` injects them into one broker.

The suite's contract: a 1-shard run and an N-shard run fed the same
seeded frame mix produce identical per-peer delivery SEQUENCES and leave
the byte pools balanced — the cross-shard handoff must be semantically
invisible.
"""

from __future__ import annotations

import asyncio
import itertools
import os
import tempfile
from dataclasses import dataclass, field
from typing import List, Sequence, Tuple

from pushcdn_tpu.broker.broker import Broker, BrokerConfig
from pushcdn_tpu.broker.connections import SubscriptionStatus
from pushcdn_tpu.broker.sharding import (
    attach_inprocess_shards,
    detach_inprocess_shards,
)
from pushcdn_tpu.broker.tasks.handlers import (
    broker_receive_loop,
    user_receive_loop,
)
from pushcdn_tpu.broker.test_harness import TestBroker, TestUser
from pushcdn_tpu.broker.versioned_map import VersionedMap
from pushcdn_tpu.proto.crypto.signature import DEFAULT_SCHEME
from pushcdn_tpu.proto.def_ import testing_run_def
from pushcdn_tpu.proto.transport.memory import gen_testing_connection_pair
from pushcdn_tpu.proto.util import AbortOnDropHandle

_UNIQUE = itertools.count()


@dataclass
class ShardTestRun:
    __test__ = False
    brokers: List[Broker]
    runtimes: list
    # user index -> (TestUser, owning shard); indices follow the flattened
    # construction order so tests can mirror a 1-shard TestDefinition
    connected_users: List[Tuple[TestUser, int]] = field(default_factory=list)
    connected_brokers: List[TestBroker] = field(default_factory=list)
    tcp_listeners: list = field(default_factory=list)  # set by tcp_users

    def user(self, i: int) -> TestUser:
        return self.connected_users[i][0]

    def user_shard(self, i: int) -> int:
        return self.connected_users[i][1]

    def peer(self, j: int) -> TestBroker:
        return self.connected_brokers[j]

    async def settle(self, ticks: int = 20) -> None:
        """Let ring drains / relayed deltas / writer flushes run."""
        for _ in range(ticks):
            await asyncio.sleep(0)
        await asyncio.sleep(0.02)

    async def shutdown(self) -> None:
        for u, _shard in self.connected_users:
            u.remote.close()
        for b in self.connected_brokers:
            b.remote.close()
        for listener in self.tcp_listeners:
            await listener.close()
        for broker in self.brokers:
            await broker.stop()
        detach_inprocess_shards(self.runtimes)


async def run_sharded(
        user_shards: Sequence[Tuple[int, Sequence[int]]],
        num_shards: int = 2,
        connected_brokers: Sequence[Tuple[Sequence[int],
                                          Sequence[bytes]]] = (),
        ring_bytes: int = 256 * 1024,
        tcp_users: bool = False,
        topics=None,
        pool_bytes: int | None = None) -> ShardTestRun:
    """Build the sharded twin of a ``TestDefinition`` run.

    ``user_shards[i] = (shard, topics)`` places injected user i (key
    ``user-<i>``, same naming as the 1-shard harness) on that worker;
    mesh peer brokers always attach to shard 0 (the link owner).
    ``tcp_users`` routes the user links over real loopback TCP (one
    listener per shard) — the io-impl (asyncio vs io_uring) A/B seam,
    mirroring ``TestDefinition.tcp_users``."""
    uid = next(_UNIQUE)
    brokers: List[Broker] = []
    pool_kw = ({"global_memory_pool_size": pool_bytes}
               if pool_bytes is not None else {})
    for s in range(num_shards):
        db = os.path.join(tempfile.mkdtemp(prefix="pushcdn-shardtest-"),
                          "discovery.sqlite")
        config = BrokerConfig(
            run_def=testing_run_def(topics=topics),
            keypair=DEFAULT_SCHEME.generate_keypair(seed=uid),
            discovery_endpoint=db,
            # ONE identity across all shards; distinct bind endpoints so
            # the Memory registry accepts every worker's listeners
            public_advertise_endpoint=f"shardtest-pub-{uid}",
            public_bind_endpoint=f"shardtest-pub-{uid}-s{s}",
            private_advertise_endpoint=f"shardtest-priv-{uid}",
            private_bind_endpoint=f"shardtest-priv-{uid}-s{s}",
            heartbeat_interval_s=3600, sync_interval_s=3600,
            whitelist_interval_s=3600,
            shard_index=s, num_shards=num_shards,
            **pool_kw,
        )
        brokers.append(await Broker.new(config))
    runtimes = attach_inprocess_shards(brokers, ring_bytes=ring_bytes)
    for rt in runtimes:
        rt.attach()
    for broker in brokers:
        await broker.start()
    run = ShardTestRun(brokers=brokers, runtimes=runtimes)

    listeners = {}
    if tcp_users:
        from pushcdn_tpu.proto.transport.tcp import Tcp
    for i, (shard, topics) in enumerate(user_shards):
        key = f"user-{i}".encode()
        broker = brokers[shard]
        if tcp_users:
            listener = listeners.get(shard)
            if listener is None:
                listener = await Tcp.bind("127.0.0.1:0")
                listeners[shard] = listener
                run.tcp_listeners.append(listener)
            accept_t = asyncio.create_task(listener.accept())
            remote = await Tcp.connect(f"127.0.0.1:{listener.bound_port}",
                                       limiter=broker.limiter)
            local = await (await accept_t).finalize(broker.limiter)
        else:
            local, remote = await gen_testing_connection_pair(broker.limiter)
        task = asyncio.create_task(user_receive_loop(broker, key, local))
        broker.connections.add_user(key, local, list(topics),
                                    AbortOnDropHandle(task))
        run.connected_users.append((TestUser(key, remote), shard))

    shard0 = brokers[0]
    for j, (topics, owned_users) in enumerate(connected_brokers):
        ident = f"testbrokerpub-{j}:0/testbrokerpriv-{j}:0"
        local, remote = await gen_testing_connection_pair(shard0.limiter)
        task = asyncio.create_task(
            broker_receive_loop(shard0, ident, local))
        shard0.connections.add_broker(ident, local,
                                      AbortOnDropHandle(task))
        if topics:
            m = VersionedMap(local_identity=ident)
            for t in topics:
                m.insert(int(t), int(SubscriptionStatus.SUBSCRIBED))
            shard0.connections.apply_topic_sync(
                ident, VersionedMap.serialize_entries(m.full()))
        if owned_users:
            m = VersionedMap(local_identity=ident)
            for u in owned_users:
                m.insert(bytes(u), ident)
            shard0.connections.apply_user_sync(
                VersionedMap.serialize_entries(m.full()))
        run.connected_brokers.append(TestBroker(ident, remote))
    await run.settle()
    return run
