"""Shared machinery for the two-OS-process deployment harnesses.

Three subprocess workers (tests/_multihost_worker.py,
tests/_multihost_kill_worker.py, benches/_straggler_worker.py) drive the
same deployment shape — jax.distributed runtime, global 8-shard mesh,
one TCP broker attached to a local shard, a stateless marshal pinned to
that broker, one authenticated TCP client — and their parents share one
spawn/collect harness. Both halves live here so a deployment-shape
change lands once (the copies had already drifted on ring/frame sizes
before this extraction).

Import ONLY after ``jax.distributed.initialize`` has run in the worker
process (the mesh helpers read the initialized process topology).
"""

from __future__ import annotations

import asyncio
import os
import subprocess
import sys
from dataclasses import dataclass
from typing import List, Optional

from pushcdn_tpu.broker.broker import Broker, BrokerConfig
from pushcdn_tpu.broker.mesh_group import MeshGroupConfig
from pushcdn_tpu.broker.multihost_group import MultiHostBrokerGroup
from pushcdn_tpu.client import Client, ClientConfig
from pushcdn_tpu.marshal import Marshal, MarshalConfig
from pushcdn_tpu.parallel.multihost import (
    local_shard_indices,
    pod_broker_mesh,
)
from pushcdn_tpu.proto.crypto.signature import DEFAULT_SCHEME
from pushcdn_tpu.proto.def_ import testing_run_def
from pushcdn_tpu.proto.discovery.base import BrokerIdentifier
from pushcdn_tpu.proto.discovery.embedded import Embedded
from pushcdn_tpu.proto.transport import Tcp

N_SHARDS = 8


@dataclass
class TwoHostNode:
    """One process's slice of the two-host deployment."""

    rank: int
    my_shard: int
    ident: BrokerIdentifier
    group: MultiHostBrokerGroup
    broker: Broker
    marshal: Marshal
    client: Client

    async def directory_rendezvous(self, want: int = 2,
                                   timeout_s: float = 20.0) -> None:
        """Wait until the user-slot directory shows ``want`` clients —
        the standard phase barrier between the two processes."""
        for _ in range(int(timeout_s / 0.1)):
            if len(await self.group.discovery.get_user_slots()) >= want:
                return
            await asyncio.sleep(0.1)
        raise AssertionError("user-slot directory never converged")

    async def publish_marker(self, marker: bytes) -> None:
        await self.group.discovery.publish_user_slots({marker: (0, 0.0)}, 60)

    async def await_markers(self, markers: List[bytes],
                            timeout_s: float = 20.0) -> None:
        for _ in range(int(timeout_s / 0.1)):
            slots = await self.group.discovery.get_user_slots()
            if all(m in slots for m in markers):
                return
            await asyncio.sleep(0.1)
        raise AssertionError(f"markers {markers} never all appeared")


async def make_two_host_node(rank: int, base: int, db: str, *,
                             client_seeds: List[int],
                             broker_seed_base: int,
                             mesh_config: Optional[MeshGroupConfig] = None,
                             directory_refresh_s: float = 0.3,
                             collective_timeout_s: float = 20.0,
                             ) -> TwoHostNode:
    """Build this process's half of the deployment and authenticate its
    client. Port layout (relative to ``base``): marshal at base+1+rank,
    broker public/private at base+10+10*rank / +1."""
    mesh = pod_broker_mesh(N_SHARDS)
    my_shard = local_shard_indices(mesh)[0]

    rd = testing_run_def(broker_protocol=Tcp, user_protocol=Tcp)
    group = MultiHostBrokerGroup(
        mesh,
        mesh_config or MeshGroupConfig(
            num_user_slots=64, ring_slots=8, frame_bytes=1024,
            extra_lanes=(), direct_bucket_slots=4, batch_window_s=0.05),
        discovery=await Embedded.new(db),
        directory_refresh_s=directory_refresh_s,
        collective_timeout_s=collective_timeout_s)

    broker_pub = base + 10 + 10 * rank
    ident = BrokerIdentifier(f"127.0.0.1:{broker_pub}",
                             f"127.0.0.1:{broker_pub + 1}")
    broker = await Broker.new(BrokerConfig(
        run_def=rd,
        keypair=DEFAULT_SCHEME.generate_keypair(
            seed=broker_seed_base + rank),
        discovery_endpoint=db,
        public_advertise_endpoint=ident.public_advertise_endpoint,
        public_bind_endpoint=f"127.0.0.1:{broker_pub}",
        private_advertise_endpoint=ident.private_advertise_endpoint,
        private_bind_endpoint=f"127.0.0.1:{broker_pub + 1}",
        heartbeat_interval_s=0.5, sync_interval_s=3600,
        whitelist_interval_s=3600, form_mesh=False))
    group.attach(broker, my_shard)
    await broker.start()

    marshal_port = base + 1 + rank
    marshal = await Marshal.new(MarshalConfig(
        run_def=rd, discovery_endpoint=db,
        bind_endpoint=f"127.0.0.1:{marshal_port}"))
    await marshal.start()

    # pin placement: THIS host's marshal always assigns THIS host's
    # broker (production load-balances; the harness needs the
    # cross-host topology)
    async def pinned():
        return ident
    marshal.discovery.get_with_least_connections = pinned

    client = Client(ClientConfig(
        marshal_endpoint=f"127.0.0.1:{marshal_port}",
        keypair=DEFAULT_SCHEME.generate_keypair(seed=client_seeds[rank]),
        protocol=Tcp, subscribed_topics={0}))
    await client.ensure_initialized()
    for _ in range(100):
        if broker.connections.num_users == 1:
            break
        await asyncio.sleep(0.05)
    assert broker.connections.num_users == 1

    return TwoHostNode(rank=rank, my_shard=my_shard, ident=ident,
                       group=group, broker=broker, marshal=marshal,
                       client=client)


def spawn_worker_pair(worker_path: str, extra_args: List[str],
                      cwd: Optional[str] = None, pipe: bool = True,
                      log_dir: Optional[str] = None):
    """Parent-side harness: pick a free coordinator port, spawn the two
    ranked worker processes with a jax-clean env, and return
    ``(procs, base_port)``. Callers own communicate()/asserts.
    ``log_dir`` redirects each worker to ``rank<N>.log`` there instead
    of a pipe (full output survives even when a worker is killed)."""
    import socket
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        base = s.getsockname()[1]
    env = {k: v for k, v in os.environ.items()
           if k not in ("JAX_PLATFORMS", "XLA_FLAGS")}
    procs = []
    for rank in (0, 1):
        logf = None
        if log_dir is not None:
            logf = open(os.path.join(log_dir, f"rank{rank}.log"), "w")
            out = logf
        elif pipe:
            out = subprocess.PIPE
        else:
            out = None
        procs.append(subprocess.Popen(
            [sys.executable, worker_path, str(rank), str(base),
             *extra_args],
            env=env, cwd=cwd, stdout=out,
            stderr=subprocess.STDOUT if out is not None else None,
            text=True))
        if logf is not None:
            logf.close()  # the child holds its own fd now
    return procs, base
