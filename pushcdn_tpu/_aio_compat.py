"""Runtime compatibility gates for older interpreters.

The stack targets Python >= 3.11 (``asyncio.timeout`` everywhere); some
deployment images still ship 3.10. Rather than thread a wrapper through
every call site, importing :mod:`pushcdn_tpu` installs a backport into
the ``asyncio`` module when the attribute is missing — the same
cancel-the-current-task design as the stdlib version (and the
``async-timeout`` package).

Deliberate tradeoff: this mutates the process-global stdlib namespace on
3.10 images, where ``hasattr(asyncio, "timeout")`` feature detection by
ANY library in the process will now find the backport. To keep that
surface honest the backport implements the full 3.11 ``Timeout`` API
(``when``/``reschedule``/``expired``), not just the context manager.
The one unfixable 3.10 gap is ``Task.uncancel`` accounting: an external
cancellation landing in the same event-loop tick as the expiry is
indistinguishable from it. On >= 3.11 images this module is a no-op.
"""

from __future__ import annotations

import asyncio
from typing import Optional


class _TimeoutBackport:
    __slots__ = ("_when", "_task", "_handle", "_expired", "_entered")

    def __init__(self, delay: Optional[float]):
        self._task = None
        self._handle = None
        self._expired = False
        self._entered = False
        self._when = None if delay is None else delay  # resolved on enter

    def when(self) -> Optional[float]:
        return self._when

    def expired(self) -> bool:
        return self._expired

    def reschedule(self, when: Optional[float]) -> None:
        """``when`` is an absolute loop time, per the 3.11 API."""
        self._when = when
        if self._handle is not None:
            self._handle.cancel()
            self._handle = None
        if self._entered and when is not None:
            self._handle = asyncio.get_running_loop().call_at(
                when, self._on_timeout)

    async def __aenter__(self):
        self._task = asyncio.current_task()
        self._entered = True
        delay = self._when
        if delay is not None:
            loop = asyncio.get_running_loop()
            self._when = loop.time() + delay  # absolute, 3.11 semantics
            self._handle = loop.call_at(self._when, self._on_timeout)
        return self

    def _on_timeout(self):
        self._expired = True
        if self._task is not None:
            self._task.cancel()

    async def __aexit__(self, exc_type, exc, tb):
        if self._handle is not None:
            self._handle.cancel()
            self._handle = None
        if self._expired and exc_type is asyncio.CancelledError:
            raise asyncio.TimeoutError() from exc
        return False


def install() -> None:
    if not hasattr(asyncio, "timeout"):
        asyncio.timeout = lambda delay: _TimeoutBackport(delay)
