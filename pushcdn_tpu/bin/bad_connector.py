"""Chaos: connection storm (parity cdn-client/src/binaries/bad-connector.rs:32-73
— a FRESH identity authenticates through the marshal every 200 ms,
hammering permit issuance and broker accept paths)."""

from __future__ import annotations

import argparse
import asyncio
import itertools
import logging
import secrets

from pushcdn_tpu.bin.common import init_logging, transport_by_name
from pushcdn_tpu.client import Client, ClientConfig
from pushcdn_tpu.proto.crypto.signature import DEFAULT_SCHEME

logger = logging.getLogger("pushcdn.bad-connector")


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(prog="pushcdn-bad-connector", description=__doc__)
    p.add_argument("--marshal-endpoint", required=True)
    p.add_argument("--transport", default="tcp")
    p.add_argument("--connect-interval", type=float, default=0.2,
                   help="seconds between fresh connections (parity 200 ms)")
    p.add_argument("--cycles", type=int, default=0, help="0 = forever")
    p.add_argument("-v", "--verbose", action="count", default=0)
    return p


async def amain(args: argparse.Namespace) -> None:
    protocol = transport_by_name(args.transport)
    for n in itertools.count():
        if args.cycles and n >= args.cycles:
            break
        client = Client(ClientConfig(
            marshal_endpoint=args.marshal_endpoint,
            keypair=DEFAULT_SCHEME.generate_keypair(
                seed=secrets.randbits(48)),
            protocol=protocol, subscribed_topics={0},
        ))
        try:
            await asyncio.wait_for(client.ensure_initialized(), 10)
            await client.send_direct_message(client.public_key, b"storm")
            logger.info("storm %d: fresh identity connected", n)
        except Exception as exc:
            logger.warning("storm %d failed: %r", n, exc)
        finally:
            client.close()
        await asyncio.sleep(args.connect_interval)


def main() -> None:
    args = build_parser().parse_args()
    init_logging(args.verbose)
    try:
        asyncio.run(amain(args))
    except KeyboardInterrupt:
        pass


if __name__ == "__main__":
    main()
