"""Broker binary (parity cdn-broker/src/binaries/broker.rs:21-131).

    python -m pushcdn_tpu.bin.broker \
        --discovery-endpoint /tmp/cdn.sqlite \
        --public-advertise-endpoint local_ip:1738 --public-bind-endpoint 0.0.0.0:1738 \
        --private-advertise-endpoint local_ip:1739 --private-bind-endpoint 0.0.0.0:1739
"""

from __future__ import annotations

import argparse
import asyncio
import os

from pushcdn_tpu.bin.common import (
    add_io_impl_flag,
    add_pump_flag,
    apply_io_impl,
    apply_pump,
    drain_grace_s,
    init_logging,
    install_drain_signals,
    keypair_from_seed,
    run_def_from_args,
    tune_gc,
)
from pushcdn_tpu.broker.broker import GIB, Broker, BrokerConfig


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(prog="pushcdn-broker", description=__doc__)
    p.add_argument("--discovery-endpoint", required=True,
                   help="sqlite path or redis:// URL")
    p.add_argument("--public-advertise-endpoint", default="local_ip:1738")
    p.add_argument("--public-bind-endpoint", default="0.0.0.0:1738")
    p.add_argument("--private-advertise-endpoint", default="local_ip:1739")
    p.add_argument("--private-bind-endpoint", default="0.0.0.0:1739")
    p.add_argument("--metrics-bind-endpoint", default=None)
    p.add_argument("--broker-transport", default="tcp")
    p.add_argument("--user-transport", default="tcp+tls")
    p.add_argument("--num-topics", type=int, default=256)
    p.add_argument("--key-seed", type=int, default=0,
                   help="deployment broker key seed (all brokers must match)")
    p.add_argument("--ca-cert-path", default=None)
    p.add_argument("--ca-key-path", default=None)
    p.add_argument("--global-memory-pool-size", type=int, default=GIB,
                   help="bytes (default 1 GiB, parity broker.rs:67-72)")
    p.add_argument("--global-permits", action="store_true")
    p.add_argument("--scheme", default="ed25519",
                   help="signature scheme: ed25519 | bls-bn254")
    p.add_argument("--heartbeat-interval", type=float, default=10.0,
                   help="discovery heartbeat cadence in seconds; chaos "
                        "drills shrink it so a killed broker ages out of "
                        "placement quickly")
    p.add_argument("--membership-ttl", type=float, default=60.0,
                   help="discovery membership TTL in seconds (parity "
                        "heartbeat.rs 60 s)")
    p.add_argument("--sync-interval", type=float, default=10.0,
                   help="mesh anti-entropy cadence in seconds (partial "
                        "user/topic syncs + LedgerSync balance sheets); "
                        "audit drills shrink it so conservation sheets "
                        "propagate quickly")
    # ---- sharded data plane (ISSUE 6) ---------------------------------
    p.add_argument("--shards", type=int, default=None,
                   help="shard the data plane across N worker OS "
                        "processes (default: PUSHCDN_SHARDS or 1 = "
                        "single-process, byte-for-byte today's behavior)."
                        " Shard 0 owns the mesh; users spread across "
                        "workers via SO_REUSEPORT (or parent fd-handoff)")
    p.add_argument("--shard-index", type=int, default=None,
                   help=argparse.SUPPRESS)  # internal: worker role
    p.add_argument("--shard-ipc", default=None,
                   help=argparse.SUPPRESS)  # internal: worker IPC spec
    # ---- device data plane (the TPU path) -----------------------------
    p.add_argument("--device-plane", action="store_true",
                   help="route eligible messages through the attached "
                        "device (single-shard plane; see --multihost for "
                        "the cross-host mesh group)")
    p.add_argument("--device-ring-slots", type=int, default=None,
                   help="staging ring slots per step (defaults: 1024 "
                        "single-shard, 256 mesh-group)")
    p.add_argument("--device-frame-bytes", type=int, default=None,
                   help="frame slot bytes (default 2048)")
    p.add_argument("--device-batch-window", type=float, default=None,
                   help="seconds. Single-shard: the coalescing window for "
                        "trickle traffic (bursts and idle arrivals skip "
                        "it; default 1 ms). Mesh group: the LOCKSTEP step "
                        "cadence every host ticks at (default 1 ms)")
    # ---- multi-host SPMD mesh group (jax.distributed) -----------------
    p.add_argument("--multihost-coordinator", default=None,
                   help="host:port of the jax.distributed coordinator; "
                        "enables the cross-host mesh broker group "
                        "(auto-detected on Cloud TPU if flags are "
                        "omitted but --mesh-shards is given)")
    p.add_argument("--multihost-process-id", type=int, default=None)
    p.add_argument("--multihost-num-processes", type=int, default=None)
    p.add_argument("--mesh-shards", type=int, default=None,
                   help="global broker-mesh shard count; this broker "
                        "attaches to --mesh-shard (default: first local)")
    p.add_argument("--mesh-shard", type=int, default=None)
    add_io_impl_flag(p)
    add_pump_flag(p)
    p.add_argument("-v", "--verbose", action="count", default=0)
    return p


def _worker_argv_base() -> list:
    """This process's argv minus the flags the supervisor rewrites per
    worker (--shards; --metrics-bind-endpoint is reassigned per shard)."""
    import sys
    argv = []
    skip = False
    for a in sys.argv[1:]:
        if skip:
            skip = False
            continue
        if a in ("--shards", "--metrics-bind-endpoint"):
            skip = True
            continue
        if a.startswith("--shards=") or \
                a.startswith("--metrics-bind-endpoint="):
            continue
        argv.append(a)
    return argv


async def run_supervisor(args: argparse.Namespace, shards: int) -> None:
    """Parent of a sharded broker: spawn N workers, relay control-plane
    deltas, aggregate observability, propagate drains (ISSUE 6)."""
    import sys

    from pushcdn_tpu.broker import sharding

    repo = os.path.dirname(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))))
    os.environ["PYTHONPATH"] = (
        repo + os.pathsep + os.environ["PYTHONPATH"]
        if os.environ.get("PYTHONPATH") else repo)
    base = _worker_argv_base()

    def worker_argv(shard: int, spec_json: str, metrics_endpoint):
        argv = [sys.executable, "-m", "pushcdn_tpu.bin.broker", *base,
                "--shard-index", str(shard), "--shard-ipc", spec_json]
        if metrics_endpoint:
            argv += ["--metrics-bind-endpoint", metrics_endpoint]
        return argv

    acceptor = None
    if not sharding.reuseport_available():
        if args.user_transport != "tcp":
            # the handoff acceptor deals RAW TCP fds; a TLS/QUIC user
            # transport would silently answer handshakes in plaintext
            # (or never accept at all) — refuse loudly instead
            raise SystemExit(
                "--shards without SO_REUSEPORT uses the parent fd-handoff "
                "acceptor, which supports only --user-transport tcp "
                f"(got {args.user_transport!r}); use a platform with "
                "SO_REUSEPORT for TLS/QUIC user transports")
        acceptor = args.public_bind_endpoint
    sup = sharding.ShardSupervisor(
        shards, args.metrics_bind_endpoint, worker_argv,
        acceptor_endpoint=acceptor)
    try:
        await sup.start()
    except BaseException:
        # half-started (e.g. parent metrics bind EADDRINUSE after the
        # workers spawned): kill whatever came up and unlink the rings —
        # REUSEPORT workers would otherwise keep serving as orphans
        sup.signal_workers()
        await sup.reap(5.0)
        await sup.stop()
        raise
    drain = asyncio.Event()
    installed = install_drain_signals(drain, on_signal=sup.begin_drain)
    exit_task = asyncio.create_task(sup.wait_any_worker_exit(),
                                    name="shard-reaper")
    drain_task = asyncio.create_task(drain.wait(), name="drain-wait")
    try:
        await asyncio.wait({exit_task, drain_task},
                           return_when=asyncio.FIRST_COMPLETED)
        if installed and drain.is_set():
            # workers flipped not-ready on the forwarded SIGTERM and are
            # serving out the grace window; reap them BEFORE the parent's
            # aggregated endpoint goes away
            await sup.reap(drain_grace_s() + 15.0)
            await sup.stop()
            return
        rc = exit_task.result() if exit_task.done() else 1
        sup.signal_workers()
        await sup.reap(5.0)
        await sup.stop()
        raise SystemExit(rc if rc not in (0, None) else 1)
    finally:
        for t in (exit_task, drain_task):
            t.cancel()


async def amain(args: argparse.Namespace) -> None:
    from pushcdn_tpu.broker import sharding

    shards = sharding.shards_from_env(args.shards)
    if shards > 1 and (args.device_plane or args.mesh_shards is not None):
        raise SystemExit("--shards is a host-data-plane feature; combine "
                         "with --device-plane/--mesh-shards once the "
                         "device plane learns shard-local staging")
    if args.shard_index is None and shards > 1:
        await run_supervisor(args, shards)
        return

    run_def = run_def_from_args(args.broker_transport, args.user_transport,
                                args.discovery_endpoint, args.num_topics,
                                args.global_permits, scheme=args.scheme)
    if args.device_plane and args.mesh_shards is not None:
        raise SystemExit("--device-plane (single-shard) and --mesh-shards "
                         "(mesh group) are mutually exclusive")
    if args.mesh_shard is not None and args.mesh_shards is None:
        raise SystemExit("--mesh-shard requires --mesh-shards")
    def _overrides():
        out = {}
        if args.device_ring_slots is not None:
            out["ring_slots"] = args.device_ring_slots
        if args.device_frame_bytes is not None:
            out["frame_bytes"] = args.device_frame_bytes
        if args.device_batch_window is not None:
            out["batch_window_s"] = args.device_batch_window
        return out

    spec = None
    if args.shard_index is not None:
        import json as json_mod
        if not args.shard_ipc:
            raise SystemExit("--shard-index is internal (spawned by "
                             "--shards); it requires --shard-ipc")
        spec = json_mod.loads(args.shard_ipc)
        # per-worker span log: the workers inherit the parent's
        # PUSHCDN_TRACE_LOG — suffix it so two shards never interleave
        # writes in one JSONL (proto.trace reads the env at import, but
        # lazily opens the file, so adjusting here is race-free)
        trace_path = os.environ.get("PUSHCDN_TRACE_LOG")
        if trace_path:
            from pushcdn_tpu.proto import trace as trace_mod_
            root, ext = os.path.splitext(trace_path)
            trace_mod_._LOG_PATH = f"{root}-shard{spec['shard']}{ext}"

    device_plane = None
    if args.device_plane:
        # Honor JAX_PLATFORMS before jax initializes: an accelerator
        # plugin's sitecustomize may overwrite the jax_platforms config
        # default (the same workaround tests/conftest.py applies), which
        # otherwise points a CPU-pinned subprocess at a dead/busy chip.
        platforms = os.environ.get("JAX_PLATFORMS")
        if platforms:
            import jax
            jax.config.update("jax_platforms", platforms)
        from pushcdn_tpu.broker.device_plane import DevicePlaneConfig
        device_plane = DevicePlaneConfig(**_overrides())
    broker = await Broker.new(BrokerConfig(
        run_def=run_def,
        keypair=keypair_from_seed(args.key_seed, args.scheme),
        discovery_endpoint=args.discovery_endpoint,
        public_advertise_endpoint=args.public_advertise_endpoint,
        public_bind_endpoint=args.public_bind_endpoint,
        private_advertise_endpoint=args.private_advertise_endpoint,
        private_bind_endpoint=args.private_bind_endpoint,
        metrics_bind_endpoint=args.metrics_bind_endpoint,
        ca_cert_path=args.ca_cert_path, ca_key_path=args.ca_key_path,
        global_memory_pool_size=args.global_memory_pool_size,
        heartbeat_interval_s=args.heartbeat_interval,
        membership_ttl_s=args.membership_ttl,
        sync_interval_s=args.sync_interval,
        device_plane=device_plane,
        # a mesh-group deployment's inter-broker plane is the device mesh
        form_mesh=args.mesh_shards is None,
        # worker-shard role (ISSUE 6): shard 0 owns mesh + control tasks
        shard_index=(spec["shard"] if spec else 0),
        num_shards=(spec["num_shards"] if spec else 1),
        bind_private=(spec is None or spec["shard"] == 0),
        reuse_port=(spec is not None and "accept_fd" not in spec),
        accept_handoff_fd=(spec.get("accept_fd") if spec else None),
    ))
    if spec is not None:
        from pushcdn_tpu.broker import sharding
        runtime = sharding.runtime_from_spec(broker, spec)
        runtime.attach()
    if args.mesh_shards is not None:
        # cross-host SPMD mesh group: join the distributed runtime, build
        # the global mesh, attach this broker to its shard
        from pushcdn_tpu.broker.mesh_group import MeshGroupConfig
        from pushcdn_tpu.broker.multihost_group import MultiHostBrokerGroup
        from pushcdn_tpu.parallel import multihost
        multihost.initialize(args.multihost_coordinator,
                             args.multihost_num_processes,
                             args.multihost_process_id)
        mesh = multihost.pod_broker_mesh(args.mesh_shards)
        group = MultiHostBrokerGroup(
            mesh, MeshGroupConfig(**_overrides()),
            discovery=broker.discovery)
        shard = (args.mesh_shard if args.mesh_shard is not None
                 else group.local_shards[0])
        if shard not in group.local_shards:
            raise SystemExit(
                f"--mesh-shard {shard} is not local to this host "
                f"(local shards: {group.local_shards}) — a non-local "
                "attachment would silently blackhole traffic")
        group.attach(broker, shard)
    # Graceful drain (ISSUE 5): SIGINT/SIGTERM flips /readyz to 503 FIRST,
    # keeps serving in-flight traffic for PUSHCDN_DRAIN_GRACE_S, then
    # stops — so a load balancer stops routing before the listeners close.
    drain = asyncio.Event()
    if not install_drain_signals(drain):
        await broker.run_until_failure()
        return
    run_task = asyncio.create_task(broker.run_until_failure(),
                                   name="broker-run")
    drain_task = asyncio.create_task(drain.wait(), name="drain-wait")
    try:
        await asyncio.wait({run_task, drain_task},
                           return_when=asyncio.FIRST_COMPLETED)
        if drain.is_set():
            broker.begin_drain("signal")
            # elastic drain (ISSUE 12): actively re-home every connected
            # user to the surviving brokers before the grace sleep — the
            # UserSync evictions land while we're still serving
            try:
                from pushcdn_tpu.broker import rehome as rehome_mod
                await rehome_mod.rehome_users(broker)
            except Exception as exc:
                import logging
                logging.getLogger("pushcdn.broker").warning(
                    "drain re-home failed: %r", exc)
            await asyncio.sleep(drain_grace_s())
            run_task.cancel()
            await asyncio.gather(run_task, return_exceptions=True)
            await broker.stop()
        else:
            await run_task  # re-raise the core-task failure
    finally:
        drain_task.cancel()


def main() -> None:
    args = build_parser().parse_args()
    init_logging(args.verbose)
    apply_io_impl(args)
    apply_pump(args)
    tune_gc()
    try:
        asyncio.run(amain(args))
    except KeyboardInterrupt:
        pass


if __name__ == "__main__":
    main()
