"""Chaos: message firehose (parity cdn-client/src/binaries/bad-sender.rs:34-105
— broadcast large messages in a tight loop; default 9 MB, the reference's
design-envelope size, exercising the byte-pool backpressure)."""

from __future__ import annotations

import argparse
import asyncio
import itertools
import logging
import os

from pushcdn_tpu.bin.common import init_logging, keypair_from_seed, transport_by_name
from pushcdn_tpu.client import Client, ClientConfig

logger = logging.getLogger("pushcdn.bad-sender")


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(prog="pushcdn-bad-sender", description=__doc__)
    p.add_argument("--marshal-endpoint", required=True)
    p.add_argument("--transport", default="tcp")
    p.add_argument("--message-size", type=int, default=9 * 1000 * 1000,
                   help="bytes per broadcast (parity: 9 MB)")
    p.add_argument("--key-seed", type=int, default=None)
    p.add_argument("--cycles", type=int, default=0, help="0 = forever")
    p.add_argument("-v", "--verbose", action="count", default=0)
    return p


async def amain(args: argparse.Namespace) -> None:
    client = Client(ClientConfig(
        marshal_endpoint=args.marshal_endpoint,
        keypair=keypair_from_seed(args.key_seed),
        protocol=transport_by_name(args.transport),
        subscribed_topics={0},
    ))
    await client.ensure_initialized()
    payload = os.urandom(args.message_size)
    sent = 0
    for n in itertools.count():
        if args.cycles and n >= args.cycles:
            break
        await client.send_broadcast_message([0], payload)
        sent += len(payload)
        if n % 10 == 0:
            logger.info("firehose: %d msgs, %.1f MB total", n + 1, sent / 1e6)


def main() -> None:
    args = build_parser().parse_args()
    init_logging(args.verbose)
    try:
        asyncio.run(amain(args))
    except KeyboardInterrupt:
        pass


if __name__ == "__main__":
    main()
