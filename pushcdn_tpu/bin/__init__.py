"""Deployment binaries (reference L10, SURVEY.md §1): ``broker``,
``marshal``, ``client`` plus the chaos generators ``bad-broker``,
``bad-connector``, ``bad-sender``. Run as ``python -m pushcdn_tpu.bin.broker``
etc.; ``scripts/local_cluster.py`` wires a full local deployment
(process-compose parity)."""
