"""Example client binary (parity cdn-client/src/binaries/client.rs:36-123):
every 5 s, send a direct message to ourselves and a broadcast, and log
everything received."""

from __future__ import annotations

import argparse
import asyncio
import logging

from pushcdn_tpu.bin.common import (
    add_io_impl_flag,
    apply_io_impl,
    init_logging,
    keypair_from_seed,
    scheme_by_name,
    transport_by_name,
)
from pushcdn_tpu.client import Client, ClientConfig
from pushcdn_tpu.proto.error import Error
from pushcdn_tpu.proto.message import Broadcast, Direct

logger = logging.getLogger("pushcdn.client-bin")


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(prog="pushcdn-client", description=__doc__)
    p.add_argument("--marshal-endpoint", required=True)
    p.add_argument("--transport", default="tcp+tls")
    p.add_argument("--key-seed", type=int, default=None)
    p.add_argument("--topic", type=int, action="append", default=None)
    p.add_argument("--interval", type=float, default=5.0)
    p.add_argument("--direct-to-seed", type=int, default=None,
                   help="send directs to the user whose keypair derives "
                        "from this seed instead of ourselves (two clients "
                        "messaging each other — the cross-shard traffic "
                        "driver for a --shards broker)")
    p.add_argument("--scheme", default="ed25519",
                   help="signature scheme: ed25519 | bls-bn254")
    p.add_argument("--metrics-bind-endpoint", default=None,
                   help="serve /metrics + /healthz + /readyz (readiness = "
                        "live broker link)")
    add_io_impl_flag(p)
    p.add_argument("-v", "--verbose", action="count", default=0)
    return p


async def amain(args: argparse.Namespace) -> None:
    topics = args.topic if args.topic is not None else [0]
    client = Client(ClientConfig(
        marshal_endpoint=args.marshal_endpoint,
        keypair=keypair_from_seed(args.key_seed, args.scheme),
        protocol=transport_by_name(args.transport),
        subscribed_topics=set(topics),
        scheme=scheme_by_name(args.scheme),
    ))
    if args.metrics_bind_endpoint:
        from pushcdn_tpu.proto import health as health_mod
        from pushcdn_tpu.proto import metrics as metrics_mod

        def _check_broker_link():
            conn = client._connection
            if conn is not None and not conn.is_closed:
                return True, "broker link up"
            return False, "no live broker connection"

        health_mod.register_readiness("broker-link", _check_broker_link)
        await metrics_mod.serve_metrics(args.metrics_bind_endpoint)
    await client.ensure_initialized()
    logger.info("connected; sending every %.1fs on topics %s",
                args.interval, topics)

    async def receiver():
        # elastic like the library (lib.rs disconnect_on_error): a broker
        # death raises Error(CONNECTION) here, and the next receive call
        # re-dials through the marshal — the process must ride it out, not
        # die (scripts/local_cluster.py --chaos SIGKILLs a broker under us)
        while True:
            try:
                message = await client.receive_message()
            except Error as exc:
                logger.info("receive failed (%s); reconnecting", exc.kind)
                continue
            if isinstance(message, Direct):
                logger.info("recv direct: %r", bytes(message.message)[:64])
            elif isinstance(message, Broadcast):
                logger.info("recv broadcast %s: %r", message.topics,
                            bytes(message.message)[:64])

    recv_task = asyncio.create_task(receiver())
    direct_target = client.public_key
    if args.direct_to_seed is not None:
        direct_target = keypair_from_seed(args.direct_to_seed,
                                          args.scheme).public_key
    n = 0
    try:
        while True:
            try:
                await client.send_direct_message(direct_target,
                                                 f"echo {n}".encode())
                await client.send_broadcast_message(topics,
                                                    f"hello {n}".encode())
                n += 1
            except Error as exc:
                logger.info("send failed (%s); reconnecting", exc.kind)
            await asyncio.sleep(args.interval)
    finally:
        recv_task.cancel()


def main() -> None:
    args = build_parser().parse_args()
    init_logging(args.verbose)
    apply_io_impl(args)
    try:
        asyncio.run(amain(args))
    except KeyboardInterrupt:
        pass


if __name__ == "__main__":
    main()
