"""Shared CLI plumbing: logging init (JSON opt-in via env, parity with the
reference's RUST_LOG_FORMAT=json switch, cdn-broker/src/binaries/broker.rs:80-91),
transport/scheme lookup by name, seeded keys."""

from __future__ import annotations

import asyncio
import json
import logging
import os
import signal as signal_mod
import sys
from typing import Optional, Type

from pushcdn_tpu.proto.crypto.signature import (
    DEFAULT_SCHEME,
    BlsBn254Scheme,
    Ed25519Scheme,
    KeyPair,
    SignatureScheme,
)
from pushcdn_tpu.proto.def_ import RunDef, ConnectionDef
from pushcdn_tpu.proto.discovery.embedded import Embedded
from pushcdn_tpu.proto.discovery.redis import Redis
from pushcdn_tpu.proto.topic import TopicSpace
from pushcdn_tpu.proto.transport import Memory, Tcp, TcpTls
from pushcdn_tpu.proto.transport.base import Protocol
from pushcdn_tpu.proto.transport.quic import Quic

TRANSPORTS = {"tcp": Tcp, "tcp+tls": TcpTls, "quic": Quic, "memory": Memory}
SCHEMES = {"ed25519": Ed25519Scheme, "bls-bn254": BlsBn254Scheme}


class _JsonFormatter(logging.Formatter):
    def format(self, record: logging.LogRecord) -> str:
        return json.dumps({
            "ts": self.formatTime(record),
            "level": record.levelname,
            "target": record.name,
            "message": record.getMessage(),
        })


def tune_gc(threshold0: int = 50_000) -> None:
    """Server-style GC tuning for the message hot path: the router
    allocates ~20 small objects per delivery, and CPython's default gen-0
    threshold (700) turns that into thousands of collections per second —
    with the periodic gen-2 passes scanning the whole (jax-sized) heap.
    Raise the thresholds and freeze the post-startup heap so steady-state
    collections only walk the young, message-sized garbage. Call once
    after bootstrap (binaries and benches do)."""
    import gc
    gc.collect()
    gc.freeze()
    gc.set_threshold(threshold0, 50, 100)


def add_io_impl_flag(p) -> None:
    """The host data-plane selector, shared by every binary: ``auto``
    probes the kernel once and demotes honestly, ``uring`` insists (and
    fails fast when denied), ``asyncio`` is the default this round."""
    from pushcdn_tpu.proto.transport.uring import IO_IMPLS
    p.add_argument("--io-impl", choices=IO_IMPLS, default=None,
                   help="host I/O engine for tcp links: auto (io_uring "
                        "when the kernel allows, else asyncio), uring "
                        "(insist), asyncio (default; also inherited via "
                        "PUSHCDN_IO_IMPL)")


def apply_io_impl(args) -> None:
    """Write the selection into PUSHCDN_IO_IMPL so THIS process and its
    children (shard workers, spawned helpers) resolve the same plane."""
    if getattr(args, "io_impl", None):
        from pushcdn_tpu.proto.transport.uring import set_io_impl
        set_io_impl(args.io_impl)


def add_pump_flag(p) -> None:
    """The fused data-plane pump selector (broker-side, ISSUE 17):
    ``auto`` engages the native recv→plan→send pump whenever BOTH the
    io_uring engine and the native route planner are live (demoting
    loudly once otherwise), ``off`` disables it unconditionally."""
    p.add_argument("--pump", choices=("auto", "off"), default=None,
                   help="fused native data-plane pump: auto (engage when "
                        "io_uring + the native planner are both live), "
                        "off (always per-chunk Python routing; also "
                        "inherited via PUSHCDN_PUMP)")


def apply_pump(args) -> None:
    """Write the selection into PUSHCDN_PUMP so shard workers inherit
    the same composition decision."""
    if getattr(args, "pump", None):
        from pushcdn_tpu.proto.transport.pump import set_pump_impl
        os.environ["PUSHCDN_PUMP"] = args.pump
        set_pump_impl(args.pump)


def init_logging(verbosity: int = 0) -> None:
    """Env-driven log format: ``PUSHCDN_LOG_FORMAT=json`` switches to
    structured JSON lines (reference: RUST_LOG_FORMAT=json)."""
    level = [logging.INFO, logging.DEBUG][min(verbosity, 1)]
    handler = logging.StreamHandler(sys.stderr)
    if os.environ.get("PUSHCDN_LOG_FORMAT") == "json":
        handler.setFormatter(_JsonFormatter())
    else:
        handler.setFormatter(logging.Formatter(
            "%(asctime)s %(levelname)-5s %(name)s: %(message)s"))
    logging.basicConfig(level=level, handlers=[handler], force=True)


def drain_grace_s() -> float:
    """How long a binary keeps serving (with /readyz already 503) between
    receiving SIGINT/SIGTERM and tearing its listeners down —
    ``PUSHCDN_DRAIN_GRACE_S`` seconds, default 0 (immediate)."""
    raw = os.environ.get("PUSHCDN_DRAIN_GRACE_S", "").strip()
    if not raw:
        return 0.0
    try:
        return max(float(raw), 0.0)
    except ValueError:
        return 0.0


def install_drain_signals(event: asyncio.Event, on_signal=None) -> bool:
    """Route SIGINT/SIGTERM to ``event.set()`` instead of
    KeyboardInterrupt, so the server binaries can drain gracefully:
    readiness flips false first, listeners close after the grace window.
    Returns False where signal handlers are unavailable (non-main thread,
    Windows proactor) — callers keep the KeyboardInterrupt fallback.

    ``on_signal`` (optional) runs in the handler alongside the latch —
    the sharded broker's parent uses it to PROPAGATE the drain: readiness
    flips false on every worker shard first (the callback forwards
    SIGTERM), the workers serve out ``PUSHCDN_DRAIN_GRACE_S``, and the
    parent reaps them before its own listeners close."""
    loop = asyncio.get_running_loop()

    def _fire() -> None:
        event.set()
        if on_signal is not None:
            try:
                on_signal()
            except Exception:
                pass

    installed = False
    for sig in (signal_mod.SIGINT, signal_mod.SIGTERM):
        try:
            loop.add_signal_handler(sig, _fire)
            installed = True
        except (NotImplementedError, RuntimeError, ValueError):
            pass
    return installed


def transport_by_name(name: str) -> Type[Protocol]:
    try:
        return TRANSPORTS[name]
    except KeyError:
        raise SystemExit(
            f"unknown transport {name!r}; pick from {sorted(TRANSPORTS)}")


def scheme_by_name(name: str) -> Type[SignatureScheme]:
    try:
        scheme = SCHEMES[name]
    except KeyError:
        raise SystemExit(f"unknown scheme {name!r}; pick from {sorted(SCHEMES)}")
    if scheme is BlsBn254Scheme and not BlsBn254Scheme.available():
        raise SystemExit("bls-bn254 requested but the native BLS library "
                         "failed to compile on this host")
    return scheme


def run_def_from_args(broker_transport: str, user_transport: str,
                      discovery_endpoint: str, num_topics: int,
                      global_permits: bool = False,
                      scheme: str = "ed25519") -> RunDef:
    discovery = Redis if discovery_endpoint.startswith("redis://") else Embedded
    sig = scheme_by_name(scheme)
    return RunDef(
        broker_def=ConnectionDef(protocol=transport_by_name(broker_transport),
                                 scheme=sig),
        user_def=ConnectionDef(protocol=transport_by_name(user_transport),
                               scheme=sig),
        discovery=discovery,
        topics=TopicSpace.range(num_topics),
        global_permits=global_permits,
    )


def keypair_from_seed(seed: Optional[int],
                      scheme: str = "ed25519") -> KeyPair:
    return scheme_by_name(scheme).generate_keypair(seed=seed)


def spawn_binary(name: str, *args: str, env_extra=None, capture=True,
                 log_path=None):
    """Launch ``pushcdn_tpu.bin.<name>`` as a child process with the repo
    prepended to PYTHONPATH (setdefault breaks under any preexisting
    PYTHONPATH, e.g. an accelerator site dir) — the one spawner the local
    cluster runner and the binary smoke tests share.

    ``capture=False`` sends the child's output to /dev/null instead of a
    pipe — REQUIRED for spawners that never drain the pipe: a chatty
    child (e.g. a ``--shards`` broker whose workers share the fd) blocks
    forever once the 64 KiB pipe buffer fills. ``log_path`` redirects
    output to a file instead: the pipe-wedge fix that still preserves
    crash output for postmortems (overrides ``capture``)."""
    import os
    import subprocess
    import sys
    repo = os.path.dirname(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))))
    env = dict(os.environ)
    env["PYTHONPATH"] = (repo + os.pathsep + env["PYTHONPATH"]
                         if env.get("PYTHONPATH") else repo)
    if env_extra:
        env.update(env_extra)
    argv = [sys.executable, "-m", f"pushcdn_tpu.bin.{name}", *args]
    if log_path is not None:
        with open(log_path, "ab") as sink_file:
            return subprocess.Popen(argv, env=env, stdout=sink_file,
                                    stderr=subprocess.STDOUT)
    sink = subprocess.PIPE if capture else subprocess.DEVNULL
    return subprocess.Popen(
        argv, env=env, stdout=sink,
        stderr=subprocess.STDOUT if capture else subprocess.DEVNULL,
        text=capture)
