"""Marshal binary (parity cdn-marshal/src/binaries/marshal.rs:17-86;
default user-facing port 1737)."""

from __future__ import annotations

import argparse
import asyncio

from pushcdn_tpu.bin.common import (
    drain_grace_s,
    init_logging,
    install_drain_signals,
    run_def_from_args,
    tune_gc,
)
from pushcdn_tpu.marshal import Marshal, MarshalConfig


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(prog="pushcdn-marshal", description=__doc__)
    p.add_argument("--discovery-endpoint", required=True)
    p.add_argument("--bind-endpoint", default="0.0.0.0:1737")
    p.add_argument("--metrics-bind-endpoint", default=None)
    p.add_argument("--user-transport", default="tcp+tls")
    p.add_argument("--num-topics", type=int, default=256)
    p.add_argument("--ca-cert-path", default=None)
    p.add_argument("--ca-key-path", default=None)
    p.add_argument("--global-memory-pool-size", type=int,
                   default=1024 * 1024 * 1024)
    p.add_argument("--global-permits", action="store_true")
    p.add_argument("--scheme", default="ed25519",
                   help="signature scheme: ed25519 | bls-bn254")
    p.add_argument("-v", "--verbose", action="count", default=0)
    return p


async def amain(args: argparse.Namespace) -> None:
    run_def = run_def_from_args("tcp", args.user_transport,
                                args.discovery_endpoint, args.num_topics,
                                args.global_permits, scheme=args.scheme)
    marshal = await Marshal.new(MarshalConfig(
        run_def=run_def,
        discovery_endpoint=args.discovery_endpoint,
        bind_endpoint=args.bind_endpoint,
        metrics_bind_endpoint=args.metrics_bind_endpoint,
        ca_cert_path=args.ca_cert_path, ca_key_path=args.ca_key_path,
        global_memory_pool_size=args.global_memory_pool_size,
    ))
    await marshal.start()
    # Graceful drain (ISSUE 5): readiness flips false on SIGINT/SIGTERM,
    # the listener stays up for the grace window, then a clean stop.
    drain = asyncio.Event()
    if not install_drain_signals(drain):
        await asyncio.Event().wait()  # serve until KeyboardInterrupt
        return
    await drain.wait()
    marshal.begin_drain("signal")
    await asyncio.sleep(drain_grace_s())
    await marshal.stop()


def main() -> None:
    args = build_parser().parse_args()
    init_logging(args.verbose)
    tune_gc()
    try:
        asyncio.run(amain(args))
    except KeyboardInterrupt:
        pass


if __name__ == "__main__":
    main()
