"""Chaos: broker churn generator (parity
cdn-broker/src/binaries/bad-broker.rs:36-98 — start a new broker every
300 ms and kill the previous one, exercising mesh self-healing)."""

from __future__ import annotations

import argparse
import asyncio
import itertools
import logging

from pushcdn_tpu.bin.common import init_logging, keypair_from_seed, run_def_from_args
from pushcdn_tpu.broker.broker import Broker, BrokerConfig

logger = logging.getLogger("pushcdn.bad-broker")


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(prog="pushcdn-bad-broker", description=__doc__)
    p.add_argument("--discovery-endpoint", required=True)
    p.add_argument("--broker-transport", default="tcp")
    p.add_argument("--user-transport", default="tcp")
    p.add_argument("--base-port", type=int, default=11000)
    p.add_argument("--churn-interval", type=float, default=0.3,
                   help="seconds between churn cycles (parity 300 ms)")
    p.add_argument("--key-seed", type=int, default=0)
    p.add_argument("--cycles", type=int, default=0, help="0 = forever")
    p.add_argument("-v", "--verbose", action="count", default=0)
    return p


async def amain(args: argparse.Namespace) -> None:
    run_def = run_def_from_args(args.broker_transport, args.user_transport,
                                args.discovery_endpoint, 256)
    previous: Broker | None = None
    prev_task: asyncio.Task | None = None
    for n in itertools.count():
        if args.cycles and n >= args.cycles:
            break
        port = args.base_port + (n % 500) * 2
        broker = await Broker.new(BrokerConfig(
            run_def=run_def, keypair=keypair_from_seed(args.key_seed),
            discovery_endpoint=args.discovery_endpoint,
            public_advertise_endpoint=f"127.0.0.1:{port}",
            public_bind_endpoint=f"127.0.0.1:{port}",
            private_advertise_endpoint=f"127.0.0.1:{port + 1}",
            private_bind_endpoint=f"127.0.0.1:{port + 1}",
            heartbeat_interval_s=0.1,  # churn fast, heal fast
            membership_ttl_s=1.0,
        ))
        task = asyncio.create_task(broker.run_until_failure())
        logger.info("churn %d: broker on ports %d/%d up", n, port, port + 1)
        if previous is not None:
            prev_task.cancel()
            await previous.stop()
            logger.info("churn %d: previous broker killed", n)
        previous, prev_task = broker, task
        await asyncio.sleep(args.churn_interval)
    if previous is not None:
        prev_task.cancel()
        await previous.stop()


def main() -> None:
    args = build_parser().parse_args()
    init_logging(args.verbose)
    try:
        asyncio.run(amain(args))
    except KeyboardInterrupt:
        pass


if __name__ == "__main__":
    main()
