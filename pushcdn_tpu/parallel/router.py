"""The device router: broadcast/direct fan-out over a broker-mesh axis.

This is the TPU lowering of the broker hot path (SURVEY.md §2e / §7 stage
7). The reference routes by hash-map lookups and per-peer TCP writes
(cdn-broker/src/tasks/broker/handler.rs:197-272); here one jitted step,
run under ``shard_map`` over the ``"brokers"`` mesh axis, does the same
work for a whole batch at once:

- **inter-broker hop** = one ``all_gather`` of the frame tensors over the
  broker axis (ICI) — every frame crosses the mesh exactly once, the
  vectorized analog of the reference's "deserialize once per hop, forward
  raw bytes" rule;
- **CRDT sync** rides the same step: per-shard DirectMap claims are
  all-gathered and folded with the versioned dominance rule
  (pushcdn_tpu.parallel.crdt) — the 10 s sync task becomes a per-step
  merge, and user topic masks travel with the ownership claim;
- **broadcast routing** = a topic-bitmask AND between every gathered frame
  and every local user (VPU; optionally the Pallas kernel in
  pushcdn_tpu.ops.topic_kernel);
- **direct routing** = equality match of the frame's destination user slot
  against locally-owned users — delivery-iff-owner makes the reference's
  ``to_user_only`` loop-prevention rule structural: nothing is ever
  re-forwarded;
- **double-connect eviction** falls out of the merge's changed-mask
  (``evictions``), exactly like ``apply_user_sync``'s kick list.

Outputs stay on device as ``(gathered frames, delivery mask)``; the host
egress pump walks the mask to enqueue frame bytes to user sockets.
"""

from __future__ import annotations

from typing import NamedTuple, Optional

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, PartitionSpec as P

from pushcdn_tpu.parallel.jax_compat import shard_map as _shard_map_compat
from pushcdn_tpu.parallel.crdt import (
    ABSENT,
    CrdtState,
    empty_state,
    merge_all_gathered_with_payload,
)
from pushcdn_tpu.ops.delivery_kernel import delivery_matrix

BROKER_AXIS = "brokers"

# None = auto (Pallas on TPU / interpreter elsewhere when shapes align);
# flip to False to force the jnp reference path (bench comparisons).
# `bench.py --delivery-impl {auto,pallas,jnp}` sets this before the first
# routing_step trace — the one-command Pallas-vs-XLA A/B.
USE_PALLAS_DELIVERY: Optional[bool] = None


class RouterState(NamedTuple):
    """Per-shard routing state: the DirectMap twin + per-user topic masks."""

    crdt: CrdtState          # owners/versions/identities, each int32/uint32[U]
    topic_masks: jax.Array   # uint32[U] or uint32[U, W] — authoritative at
                             # the owner (W words cover 32·W topics)


class IngressBatch(NamedTuple):
    """One step of packed ingress frames (see parallel.frames)."""

    frame_bytes: jax.Array  # uint8[S, F]
    kind: jax.Array         # int32[S]
    length: jax.Array       # int32[S]
    topic_mask: jax.Array   # uint32[S] or uint32[S, W]
    dest: jax.Array         # int32[S]
    valid: jax.Array        # bool[S]


class DirectIngress(NamedTuple):
    """Per-destination-shard direct frames (see frames.DirectBuckets):
    axis 0 = destination shard. Exchanged with ONE ``all_to_all`` over the
    broker axis — each direct frame crosses ICI exactly once, to its owner
    (SURVEY.md §2e: the point-to-point collective keyed by owner-device
    index), instead of being all-gathered to every shard."""

    frame_bytes: jax.Array  # uint8[B, C, F]
    length: jax.Array       # int32[B, C]
    dest: jax.Array         # int32[B, C]
    valid: jax.Array        # bool[B, C]


class RouteResult(NamedTuple):
    gathered_bytes: jax.Array   # uint8[B*S, F] — every frame, post-ICI
    gathered_length: jax.Array  # int32[B*S]
    deliver: jax.Array          # bool[U, B*S] — local delivery matrix
    state: RouterState          # merged CRDT + masks
    evictions: jax.Array        # bool[U] — locally-owned users now owned elsewhere
    # all_to_all direct path (None when no DirectIngress was passed):
    direct_bytes: Optional[jax.Array] = None    # uint8[B*C, F] — received frames
    direct_length: Optional[jax.Array] = None   # int32[B*C]
    direct_deliver: Optional[jax.Array] = None  # bool[U, B*C]


def empty_router_state(num_users: int, topic_words: int = 1) -> RouterState:
    shape = (num_users,) if topic_words == 1 else (num_users, topic_words)
    return RouterState(
        crdt=empty_state(num_users),
        topic_masks=jnp.zeros(shape, dtype=jnp.uint32),
    )


def _direct_route(direct: DirectIngress, now_local: jax.Array,
                  axis_name: Optional[str],
                  liveness: Optional[jax.Array] = None,
                  gather_bytes: bool = True):
    """Exchange per-destination buckets and build the local delivery mask.

    ``all_to_all`` swaps the destination-shard axis for a source-shard
    axis: received[j] = what shard j staged for *this* shard. Delivery is
    iff the addressed slot is locally owned — ownership moves race exactly
    like the reference's forward-to-old-owner during CRDT convergence, and
    resolve the same way (deliver-iff-owner, never re-forward)."""
    if axis_name is None:
        r_bytes, r_length, r_dest, r_valid = (
            direct.frame_bytes, direct.length, direct.dest, direct.valid)
    else:
        r_bytes = (jax.lax.all_to_all(direct.frame_bytes, axis_name, 0, 0)
                   if gather_bytes else None)
        r_length = jax.lax.all_to_all(direct.length, axis_name, 0, 0)
        r_dest = jax.lax.all_to_all(direct.dest, axis_name, 0, 0)
        r_valid = jax.lax.all_to_all(direct.valid, axis_name, 0, 0)
    if liveness is not None:
        # axis 0 is the SOURCE shard post-exchange: a dead shard's stale
        # frames (in flight when it was declared down) never deliver
        r_valid = r_valid & liveness[:, None]
    B, C = r_dest.shape
    dest_f = r_dest.reshape(B * C)
    valid_f = r_valid.reshape(B * C)
    U = now_local.shape[0]
    slots = jnp.arange(U, dtype=jnp.int32)
    deliver = (valid_f[None, :]
               & (dest_f[None, :] == slots[:, None])
               & now_local[:, None])
    return (None if r_bytes is None else r_bytes.reshape(B * C, -1),
            r_length.reshape(B * C), deliver)


def routing_step(state: RouterState, batch: IngressBatch,
                 my_index: jax.Array, axis_name: Optional[str],
                 direct: Optional[DirectIngress] = None
                 ) -> RouteResult:
    """One routing step for one broker shard — the single-lane special case
    of :func:`routing_step_lanes` (one copy of the collective/merge logic).

    With ``axis_name=None`` this is the single-broker fast path (no
    collectives — the degenerate mesh). Under ``shard_map`` the gathers run
    over ICI.
    """
    r = routing_step_lanes(state, (batch,), my_index, axis_name,
                           directs=() if direct is None else (direct,))
    lane = r.lanes[0]
    d = r.direct_lanes[0] if r.direct_lanes else None
    return RouteResult(
        gathered_bytes=lane.gathered_bytes,
        gathered_length=lane.gathered_length,
        deliver=lane.deliver,
        state=r.state,
        evictions=r.evictions,
        direct_bytes=None if d is None else d.gathered_bytes,
        direct_length=None if d is None else d.gathered_length,
        direct_deliver=None if d is None else d.deliver,
    )


# ---------------------------------------------------------------------------
# size-bucketed lanes (SURVEY.md §7 hard-part #1)
# ---------------------------------------------------------------------------
#
# One fixed frame size can't serve 100 B acks and 32 KB proposals at once:
# sizing slots for the big ones wastes HBM and ICI bandwidth on padding,
# sizing for the small ones bounces everything else to the host path. A
# *lane* is an independently-shaped FrameRing (slots × frame_bytes); the
# lane step routes any number of lanes in ONE jitted program with ONE CRDT
# merge — per-lane all_gathers over the broker axis, per-lane delivery
# matrices against the same merged ownership/mask state.


class LaneDelivery(NamedTuple):
    """Per-lane router output: the gathered frames + delivery matrix."""

    gathered_bytes: jax.Array   # uint8[B*S_l, F_l]
    gathered_length: jax.Array  # int32[B*S_l]
    deliver: jax.Array          # bool[U, B*S_l]


class MultiRouteResult(NamedTuple):
    lanes: tuple                # Tuple[LaneDelivery, ...] (broadcast lanes)
    direct_lanes: tuple         # Tuple[LaneDelivery, ...] (all_to_all lanes)
    state: RouterState
    evictions: jax.Array        # bool[U]


def routing_step_lanes(state: RouterState,
                       batches: tuple,
                       my_index: jax.Array,
                       axis_name: Optional[str],
                       directs: tuple = (),
                       liveness: Optional[jax.Array] = None,
                       gather_bytes: bool = True,
                       ) -> MultiRouteResult:
    """One routing step over any number of size-bucketed lanes.

    ``batches`` is a tuple of :class:`IngressBatch` (one per broadcast
    lane, any slot counts / frame widths); ``directs`` a tuple of
    :class:`DirectIngress` (one per direct lane). The CRDT/topic-mask
    merge runs ONCE; every lane's delivery matrix is computed against the
    same merged state, so cross-lane semantics are identical to a single
    ring — a lane is purely a shape bucket.

    ``gather_bytes=False`` skips the frame-byte collectives entirely
    (lanes come back with ``gathered_bytes=None``): on a single-host
    multi-chip topology every shard's staged frames already live in the
    one host's memory, so moving payload bytes over ICI and back through
    D2H is pure waste — only the *delivery decision* needs the mesh. The
    egress pump reads payloads from the host ring snapshots instead
    (broker/mesh_group.py). Multi-host deployments keep the default: a
    remote host's frame bytes exist nowhere locally except via the
    step's collectives.

    ``liveness`` (bool[B], identical on every shard) is the dynamic-
    membership mask over the STATIC device mesh (SURVEY.md §7 hard-part
    #3): the physical mesh can't churn the way the reference's broker
    mesh does (heartbeat.rs:69-107), so a departed shard is instead
    declared dead by the host control plane. In-step that means (a) its
    gathered frames never deliver, and (b) every slot it owned is
    tombstoned with a deterministic version bump — all shards compute the
    identical release from the identical gathered state, so the CRDT
    stays convergent, exactly like the reference aging a dead broker's
    users out of the DirectMap.
    """
    def gather(x):
        if axis_name is None:
            return x[None]
        return jax.lax.all_gather(x, axis_name)

    # ---- CRDT anti-entropy: once, shared by every lane -------------------
    g_owners = gather(state.crdt.owners)
    g_versions = gather(state.crdt.versions)
    g_ids = gather(state.crdt.identities)
    g_masks = gather(state.topic_masks)
    was_local = state.crdt.owners == my_index
    merged, masks, _changed = merge_all_gathered_with_payload(
        state.crdt, state.topic_masks,
        CrdtState(g_owners, g_versions, g_ids), g_masks)
    if liveness is not None:
        # release every slot owned by a dead shard (owner index is a mesh
        # coordinate; ABSENT maps to "live" so tombstones pass through)
        owner_live = jnp.where(merged.owners == ABSENT, True,
                               liveness[jnp.clip(merged.owners, 0)])
        merged = CrdtState(
            owners=jnp.where(owner_live, merged.owners, ABSENT),
            versions=jnp.where(owner_live, merged.versions,
                               merged.versions + 1),
            identities=merged.identities,
        )
        live_b = owner_live.reshape(
            owner_live.shape + (1,) * (masks.ndim - owner_live.ndim))
        masks = jnp.where(live_b, masks, 0)
    now_local = merged.owners == my_index
    evictions = was_local & ~now_local

    # ---- per-lane inter-broker hop + delivery matrix ---------------------
    lanes = []
    for batch in batches:
        g_bytes = gather(batch.frame_bytes) if gather_bytes else None
        g_kind = gather(batch.kind)
        g_length = gather(batch.length)
        g_tmask = gather(batch.topic_mask)
        g_dest = gather(batch.dest)
        g_valid = gather(batch.valid)
        B, S = g_kind.shape
        if liveness is not None:
            g_valid = g_valid & liveness[:, None]  # dead shards' frames
        valid_f = g_valid.reshape(B * S)
        kind_f = jnp.where(valid_f, g_kind.reshape(B * S), 0)
        # topic masks may be multi-word ([.., W]) for >32-topic spaces
        tmask_f = g_tmask.reshape((B * S,) + g_tmask.shape[2:])
        deliver = delivery_matrix(
            masks, now_local, tmask_f, kind_f,
            g_dest.reshape(B * S), use_pallas=USE_PALLAS_DELIVERY)
        lanes.append(LaneDelivery(
            gathered_bytes=(None if g_bytes is None
                            else g_bytes.reshape(B * S, -1)),
            gathered_length=g_length.reshape(B * S),
            deliver=deliver))

    direct_lanes = []
    for direct in directs:
        d_bytes, d_length, d_deliver = _direct_route(
            direct, now_local, axis_name, liveness,
            gather_bytes=gather_bytes)
        direct_lanes.append(LaneDelivery(
            gathered_bytes=d_bytes, gathered_length=d_length,
            deliver=d_deliver))

    return MultiRouteResult(
        lanes=tuple(lanes), direct_lanes=tuple(direct_lanes),
        state=RouterState(crdt=merged, topic_masks=masks),
        evictions=evictions)


# ---------------------------------------------------------------------------
# jitted entry points
# ---------------------------------------------------------------------------

@jax.jit
def routing_step_single(state: RouterState, batch: IngressBatch
                        ) -> RouteResult:
    """Single-chip step (mesh of one): the compile-checked `entry()` path."""
    return routing_step(state, batch, jnp.int32(0), axis_name=None)


import functools


@functools.partial(jax.jit, static_argnames=("gather_bytes",))
def routing_step_lanes_single(state: RouterState, batches: tuple,
                              directs: tuple = (),
                              gather_bytes: bool = True
                              ) -> MultiRouteResult:
    """Single-chip lane step (a change in the number of lanes is a pytree
    structure change, so jit retraces per lane-set shape).
    ``gather_bytes=False`` keeps frame bytes out of the step entirely —
    the single-shard plane's egress reads them from the host ring
    snapshot, so only the delivery matrix crosses PCIe back."""
    return routing_step_lanes(state, batches, jnp.int32(0), axis_name=None,
                              directs=directs, gather_bytes=gather_bytes)


def make_mesh_lane_step(mesh: Mesh, gather_bytes: bool = True):
    """Build the multi-chip lane step: every leaf of (state, batches,
    directs) is stacked on a leading broker axis and sharded over the mesh;
    one jitted shard_map program routes all lanes (per-lane all_gather /
    all_to_all over ICI, one shared CRDT merge). ``liveness`` is stacked
    [B, B] (every shard carries the full membership mask).
    ``gather_bytes=False`` builds the single-host variant whose lanes skip
    the frame-byte collectives (see :func:`routing_step_lanes`)."""

    def per_shard(state: RouterState, batches: tuple, directs: tuple,
                  liveness: jax.Array):
        state = jax.tree.map(lambda x: x[0], state)
        batches = jax.tree.map(lambda x: x[0], batches)
        directs = jax.tree.map(lambda x: x[0], directs)
        my = jax.lax.axis_index(BROKER_AXIS).astype(jnp.int32)
        result = routing_step_lanes(state, batches, my,
                                    axis_name=BROKER_AXIS, directs=directs,
                                    liveness=liveness[0],
                                    gather_bytes=gather_bytes)
        return jax.tree.map(lambda x: x[None], result)

    sharded = _shard_map_compat(
        per_shard, mesh=mesh,
        in_specs=(P(BROKER_AXIS), P(BROKER_AXIS), P(BROKER_AXIS),
                  P(BROKER_AXIS)),
        out_specs=P(BROKER_AXIS))

    @jax.jit
    def step(state, batches, directs, liveness=None):
        if liveness is None:
            B = mesh.devices.size
            liveness = jnp.ones((B, B), dtype=bool)
        return sharded(state, batches, directs, liveness)

    return step


def make_mesh_routing_step(mesh: Mesh, with_direct: bool = False):
    """Build the multi-chip step: state+batch sharded over the broker axis,
    one jitted shard_map program (SURVEY.md §7 stage 7: broker shards ↔
    devices of a jax mesh). With ``with_direct`` the step also takes
    stacked :class:`DirectIngress` buckets ([B_src, B_dest, C, F]) and runs
    the one-hop ``all_to_all`` direct path inside the same program."""

    def per_shard(state_leaves, batch_leaves, *direct_leaves):
        state = RouterState(CrdtState(*state_leaves[:3]), state_leaves[3])
        batch = IngressBatch(*batch_leaves)
        # shard_map gives each shard its [1, ...] block; drop the outer axis
        state = jax.tree.map(lambda x: x[0], state)
        batch = jax.tree.map(lambda x: x[0], batch)
        direct = None
        if direct_leaves:
            direct = DirectIngress(*(x[0] for x in direct_leaves[0]))
        my = jax.lax.axis_index(BROKER_AXIS).astype(jnp.int32)
        result = routing_step(state, batch, my, axis_name=BROKER_AXIS,
                              direct=direct)
        # re-add the sharded leading axis for the outputs
        return jax.tree.map(lambda x: x[None], tuple(result))

    n_in = 3 if with_direct else 2
    sharded = _shard_map_compat(
        per_shard, mesh=mesh,
        in_specs=tuple(P(BROKER_AXIS) for _ in range(n_in)),
        out_specs=P(BROKER_AXIS))

    def _unpack(out):
        return RouteResult(
            gathered_bytes=out[0], gathered_length=out[1], deliver=out[2],
            state=out[3], evictions=out[4],
            direct_bytes=out[5], direct_length=out[6], direct_deliver=out[7])

    if with_direct:
        @jax.jit
        def step(state_stacked: RouterState, batch_stacked: IngressBatch,
                 direct_stacked: DirectIngress):
            out = sharded(
                tuple((*state_stacked.crdt, state_stacked.topic_masks)),
                tuple(batch_stacked), tuple(direct_stacked))
            return _unpack(out)
    else:
        @jax.jit
        def step(state_stacked: RouterState, batch_stacked: IngressBatch):
            out = sharded(
                tuple((*state_stacked.crdt, state_stacked.topic_masks)),
                tuple(batch_stacked))
            return _unpack(out)

    return step
