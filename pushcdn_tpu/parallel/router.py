"""The device router: broadcast/direct fan-out over a broker-mesh axis.

This is the TPU lowering of the broker hot path (SURVEY.md §2e / §7 stage
7). The reference routes by hash-map lookups and per-peer TCP writes
(cdn-broker/src/tasks/broker/handler.rs:197-272); here one jitted step,
run under ``shard_map`` over the ``"brokers"`` mesh axis, does the same
work for a whole batch at once:

- **inter-broker hop** = one ``all_gather`` of the frame tensors over the
  broker axis (ICI) — every frame crosses the mesh exactly once, the
  vectorized analog of the reference's "deserialize once per hop, forward
  raw bytes" rule;
- **CRDT sync** rides the same step: per-shard DirectMap claims are
  all-gathered and folded with the versioned dominance rule
  (pushcdn_tpu.parallel.crdt) — the 10 s sync task becomes a per-step
  merge, and user topic masks travel with the ownership claim;
- **broadcast routing** = a topic-bitmask AND between every gathered frame
  and every local user (VPU; optionally the Pallas kernel in
  pushcdn_tpu.ops.topic_kernel);
- **direct routing** = equality match of the frame's destination user slot
  against locally-owned users — delivery-iff-owner makes the reference's
  ``to_user_only`` loop-prevention rule structural: nothing is ever
  re-forwarded;
- **double-connect eviction** falls out of the merge's changed-mask
  (``evictions``), exactly like ``apply_user_sync``'s kick list.

Outputs stay on device as ``(gathered frames, delivery mask)``; the host
egress pump walks the mask to enqueue frame bytes to user sockets.
"""

from __future__ import annotations

from typing import NamedTuple, Optional

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, PartitionSpec as P

from pushcdn_tpu.parallel.jax_compat import shard_map as _shard_map_compat
from pushcdn_tpu.parallel.crdt import (
    ABSENT,
    CrdtState,
    empty_state,
    merge_all_gathered_with_payload,
)
from pushcdn_tpu.ops.delivery_kernel import delivery_matrix
from pushcdn_tpu.ops.ragged_delivery import ragged_delivery

BROKER_AXIS = "brokers"

# None = auto (Pallas on TPU / interpreter elsewhere when shapes align);
# flip to False to force the jnp reference path (bench comparisons).
# `bench.py --delivery-impl {auto,pallas,jnp,ragged}` sets this before the
# first routing_step trace — the one-command delivery-impl A/B.
USE_PALLAS_DELIVERY: Optional[bool] = None

# The selected delivery implementation by name (None = auto). "ragged"
# switches consumers (bench.py, DevicePlane) onto the paged walk
# (ops.ragged_delivery) with the dense kernel kept as the in-repo twin.
DELIVERY_IMPL: Optional[str] = None

# Pallas-vs-jnp switch for the RAGGED kernel specifically (None = auto:
# Pallas on real TPU, jnp twin elsewhere — same policy as the dense flag).
RAGGED_USE_PALLAS: Optional[bool] = None


def set_delivery_impl(impl: str) -> None:
    """One switch for every delivery-impl consumer: 'auto' restores the
    backend-keyed default, 'pallas'/'jnp' force the dense kernel's mode,
    'ragged' selects the paged walk (jnp twin off-TPU)."""
    global DELIVERY_IMPL, USE_PALLAS_DELIVERY
    if impl not in ("auto", "pallas", "jnp", "ragged"):
        raise ValueError(f"unknown delivery impl {impl!r}")
    DELIVERY_IMPL = None if impl == "auto" else impl
    USE_PALLAS_DELIVERY = {"pallas": True, "jnp": False}.get(impl)


# ---------------------------------------------------------------------------
# collective accounting (the one-collective-per-tick invariant)
# ---------------------------------------------------------------------------
#
# Every collective the router's programs issue goes through the two
# helpers below, which bump a trace-time counter — ``trace_collectives()``
# deltas around a jit trace count a program's collectives without parsing
# HLO. ``count_collectives`` is the lowering-level twin (counts collective
# ops in ``jit(...).lower(...).as_text()``): the mesh dryrun test asserts
# BOTH agree that a fused tick is exactly one collective.

_TRACE_COLLECTIVES = [0]


def trace_collectives() -> int:
    """Collectives traced so far in this process (diff around a trace)."""
    return _TRACE_COLLECTIVES[0]


def _all_gather_counted(x: jax.Array, axis_name: str) -> jax.Array:
    _TRACE_COLLECTIVES[0] += 1
    return jax.lax.all_gather(x, axis_name)


def _all_to_all_counted(x: jax.Array, axis_name: str) -> jax.Array:
    _TRACE_COLLECTIVES[0] += 1
    return jax.lax.all_to_all(x, axis_name, 0, 0)


def count_collectives(lowered_text: str) -> int:
    """Count collective ops in a lowered program text. Feed it
    ``jit(step).lower(*args).as_text()`` (StableHLO — one textual op per
    collective); compiled HLO can split one collective into start/done
    pairs and is not a supported input."""
    ops = ("stablehlo.all_gather", "stablehlo.all_to_all",
           "stablehlo.all_reduce", "stablehlo.collective_permute")
    if any(op in lowered_text for op in ops):
        return sum(lowered_text.count(op) for op in ops)
    # pre-stablehlo (mhlo) spelling, same one-op-per-collective property
    return sum(lowered_text.count(op) for op in
               ("mhlo.all_gather", "mhlo.all_to_all", "mhlo.all_reduce",
                "mhlo.collective_permute"))


class RouterState(NamedTuple):
    """Per-shard routing state: the DirectMap twin + per-user topic masks."""

    crdt: CrdtState          # owners/versions/identities, each int32/uint32[U]
    topic_masks: jax.Array   # uint32[U] or uint32[U, W] — authoritative at
                             # the owner (W words cover 32·W topics)


class IngressBatch(NamedTuple):
    """One step of packed ingress frames (see parallel.frames)."""

    frame_bytes: jax.Array  # uint8[S, F]
    kind: jax.Array         # int32[S]
    length: jax.Array       # int32[S]
    topic_mask: jax.Array   # uint32[S] or uint32[S, W]
    dest: jax.Array         # int32[S]
    valid: jax.Array        # bool[S]


class DirectIngress(NamedTuple):
    """Per-destination-shard direct frames (see frames.DirectBuckets):
    axis 0 = destination shard. Exchanged with ONE ``all_to_all`` over the
    broker axis — each direct frame crosses ICI exactly once, to its owner
    (SURVEY.md §2e: the point-to-point collective keyed by owner-device
    index), instead of being all-gathered to every shard."""

    frame_bytes: jax.Array  # uint8[B, C, F]
    length: jax.Array       # int32[B, C]
    dest: jax.Array         # int32[B, C]
    valid: jax.Array        # bool[B, C]


class RouteResult(NamedTuple):
    gathered_bytes: jax.Array   # uint8[B*S, F] — every frame, post-ICI
    gathered_length: jax.Array  # int32[B*S]
    deliver: jax.Array          # bool[U, B*S] — local delivery matrix
    state: RouterState          # merged CRDT + masks
    evictions: jax.Array        # bool[U] — locally-owned users now owned elsewhere
    # all_to_all direct path (None when no DirectIngress was passed):
    direct_bytes: Optional[jax.Array] = None    # uint8[B*C, F] — received frames
    direct_length: Optional[jax.Array] = None   # int32[B*C]
    direct_deliver: Optional[jax.Array] = None  # bool[U, B*C]


def empty_router_state(num_users: int, topic_words: int = 1) -> RouterState:
    shape = (num_users,) if topic_words == 1 else (num_users, topic_words)
    return RouterState(
        crdt=empty_state(num_users),
        topic_masks=jnp.zeros(shape, dtype=jnp.uint32),
    )


def _merge_gathered(state: RouterState, g_owners, g_versions, g_ids,
                    g_masks, my_index, liveness):
    """The shared CRDT anti-entropy fold over already-gathered state rows
    (one copy of the merge/liveness/eviction logic for the per-array,
    fused-packed, and ragged steps)."""
    was_local = state.crdt.owners == my_index
    merged, masks, _changed = merge_all_gathered_with_payload(
        state.crdt, state.topic_masks,
        CrdtState(g_owners, g_versions, g_ids), g_masks)
    if liveness is not None:
        # release every slot owned by a dead shard (owner index is a mesh
        # coordinate; ABSENT maps to "live" so tombstones pass through)
        owner_live = jnp.where(merged.owners == ABSENT, True,
                               liveness[jnp.clip(merged.owners, 0)])
        merged = CrdtState(
            owners=jnp.where(owner_live, merged.owners, ABSENT),
            versions=jnp.where(owner_live, merged.versions,
                               merged.versions + 1),
            identities=merged.identities,
        )
        live_b = owner_live.reshape(
            owner_live.shape + (1,) * (masks.ndim - owner_live.ndim))
        masks = jnp.where(live_b, masks, 0)
    now_local = merged.owners == my_index
    evictions = was_local & ~now_local
    return merged, masks, now_local, evictions


def _lane_deliver(masks, now_local, g_bytes, g_kind, g_length, g_tmask,
                  g_dest, g_valid, liveness) -> LaneDelivery:
    """One broadcast lane's delivery matrix from gathered frame columns."""
    B, S = g_kind.shape
    if liveness is not None:
        g_valid = g_valid & liveness[:, None]  # dead shards' frames
    valid_f = g_valid.reshape(B * S)
    kind_f = jnp.where(valid_f, g_kind.reshape(B * S), 0)
    # topic masks may be multi-word ([.., W]) for >32-topic spaces
    tmask_f = g_tmask.reshape((B * S,) + g_tmask.shape[2:])
    deliver = delivery_matrix(
        masks, now_local, tmask_f, kind_f,
        g_dest.reshape(B * S), use_pallas=USE_PALLAS_DELIVERY)
    return LaneDelivery(
        gathered_bytes=(None if g_bytes is None
                        else g_bytes.reshape(B * S, -1)),
        gathered_length=g_length.reshape(B * S),
        deliver=deliver)


def _direct_deliver(r_bytes, r_length, r_dest, r_valid, now_local,
                    liveness) -> LaneDelivery:
    """Build the local delivery mask from RECEIVED direct buckets (axis 0
    = source shard post-exchange). Delivery is iff the addressed slot is
    locally owned — ownership moves race exactly like the reference's
    forward-to-old-owner during CRDT convergence, and resolve the same
    way (deliver-iff-owner, never re-forward)."""
    if liveness is not None:
        # a dead shard's stale frames (in flight when it was declared
        # down) never deliver
        r_valid = r_valid & liveness[:, None]
    B, C = r_dest.shape
    dest_f = r_dest.reshape(B * C)
    valid_f = r_valid.reshape(B * C)
    U = now_local.shape[0]
    slots = jnp.arange(U, dtype=jnp.int32)
    deliver = (valid_f[None, :]
               & (dest_f[None, :] == slots[:, None])
               & now_local[:, None])
    return LaneDelivery(
        gathered_bytes=(None if r_bytes is None
                        else r_bytes.reshape(B * C, -1)),
        gathered_length=r_length.reshape(B * C),
        deliver=deliver)


def _direct_route(direct: DirectIngress, now_local: jax.Array,
                  axis_name: Optional[str],
                  liveness: Optional[jax.Array] = None,
                  gather_bytes: bool = True):
    """Exchange per-destination buckets and build the local delivery mask.

    ``all_to_all`` swaps the destination-shard axis for a source-shard
    axis: received[j] = what shard j staged for *this* shard."""
    if axis_name is None:
        r_bytes, r_length, r_dest, r_valid = (
            direct.frame_bytes, direct.length, direct.dest, direct.valid)
    else:
        r_bytes = (_all_to_all_counted(direct.frame_bytes, axis_name)
                   if gather_bytes else None)
        r_length = _all_to_all_counted(direct.length, axis_name)
        r_dest = _all_to_all_counted(direct.dest, axis_name)
        r_valid = _all_to_all_counted(direct.valid, axis_name)
    lane = _direct_deliver(r_bytes, r_length, r_dest, r_valid, now_local,
                           liveness)
    return lane.gathered_bytes, lane.gathered_length, lane.deliver


def routing_step(state: RouterState, batch: IngressBatch,
                 my_index: jax.Array, axis_name: Optional[str],
                 direct: Optional[DirectIngress] = None
                 ) -> RouteResult:
    """One routing step for one broker shard — the single-lane special case
    of :func:`routing_step_lanes` (one copy of the collective/merge logic).

    With ``axis_name=None`` this is the single-broker fast path (no
    collectives — the degenerate mesh). Under ``shard_map`` the gathers run
    over ICI.
    """
    r = routing_step_lanes(state, (batch,), my_index, axis_name,
                           directs=() if direct is None else (direct,))
    lane = r.lanes[0]
    d = r.direct_lanes[0] if r.direct_lanes else None
    return RouteResult(
        gathered_bytes=lane.gathered_bytes,
        gathered_length=lane.gathered_length,
        deliver=lane.deliver,
        state=r.state,
        evictions=r.evictions,
        direct_bytes=None if d is None else d.gathered_bytes,
        direct_length=None if d is None else d.gathered_length,
        direct_deliver=None if d is None else d.deliver,
    )


# ---------------------------------------------------------------------------
# size-bucketed lanes (SURVEY.md §7 hard-part #1)
# ---------------------------------------------------------------------------
#
# One fixed frame size can't serve 100 B acks and 32 KB proposals at once:
# sizing slots for the big ones wastes HBM and ICI bandwidth on padding,
# sizing for the small ones bounces everything else to the host path. A
# *lane* is an independently-shaped FrameRing (slots × frame_bytes); the
# lane step routes any number of lanes in ONE jitted program with ONE CRDT
# merge — per-lane all_gathers over the broker axis, per-lane delivery
# matrices against the same merged ownership/mask state.


class LaneDelivery(NamedTuple):
    """Per-lane router output: the gathered frames + delivery matrix."""

    gathered_bytes: jax.Array   # uint8[B*S_l, F_l]
    gathered_length: jax.Array  # int32[B*S_l]
    deliver: jax.Array          # bool[U, B*S_l]


class MultiRouteResult(NamedTuple):
    lanes: tuple                # Tuple[LaneDelivery, ...] (broadcast lanes)
    direct_lanes: tuple         # Tuple[LaneDelivery, ...] (all_to_all lanes)
    state: RouterState
    evictions: jax.Array        # bool[U]


def routing_step_lanes(state: RouterState,
                       batches: tuple,
                       my_index: jax.Array,
                       axis_name: Optional[str],
                       directs: tuple = (),
                       liveness: Optional[jax.Array] = None,
                       gather_bytes: bool = True,
                       fused: bool = False,
                       ) -> MultiRouteResult:
    """One routing step over any number of size-bucketed lanes.

    ``batches`` is a tuple of :class:`IngressBatch` (one per broadcast
    lane, any slot counts / frame widths); ``directs`` a tuple of
    :class:`DirectIngress` (one per direct lane). The CRDT/topic-mask
    merge runs ONCE; every lane's delivery matrix is computed against the
    same merged state, so cross-lane semantics are identical to a single
    ring — a lane is purely a shape bucket.

    ``gather_bytes=False`` skips the frame-byte collectives entirely
    (lanes come back with ``gathered_bytes=None``): on a single-host
    multi-chip topology every shard's staged frames already live in the
    one host's memory, so moving payload bytes over ICI and back through
    D2H is pure waste — only the *delivery decision* needs the mesh. The
    egress pump reads payloads from the host ring snapshots instead
    (broker/mesh_group.py). Multi-host deployments keep the default: a
    remote host's frame bytes exist nowhere locally except via the
    step's collectives.

    ``liveness`` (bool[B], identical on every shard) is the dynamic-
    membership mask over the STATIC device mesh (SURVEY.md §7 hard-part
    #3): the physical mesh can't churn the way the reference's broker
    mesh does (heartbeat.rs:69-107), so a departed shard is instead
    declared dead by the host control plane. In-step that means (a) its
    gathered frames never deliver, and (b) every slot it owned is
    tombstoned with a deterministic version bump — all shards compute the
    identical release from the identical gathered state, so the CRDT
    stays convergent, exactly like the reference aging a dead broker's
    users out of the DirectMap.

    ``fused=True`` re-expresses the whole inter-broker hop as ONE
    sharding-aware collective (see :func:`_routing_step_lanes_fused`).
    """
    if fused and axis_name is not None:
        return _routing_step_lanes_fused(state, batches, my_index,
                                         axis_name, directs, liveness,
                                         gather_bytes)

    def gather(x):
        if axis_name is None:
            return x[None]
        return _all_gather_counted(x, axis_name)

    # ---- CRDT anti-entropy: once, shared by every lane -------------------
    merged, masks, now_local, evictions = _merge_gathered(
        state, gather(state.crdt.owners), gather(state.crdt.versions),
        gather(state.crdt.identities), gather(state.topic_masks),
        my_index, liveness)

    # ---- per-lane inter-broker hop + delivery matrix ---------------------
    lanes = []
    for batch in batches:
        lanes.append(_lane_deliver(
            masks, now_local,
            gather(batch.frame_bytes) if gather_bytes else None,
            gather(batch.kind), gather(batch.length),
            gather(batch.topic_mask), gather(batch.dest),
            gather(batch.valid), liveness))

    direct_lanes = []
    for direct in directs:
        d_bytes, d_length, d_deliver = _direct_route(
            direct, now_local, axis_name, liveness,
            gather_bytes=gather_bytes)
        direct_lanes.append(LaneDelivery(
            gathered_bytes=d_bytes, gathered_length=d_length,
            deliver=d_deliver))

    return MultiRouteResult(
        lanes=tuple(lanes), direct_lanes=tuple(direct_lanes),
        state=RouterState(crdt=merged, topic_masks=masks),
        evictions=evictions)


# ---------------------------------------------------------------------------
# the fused one-collective tick
# ---------------------------------------------------------------------------
#
# The per-array step above issues 4 state gathers + 5-6 gathers per lane +
# 3-4 all_to_alls per direct lane — a dozen-plus collectives per tick,
# each paying its own dispatch latency. Following the array-redistribution
# decomposition of "Memory-efficient array redistribution through portable
# collective communication" (PAPERS.md), the whole tick's exchange is ONE
# redistribution over a packed ragged buffer: every gathered leaf is
# bitcast to u32 words and concatenated (the per-shard segment layout is a
# trace-time constant), one all_gather moves it, and the leaves are sliced
# back out of the [B, L] result. The per-lane all_to_all of the direct
# path folds into the same collective: an all_to_all is an all_gather
# composed with a local slice (each shard keeps column ``my_index`` of the
# gathered destination axis), so directs ride the one buffer too — at a
# B-fold redundancy on direct payload bytes, which the single-host planes
# (gather_bytes=False, metadata only) never pay; multi-host deployments
# that gather payload can flip ``fused=False`` to get the leaner
# two-schedule form back.


class _WordPacker:
    """Trace-time leaf packer: add() bitcasts each array to u32 words,
    pack() concatenates, unpack() slices a gathered [B, L] buffer back
    into [B, ...]-shaped leaves in add() order."""

    def __init__(self):
        self._parts = []
        self._specs = []  # (kind, shape, pad)

    def add(self, x: jax.Array) -> None:
        shape = x.shape
        if x.dtype == jnp.bool_:
            words = x.astype(jnp.uint32).reshape(-1)
            self._specs.append(("bool", shape, 0))
        elif x.dtype == jnp.uint8:
            flat = x.reshape(-1)
            pad = (-flat.shape[0]) % 4
            if pad:
                flat = jnp.concatenate([flat, jnp.zeros(pad, jnp.uint8)])
            words = jax.lax.bitcast_convert_type(
                flat.reshape(-1, 4), jnp.uint32)
            self._specs.append(("u8", shape, pad))
        elif x.dtype == jnp.uint32:
            words = x.reshape(-1)
            self._specs.append(("u32", shape, 0))
        elif x.dtype == jnp.int32:
            words = jax.lax.bitcast_convert_type(x, jnp.uint32).reshape(-1)
            self._specs.append(("i32", shape, 0))
        else:  # pragma: no cover - router leaves are the four above
            raise TypeError(f"unpackable dtype {x.dtype}")
        self._parts.append(words)

    def pack(self) -> jax.Array:
        return jnp.concatenate(self._parts)

    def unpack(self, gathered: jax.Array) -> list:
        B = gathered.shape[0]
        outs = []
        off = 0
        for (kind, shape, pad), part in zip(self._specs, self._parts):
            n = part.shape[0]
            words = gathered[:, off:off + n]
            off += n
            if kind == "bool":
                out = (words != 0).reshape((B,) + shape)
            elif kind == "u8":
                u8 = jax.lax.bitcast_convert_type(
                    words, jnp.uint8).reshape(B, -1)
                if pad:
                    u8 = u8[:, :-pad]
                out = u8.reshape((B,) + shape)
            elif kind == "u32":
                out = words.reshape((B,) + shape)
            else:
                out = jax.lax.bitcast_convert_type(
                    words, jnp.int32).reshape((B,) + shape)
            outs.append(out)
        return outs


def _routing_step_lanes_fused(state: RouterState, batches: tuple,
                              my_index: jax.Array, axis_name: str,
                              directs: tuple,
                              liveness: Optional[jax.Array],
                              gather_bytes: bool) -> MultiRouteResult:
    """One-collective tick: pack → all_gather → unpack → the same merge
    and delivery math as the per-array step (bit-identical outputs)."""
    pk = _WordPacker()
    pk.add(state.crdt.owners)
    pk.add(state.crdt.versions)
    pk.add(state.crdt.identities)
    pk.add(state.topic_masks)
    for batch in batches:
        if gather_bytes:
            pk.add(batch.frame_bytes)
        pk.add(batch.kind)
        pk.add(batch.length)
        pk.add(batch.topic_mask)
        pk.add(batch.dest)
        pk.add(batch.valid)
    for direct in directs:
        if gather_bytes:
            pk.add(direct.frame_bytes)
        pk.add(direct.length)
        pk.add(direct.dest)
        pk.add(direct.valid)

    # the tick's ONE collective
    gathered = _all_gather_counted(pk.pack(), axis_name)
    fields = iter(pk.unpack(gathered))

    merged, masks, now_local, evictions = _merge_gathered(
        state, next(fields), next(fields), next(fields), next(fields),
        my_index, liveness)

    lanes = []
    for _batch in batches:
        g_bytes = next(fields) if gather_bytes else None
        lanes.append(_lane_deliver(
            masks, now_local, g_bytes, next(fields), next(fields),
            next(fields), next(fields), next(fields), liveness))

    def sel(x):
        # the all_to_all re-expressed post-gather: keep column `my_index`
        # of the gathered destination axis (received[src] = what src
        # staged for THIS shard)
        if x is None:
            return None
        return jax.lax.dynamic_index_in_dim(x, my_index, axis=1,
                                            keepdims=False)

    direct_lanes = []
    for _direct in directs:
        g_bytes = next(fields) if gather_bytes else None
        g_length = next(fields)
        g_dest = next(fields)
        g_valid = next(fields)
        direct_lanes.append(_direct_deliver(
            sel(g_bytes), sel(g_length), sel(g_dest), sel(g_valid),
            now_local, liveness))

    return MultiRouteResult(
        lanes=tuple(lanes), direct_lanes=tuple(direct_lanes),
        state=RouterState(crdt=merged, topic_masks=masks),
        evictions=evictions)


# ---------------------------------------------------------------------------
# the ragged delivery step (single-shard planes + bench)
# ---------------------------------------------------------------------------


class RaggedRouteResult(NamedTuple):
    """Compact per-candidate delivery output: row ``w`` of ``out_user``
    is a receiver run for frame ``walk_frame[w]`` (-1 lanes empty)."""

    out_user: jax.Array  # int32[Wp, PAGE]
    counts: jax.Array    # int32[Wp]
    state: RouterState
    evictions: jax.Array


def routing_step_ragged(state: RouterState, batch: IngressBatch,
                        pages: jax.Array, walk_page: jax.Array,
                        walk_frame: jax.Array, my_index: jax.Array,
                        use_pallas: Optional[bool] = None,
                        interpret: Optional[bool] = None
                        ) -> RaggedRouteResult:
    """One single-shard routing step through the ragged paged kernel
    (ops.ragged_delivery): the same CRDT fold as the dense step, then a
    page walk instead of the U x N sweep. The walk inputs come from
    ``RaggedInterest.pack`` on the host. Single-shard by design — the
    mesh planes keep the dense kernel (their fan-out is dominated by the
    gathered frame set); the ragged walk is where the single-broker
    fan-out cost lives."""
    merged, masks, now_local, evictions = _merge_gathered(
        state, state.crdt.owners[None], state.crdt.versions[None],
        state.crdt.identities[None], state.topic_masks[None],
        my_index, None)
    kind_f = jnp.where(batch.valid, batch.kind, 0)
    if use_pallas is None:
        use_pallas = RAGGED_USE_PALLAS
    out_user, counts = ragged_delivery(
        pages, walk_page, walk_frame, now_local, masks,
        batch.topic_mask, kind_f, batch.dest,
        use_pallas=use_pallas, interpret=interpret)
    return RaggedRouteResult(
        out_user=out_user, counts=counts,
        state=RouterState(crdt=merged, topic_masks=masks),
        evictions=evictions)


# ---------------------------------------------------------------------------
# jitted entry points
# ---------------------------------------------------------------------------

@jax.jit
def routing_step_single(state: RouterState, batch: IngressBatch
                        ) -> RouteResult:
    """Single-chip step (mesh of one): the compile-checked `entry()` path."""
    return routing_step(state, batch, jnp.int32(0), axis_name=None)


import functools


@functools.partial(jax.jit, static_argnames=("gather_bytes",))
def routing_step_lanes_single(state: RouterState, batches: tuple,
                              directs: tuple = (),
                              gather_bytes: bool = True
                              ) -> MultiRouteResult:
    """Single-chip lane step (a change in the number of lanes is a pytree
    structure change, so jit retraces per lane-set shape).
    ``gather_bytes=False`` keeps frame bytes out of the step entirely —
    the single-shard plane's egress reads them from the host ring
    snapshot, so only the delivery matrix crosses PCIe back."""
    return routing_step_lanes(state, batches, jnp.int32(0), axis_name=None,
                              directs=directs, gather_bytes=gather_bytes)


@functools.partial(jax.jit, static_argnames=("use_pallas", "interpret"))
def routing_step_ragged_single(state: RouterState, batch: IngressBatch,
                               pages: jax.Array, walk_page: jax.Array,
                               walk_frame: jax.Array,
                               use_pallas: Optional[bool] = None,
                               interpret: Optional[bool] = None
                               ) -> RaggedRouteResult:
    """Jitted single-chip ragged step (walk shapes key the jit cache —
    ``RaggedInterest.pack`` pads them to WALK_ROUND granules)."""
    return routing_step_ragged(state, batch, pages, walk_page, walk_frame,
                               jnp.int32(0), use_pallas=use_pallas,
                               interpret=interpret)


def make_mesh_lane_step(mesh: Mesh, gather_bytes: bool = True,
                        fused: bool = False):
    """Build the multi-chip lane step: every leaf of (state, batches,
    directs) is stacked on a leading broker axis and sharded over the mesh;
    one jitted shard_map program routes all lanes (one shared CRDT merge).
    ``liveness`` is stacked [B, B] (every shard carries the full
    membership mask). ``gather_bytes=False`` builds the single-host
    variant whose lanes skip the frame-byte collectives (see
    :func:`routing_step_lanes`). ``fused=True`` builds the
    one-collective-per-tick variant: the whole exchange rides a single
    packed all_gather (see :func:`_routing_step_lanes_fused`)."""

    def per_shard(state: RouterState, batches: tuple, directs: tuple,
                  liveness: jax.Array):
        state = jax.tree.map(lambda x: x[0], state)
        batches = jax.tree.map(lambda x: x[0], batches)
        directs = jax.tree.map(lambda x: x[0], directs)
        my = jax.lax.axis_index(BROKER_AXIS).astype(jnp.int32)
        result = routing_step_lanes(state, batches, my,
                                    axis_name=BROKER_AXIS, directs=directs,
                                    liveness=liveness[0],
                                    gather_bytes=gather_bytes,
                                    fused=fused)
        return jax.tree.map(lambda x: x[None], result)

    sharded = _shard_map_compat(
        per_shard, mesh=mesh,
        in_specs=(P(BROKER_AXIS), P(BROKER_AXIS), P(BROKER_AXIS),
                  P(BROKER_AXIS)),
        out_specs=P(BROKER_AXIS))

    @jax.jit
    def step(state, batches, directs, liveness=None):
        if liveness is None:
            B = mesh.devices.size
            liveness = jnp.ones((B, B), dtype=bool)
        return sharded(state, batches, directs, liveness)

    return step


def make_mesh_routing_step(mesh: Mesh, with_direct: bool = False):
    """Build the multi-chip step: state+batch sharded over the broker axis,
    one jitted shard_map program (SURVEY.md §7 stage 7: broker shards ↔
    devices of a jax mesh). With ``with_direct`` the step also takes
    stacked :class:`DirectIngress` buckets ([B_src, B_dest, C, F]) and runs
    the one-hop ``all_to_all`` direct path inside the same program."""

    def per_shard(state_leaves, batch_leaves, *direct_leaves):
        state = RouterState(CrdtState(*state_leaves[:3]), state_leaves[3])
        batch = IngressBatch(*batch_leaves)
        # shard_map gives each shard its [1, ...] block; drop the outer axis
        state = jax.tree.map(lambda x: x[0], state)
        batch = jax.tree.map(lambda x: x[0], batch)
        direct = None
        if direct_leaves:
            direct = DirectIngress(*(x[0] for x in direct_leaves[0]))
        my = jax.lax.axis_index(BROKER_AXIS).astype(jnp.int32)
        result = routing_step(state, batch, my, axis_name=BROKER_AXIS,
                              direct=direct)
        # re-add the sharded leading axis for the outputs
        return jax.tree.map(lambda x: x[None], tuple(result))

    n_in = 3 if with_direct else 2
    sharded = _shard_map_compat(
        per_shard, mesh=mesh,
        in_specs=tuple(P(BROKER_AXIS) for _ in range(n_in)),
        out_specs=P(BROKER_AXIS))

    def _unpack(out):
        return RouteResult(
            gathered_bytes=out[0], gathered_length=out[1], deliver=out[2],
            state=out[3], evictions=out[4],
            direct_bytes=out[5], direct_length=out[6], direct_deliver=out[7])

    if with_direct:
        @jax.jit
        def step(state_stacked: RouterState, batch_stacked: IngressBatch,
                 direct_stacked: DirectIngress):
            out = sharded(
                tuple((*state_stacked.crdt, state_stacked.topic_masks)),
                tuple(batch_stacked), tuple(direct_stacked))
            return _unpack(out)
    else:
        @jax.jit
        def step(state_stacked: RouterState, batch_stacked: IngressBatch):
            out = sharded(
                tuple((*state_stacked.crdt, state_stacked.topic_masks)),
                tuple(batch_stacked))
            return _unpack(out)

    return step
