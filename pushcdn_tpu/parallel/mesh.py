"""Broker-mesh topology: devices as broker shards.

Capability parity with SURVEY.md §2e's north-star row "Discovery registry →
device-mesh topology query": on a TPU pod the broker mesh is *static* — its
membership is the device list of a ``jax.sharding.Mesh`` — so
``get_other_brokers`` is answered from mesh coordinates with **zero I/O**,
while permits + whitelist (durable, user-facing state) stay in a backing
discovery store. Dynamic membership (the reference's churn case, bad-broker)
maps to a **liveness mask** over a fixed max-size mesh (SURVEY.md §7 hard
part #3): dead shards are masked out of routing rather than reshaping the
mesh; re-forming the physical mesh is a slow-path host event.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import List, Optional

import jax
import numpy as np
from jax.sharding import Mesh

from pushcdn_tpu.parallel.router import BROKER_AXIS
from pushcdn_tpu.proto.discovery.base import BrokerIdentifier, DiscoveryClient
from pushcdn_tpu.proto.discovery.embedded import Embedded
from pushcdn_tpu.proto.error import ErrorKind, bail


def make_broker_mesh(num_brokers: Optional[int] = None,
                     devices=None) -> Mesh:
    """A 1-D mesh whose ``"brokers"`` axis is the broker-shard axis.

    On a pod slice the devices are laid out so neighboring broker indexes
    are ICI neighbors (jax's default device order follows the torus);
    inter-broker all_gathers then ride ICI rings, never DCN/host.
    """
    if devices is None:
        devices = jax.devices()
    if num_brokers is not None:
        if num_brokers > len(devices):
            bail(ErrorKind.PARSE,
                 f"asked for {num_brokers} broker shards but only "
                 f"{len(devices)} devices are attached")
        devices = devices[:num_brokers]
    return Mesh(np.array(devices), (BROKER_AXIS,))


def broker_identifier_for_device(mesh: Mesh, index: int) -> BrokerIdentifier:
    """Synthesize the canonical identity of a device-resident broker shard.

    The string form keeps the BrokerIdentifier total order aligned with the
    mesh index order, so CRDT tie-breaks agree between the host plane and
    the device plane.
    """
    dev = mesh.devices.flat[index]
    return BrokerIdentifier(
        public_advertise_endpoint=f"mesh{index:04d}:pub",
        private_advertise_endpoint=f"device:{dev.id}",
    )


class MeshDiscovery(DiscoveryClient):
    """Discovery backed by mesh topology for membership + an embedded store
    for permits/whitelist.

    - ``get_other_brokers`` / ``get_with_least_connections``: answered from
      the mesh (+ liveness mask, + host-reported load), no I/O;
    - ``issue_permit`` / ``validate_permit`` / whitelist: delegated to the
      backing :class:`Embedded` store (durable, shared with the marshal).
    """

    def __init__(self, mesh: Mesh, backing: Embedded,
                 identity: Optional[BrokerIdentifier]):
        self.mesh = mesh
        self.backing = backing
        self.identity = identity
        n = mesh.devices.size
        self.live = np.ones(n, dtype=bool)     # liveness mask (host-managed)
        self.load = np.zeros(n, dtype=np.int64)  # host-reported user counts
        if identity is not None and identity not in self._identifiers():
            bail(ErrorKind.PARSE,
                 f"identity {identity} is not a shard of this mesh; use "
                 "broker_identifier_for_device(mesh, i)")

    @classmethod
    async def new(cls, endpoint: str,
                  identity: Optional[BrokerIdentifier] = None,
                  global_permits: bool = False,
                  mesh: Optional[Mesh] = None) -> "MeshDiscovery":
        backing = await Embedded.new(endpoint, identity=identity,
                                     global_permits=global_permits)
        return cls(mesh if mesh is not None else make_broker_mesh(),
                   backing, identity)

    # -- membership from topology ------------------------------------------

    def _identifiers(self) -> List[BrokerIdentifier]:
        return [broker_identifier_for_device(self.mesh, i)
                for i in range(self.mesh.devices.size)]

    def mark_dead(self, index: int) -> None:
        """Mask a shard out of routing (the churn slow-path)."""
        self.live[index] = False

    def mark_live(self, index: int) -> None:
        self.live[index] = True

    async def perform_heartbeat(self, num_connections: int,
                                heartbeat_expiry_s: float) -> None:
        """Load is recorded in-process; mesh membership needs no TTL (a
        device doesn't silently leave — the host marks it dead)."""
        if self.identity is None:
            bail(ErrorKind.PARSE, "heartbeat requires a broker identity")
        for i, ident in enumerate(self._identifiers()):
            if ident == self.identity:
                self.load[i] = num_connections
                return

    async def get_other_brokers(self) -> List[BrokerIdentifier]:
        return [ident for i, ident in enumerate(self._identifiers())
                if self.live[i] and ident != self.identity]

    async def get_with_least_connections(self) -> BrokerIdentifier:
        live = [(self.load[i], i) for i in range(self.mesh.devices.size)
                if self.live[i]]
        if not live:
            bail(ErrorKind.CONNECTION, "no live broker shards in the mesh")
        _load, i = min(live)
        return broker_identifier_for_device(self.mesh, i)

    # -- durable state: delegate -------------------------------------------

    async def issue_permit(self, for_broker: BrokerIdentifier,
                           expiry_s: float, public_key: bytes) -> int:
        return await self.backing.issue_permit(for_broker, expiry_s, public_key)

    async def _validate_permit(self, broker: BrokerIdentifier,
                               permit: int) -> Optional[bytes]:
        # the base-class template already range-checked; delegate to the
        # backing store's public entry (idempotent re-check is harmless)
        return await self.backing.validate_permit(broker, permit)

    async def set_whitelist(self, users: List[bytes]) -> None:
        await self.backing.set_whitelist(users)

    async def check_whitelist(self, user: bytes) -> bool:
        return await self.backing.check_whitelist(user)

    async def close(self) -> None:
        await self.backing.close()
