"""JAX API compatibility: ``shard_map`` moved from
``jax.experimental.shard_map`` (<= 0.4.x, kwarg ``check_rep``) to
``jax.shard_map`` (newer, kwarg ``check_vma``). The deployment images span
both — INCLUDING the jax 0.5.x window where ``jax.shard_map`` already
exists but still takes ``check_rep`` — so the check kwarg is keyed on the
function's actual signature, not on ``hasattr(jax, "shard_map")``. Every
call site goes through :func:`shard_map` here."""

from __future__ import annotations

import inspect

import jax


def _resolve(mod=None):
    """Pick (shard_map function, check-kwarg name) for ``mod`` (default:
    the installed jax). Signature inspection first; for opaque signatures
    (``**kwargs`` wrappers) the version tuple decides; no ``jax.shard_map``
    at all means the old experimental module."""
    if mod is None:
        mod = jax
    fn = getattr(mod, "shard_map", None)
    if fn is not None:
        try:
            params = inspect.signature(fn).parameters
        except (TypeError, ValueError):
            params = None
        if params is not None:
            if "check_vma" in params:
                return fn, "check_vma"
            if "check_rep" in params:
                # the 0.5.x window: top-level name, old kwarg
                return fn, "check_rep"
            if any(p.kind is inspect.Parameter.VAR_KEYWORD
                   for p in params.values()):
                ver = getattr(mod, "__version_info__", None) or (0, 0, 0)
                return fn, ("check_vma" if tuple(ver) >= (0, 6)
                            else "check_rep")
        # inspectable but with neither kwarg and no **kwargs: fall through
        # to the experimental module rather than guess
    from jax.experimental.shard_map import shard_map as legacy
    return legacy, "check_rep"


_SHARD_MAP, _CHECK_KW = _resolve()


def shard_map(f, mesh, in_specs, out_specs, check: bool = False):
    return _SHARD_MAP(f, mesh=mesh, in_specs=in_specs, out_specs=out_specs,
                      **{_CHECK_KW: check})
