"""JAX API compatibility: ``shard_map`` moved from
``jax.experimental.shard_map`` (<= 0.4.x, kwarg ``check_rep``) to
``jax.shard_map`` (newer, kwarg ``check_vma``). The deployment images span
both; every call site goes through :func:`shard_map` here."""

from __future__ import annotations

import jax


def shard_map(f, mesh, in_specs, out_specs, check: bool = False):
    if hasattr(jax, "shard_map"):
        return jax.shard_map(f, mesh=mesh, in_specs=in_specs,
                             out_specs=out_specs, check_vma=check)
    from jax.experimental.shard_map import shard_map as _shard_map
    return _shard_map(f, mesh=mesh, in_specs=in_specs,
                      out_specs=out_specs, check_rep=check)
