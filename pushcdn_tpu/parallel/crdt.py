"""Vectorized versioned-map CRDT — the device twin of
:class:`pushcdn_tpu.broker.versioned_map.VersionedMap`.

The host CRDT is a hash map with branchy per-key merge; on TPU the same
semantics become an elementwise ``select`` over fixed-shape arrays
(SURVEY.md §7 hard-part #2: per-key argmax over (version, identity)):

- state is three aligned arrays over user slots:
  ``owners[i]`` (int32 owning-broker mesh index, ``-1`` = absent/tombstone),
  ``versions[i]`` (uint32 modification counter),
  ``identities[i]`` (int32 conflict identity of the last modifier);
- ``merge`` adopts the incoming entry wherever
  ``(v_in > v_loc) | ((v_in == v_loc) & (id_in > id_loc))`` — exactly the
  host ``VersionedValue.dominates`` rule, so the two implementations are
  property-tested for equivalence (tests/test_crdt_device.py);
- eviction ("user connected elsewhere", connections/mod.rs:154-162) falls
  out as a mask: slots that changed AND are locally connected AND whose new
  owner is not us.

All functions are jit-safe (static shapes, no data-dependent control flow)
and run identically under ``shard_map`` per mesh shard.
"""

from __future__ import annotations

from typing import NamedTuple, Tuple

import jax
import jax.numpy as jnp

ABSENT = -1  # owner value for "no claim / tombstone"


class CrdtState(NamedTuple):
    """Aligned per-slot CRDT arrays (one row of the DirectMap twin)."""

    owners: jax.Array      # int32[N]
    versions: jax.Array    # uint32[N]
    identities: jax.Array  # int32[N]


def empty_state(num_slots: int) -> CrdtState:
    return CrdtState(
        owners=jnp.full((num_slots,), ABSENT, dtype=jnp.int32),
        versions=jnp.zeros((num_slots,), dtype=jnp.uint32),
        identities=jnp.full((num_slots,), ABSENT, dtype=jnp.int32),
    )


def dominates(v_in: jax.Array, id_in: jax.Array,
              v_loc: jax.Array, id_loc: jax.Array) -> jax.Array:
    """Elementwise last-writer-wins: version, then ordered identity
    (VersionedValue.dominates / versioned_map.rs:201-269)."""
    return (v_in > v_loc) | ((v_in == v_loc) & (id_in > id_loc))


def _adopt_mask(local: CrdtState, incoming: CrdtState) -> jax.Array:
    """Where does the incoming entry win? The single source of truth for
    the LWW rule (property-tested against the host VersionedMap); every
    merge variant below routes through this."""
    adopt = dominates(incoming.versions, incoming.identities,
                      local.versions, local.identities)
    # Slots the incoming delta doesn't mention carry version 0 → never adopt
    # (version 0 is reserved: host versions start at 1).
    return adopt & (incoming.versions > 0)


@jax.jit
def merge(local: CrdtState, incoming: CrdtState) -> Tuple[CrdtState, jax.Array]:
    """Merge ``incoming`` into ``local``; returns (state', changed_mask).

    ``changed_mask[i]`` is True where the live value (owner) actually
    changed — the signal callers use for eviction, mirroring the host
    ``VersionedMap.merge`` return value.
    """
    adopt = _adopt_mask(local, incoming)
    new = CrdtState(
        owners=jnp.where(adopt, incoming.owners, local.owners),
        versions=jnp.where(adopt, incoming.versions, local.versions),
        identities=jnp.where(adopt, incoming.identities, local.identities),
    )
    changed = adopt & (incoming.owners != local.owners)
    return new, changed


@jax.jit
def eviction_mask(changed: jax.Array, new_owners: jax.Array,
                  locally_connected: jax.Array, self_index: jax.Array
                  ) -> jax.Array:
    """Which locally-connected users must be kicked because the merged map
    says another broker now owns them (the cross-broker double-connect
    kick)."""
    return changed & locally_connected & (new_owners != self_index) \
        & (new_owners != ABSENT)


@jax.jit
def local_claim(state: CrdtState, slot_mask: jax.Array,
                self_index: jax.Array) -> CrdtState:
    """Claim every slot in ``slot_mask`` for ``self_index`` (vectorized
    ``insert``: bump version, set identity)."""
    return CrdtState(
        owners=jnp.where(slot_mask, self_index, state.owners),
        versions=jnp.where(slot_mask, state.versions + 1, state.versions),
        identities=jnp.where(slot_mask, self_index, state.identities),
    )


@jax.jit
def local_release(state: CrdtState, slot_mask: jax.Array,
                  self_index: jax.Array) -> CrdtState:
    """Tombstone every slot in ``slot_mask`` we still own (vectorized
    ``remove_if_equals(slot, self)``)."""
    ours = slot_mask & (state.owners == self_index)
    return CrdtState(
        owners=jnp.where(ours, ABSENT, state.owners),
        versions=jnp.where(ours, state.versions + 1, state.versions),
        identities=jnp.where(ours, self_index, state.identities),
    )


def merge_all_gathered_with_payload(
        local: CrdtState, local_payload: jax.Array,
        gathered: CrdtState, gathered_payload: jax.Array
) -> Tuple[CrdtState, jax.Array, jax.Array]:
    """Fold every mesh peer's delta (stacked on axis 0, e.g. from an
    ``all_gather`` over the broker axis) into ``local`` — the device analog
    of applying every peer's UserSync in one step — with an aligned per-slot
    ``payload`` array riding the same dominance decision: wherever a peer's
    CRDT entry is adopted, its payload is adopted too.

    The router uses the payload for each user's **topic-subscription
    bitmask**: the owning broker is authoritative for the mask, so the mask
    travels with the ownership claim (the device analog of the reference
    pairing UserSync with TopicSync, tasks/broker/sync.rs).

    ``gathered`` arrays have shape [num_peers, N]. The merge is associative
    & commutative (a join-semilattice), so the sequential fold is exact.
    """
    def body(carry, xs):
        state, payload, changed_any = carry
        in_owners, in_versions, in_ids, in_payload = xs
        incoming = CrdtState(in_owners, in_versions, in_ids)
        adopt = _adopt_mask(state, incoming)
        new_state = CrdtState(
            owners=jnp.where(adopt, incoming.owners, state.owners),
            versions=jnp.where(adopt, incoming.versions, state.versions),
            identities=jnp.where(adopt, incoming.identities, state.identities),
        )
        # the payload may carry trailing dims beyond the slot axis (e.g.
        # multi-word topic masks [U, W]): broadcast the adoption decision
        a = adopt.reshape(adopt.shape + (1,) * (payload.ndim - adopt.ndim))
        new_payload = jnp.where(a, in_payload, payload)
        changed = adopt & (incoming.owners != state.owners)
        return (new_state, new_payload, changed_any | changed), None

    init_changed = jnp.zeros(local.owners.shape, dtype=bool)
    (state, payload, changed), _ = jax.lax.scan(
        body, (local, local_payload, init_changed),
        (gathered.owners, gathered.versions, gathered.identities,
         gathered_payload))
    return state, payload, changed


def merge_all_gathered(local: CrdtState,
                       gathered: CrdtState) -> Tuple[CrdtState, jax.Array]:
    """Payload-free variant of :func:`merge_all_gathered_with_payload`."""
    dummy = jnp.zeros(local.owners.shape, dtype=jnp.uint32)
    g_dummy = jnp.zeros(gathered.owners.shape, dtype=jnp.uint32)
    state, _payload, changed = merge_all_gathered_with_payload(
        local, dummy, gathered, g_dummy)
    return state, changed
