"""Message frames as fixed-shape HBM byte tensors.

This is the device-side twin of the wire format (SURVEY.md §7 stage 1
"tensor packing" and hard-part #1): a batch of variable-length messages is
packed into a fixed ``[SLOTS, FRAME_BYTES]`` uint8 tensor plus aligned
metadata columns, so routing runs as vectorized ops instead of per-message
Python:

- ``kind``       int32[S]  — the wire kind tag (KIND_DIRECT/KIND_BROADCAST)
- ``length``     int32[S]  — payload length in bytes (0 ⇒ empty slot)
- ``topic_mask`` uint32[S] — broadcast interest bits (1 << topic)
- ``dest``       int32[S]  — direct-recipient *user slot* (-1 for broadcast)
- ``valid``      bool[S]   — slot occupancy

The byte-semaphore backpressure of the host limiter becomes slot-credit
accounting here: a ``FrameRing`` has a fixed number of slots, ``push`` fails
when full, and the host pumps only as many messages per step as there are
free slots ("block the reader, not the router" re-expressed for HBM).

User identity on device is a dense *user slot* index managed by
``UserSlots`` (public key ↔ slot), so the DirectMap twin
(pushcdn_tpu.parallel.crdt) and the router index the same space.

Messages larger than ``frame_bytes`` stay on the host path (the reference
streams up to 512 MiB through one socket frame; the device plane is for the
fan-out-heavy small/medium message regime where throughput is won).
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

from pushcdn_tpu.proto.error import ErrorKind, bail
from pushcdn_tpu.proto.message import KIND_BROADCAST, KIND_DIRECT

DEFAULT_FRAME_BYTES = 1024
DEFAULT_SLOTS = 1024

# The reference's topic type is a u8 (message.rs:26) — 256 possible topics.
# A topic set on device is a multi-word u32 bitmask; 8 words cover the full
# space. Rings are parameterized (``topic_words=1`` keeps the compact mask
# for deployments with ≤32 topics).
TOPIC_WORDS_FULL = 8
MAX_TOPICS = 32 * TOPIC_WORDS_FULL


def split_mask(mask: int, words: int) -> np.ndarray:
    """Split an arbitrary-width Python-int topic mask into u32 words
    (little-endian: word w holds topics 32w..32w+31)."""
    out = np.zeros(words, np.uint32)
    w = 0
    while mask and w < words:
        out[w] = mask & 0xFFFFFFFF
        mask >>= 32
        w += 1
    return out


def mask_of_topics(topics, words: int) -> int:
    """Python-int bitmask of every topic representable in ``words`` u32
    words; out-of-range topics are ignored (callers pre-check)."""
    mask = 0
    limit = 32 * words
    for t in topics:
        t = int(t)
        if t < limit:
            mask |= 1 << t
    return mask


def mask_mirror_shape(n: int, words: int):
    """Shape of an ``n``-slot topic-mask mirror/column: 1-D for the
    compact 1-word representation, [n, words] otherwise. The single place
    that encodes the dual representation rule."""
    return n if words == 1 else (n, words)


def mask_row_of(topics, words: int):
    """The mask-mirror row for a topic set: a u32 scalar when ``words`` is
    1 (compact deployments, 1-D mirrors) or a uint32[words] row otherwise —
    assignable to ``mirror[slot]`` either way."""
    mask = mask_of_topics(topics, words)
    return mask & 0xFFFFFFFF if words == 1 else split_mask(mask, words)


class UserSlots:
    """Dense user-slot allocator: public key ↔ int slot (device identity)."""

    def __init__(self, capacity: int):
        self.capacity = capacity
        self._key_to_slot: Dict[bytes, int] = {}
        self._slot_to_key: List[Optional[bytes]] = [None] * capacity
        self._free: List[int] = list(range(capacity - 1, -1, -1))
        # 1 + highest slot ever assigned (lowest-free allocation order keeps
        # this tight): the device planes slice their state/delivery tensors
        # to this mark, so a 1024-slot table with 16 users costs 16-user
        # matrices, not 1024-user ones
        self.high_water = 0

    def assign(self, public_key: bytes) -> int:
        slot = self._key_to_slot.get(public_key)
        if slot is not None:
            return slot
        if not self._free:
            bail(ErrorKind.EXCEEDED_SIZE,
                 f"user-slot table full ({self.capacity})")
        slot = self._free.pop()
        self._key_to_slot[public_key] = slot
        self._slot_to_key[slot] = public_key
        if slot + 1 > self.high_water:
            self.high_water = slot + 1
        return slot

    def assign_slot(self, public_key: bytes, slot: int) -> None:
        """Bind ``public_key`` to a SPECIFIC slot (multi-host planes
        allocate from statically partitioned per-shard ranges and bind
        here). The slot must be unbound."""
        if self._slot_to_key[slot] is not None:
            bail(ErrorKind.EXCEEDED_SIZE, f"slot {slot} already bound")
        self._key_to_slot[public_key] = slot
        self._slot_to_key[slot] = public_key
        if slot + 1 > self.high_water:
            self.high_water = slot + 1

    def release(self, public_key: bytes) -> None:
        slot = self.unmap(public_key)
        if slot is not None:
            self.free_slot(slot)

    def unmap(self, public_key: bytes) -> Optional[int]:
        """Drop the key↔slot mapping WITHOUT recycling the slot index —
        callers that may still have in-flight frames addressed to the slot
        quarantine it and call :meth:`free_slot` later."""
        slot = self._key_to_slot.pop(public_key, None)
        if slot is not None:
            self._slot_to_key[slot] = None
        return slot

    def free_slot(self, slot: int) -> None:
        """Return a previously :meth:`unmap`-ed slot index to the free list."""
        if self._slot_to_key[slot] is None and slot not in self._free:
            self._free.append(slot)

    def slot_of(self, public_key: bytes) -> Optional[int]:
        return self._key_to_slot.get(public_key)

    def key_of(self, slot: int) -> Optional[bytes]:
        return self._slot_to_key[slot]

    def __len__(self) -> int:
        return len(self._key_to_slot)


@dataclass
class FrameBatch:
    """One step's worth of packed ingress frames (numpy, host-side; the
    router moves them to device)."""

    bytes_: np.ndarray      # uint8[S, F]
    kind: np.ndarray        # int32[S]
    length: np.ndarray     # int32[S]
    topic_mask: np.ndarray  # uint32[S]
    dest: np.ndarray        # int32[S]
    valid: np.ndarray       # bool[S]

    @property
    def num_valid(self) -> int:
        return int(self.valid.sum())


class FrameRing:
    """Fixed-capacity staging ring the host packs messages into.

    ``push_*`` returns False when no slot is free (backpressure: the caller
    keeps the message queued on the host). ``take_batch`` snapshots and
    clears up to ``slots`` frames for one router step.
    """

    def __init__(self, slots: int = DEFAULT_SLOTS,
                 frame_bytes: int = DEFAULT_FRAME_BYTES,
                 topic_words: int = 1):
        self.slots = slots
        self.frame_bytes = frame_bytes
        self.topic_words = topic_words
        self._bytes = np.zeros((slots, frame_bytes), dtype=np.uint8)
        self._kind = np.zeros(slots, dtype=np.int32)
        self._length = np.zeros(slots, dtype=np.int32)
        # [S] for the compact 1-word mask, [S, W] for wider topic spaces
        self._topic_mask = np.zeros(mask_mirror_shape(slots, topic_words),
                                    dtype=np.uint32)
        self._dest = np.full(slots, -1, dtype=np.int32)
        self._valid = np.zeros(slots, dtype=bool)
        self._next = 0
        self._used = 0
        self._empty: Optional[FrameBatch] = None
        self._mask_rows: dict = {}  # mask int -> uint32[W] word expansion

    @property
    def free_slots(self) -> int:
        return self.slots - self._used

    def _alloc(self) -> Optional[int]:
        # Slots fill sequentially and are only freed wholesale by
        # take_batch, so the cursor always points at a free slot.
        if self._used >= self.slots:
            return None
        i = self._next
        self._next += 1
        self._used += 1
        return i

    def _put(self, i: int, payload: bytes, kind: int, topic_mask: int,
             dest: int) -> None:
        n = len(payload)
        self._bytes[i, :n] = np.frombuffer(payload, dtype=np.uint8)
        if n < self.frame_bytes:
            self._bytes[i, n:] = 0
        self._kind[i] = kind
        self._length[i] = n
        if self.topic_words == 1:
            self._topic_mask[i] = topic_mask & 0xFFFFFFFF
        else:
            self._topic_mask[i] = split_mask(topic_mask, self.topic_words)
        self._dest[i] = dest
        self._valid[i] = True

    def push_broadcast(self, payload: bytes, topic_mask: int) -> bool:
        if len(payload) > self.frame_bytes:
            bail(ErrorKind.EXCEEDED_SIZE,
                 f"payload {len(payload)} B exceeds frame slot "
                 f"{self.frame_bytes} B; use the host path")
        i = self._alloc()
        if i is None:
            return False
        self._put(i, payload, KIND_BROADCAST, topic_mask, -1)
        return True

    def push_direct(self, payload: bytes, dest_slot: int) -> bool:
        if len(payload) > self.frame_bytes:
            bail(ErrorKind.EXCEEDED_SIZE,
                 f"payload {len(payload)} B exceeds frame slot "
                 f"{self.frame_bytes} B; use the host path")
        i = self._alloc()
        if i is None:
            return False
        self._put(i, payload, KIND_DIRECT, 0, dest_slot)
        return True

    def push_batch(self, payloads: Sequence[bytes], kinds: Sequence[int],
                   tmasks: Sequence[int], dests: Sequence[int]) -> int:
        """Pack many messages in one call via the C++ framing kernel
        (native/framing.cpp, writing straight into the ring's buffers at
        the current cursor; falls back to the Python loop). Works on a
        partially-filled ring — the batch lands after any singly-pushed
        frames.

        Returns the number packed; fewer than ``len(payloads)`` means
        exactly "ring full — re-queue the rest". Oversized payloads raise
        ``ValueError`` up front (pre-filter them to the host path), so the
        return value is never ambiguous between full and unroutable.
        """
        if not (len(kinds) == len(tmasks) == len(dests) == len(payloads)):
            raise ValueError("payloads/kinds/tmasks/dests length mismatch")
        if payloads and max(map(len, payloads)) > self.frame_bytes:
            i = next(i for i, p in enumerate(payloads)
                     if len(p) > self.frame_bytes)
            raise ValueError(
                f"payload {i} is {len(payloads[i])} B > frame slot "
                f"{self.frame_bytes} B; pre-filter to the host path")
        from pushcdn_tpu import native
        start = self._next
        kinds_a = np.asarray(kinds, np.int32)
        dests_a = np.asarray(dests, np.int32)
        if self.topic_words == 1:
            try:  # C-speed for in-range masks (the ≤32-topic contract)
                tmasks_a = np.fromiter(tmasks, np.uint32,
                                       count=len(payloads))
            except (OverflowError, ValueError, TypeError):
                tmasks_a = np.asarray(
                    [m & 0xFFFFFFFF for m in tmasks], np.uint32)
        else:
            W = self.topic_words
            tmasks_a = np.zeros((len(payloads), W), np.uint32)
            # memoized word expansion: a step's masks are drawn from the
            # few distinct topic sets in flight, so expand each distinct
            # mask once (byte-exact: little-endian u32 words == the old
            # per-word shift loop) instead of W shifts per frame
            rows = self._mask_rows

            allbits = (1 << (32 * W)) - 1

            def expand(m):
                # truncate first (same semantics as the old per-word
                # shift loop): out-of-range or negative masks must not
                # turn into OverflowError from to_bytes
                m = int(m) & allbits
                row = rows.get(m)
                if row is None:
                    if len(rows) >= 4096:  # bound pathological churn
                        rows.clear()
                    row = rows[m] = np.frombuffer(
                        m.to_bytes(4 * W, "little"), np.uint32).copy()
                return row

            if not isinstance(tmasks, list):
                tmasks = list(tmasks)  # tuples/arrays get the fast path too
            first = tmasks[0] if len(tmasks) else 0
            if tmasks.count(first) == len(tmasks):
                # one publisher, one topic set — the dominant step shape:
                # a single vectorized fill instead of a row per frame
                tmasks_a[:] = expand(first)
            else:
                for i, m in enumerate(tmasks):
                    tmasks_a[i] = expand(m)
        valid_u8 = np.zeros(self.slots - start, np.uint8)
        n = native.pack_frames_into(
            list(payloads), kinds_a, tmasks_a, dests_a,
            self._bytes[start:], self._kind[start:], self._length[start:],
            self._topic_mask[start:], self._dest[start:], valid_u8)
        if n is not None:
            self._valid[start:start + n] = True
            self._used += n
            self._next += n
            return n
        # Python fallback (identical semantics)
        n = 0
        for payload, k, tm, d in zip(payloads, kinds_a, list(tmasks),
                                     dests_a):
            i = self._alloc()
            if i is None:
                break
            self._put(i, payload, int(k), int(tm), int(d))
            n += 1
        return n

    def take_batch(self) -> FrameBatch:
        """Snapshot the ring as one step's batch and clear it (slot credits
        return to the host pump). An idle ring returns a cached all-zero
        batch (batches are read-only downstream), so idle lanes cost no
        copy per step."""
        if self._used == 0:
            if self._empty is None:
                self._empty = empty_batch(self.slots, self.frame_bytes,
                                          self.topic_words)
            return self._empty
        batch = FrameBatch(
            bytes_=self._bytes.copy(), kind=self._kind.copy(),
            length=self._length.copy(), topic_mask=self._topic_mask.copy(),
            dest=self._dest.copy(), valid=self._valid.copy(),
        )
        self._valid[:] = False
        self._length[:] = 0
        self._used = 0
        self._next = 0
        return batch


@dataclass
class DirectBatch:
    """One step of per-destination-shard direct frames (axis 0 indexes the
    DESTINATION shard). The router exchanges these with one ``all_to_all``
    over the broker axis — each frame crosses ICI exactly once, to its
    owner, instead of riding the broadcast ``all_gather`` to every shard
    (SURVEY.md §2e: direct routing = point-to-point collective keyed by
    owner-device index)."""

    bytes_: np.ndarray   # uint8[B, C, F]
    length: np.ndarray   # int32[B, C]
    dest: np.ndarray     # int32[B, C] — user slot at the destination shard
    valid: np.ndarray    # bool[B, C]


class DirectBuckets:
    """Host staging for direct frames, bucketed by owner shard. The host
    knows the owner at staging time (the group's slot table), so bucketing
    costs a list-append — no device-side sort. A full bucket is per-LINK
    backpressure (only senders targeting that shard stall), the analog of
    the reference's per-connection bounded channels."""

    def __init__(self, num_shards: int, capacity: int = 64,
                 frame_bytes: int = DEFAULT_FRAME_BYTES):
        self.num_shards = num_shards
        self.capacity = capacity
        self.frame_bytes = frame_bytes
        self._bytes = np.zeros((num_shards, capacity, frame_bytes), np.uint8)
        self._length = np.zeros((num_shards, capacity), np.int32)
        self._dest = np.full((num_shards, capacity), -1, np.int32)
        self._valid = np.zeros((num_shards, capacity), bool)
        self._used = np.zeros(num_shards, np.int64)
        self._empty: Optional[DirectBatch] = None

    @property
    def total_used(self) -> int:
        return int(self._used.sum())

    @property
    def max_used(self) -> int:
        """Largest per-destination fill — the latency-slice eligibility
        check (every bucket's frames must fit the prefix slice)."""
        return int(self._used.max())

    def push(self, dest_shard: int, payload: bytes, dest_slot: int) -> bool:
        if len(payload) > self.frame_bytes:
            bail(ErrorKind.EXCEEDED_SIZE,
                 f"payload {len(payload)} B exceeds frame slot "
                 f"{self.frame_bytes} B; use the host path")
        i = int(self._used[dest_shard])
        if i >= self.capacity:
            return False  # this link is backpressured
        n = len(payload)
        self._bytes[dest_shard, i, :n] = np.frombuffer(payload, np.uint8)
        if n < self.frame_bytes:
            self._bytes[dest_shard, i, n:] = 0
        self._length[dest_shard, i] = n
        self._dest[dest_shard, i] = dest_slot
        self._valid[dest_shard, i] = True
        self._used[dest_shard] = i + 1
        return True

    def take_batch(self) -> DirectBatch:
        if self.total_used == 0:  # idle: cached zero batch, no copies
            if self._empty is None:
                self._empty = empty_direct_batch(
                    self.num_shards, self.capacity, self.frame_bytes)
            return self._empty
        batch = DirectBatch(
            bytes_=self._bytes.copy(), length=self._length.copy(),
            dest=self._dest.copy(), valid=self._valid.copy())
        self._valid[:] = False
        self._length[:] = 0
        self._dest[:] = -1
        self._used[:] = 0
        return batch


def empty_direct_batch(num_shards: int, capacity: int,
                       frame_bytes: int) -> DirectBatch:
    return DirectBatch(
        bytes_=np.zeros((num_shards, capacity, frame_bytes), np.uint8),
        length=np.zeros((num_shards, capacity), np.int32),
        dest=np.full((num_shards, capacity), -1, np.int32),
        valid=np.zeros((num_shards, capacity), bool),
    )


def slice_batch(b: FrameBatch, n: int) -> FrameBatch:
    """Prefix-slice a batch to its first ``n`` slots (views, no copies) —
    the latency-shape path: rings fill sequentially from slot 0, so when
    ``used <= n`` the prefix holds every staged frame."""
    return FrameBatch(
        bytes_=b.bytes_[:n], kind=b.kind[:n], length=b.length[:n],
        topic_mask=b.topic_mask[:n], dest=b.dest[:n], valid=b.valid[:n])


def slice_direct_batch(d: DirectBatch, n: int) -> DirectBatch:
    """Prefix-slice every destination bucket to ``n`` slots (views)."""
    return DirectBatch(
        bytes_=d.bytes_[:, :n], length=d.length[:, :n],
        dest=d.dest[:, :n], valid=d.valid[:, :n])


def stage_best_fit(lanes, size: int, push) -> bool:
    """Stage into the smallest lane a ``size``-byte frame fits, spilling to
    wider lanes when the best fit is full (a wider slot just pads more).
    ``lanes`` must be sorted ascending by ``frame_bytes``; ``push(lane)``
    does the actual staging and returns False when that lane is full.
    Returns False only when every eligible lane is full (backpressure) —
    callers pre-check ``size`` against the widest lane for eligibility."""
    for lane in lanes:
        if size <= lane.frame_bytes and push(lane):
            return True
    return False


def empty_batch(slots: int, frame_bytes: int,
                topic_words: int = 1) -> FrameBatch:
    return FrameBatch(
        bytes_=np.zeros((slots, frame_bytes), np.uint8),
        kind=np.zeros(slots, np.int32),
        length=np.zeros(slots, np.int32),
        topic_mask=np.zeros(mask_mirror_shape(slots, topic_words),
                            np.uint32),
        dest=np.full(slots, -1, np.int32),
        valid=np.zeros(slots, bool),
    )
