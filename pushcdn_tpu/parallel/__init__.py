"""The device data plane: broker shards on a JAX mesh.

This package is the TPU-native heart of the framework (SURVEY.md §2e /
§7 stage 7). The host control plane (transports, auth, discovery) feeds
fixed-shape HBM-resident state here:

- ``frames``  — message frames packed into byte tensors (slot rings)
- ``crdt``    — vectorized versioned-map merge (the DirectMap twin)
- ``router``  — jitted broadcast/direct routing over a broker-mesh axis:
  masked ``all_gather`` fan-out, ``ppermute`` direct hops
- ``mesh``    — broker-mesh topology; answers "get_other_brokers" from mesh
  coordinates instead of the discovery registry
"""
