"""Multi-host broker meshes: ICI within a slice, DCN across hosts.

The reference scales across machines with per-peer TCP links coordinated
by the discovery registry (SURVEY.md §1-L0/L5). The TPU-native equivalent
is a **global device mesh spanning every host's chips**: jax's runtime is
SPMD — every host process runs the same jitted routing step over the same
global mesh, XLA partitions the collectives so the all_gather/all_to_all
hops ride ICI inside each slice and DCN only where the mesh crosses
slices. No NCCL/MPI and no per-peer socket code: the collective IS the
inter-broker transport (BASELINE.json north star).

Deployment contract (mirrors jax.distributed):

1. every host calls :func:`initialize` with the same coordinator address
   and its own ``process_id`` (on Cloud TPU all three args are inferred);
2. every host builds the same global mesh via :func:`pod_broker_mesh`;
3. each host's brokers attach only to its LOCAL shards
   (:func:`local_shard_indices`) — users terminate at the host that owns
   their shard, exactly like the reference pinning a user to one broker;
4. every host participates in every step (SPMD): the per-shard CRDT
   claims diverge across hosts and the in-step merge converges them —
   the device program is identical to the single-host one
   (pushcdn_tpu.parallel.router), which is why the single-host group
   property-tests stand in for pod behavior.

Mesh geometry: :func:`pod_broker_mesh` keeps jax's default device order,
which walks each process's devices consecutively — so the broker axis is
contiguous per host and ICI neighbors stay mesh neighbors; the all_gather
ring crosses DCN exactly (num_hosts) times per step, the minimum any
all-host exchange can do.
"""

from __future__ import annotations

from typing import List, Optional

import jax
from jax.sharding import Mesh

from pushcdn_tpu.parallel.mesh import make_broker_mesh


def _distributed_initialized() -> bool:
    """``jax.distributed.is_initialized`` appeared after 0.4.37; older
    images expose the same fact via the private global client handle."""
    checker = getattr(jax.distributed, "is_initialized", None)
    if checker is not None:
        return bool(checker())
    try:
        from jax._src import distributed as _dist
        return _dist.global_state.client is not None
    except Exception:
        return False


def initialize(coordinator_address: Optional[str] = None,
               num_processes: Optional[int] = None,
               process_id: Optional[int] = None) -> None:
    """Join the multi-host runtime (idempotent). On Cloud TPU all args are
    auto-detected; elsewhere pass the coordinator's ``host:port``, the
    process count, and this process's rank — the same contract as the
    reference's discovery endpoint + broker identity pair."""
    if _distributed_initialized():
        return  # idempotent: already joined (explicit or auto)
    kwargs = {}
    if coordinator_address is not None:
        kwargs["coordinator_address"] = coordinator_address
    if num_processes is not None:
        kwargs["num_processes"] = num_processes
    if process_id is not None:
        kwargs["process_id"] = process_id
    try:
        jax.distributed.initialize(**kwargs)
    except (RuntimeError, ValueError):
        if kwargs:
            raise  # an explicit join that failed is a real error
        # bare call with nothing to auto-detect (off-pod: ValueError) or
        # after the backend already started (RuntimeError): single-process
        # runtime, nothing to join


def pod_broker_mesh(num_brokers: Optional[int] = None) -> Mesh:
    """The GLOBAL broker mesh over every host's devices. Must be called
    with identical arguments on every process (SPMD).

    ``num_brokers`` may not exclude a whole host: jax's device order is
    process-contiguous, so truncating past a host boundary would leave
    that process with zero local shards in a mesh it must still execute
    collectively — a guaranteed hang or failure. Use every host or run a
    smaller deployment.
    """
    mesh = make_broker_mesh(num_brokers, devices=jax.devices())
    covered = {d.process_index for d in mesh.devices.flat}
    if len(covered) != jax.process_count():
        from pushcdn_tpu.proto.error import ErrorKind, bail
        bail(ErrorKind.PARSE,
             f"num_brokers={num_brokers} covers only {len(covered)} of "
             f"{jax.process_count()} host processes; every SPMD process "
             "needs at least one local shard")
    return mesh


def local_shard_indices(mesh: Mesh) -> List[int]:
    """Broker-shard indices whose device lives on THIS host — the shards
    this process's brokers may attach to (users terminate here)."""
    me = jax.process_index()
    return [i for i, d in enumerate(mesh.devices.flat)
            if d.process_index == me]


def dcn_crossings(mesh: Mesh) -> int:
    """How many times the broker-axis ring crosses a host boundary — the
    per-step DCN hop count of the all_gather (diagnostic; minimal when
    each host's devices are contiguous on the axis)."""
    devs = list(mesh.devices.flat)
    return sum(1 for a, b in zip(devs, devs[1:] + devs[:1])
               if a.process_index != b.process_index)
