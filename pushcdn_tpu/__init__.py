"""tpu-push-cdn: a TPU-native publish/subscribe + direct-messaging framework.

A brand-new design with the capabilities of EspressoSystems/Push-CDN
(reference layer map in SURVEY.md): a marshal (authentication gateway /
load balancer), a mesh of brokers routing broadcast + direct messages via
eventually-consistent (versioned-map CRDT) state, and an elastic
self-reconnecting client.

Architecture (TPU-first, not a port):

- **Host control plane** (``pushcdn_tpu.proto``, ``.broker``, ``.marshal``,
  ``.client``): asyncio transports, authenticated handshakes, discovery,
  supervision. Mirrors the *capabilities* of the reference's Rust actor
  stack (cdn-proto / cdn-broker / cdn-marshal / cdn-client).
- **Device data plane** (``pushcdn_tpu.parallel``, ``.ops``): broker shards
  mapped onto a ``jax.sharding.Mesh``; message frames packed into
  HBM-resident byte tensors; broadcast fan-out as masked ``all_gather`` and
  direct routing as ``ppermute``/all-to-all over ICI; topic-subscription
  masking and frame scatter/gather as Pallas kernels; the versioned-map CRDT
  merge as a vectorized jittable kernel.
"""

__version__ = "0.1.0"

from pushcdn_tpu import _aio_compat

_aio_compat.install()  # asyncio.timeout backport for 3.10 images

from pushcdn_tpu.proto.error import Error, ErrorKind  # noqa: F401
