"""Fused delivery-matrix kernel: the router's inner loop as one Pallas op.

Computes, for every (local user u, gathered frame n):

    deliver[u, n] = local[u] & ( broadcast_hit(u, n) | direct_hit(u, n) )
    broadcast_hit = kind[n]==BROADCAST and (user_mask[u] & frame_mask[n]) != 0
    direct_hit    = kind[n]==DIRECT    and dest[n] == u
    local         = owners[u] == my_index   (precomputed on entry)

This is the vectorized twin of ``get_interested_by_topic`` +
``get_broker_identifier_of_user`` dispatch (cdn-broker routing core,
tasks/broker/handler.rs:197-272), fused so the delivery matrix is produced
in one VMEM pass. Invalid slots must be pre-masked by the caller (kind=0).

Tiling: users ride the sublane axis (8/tile), frames the lane axis
(128/tile) — int32-native VPU shapes. Inputs are row/column vectors
broadcast into each tile, so HBM traffic is O(U + N), not O(U×N).

Off-TPU the kernel runs in interpreter mode; the pure-jnp reference
implementation is exported for equivalence tests and as the XLA-fusion
baseline the kernel must beat.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

from pushcdn_tpu.proto.message import KIND_BROADCAST, KIND_DIRECT

TILE_U = 8     # sublane tile (int32 min sublane = 8)
TILE_N = 128   # lane tile


def delivery_matrix_reference(user_masks: jax.Array, local: jax.Array,
                              frame_tmask: jax.Array, kind: jax.Array,
                              dest: jax.Array) -> jax.Array:
    """Pure-jnp reference. ``local`` is bool[U] (owners == my_index);
    ``kind`` must already be 0 on invalid slots. Masks are either [U]/[N]
    (one u32 word, topics 0..31) or [U, W]/[N, W] (multi-word masks
    covering the reference's full u8 topic space at W=8)."""
    U = user_masks.shape[0]
    N = frame_tmask.shape[0]
    is_b = kind == KIND_BROADCAST
    is_d = kind == KIND_DIRECT
    if user_masks.ndim == 1:
        bcast = (user_masks[:, None] & frame_tmask[None, :]) != 0
    else:
        bcast = ((user_masks[:, None, :] & frame_tmask[None, :, :]) != 0
                 ).any(axis=-1)
    uidx = jax.lax.broadcasted_iota(jnp.int32, (U, N), 0)
    direct = dest[None, :] == uidx
    return ((bcast & is_b[None, :]) | (direct & is_d[None, :])) \
        & local[:, None]


def _make_kernel(W: int):
    def _kernel(umask_ref, local_ref, tmask_ref, kind_ref, dest_ref,
                out_ref):
        i = pl.program_id(0)
        umask = umask_ref[:]            # [TILE_U, W] uint32
        local = local_ref[:]            # [TILE_U, 1] int32 (0/1)
        tmask = tmask_ref[:]            # [W, TILE_N] uint32
        kind = kind_ref[:]              # [1, TILE_N] int32
        dest = dest_ref[:]              # [1, TILE_N] int32

        is_b = kind == KIND_BROADCAST
        is_d = kind == KIND_DIRECT
        # OR of the per-word AND — W is static, the loop unrolls
        bcast = (umask[:, 0:1] & tmask[0:1, :]) != 0    # [TILE_U, TILE_N]
        for w in range(1, W):
            bcast |= (umask[:, w:w + 1] & tmask[w:w + 1, :]) != 0
        # global user index of each tile row
        row = jax.lax.broadcasted_iota(jnp.int32, (TILE_U, TILE_N), 0) \
            + i * TILE_U
        direct = dest == row
        out_ref[:] = ((bcast & is_b) | (direct & is_d)) & (local != 0)
    return _kernel


@functools.partial(jax.jit, static_argnames=("interpret",))
def delivery_matrix_pallas(user_masks: jax.Array, local: jax.Array,
                           frame_tmask: jax.Array, kind: jax.Array,
                           dest: jax.Array,
                           interpret: bool = False) -> jax.Array:
    """Pallas version. Shapes: user_masks [U] or [U, W], local [U],
    frame_tmask [N] or [N, W], kind/dest [N]; U must be a multiple of
    TILE_U and N of TILE_N (the router pads)."""
    U = user_masks.shape[0]
    N = frame_tmask.shape[0]
    W = 1 if user_masks.ndim == 1 else user_masks.shape[1]
    grid = (U // TILE_U, N // TILE_N)
    return pl.pallas_call(
        _make_kernel(W),
        out_shape=jax.ShapeDtypeStruct((U, N), jnp.bool_),
        grid=grid,
        in_specs=[
            pl.BlockSpec((TILE_U, W), lambda i, j: (i, 0)),       # user_masks
            pl.BlockSpec((TILE_U, 1), lambda i, j: (i, 0)),       # local
            pl.BlockSpec((W, TILE_N), lambda i, j: (0, j)),       # tmask
            pl.BlockSpec((1, TILE_N), lambda i, j: (0, j)),       # kind
            pl.BlockSpec((1, TILE_N), lambda i, j: (0, j)),       # dest
        ],
        out_specs=pl.BlockSpec((TILE_U, TILE_N), lambda i, j: (i, j)),
        interpret=interpret,
    )(
        user_masks.reshape(U, W),
        local.astype(jnp.int32).reshape(U, 1),
        frame_tmask.reshape(N, W).T,
        kind.reshape(1, N),
        dest.reshape(1, N),
    )


def delivery_matrix(user_masks, local, frame_tmask, kind, dest,
                    use_pallas: bool | None = None,
                    interpret: bool | None = None) -> jax.Array:
    """Dispatch: Pallas on real TPU, jnp reference everywhere else (the
    Pallas CPU interpreter walks the grid tile-by-tile in Python — ~9x
    slower than the fused XLA reference on an 8-shard CPU mesh step — so
    auto mode only picks the kernel where it actually wins; pass
    ``use_pallas=True`` explicitly to test interpreter equivalence)."""
    backend = jax.default_backend()
    if use_pallas is None:
        use_pallas = backend == "tpu"
    if interpret is None:
        interpret = backend != "tpu"
    U, N = user_masks.shape[0], frame_tmask.shape[0]
    if use_pallas and U % TILE_U == 0 and N % TILE_N == 0:
        return delivery_matrix_pallas(user_masks, local, frame_tmask,
                                      kind, dest, interpret=interpret)
    return delivery_matrix_reference(user_masks, local, frame_tmask,
                                     kind, dest)
