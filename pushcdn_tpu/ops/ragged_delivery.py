"""Ragged paged delivery: fan-out as a page walk, not a dense matrix.

The dense kernel (``ops.delivery_kernel``) computes ``deliver[u, n]`` for
EVERY (user, frame) cell — O(U x N) VPU work per tick regardless of how
many deliveries actually happen. Under skewed (zipf) topic popularity most
frames fan out to a tiny receiver set, so almost all of that sweep is
wasted. This module re-expresses delivery in the *Ragged Paged Attention*
layout (PAPERS.md): per-frame receiver lists packed into fixed-size
**pages**, a **page table** (the walk list) mapping frames to pages, and
**ragged lengths** — the kernel walks only real (user, frame) candidate
pairs, so per-tick device work scales with fan-out, not with the user
table.

Layout
------
- **Page pool** ``page_users: int32[max_pages, PAGE]`` — each page holds up
  to ``PAGE`` candidate user slots (-1 = empty lane). Page 0 is the
  reserved null page (always all -1): walk padding points at it.
- **Walk list** (the flattened page table): ``walk_page[w]`` /
  ``walk_frame[w]`` — walk entry ``w`` says "frame ``walk_frame[w]``'s
  receivers include page ``walk_page[w]``'s candidates". Frames with big
  fan-out own several entries; empty frames own none; frames on the same
  topic SHARE pages (the hot-topic receiver list is packed once and
  referenced by every frame on it — the page-sharing trick that makes
  packing O(frames + topics), not O(total fan-out)).
- **Ragged lengths** live implicitly in the pages (-1 lanes) and
  explicitly per topic in :class:`RaggedInterest`.

The kernel (Pallas, with a pure-jnp twin) walks the list and confirms
every candidate against DEVICE state — ``now_local`` ownership (post-CRDT
merge / liveness tombstones) and the topic-mask AND — so stale or garbage
pages can only ever under- or exactly-deliver, never misdeliver. Output is
the compact ``(out_user[w, lane], counts[w])`` pair list: row ``w`` is a
receiver run for frame ``walk_frame[w]``, fed straight to the egress path
(``senders.egress_delivery_rows``) with no bool[U, N] re-scan.

Interest index
--------------
:class:`RaggedInterest` maintains the per-topic receiver pages
*incrementally* (subscribe/unsubscribe = O(changed topics), removal =
swap-with-last inside a page), so steady-state packing for single-topic
frames is one table append per frame. Multi-topic frames get a transient
deduplicated union page run (memoized per distinct mask per tick);
directs share transient pages (up to PAGE dests per page — the kernel's
dest-equality confirm filters each frame down to its own recipient).
Transient pages are released after the tick (:meth:`RaggedInterest.
release_transient`), which is what exercises pool wraparound.

Honesty note: the real TPU tunnel has been dead since round 4
(TPU_PROBES_r1x.md) — the Pallas kernel is exercised in interpreter mode
and the jnp twin is the CPU-backend performance path benchmarked in
BENCH_r12.json (rows labeled cpu/dryrun). The kernel's per-candidate
gathers (``jnp.take``) compile in interpreter mode; on-chip lowering may
want a one-hot MXU gather instead — one flag away when a chip answers.
"""

from __future__ import annotations

from typing import Dict, List, NamedTuple, Optional, Tuple

import numpy as np

from pushcdn_tpu.proto.message import KIND_BROADCAST, KIND_DIRECT

# One page = one VPU lane row of candidates. 128 matches the TPU lane
# width (the dense kernel's TILE_N) so a page confirm is one vector op.
PAGE = 128
_PAGE_SHIFT = 7  # log2(PAGE): flat walk-slot index -> walk row

# walk lists are padded up to this granule so the jit cache sees a few
# stable shapes instead of one per traffic mix
WALK_ROUND = 64


def _round_walk(n: int) -> int:
    if n <= 0:
        return WALK_ROUND
    return ((n + WALK_ROUND - 1) // WALK_ROUND) * WALK_ROUND


class RaggedWalk(NamedTuple):
    """One tick's packed page table (see module docstring)."""

    pages: np.ndarray       # int32[num_pages, PAGE] — pool snapshot
    walk_page: np.ndarray   # int32[Wp] (padded entries point at page 0)
    walk_frame: np.ndarray  # int32[Wp] (padded entries say frame 0 — page
    #                         0 is all -1, so they can never deliver)
    n_walk: int             # real entries (<= Wp)
    spilled: tuple          # frame indices the pool couldn't carry this
    #                         tick (transient-page exhaustion) — the
    #                         caller routes THOSE frames dense/host-side
    # mask-group factorization (pair-extraction accelerator): broadcast
    # frames sharing one topic-mask deliver to the IDENTICAL receiver
    # set, so one member's walk rows decide for the whole group.
    # Each entry: (rep_row, n_rows, frames) — the representative's walk
    # row range + every member frame (ascending). ``solo_rows`` are walk
    # rows that decide only for themselves (directs).
    groups: tuple = ()
    solo_rows: tuple = ()


class RaggedInterest:
    """Incremental per-topic receiver pages over a user-slot space.

    The host-side index half of the RPA layout: for every topic, the
    subscribed user slots packed into pages of ``PAGE`` entries (last page
    ragged). Mutations are O(topics changed); the per-tick ``pack`` emits
    walk entries referencing these pages directly for single-topic
    broadcasts — zero per-tick interest work for the hot path.
    """

    def __init__(self, num_topics: int, max_pages: int = 1024):
        if max_pages < 2:
            raise ValueError("max_pages must be >= 2 (page 0 is reserved)")
        self.num_topics = num_topics
        self.max_pages = max_pages
        self.page_users = np.full((max_pages, PAGE), -1, np.int32)
        # page 0 = the reserved null page; never allocated, always all -1
        self._free: List[int] = list(range(max_pages - 1, 0, -1))
        self._topic_pages: List[List[int]] = [[] for _ in range(num_topics)]
        self._topic_len: List[int] = [0] * num_topics
        self._pos: List[Dict[int, int]] = [dict() for _ in range(num_topics)]
        self._user_mask: Dict[int, int] = {}  # slot -> python-int mask
        # persistent (subscription) pages the pool couldn't hold: the
        # index is incomplete from here on — consumers must fall back to
        # the dense path until a rebuild succeeds
        self.overflowed = False
        self._transient: List[int] = []
        self._union_memo: Dict[int, List[int]] = {}
        # 1 + highest pool row ever touched — device uploads slice to it
        self.high_water = 1

    # ---- allocation -------------------------------------------------------

    def _alloc(self) -> Optional[int]:
        if not self._free:
            return None
        pg = self._free.pop()
        # clear-on-alloc: a recycled page may hold a previous tick's
        # candidates, and walk padding relies on vacated lanes being -1
        self.page_users[pg] = -1
        if pg + 1 > self.high_water:
            self.high_water = pg + 1
        return pg

    def _free_page(self, pg: int) -> None:
        self._free.append(pg)

    @property
    def free_pages(self) -> int:
        return len(self._free)

    def __len__(self) -> int:
        """Users with a (desired) non-empty mask — the membership size
        the device plane's overflow-recovery policy watches."""
        return len(self._user_mask)

    # ---- incremental topic index -----------------------------------------

    def _topic_add(self, t: int, slot: int) -> bool:
        n = self._topic_len[t]
        if n % PAGE == 0:
            pg = self._alloc()
            if pg is None:
                return False
            self._topic_pages[t].append(pg)
        pg = self._topic_pages[t][-1]
        self.page_users[pg, n % PAGE] = slot
        self._pos[t][slot] = n
        self._topic_len[t] = n + 1
        return True

    def _topic_remove(self, t: int, slot: int) -> None:
        i = self._pos[t].pop(slot, None)
        if i is None:
            return
        last = self._topic_len[t] - 1
        pages = self._topic_pages[t]
        if i != last:
            # swap-with-last keeps pages dense (receiver order within a
            # frame is set semantics — the dense matrix had none either)
            moved = int(self.page_users[pages[last // PAGE], last % PAGE])
            self.page_users[pages[i // PAGE], i % PAGE] = moved
            self._pos[t][moved] = i
        self.page_users[pages[last // PAGE], last % PAGE] = -1
        self._topic_len[t] = last
        if last % PAGE == 0 and pages:  # the tail page emptied
            self._free_page(pages.pop())

    def set_mask(self, slot: int, mask: int) -> None:
        """Update one user's subscription mask (a python int over the
        topic space); diffs against the stored mask and touches only the
        changed topics. ``mask == 0`` removes the user entirely."""
        mask &= (1 << self.num_topics) - 1
        old = self._user_mask.get(slot, 0)
        changed = old ^ mask
        if not changed:
            return
        t = 0
        while changed:
            if changed & 1:
                if mask & (1 << t):
                    if not self._topic_add(t, slot):
                        # pool exhausted: the pages are now INCOMPLETE —
                        # ``overflowed`` gates every consumer onto the
                        # dense path. The DESIRED mask is still stored,
                        # so :meth:`rebuild` can restore the index once
                        # membership shrinks.
                        self.overflowed = True
                        break
                else:
                    self._topic_remove(t, slot)
            changed >>= 1
            t += 1
        if mask:
            self._user_mask[slot] = mask
        else:
            self._user_mask.pop(slot, None)

    def rebuild(self) -> bool:
        """Re-derive every topic page from the stored masks (recovery path
        after an overflow once enough users left). Returns success."""
        masks = dict(self._user_mask)
        self._free = list(range(self.max_pages - 1, 0, -1))
        # the pool is empty again: let the high-water mark re-derive from
        # the rebuilt allocation, or every later pack() would snapshot and
        # upload a pool prefix sized to the historical peak forever
        self.high_water = 1
        self._topic_pages = [[] for _ in range(self.num_topics)]
        self._topic_len = [0] * self.num_topics
        self._pos = [dict() for _ in range(self.num_topics)]
        self._user_mask = {}
        self._transient = []
        self._union_memo = {}
        self.page_users[1:] = -1
        self.overflowed = False
        for slot, mask in masks.items():
            self.set_mask(slot, mask)
            if self.overflowed:
                return False
        return True

    def topic_receivers(self, t: int) -> np.ndarray:
        """The topic's current receiver slots (test/introspection aid)."""
        n = self._topic_len[t]
        out = np.empty(n, np.int32)
        for i, pg in enumerate(self._topic_pages[t]):
            take = min(PAGE, n - i * PAGE)
            out[i * PAGE:i * PAGE + take] = self.page_users[pg, :take]
        return out

    # ---- per-tick packing -------------------------------------------------

    def _union_pages(self, mask: int) -> Optional[List[int]]:
        """Transient deduplicated page run for a multi-topic mask
        (memoized per distinct mask until :meth:`release_transient`)."""
        pages = self._union_memo.get(mask)
        if pages is not None:
            return pages
        parts = []
        t = 0
        m = mask
        while m:
            if m & 1 and self._topic_len[t]:
                parts.append(self.topic_receivers(t))
            m >>= 1
            t += 1
        if not parts:
            self._union_memo[mask] = []
            return []
        cand = np.unique(np.concatenate(parts))  # dedup: one delivery max
        pages = []
        for off in range(0, len(cand), PAGE):
            pg = self._alloc()
            if pg is None:
                for p in pages:  # roll the partial union back
                    self._free_page(p)
                return None
            chunk = cand[off:off + PAGE]
            self.page_users[pg, :len(chunk)] = chunk
            pages.append(pg)
        self._transient.extend(pages)
        self._union_memo[mask] = pages
        return pages

    def pack(self, kind: np.ndarray, topic_mask: np.ndarray,
             dest: np.ndarray, valid: np.ndarray,
             page_round: int = 1) -> RaggedWalk:
        """Build one tick's walk list from frame metadata (the same
        columns the dense step consumes). Invalid slots and non-delivery
        kinds get no walk entries; broadcasts reference the live topic
        pages (single topic) or a transient union run; directs share
        transient dest pages, ``PAGE`` frames per page.

        ``page_round`` rounds the returned pool-snapshot row count up to a
        multiple (device callers pass a granule so the jit cache doesn't
        retrace every time a page is allocated).

        Call :meth:`release_transient` once the tick's consumers are done
        with the returned pool snapshot."""
        walk_page: List[int] = []
        walk_frame: List[int] = []
        spilled: List[int] = []
        direct_page = -1
        direct_used = 0
        multiword = topic_mask.ndim == 2
        # C-speed scalarization once, then dict-memoized mask decisions:
        # a tick's frames draw from a few distinct topic sets, so the
        # mask-int reconstruction and page-list resolution run once per
        # DISTINCT mask, not once per frame (the page-sharing property
        # that keeps packing O(frames + topics))
        kind_l = kind.tolist()
        valid_l = valid.tolist()
        dest_l = dest.tolist()
        if multiword:
            row_bytes = topic_mask.shape[1] * 4
            mask_buf = np.ascontiguousarray(topic_mask).tobytes()
        else:
            tmask_l = topic_mask.tolist()
        decisions: Dict = {}  # mask key -> page-id list | None (= spill)
        group_of: Dict = {}   # mask key -> [rep_row, n_rows, frames list]
        solo_rows: List[int] = []
        direct_seen: Dict[int, bool] = {}  # dests in the CURRENT page —
        # a repeated dest must not occupy a second lane, or every frame
        # sharing the page would match it twice (double delivery)
        allbits = (1 << self.num_topics) - 1
        for n in range(len(kind_l)):
            if not valid_l[n]:
                continue
            k = kind_l[n]
            if k == KIND_BROADCAST:
                if multiword:
                    key = mask_buf[n * row_bytes:(n + 1) * row_bytes]
                else:
                    key = tmask_l[n]
                pages = decisions.get(key, decisions)
                if pages is decisions:  # first sight of this mask
                    mask = (int.from_bytes(key, "little") if multiword
                            else key) & allbits
                    if mask == 0:
                        pages = []  # no valid topics: empty fan-out
                    elif mask & (mask - 1) == 0:  # single topic: live pages
                        pages = self._topic_pages[mask.bit_length() - 1]
                    else:
                        pages = self._union_pages(mask)
                    decisions[key] = pages
                    if pages:
                        group_of[key] = [len(walk_page), len(pages), [n]]
                elif pages:
                    group_of[key][2].append(n)
                if pages is None:
                    spilled.append(n)
                    continue
                walk_page.extend(pages)
                walk_frame.extend([n] * len(pages))
            elif k == KIND_DIRECT:
                d = dest_l[n]
                if d < 0:
                    continue  # garbage dest: nothing to deliver
                if d not in direct_seen:
                    if direct_used % PAGE == 0:
                        pg = self._alloc()
                        if pg is None:
                            spilled.append(n)
                            continue
                        direct_page = pg
                        self._transient.append(pg)
                        direct_used = 0
                        direct_seen = {}
                    self.page_users[direct_page, direct_used] = d
                    direct_seen[d] = True
                    direct_used += 1
                solo_rows.append(len(walk_page))
                walk_page.append(direct_page)
                walk_frame.append(n)
            # other kinds (control/garbage): no device delivery

        n_walk = len(walk_page)
        wp = _round_walk(n_walk)
        wpage = np.zeros(wp, np.int32)   # padding -> null page 0
        wframe = np.zeros(wp, np.int32)
        if n_walk:
            wpage[:n_walk] = walk_page
            wframe[:n_walk] = walk_frame
        # snapshot the referenced pool prefix: observers may mutate live
        # topic pages while a device step holds this tick's walk
        rows = self.high_water
        if page_round > 1:
            rows = min(((rows + page_round - 1) // page_round) * page_round,
                       self.max_pages)
        pages = self.page_users[:rows].copy()
        groups = tuple(
            (rep, n_rows, np.asarray(frames, np.int32))
            for rep, n_rows, frames in group_of.values())
        return RaggedWalk(pages, wpage, wframe, n_walk, tuple(spilled),
                          groups, tuple(solo_rows))

    def release_transient(self) -> None:
        """Return this tick's union/direct pages to the pool (wraparound:
        the next tick re-allocates them, cleared on alloc)."""
        for pg in self._transient:
            self._free_page(pg)
        self._transient = []
        self._union_memo = {}


# ---------------------------------------------------------------------------
# the kernel: jnp twin + Pallas walk
# ---------------------------------------------------------------------------


def ragged_delivery_reference(pages, walk_page, walk_frame, local,
                              user_masks, frame_tmask, kind, dest):
    """Pure-jnp twin: confirm every packed candidate pair against device
    state. Shapes: pages int32[G, PAGE]; walk_* int32[Wp]; local bool[U];
    user_masks uint32[U] or [U, W]; frame_tmask uint32[N] or [N, W];
    kind/dest int32[N] (``kind`` already 0 on invalid slots, the dense
    kernel's contract). Returns ``(out_user int32[Wp, PAGE], counts
    int32[Wp])`` — -1 lanes are non-deliveries."""
    import jax.numpy as jnp

    cand = pages[walk_page]                       # [Wp, PAGE]
    f = walk_frame
    k = kind[f]                                   # [Wp]
    U = local.shape[0]
    # out-of-range candidates (garbage direct dests beyond the sliced
    # user table) must be INVALID, not clamp-gathered onto slot U-1
    cvalid = (cand >= 0) & (cand < U)
    u = jnp.clip(cand, 0)
    loc = local[u]                                # [Wp, PAGE]
    if user_masks.ndim == 1:
        hit_b = (user_masks[u] & frame_tmask[f][:, None]) != 0
    else:
        hit_b = ((user_masks[u] & frame_tmask[f][:, None, :]) != 0
                 ).any(axis=-1)
    is_b = (k == KIND_BROADCAST)[:, None]
    is_d = (k == KIND_DIRECT)[:, None]
    hit_d = cand == dest[f][:, None]
    ok = cvalid & loc & ((is_b & hit_b) | (is_d & hit_d))
    out_user = jnp.where(ok, cand, -1)
    return out_user, ok.sum(axis=-1, dtype=jnp.int32)


def _ragged_kernel(W: int):
    import jax.numpy as jnp

    def kernel(wp_ref, wf_ref, page_ref, local_ref, umask_ref, tmask_ref,
               kind_ref, dest_ref, out_ref, cnt_ref):
        # page_ref: [1, PAGE] — THIS walk entry's page (index-mapped);
        # tmask/kind/dest: [1, W]/[1, 1] rows of the walk entry's frame
        cand = page_ref[:]                        # [1, PAGE]
        # out-of-range candidates are invalid (see the jnp twin)
        cvalid = (cand >= 0) & (cand < local_ref.shape[0])
        u = jnp.clip(cand, 0)
        # per-candidate gathers from device state (interpret-mode exact;
        # see module docstring for the on-chip lowering caveat)
        loc = jnp.take(local_ref[:, 0], u) != 0   # [1, PAGE]
        um = jnp.take(umask_ref[:], u[0], axis=0)  # [PAGE, W]
        hit_b = ((um & tmask_ref[:]) != 0).any(axis=-1)[None, :]
        k = kind_ref[0, 0]
        hit_d = cand == dest_ref[0, 0]
        ok = cvalid & loc & jnp.where(
            k == KIND_BROADCAST, hit_b,
            jnp.where(k == KIND_DIRECT, hit_d, False))
        out_ref[:] = jnp.where(ok, cand, -1)
        cnt_ref[0, 0] = ok.sum(dtype=jnp.int32)

    return kernel


def ragged_delivery_pallas(pages, walk_page, walk_frame, local, user_masks,
                           frame_tmask, kind, dest, interpret: bool = True):
    """Pallas walk over the page table: grid = one step per walk entry,
    the entry's page and its frame's metadata blocks selected by the
    scalar-prefetched walk lists (the RPA indexing pattern)."""
    import jax
    import jax.numpy as jnp
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    U = local.shape[0]
    N = kind.shape[0]
    Wp = walk_page.shape[0]
    W = 1 if user_masks.ndim == 1 else user_masks.shape[1]

    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=2,
        grid=(Wp,),
        in_specs=[
            pl.BlockSpec((1, PAGE), lambda w, wp, wf: (wp[w], 0)),
            pl.BlockSpec((U, 1), lambda w, wp, wf: (0, 0)),
            pl.BlockSpec((U, W), lambda w, wp, wf: (0, 0)),
            pl.BlockSpec((1, W), lambda w, wp, wf: (wf[w], 0)),
            pl.BlockSpec((1, 1), lambda w, wp, wf: (wf[w], 0)),
            pl.BlockSpec((1, 1), lambda w, wp, wf: (wf[w], 0)),
        ],
        out_specs=[
            pl.BlockSpec((1, PAGE), lambda w, wp, wf: (w, 0)),
            pl.BlockSpec((1, 1), lambda w, wp, wf: (w, 0)),
        ],
    )
    out_user, counts = pl.pallas_call(
        _ragged_kernel(W),
        grid_spec=grid_spec,
        out_shape=[
            jax.ShapeDtypeStruct((Wp, PAGE), jnp.int32),
            jax.ShapeDtypeStruct((Wp, 1), jnp.int32),
        ],
        interpret=interpret,
    )(
        walk_page, walk_frame,
        pages,
        local.astype(jnp.int32).reshape(U, 1),
        user_masks.reshape(U, W),
        frame_tmask.reshape(N, W),
        kind.reshape(N, 1),
        dest.reshape(N, 1),
    )
    return out_user, counts.reshape(Wp)


def ragged_delivery(pages, walk_page, walk_frame, local, user_masks,
                    frame_tmask, kind, dest,
                    use_pallas: Optional[bool] = None,
                    interpret: Optional[bool] = None):
    """Dispatch: Pallas on real TPU, jnp twin everywhere else (the same
    policy as :func:`ops.delivery_kernel.delivery_matrix` — the Pallas
    interpreter walks the grid in Python, so auto only picks it where it
    wins; pass ``use_pallas=True`` to test interpreter equivalence)."""
    import jax
    backend = jax.default_backend()
    if use_pallas is None:
        use_pallas = backend == "tpu"
    if interpret is None:
        interpret = backend != "tpu"
    if use_pallas:
        return ragged_delivery_pallas(pages, walk_page, walk_frame, local,
                                      user_masks, frame_tmask, kind, dest,
                                      interpret=interpret)
    return ragged_delivery_reference(pages, walk_page, walk_frame, local,
                                     user_masks, frame_tmask, kind, dest)


# ---------------------------------------------------------------------------
# output adapters
# ---------------------------------------------------------------------------


def ragged_pairs(out_user: np.ndarray, walk_frame: np.ndarray,
                 num_users: Optional[int] = None
                 ) -> Tuple[np.ndarray, np.ndarray]:
    """Compact (users, frames) delivery pairs grouped per user (frames
    ascending within each user) — exactly what
    ``senders.egress_delivery_rows`` walks. Cost scales with delivered
    candidates, never O(U x N).

    The walk emits pairs frame-major (pack scans frames in order), so a
    STABLE sort on the user key alone preserves per-user frame order —
    and with ``num_users`` < 65536 the key casts to uint16, where
    numpy's stable sort is a radix pass (~6x the u64-comparison sort's
    throughput on million-pair fan-outs)."""
    flat = out_user.ravel()
    idx = np.flatnonzero(flat >= 0)
    users = flat[idx]
    frames = walk_frame[idx >> _PAGE_SHIFT]
    if num_users is not None and num_users <= 0xFFFF:
        order = np.argsort(users.astype(np.uint16), kind="stable")
    else:
        order = np.argsort(users, kind="stable")
    return users[order], frames[order]


def ragged_pairs_grouped(out_user: np.ndarray, walk: RaggedWalk,
                         num_users: int
                         ) -> Tuple[np.ndarray, np.ndarray]:
    """Mask-group-factorized twin of :func:`ragged_pairs`: extract each
    group's receiver set ONCE from its representative walk rows, then
    broadcast it to every member frame with vectorized segment expansion.
    Extraction cost is O(unique (user, mask) pairs + total pairs) with
    small constants — at skewed fan-out (hot topics carrying both the
    subscriptions and the traffic) this is the difference between the
    pair sort dominating the tick and it vanishing.

    Output is grouped per user; within a user, frames ascend inside each
    mask group and groups follow first-staged order (the dense nonzero
    listing interleaves a multi-topic user's groups by frame index
    instead — same pair SET, one documented ordering difference).
    """
    if not walk.groups and not walk.solo_rows:
        return ragged_pairs(out_user, walk.walk_frame, num_users)
    u_parts: List[np.ndarray] = []  # (user, group) incidence entries
    g_parts: List[np.ndarray] = []
    frames_per_group: List[np.ndarray] = []
    for gi, (rep, n_rows, frames) in enumerate(walk.groups):
        rows = out_user[rep:rep + n_rows].ravel()
        receivers = rows[rows >= 0]
        if len(receivers):
            u_parts.append(receivers)
            g_parts.append(np.full(len(receivers), gi, np.int32))
            frames_per_group.append(frames)
        else:
            frames_per_group.append(frames)
    if walk.solo_rows:
        solo = np.asarray(walk.solo_rows, np.int64)
        srows = out_user[solo]                       # [D, PAGE]
        d_idx, lane = np.nonzero(srows >= 0)
        if len(d_idx):
            base = len(walk.groups)
            u_parts.append(srows[d_idx, lane])
            g_parts.append((base + np.arange(len(d_idx))).astype(np.int32))
            for i in d_idx:
                frames_per_group.append(
                    walk.walk_frame[solo[i]:solo[i] + 1])
    if not u_parts:
        return (np.empty(0, np.int32), np.empty(0, np.int32))
    u2 = np.concatenate(u_parts)
    g2 = np.concatenate(g_parts)
    # stable user sort over the SMALL incidence listing (radix for u16)
    key = u2.astype(np.uint16) if num_users <= 0xFFFF else u2
    order = np.argsort(key, kind="stable")
    u2, g2 = u2[order], g2[order]
    flen = np.asarray([len(f) for f in frames_per_group], np.int64)
    fstart = np.cumsum(flen) - flen
    frames_table = np.concatenate(frames_per_group) if frames_per_group \
        else np.empty(0, np.int32)
    lens = flen[g2]
    total = int(lens.sum())
    out_users = np.repeat(u2, lens)
    # segment gather: entry i contributes frames_table[fstart[g2[i]] : +len]
    seg_cum = np.cumsum(lens) - lens
    pos = (np.arange(total, dtype=np.int64)
           - np.repeat(seg_cum, lens) + np.repeat(fstart[g2], lens))
    return out_users, frames_table[pos].astype(np.int32, copy=False)


def ragged_to_dense(out_user: np.ndarray, walk_frame: np.ndarray,
                    num_users: int, num_frames: int) -> np.ndarray:
    """Scatter the compact output back to ``bool[U, N]`` (equivalence
    tests against the dense kernel; never on the hot path)."""
    deliver = np.zeros((num_users, num_frames), bool)
    w_idx, lane = np.nonzero(out_user >= 0)
    deliver[out_user[w_idx, lane], walk_frame[w_idx]] = True
    return deliver
