"""Pallas TPU kernels for the hot routing ops (SURVEY.md §7 stage 7:
"Pallas kernels for topic-mask × subscriber-gather")."""
