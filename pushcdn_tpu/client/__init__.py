"""The elastic, self-healing client (parity ``cdn-client``, SURVEY.md §2d)."""

from pushcdn_tpu.client.client import Client, ClientConfig  # noqa: F401
