"""The elastic client: auto-reconnecting, subscription-replaying.

Capability parity with cdn-client/src/lib.rs:37-481:

- shared state: marshal endpoint, keypair, subscribed-topic set, and an
  optional live connection (lib.rs:37-69);
- **single-flight reconnect**: one reconnect at a time, guarded by a
  1-permit semaphore; concurrent callers wait for the winner
  (lib.rs:204-258), retrying every 2 s with a 10 s per-attempt timeout;
- on ANY send/recv error the connection is torn down and lazily re-dialed
  (``disconnect_on_error!``, lib.rs:149-165) — the client re-authenticates
  through the marshal, which re-load-balances it;
- subscriptions are replayed during the broker handshake (topics ride the
  ``Subscribe`` sent at auth, lib.rs:112-121), so a reconnect restores
  delivery without caller involvement;
- ``subscribe``/``unsubscribe`` compute deltas against the local topic set
  and update it only on successful send (lib.rs:295-481 API semantics).
"""

from __future__ import annotations

import asyncio
import logging
import os
import random
import time
from collections import deque
from dataclasses import dataclass, field
from typing import Callable, Dict, List, Optional, Set, Tuple, Type

from pushcdn_tpu.proto import metrics as metrics_mod
from pushcdn_tpu.proto import trace as trace_mod
from pushcdn_tpu.proto.crypto.signature import DEFAULT_SCHEME, KeyPair, SignatureScheme
from pushcdn_tpu.proto.error import Error, ErrorKind, bail
from pushcdn_tpu.proto.limiter import Limiter, NO_LIMIT
from pushcdn_tpu.proto.auth import user as user_auth
from pushcdn_tpu.proto.message import (
    SEQ_LAST,
    SEQ_LIVE,
    AuthenticateResponse,
    Broadcast,
    Direct,
    Message,
    Migrate,
    Subscribe,
    SubscribeFrom,
    Unsubscribe,
    deserialize_owned,
    serialize,
    with_trace,
)
from pushcdn_tpu.proto.transport.base import Connection, Protocol

logger = logging.getLogger("pushcdn.client")

CONNECT_TIMEOUT_S = 10.0    # per-attempt timeout

# Reconnect backoff (ISSUE 12): exponential with FULL jitter —
# delay = uniform(0, min(cap, base * 2^attempt)) — so a broker death
# under 10K clients produces a spread-out reconnect storm instead of
# synchronized waves (the classic full-jitter result: contention decays
# instead of echoing). A typed Error(SHED) retry-after hint acts as a
# FLOOR on the draw: the server told us when it expects to be useful
# again, so retrying earlier is wasted work for both sides.
BACKOFF_BASE_S = float(os.environ.get("PUSHCDN_BACKOFF_BASE_S", "") or 0.25)
BACKOFF_CAP_S = float(os.environ.get("PUSHCDN_BACKOFF_CAP_S", "") or 30.0)

# Bounded final drain of the OLD connection during a migration: the old
# broker closes it once the target's UserSync eviction lands; this is
# only the backstop when that propagation stalls (mesh partition).
MIGRATE_DRAIN_TIMEOUT_S = float(
    os.environ.get("PUSHCDN_MIGRATE_DRAIN_S", "") or 2.0)


def backoff_delay(attempt: int, retry_after_s: Optional[float] = None,
                  base_s: Optional[float] = None,
                  cap_s: Optional[float] = None) -> float:
    """The full-jitter reconnect delay for ``attempt`` (0-based), with an
    optional typed retry-after floor. Module-level so the backoff policy
    is unit-testable without a socket in sight."""
    base = BACKOFF_BASE_S if base_s is None else base_s
    cap = BACKOFF_CAP_S if cap_s is None else cap_s
    delay = random.uniform(0.0, min(cap, base * (2 ** attempt)))
    if retry_after_s is not None and retry_after_s > 0:
        delay = max(delay, float(retry_after_s))
    return delay


class GapDetector:
    """Live delivery-gap detector (ISSUE 20): the subscriber's half of
    the frame-fate ledger. The application tells the client how to read
    a (stream, sequence) pair out of a delivery (``ClientConfig.
    seq_extractor``) and the client accounts every arrival AS IT LANDS:

    - a sequence jumping past the stream's high-water mark opens a hole
      per skipped value (``cdn_client_gap_events`` — counted live, not
      at wrap-up);
    - a late arrival filling a tracked hole HEALS it
      (``cdn_client_gap_healed`` — an at-least-once redelivery or
      reorder, which stays legal);
    - a re-delivery of an already-seen value is a duplicate and touches
      neither counter.

    Outstanding loss as this client sees it is ``events - healed``
    (equivalently :attr:`open_gaps`); harness wrap-up loss checks read
    that instead of diffing delivery logs after the fact. The first
    observation of a stream anchors its high-water mark — joining late
    is not a gap. Hole tracking is bounded (``MAX_OPEN`` per stream,
    oldest evicted first); an evicted hole can no longer heal, which
    over-counts residual loss only in runs already losing thousands of
    frames per stream."""

    MAX_OPEN = 4096

    __slots__ = ("_hi", "_holes", "events", "healed", "unique",
                 "duplicates")

    def __init__(self) -> None:
        self._hi: Dict[int, int] = {}       # stream -> highest seq + 1
        self._holes: Dict[int, set] = {}    # stream -> open (missed) seqs
        self.events = 0
        self.healed = 0
        self.unique = 0
        self.duplicates = 0

    def observe(self, stream: int, seq: int) -> None:
        hi = self._hi.get(stream)
        if hi is None:
            self._hi[stream] = seq + 1
            self.unique += 1
            return
        if seq >= hi:
            missed = seq - hi
            if missed:
                self.events += missed
                metrics_mod.CLIENT_GAP_EVENTS.inc(missed)
                holes = self._holes.setdefault(stream, set())
                holes.update(range(max(hi, seq - self.MAX_OPEN), seq))
                while len(holes) > self.MAX_OPEN:
                    holes.discard(min(holes))  # rare: cap the tracker
            self._hi[stream] = seq + 1
            self.unique += 1
            return
        holes = self._holes.get(stream)
        if holes is not None and seq in holes:
            holes.discard(seq)
            self.healed += 1
            self.unique += 1
            metrics_mod.CLIENT_GAP_HEALED.inc()
            return
        self.duplicates += 1

    @property
    def open_gaps(self) -> int:
        """Holes still unfilled — the live residual-loss figure."""
        return sum(len(h) for h in self._holes.values())


def decode_received(items) -> List[Message]:
    """Decode a ``Connection.recv_frames`` drain into Message objects —
    the client receive path's batch decoder, shared with the benches so
    the measured decode IS what ``receive_messages`` runs. FrameChunks
    batch-decode off the shared buffer with ZERO-COPY memoryview payloads
    for Broadcast/Direct (FrameChunk.decode_remaining); bare frames take
    the owned single-frame decoder. Every item is released here on
    success; on failure the caller owns cleanup (the client tears the
    connection down, which releases the rest)."""
    from pushcdn_tpu.proto.transport.base import FrameChunk
    out: List[Message] = []
    i = 0
    try:
        for i, item in enumerate(items):
            if type(item) is FrameChunk:
                # whole-chunk batch decode off the shared buffer: zero
                # payload copies, one release for the lot (the returned
                # views keep the buffer alive)
                out.extend(item.decode_remaining())
            else:
                out.append(deserialize_owned(item.data))
                item.release()
    except BaseException:
        # the failing item's chunk path already released itself
        # (decode_remaining is try/finally; release is idempotent);
        # everything at and after the failure returns its permit here
        for item in items[i:]:
            item.release()
        raise
    return out


@dataclass
class ClientConfig:
    """Parity with the client Config (cdn-client/src/lib.rs)."""

    marshal_endpoint: str
    keypair: KeyPair
    protocol: Type[Protocol]
    scheme: Type[SignatureScheme] = DEFAULT_SCHEME
    subscribed_topics: Set[int] = field(default_factory=set)
    use_local_authority: bool = True
    limiter: Limiter = NO_LIMIT
    # live gap detection (ISSUE 20): maps a delivered message to its
    # (stream, sequence) pair, or None for messages that carry no
    # sequence. Setting it arms :class:`GapDetector` on the receive
    # path (``Client.gap_detector``).
    seq_extractor: Optional[Callable[[Message],
                                     Optional[Tuple[int, int]]]] = None


class Client:
    """Clonable-by-reference handle over an elastic connection."""

    def __init__(self, config: ClientConfig):
        self.config = config
        self._topics: Set[int] = set(config.subscribed_topics)
        self._connection: Optional[Connection] = None
        self._reconnect_sem = asyncio.Semaphore(1)  # single-flight guard
        # lifecycle tracing: deterministic 1-in-N publish sampler; the
        # first publish after a (re)connect reuses the connection's trace
        # id so the marshal-auth span chains to a message lifecycle
        self._sampler = trace_mod.Sampler()
        # a broker load-shed notice that arrived in the same batch as
        # real deliveries: the deliveries are returned first, the typed
        # Error(SHED) raises on the next receive call (ISSUE 7)
        self._pending_shed: Optional[Error] = None
        # once the broker has shed ANY mutation on this connection, the
        # optimistic local topic mirror can no longer be trusted (the
        # notice doesn't say which mutation was dropped) — until the next
        # reconnect replays the full set, subscribe/unsubscribe send the
        # requested topics verbatim instead of the delta
        self._topics_dirty = False
        # elastic re-home (ISSUE 12): a Migrate frame seen mid-batch is
        # stashed here until the deliveries ahead of it are handed over;
        # the backlog holds old-connection stragglers collected during
        # the make-before-break switch, delivered before anything from
        # the new connection
        self._pending_migrate: Optional[Migrate] = None
        self._migration_backlog: deque = deque()
        # re-home observability: wall-clock ms per completed migration
        # (Migrate processed -> new home live), read by the swarm soak
        # harness for its re-home latency percentiles
        self.rehome_ms: List[float] = []
        # live gap detection (armed only when the config supplies a
        # sequence extractor — zero cost otherwise)
        self.gap_detector: Optional[GapDetector] = \
            GapDetector() if config.seq_extractor is not None else None

    def _shed_error(self, message: AuthenticateResponse) -> Error:
        """A post-handshake ``permit=0`` response is the broker's typed
        load-shed notice (ISSUE 7): the request (e.g. a subscribe) was
        REFUSED but the connection is still live — surface it as
        ``Error(SHED)`` without tearing the connection down (reconnecting
        into an overloaded broker would make the overload worse)."""
        self._topics_dirty = True
        return Error(ErrorKind.SHED,
                     message.context or "server shed the request")

    # -- connection management ---------------------------------------------

    async def _connect_once(self) -> Connection:
        """One full marshal→broker dance (ClientRef::connect, lib.rs:79-121)."""
        c = self.config
        # lifecycle tracing: the connection trace originates at dial time;
        # the marshal stamps the auth span on it, and the first publish
        # after connect reuses the id (a complete chain per connect under
        # any sampling rate)
        conn_trace = trace_mod.new_trace() if trace_mod.ENABLED else None
        # hop 1: marshal — the timestamp signature (pure CPU; ~0.13 ms for
        # a pairing scheme) is computed WHILE the dial waits on the
        # marshal's accept, so the two costs overlap instead of adding.
        # The sleep(0) is what makes the overlap real: ensure_future only
        # SCHEDULES the coroutine, and the sync sign would otherwise run
        # before the dial ever issues its connect syscall.
        dial = asyncio.ensure_future(c.protocol.connect(
            c.marshal_endpoint, c.use_local_authority, c.limiter))
        try:
            await asyncio.sleep(0)
            presigned = user_auth.presign_timestamp(c.scheme, c.keypair)
        except BaseException:
            dial.cancel()
            try:
                (await dial).close()  # dial may have already resolved
            except BaseException:
                pass
            raise
        marshal_conn = await dial
        # a SLOW dial (SYN retries, TLS stalls — legal within the connect
        # timeout) ages the presigned timestamp toward the marshal's ±5 s
        # replay window; re-sign rather than burn the window on transit
        if int(time.time()) - presigned[0] > 2:
            presigned = None  # authenticate_with_marshal signs fresh
        try:
            permit, broker_endpoint = await user_auth.authenticate_with_marshal(
                marshal_conn, c.scheme, c.keypair, presigned=presigned,
                trace=conn_trace)
        finally:
            marshal_conn.close()
        # hop 2: the assigned broker
        broker_conn = await c.protocol.connect(
            broker_endpoint, c.use_local_authority, c.limiter)
        try:
            await user_auth.authenticate_with_broker(
                broker_conn, permit, sorted(self._topics))
        except BaseException:
            broker_conn.close()
            raise
        if conn_trace is not None:
            # the first publish reuses the connection trace id; the AUTH
            # span is the MARSHAL's to emit (server-side stamp/strip) —
            # a client-side twin would double-populate the hop histogram
            # with a second latency population and let the chain check
            # pass even when the marshal path is broken
            self._sampler.pending = conn_trace[0]
        # the handshake replayed the FULL desired topic set, so the
        # broker mirror is authoritative again (post-shed staleness gone)
        self._topics_dirty = False
        logger.info("connected to broker at %s", broker_endpoint)
        return broker_conn

    async def ensure_initialized(self) -> None:
        """Block until a live connection exists (lib.rs:321)."""
        await self._get_connection()

    async def _get_connection(self) -> Connection:
        conn = self._connection
        if conn is not None and not conn.is_closed:
            return conn
        async with self._reconnect_sem:  # single-flight (lib.rs:204-258)
            conn = self._connection
            if conn is not None and not conn.is_closed:
                return conn
            attempt = 0
            while True:
                try:
                    async with asyncio.timeout(CONNECT_TIMEOUT_S):
                        self._connection = await self._connect_once()
                    return self._connection
                except asyncio.CancelledError:
                    raise
                except Exception as exc:
                    # full-jitter exponential backoff; a typed SHED
                    # retry-after hint floors the draw (ISSUE 12). A
                    # rejected permit re-runs the whole marshal dance on
                    # the next attempt, so the marshal re-load-balances
                    # us for free.
                    delay = backoff_delay(
                        attempt, getattr(exc, "retry_after_s", None))
                    attempt += 1
                    logger.info("connect attempt %d failed (%r); "
                                "retrying in %.2fs", attempt, exc, delay)
                    await asyncio.sleep(delay)

    def _disconnect_on_error(self) -> None:
        """Tear the connection down so the next call re-dials
        (disconnect_on_error!, lib.rs:149-165)."""
        conn, self._connection = self._connection, None
        if conn is not None:
            conn.close()

    # -- elastic re-home (ISSUE 12) ------------------------------------------

    async def _complete_migration(self, migrate: Migrate) -> None:
        """Make-before-break re-home. The OLD connection stays open while
        the new home is established: closing it first would release the
        old broker's DirectMap claim before the target claims the user —
        a zero-home window where a mid-migration direct is lost. Instead
        the target's ``add_user`` out-versions the claim, the UserSync
        eviction makes the old broker close its half, and we do a bounded
        final drain of the old connection into the backlog so stragglers
        are delivered (in order) before anything from the new home.
        Subscriptions replay inside the target handshake, riding the same
        full-set replay a reconnect uses."""
        c = self.config
        t0 = time.monotonic()
        old, self._connection = self._connection, None
        new_conn = None
        async with self._reconnect_sem:  # serialize vs lazy reconnects
            if migrate.permit >= 2 and migrate.target:
                # pre-issued permit: dial the new home DIRECTLY — the
                # draining broker already did the placement + permit work
                # in one batch, no per-connection marshal round-trip
                try:
                    async with asyncio.timeout(CONNECT_TIMEOUT_S):
                        new_conn = await c.protocol.connect(
                            migrate.target, c.use_local_authority, c.limiter)
                        await user_auth.authenticate_with_broker(
                            new_conn, migrate.permit, sorted(self._topics))
                    self._topics_dirty = False
                    logger.info("re-homed to broker at %s", migrate.target)
                except asyncio.CancelledError:
                    if new_conn is not None:
                        new_conn.close()
                    raise
                except Exception as exc:
                    logger.info("direct re-home to %s failed (%r); "
                                "falling back to the marshal",
                                migrate.target, exc)
                    if new_conn is not None:
                        new_conn.close()
                    new_conn = None
            if new_conn is None:
                # fallback: the full marshal re-dance (it re-load-balances
                # us); a failure here leaves the client disconnected and
                # the NEXT call enters the ordinary backoff loop
                try:
                    async with asyncio.timeout(CONNECT_TIMEOUT_S):
                        new_conn = await self._connect_once()
                except asyncio.CancelledError:
                    raise
                except Exception as exc:
                    logger.info("marshal fallback after migrate failed: %r",
                                exc)
            # bounded final drain: collect every delivery still buffered
            # on (or in flight to) the old connection. Normally ends fast
            # — the old broker closes the connection once the UserSync
            # eviction lands; the timeout is the partition backstop.
            if old is not None and not old.is_closed:
                try:
                    async with asyncio.timeout(MIGRATE_DRAIN_TIMEOUT_S):
                        while True:
                            items = await old.recv_frames()
                            for m in decode_received(items):
                                if isinstance(m, (Broadcast, Direct)):
                                    self._migration_backlog.append(m)
                except asyncio.CancelledError:
                    raise
                except Exception:
                    pass  # closed by the old broker, or timed out
            if old is not None:
                old.close()
            self._connection = new_conn
            if new_conn is not None:
                self.rehome_ms.append((time.monotonic() - t0) * 1000.0)

    # -- messaging API (lib.rs:295-481) -------------------------------------

    async def send_message(self, message: Message) -> None:
        conn = self._connection  # fast path: live connection, no coroutine
        if conn is None or conn.is_closed:
            conn = await self._get_connection()
        # sampled lifecycle tracing: every Nth hot message is stamped with
        # a trace context (one class-attr check + one counter inc on the
        # untraced 1023/1024; nothing at all when tracing is disabled)
        if trace_mod.ENABLED and message.kind in (Broadcast.kind, Direct.kind) \
                and message.trace is None:
            tr = self._sampler.next_trace()
            if tr is not None:
                message = with_trace(message, tr)
                trace_mod.emit("publish", tr, f"{len(message.message)} B")
        try:
            await conn.send_message(message)
        except Exception as exc:
            self._disconnect_on_error()
            bail(ErrorKind.CONNECTION, "send failed; connection reset", exc)

    async def send_broadcast_message(self, topics: List[int],
                                     payload: bytes) -> None:
        await self.send_message(Broadcast(topics=topics, message=payload))

    async def send_direct_message(self, recipient_public_key: bytes,
                                  payload: bytes) -> None:
        await self.send_message(Direct(recipient=recipient_public_key,
                                       message=payload))

    def _observe_gaps(self, messages) -> None:
        """Feed delivered messages through the live gap detector (no-op
        unless the config armed one)."""
        extract = self.config.seq_extractor
        det = self.gap_detector
        for m in messages:
            key = extract(m)
            if key is not None:
                det.observe(key[0], key[1])

    async def receive_message(self) -> Message:
        while True:
            if self._pending_shed is not None:
                err, self._pending_shed = self._pending_shed, None
                raise err
            if self._migration_backlog:
                m = self._migration_backlog.popleft()
                if self.gap_detector is not None:
                    self._observe_gaps((m,))
                return m
            if self._pending_migrate is not None:
                mig, self._pending_migrate = self._pending_migrate, None
                await self._complete_migration(mig)
                continue
            conn = self._connection  # fast path: live conn, no coroutine
            if conn is None or conn.is_closed:
                conn = await self._get_connection()
            try:
                message = await conn.recv_message()
            except Exception as exc:
                self._disconnect_on_error()
                bail(ErrorKind.CONNECTION,
                     "receive failed; connection reset", exc)
            if isinstance(message, Migrate):
                await self._complete_migration(message)
                continue
            if isinstance(message, AuthenticateResponse) and message.permit == 0:
                raise self._shed_error(message)
            if trace_mod.ENABLED:
                tr = getattr(message, "trace", None)
                if tr is not None:
                    trace_mod.emit("delivery", tr)
            if self.gap_detector is not None:
                self._observe_gaps((message,))
            return message

    async def receive_messages(self, max_messages: int = 1024
                               ) -> List[Message]:
        """Receive every message currently available (at least one; blocks
        only when none are pending) in ONE wakeup — the batch twin of
        :meth:`receive_message` for consumers that keep up with fan-out
        rates: per-message task wakeups are what bound a single-process
        drain loop, exactly like the transport's own batched reader
        (transport/base.py). Same elastic semantics: any error (transport
        OR a malformed frame) tears the connection down for lazy re-dial.

        ``max_messages`` is approximate: the transport hands over whole
        parse batches, so one call may return more than asked (never
        fewer than 1)."""
        while True:
            if self._pending_shed is not None:
                err, self._pending_shed = self._pending_shed, None
                raise err
            if self._migration_backlog:
                out = list(self._migration_backlog)
                self._migration_backlog.clear()
                if self.gap_detector is not None:
                    self._observe_gaps(out)
                return out
            if self._pending_migrate is not None:
                mig, self._pending_migrate = self._pending_migrate, None
                await self._complete_migration(mig)
                continue
            conn = self._connection
            if conn is None or conn.is_closed:
                conn = await self._get_connection()
            try:
                items = await conn.recv_frames(max_messages)
            except Exception as exc:
                self._disconnect_on_error()
                bail(ErrorKind.CONNECTION,
                     "receive failed; connection reset", exc)
            try:
                # batch decode with ZERO-COPY payloads (decode_received
                # docs): the old one-copy-per-message residue is gone —
                # Broadcast/Direct ``message`` fields are memoryviews
                # over the chunk
                out = decode_received(items)
            except Exception as exc:
                self._disconnect_on_error()
                bail(ErrorKind.CONNECTION,
                     "malformed frame in receive batch; connection reset", exc)
            # a Migrate mid-batch splits it: deliveries ahead of it are
            # returned now, the frame is stashed for the next call (the
            # re-home completes then), and anything decoded after it is
            # backlogged so nothing is lost or reordered
            for i, m in enumerate(out):
                if isinstance(m, Migrate):
                    self._pending_migrate = m
                    self._migration_backlog.extend(
                        x for x in out[i + 1:] if not isinstance(x, Migrate))
                    out = out[:i]
                    break
            # load-shed notices (permit=0 post-handshake) surface as typed
            # Error(SHED): immediately when nothing else arrived, otherwise
            # after the real deliveries are handed over (next receive call)
            # — a shed is never a silent drop and never loses deliveries
            shed = [m for m in out
                    if isinstance(m, AuthenticateResponse) and m.permit == 0]
            if shed:
                out = [m for m in out
                       if not (isinstance(m, AuthenticateResponse)
                               and m.permit == 0)]
                err = self._shed_error(shed[-1])
                if not out:
                    raise err
                self._pending_shed = err
            if not out:
                continue  # the batch was pure control traffic (a Migrate)
            if trace_mod.ENABLED:
                for m in out:
                    tr = getattr(m, "trace", None)
                    if tr is not None:
                        trace_mod.emit("delivery", tr)
            if self.gap_detector is not None:
                self._observe_gaps(out)
            return out

    # -- subscriptions -------------------------------------------------------

    async def subscribe(self, topics: List[int]) -> None:
        """Send only the delta; update local state on success (lib.rs
        subscribe semantics). After a load shed the local mirror may be
        stale (a shed mutation was never applied), so the delta filter is
        suspended and the requested topics go out verbatim — the broker's
        subscribe is an idempotent set-union, so convergence is safe."""
        if self._topics_dirty:
            new = list(dict.fromkeys(topics))
        else:
            new = [t for t in topics if t not in self._topics]
        if not new:
            return
        conn = await self._get_connection()
        try:
            await conn.send_message(Subscribe(new), flush=True)
        except Exception as exc:
            self._disconnect_on_error()
            bail(ErrorKind.CONNECTION, "subscribe failed", exc)
        self._topics.update(new)

    async def subscribe_from(self, topic: int, seq: int = 0) -> None:
        """Durable replay subscribe (ISSUE 14): subscribe to ``topic`` AND
        replay every retained broadcast with sequence ``>= seq`` as
        ``Retained`` frames ahead of the live stream (gap-free, dup-free —
        see broker/retention.py). ``seq=0`` replays everything the broker
        still retains; :data:`SEQ_LAST` fetches only the last-value-cache
        entry; :data:`SEQ_LIVE` degrades to a plain subscribe.

        Retained frames surface as typed ``Retained`` messages from the
        receive calls. Sequence numbers are broker-local: after a re-home
        to a different broker, resume with ``seq=0`` or ``SEQ_LAST`` (the
        reconnect handshake replays only a plain ``Subscribe``)."""
        conn = await self._get_connection()
        try:
            await conn.send_message(SubscribeFrom(topic=topic, seq=seq),
                                    flush=True)
        except Exception as exc:
            self._disconnect_on_error()
            bail(ErrorKind.CONNECTION, "subscribe_from failed", exc)
        self._topics.add(topic)

    async def last_value(self, topic: int) -> None:
        """Fetch ``topic``'s last-value-cache entry (and subscribe): sugar
        for ``subscribe_from(topic, SEQ_LAST)``. The LVC frame arrives as
        a ``Retained`` message on the next receive call (nothing arrives
        when the broker retains nothing for the topic)."""
        await self.subscribe_from(topic, SEQ_LAST)

    async def subscribe_pattern(self, pattern: str,
                                seq: int = SEQ_LIVE) -> None:
        """Hierarchical wildcard subscribe (``consensus.view.*``): the
        broker compiles the pattern against its topic namespace into plain
        per-topic subscriptions and keeps the union live as names bind and
        unbind. ``seq`` other than :data:`SEQ_LIVE` additionally replays
        retained frames for every covered durable topic. The local topic
        mirror is NOT updated (coverage is broker-side state), so a
        re-home requires re-sending the pattern."""
        conn = await self._get_connection()
        try:
            await conn.send_message(
                SubscribeFrom(topic=0, seq=seq, pattern=pattern),
                flush=True)
        except Exception as exc:
            self._disconnect_on_error()
            bail(ErrorKind.CONNECTION, "subscribe_pattern failed", exc)

    async def unsubscribe(self, topics: List[int]) -> None:
        if self._topics_dirty:
            gone = list(dict.fromkeys(topics))
        else:
            gone = [t for t in topics if t in self._topics]
        if not gone:
            return
        conn = await self._get_connection()
        try:
            await conn.send_message(Unsubscribe(gone), flush=True)
        except Exception as exc:
            self._disconnect_on_error()
            bail(ErrorKind.CONNECTION, "unsubscribe failed", exc)
        self._topics.difference_update(gone)

    @property
    def subscribed_topics(self) -> Set[int]:
        return set(self._topics)

    @property
    def public_key(self) -> bytes:
        return self.config.keypair.public_key

    # -- teardown ------------------------------------------------------------

    async def soft_close(self) -> None:
        conn = self._connection
        if conn is not None:
            await conn.soft_close()
            self._connection = None

    def close(self) -> None:
        self._disconnect_on_error()
