"""Binding for the LD_PRELOAD syscall-attribution interposer.

The bench parent builds the library and re-execs the measurement child
with ``LD_PRELOAD`` set; inside the child, :func:`snapshot` reads the
interposer's counters through ctypes (dlopen of an already-preloaded DSO
returns the same mapping, so the counters are the live ones). A process
without the preload reports :func:`active` False and the bench emits a
skipped row instead of a zero-syscall lie.
"""

from __future__ import annotations

import ctypes
import os
from typing import Dict, Optional

from pushcdn_tpu.native import _BUILD_DIR, _REPO, _build_lib

_SRC = os.path.join(_REPO, "native", "syscount.cpp")
_LIB_PATH = os.path.join(_BUILD_DIR, "libpushcdn_syscount.so")

# index order must match the C_* enum in native/syscount.cpp
NAMES = ("write", "writev", "send", "sendto", "sendmsg",
         "read", "recv", "recvfrom", "recvmsg",
         "epoll_wait", "epoll_pwait", "io_uring_enter")

_lib = None
_lib_tried = False


def build() -> Optional[str]:
    """Compile (or reuse) the interposer; returns its path or None.
    Called by the bench PARENT, before spawning the preloaded child."""
    path = _build_lib(_SRC, _LIB_PATH, loader=lambda p: p,
                      extra_flags=("-ldl",))
    return path


def _load():
    global _lib, _lib_tried
    if _lib_tried:
        return _lib
    _lib_tried = True
    preload = os.environ.get("LD_PRELOAD", "")
    if "libpushcdn_syscount" not in preload:
        return None
    try:
        lib = ctypes.CDLL(str(_LIB_PATH))
        lib.pcu_syscount.restype = ctypes.c_ulonglong
        lib.pcu_syscount.argtypes = [ctypes.c_int]
        lib.pcu_syscount_n.restype = ctypes.c_int
        if lib.pcu_syscount_n() != len(NAMES):
            return None
        _lib = lib
    except OSError:
        return None
    return _lib


def active() -> bool:
    """True when this process runs under the interposer preload."""
    return _load() is not None


def snapshot() -> Dict[str, int]:
    """Current per-syscall counters (empty dict when not preloaded)."""
    lib = _load()
    if lib is None:
        return {}
    return {name: int(lib.pcu_syscount(i)) for i, name in enumerate(NAMES)}


def delta(before: Dict[str, int], after: Dict[str, int]) -> Dict[str, int]:
    return {k: after.get(k, 0) - before.get(k, 0) for k in NAMES}
