"""ctypes binding for the raw io_uring shim (native/io_uring.cpp).

Same build idiom as the framing library: compiled on first use with the
image's g++, cached under ``.build/``, and every failure path degrades
to "uring unavailable" — callers (the transport engine, benches, CI
probes) ask :func:`probe` and fall back to asyncio honestly.

The :class:`Ring` wrapper owns one kernel ring (one per event loop /
shard worker) and exposes the exact prep/submit/drain surface the
engine needs. It deliberately does NOT manage buffer lifetimes or
ordering: that policy lives in ``proto/transport/uring.py`` next to the
writer-queue contract it must preserve.
"""

from __future__ import annotations

import ctypes
import errno as _errno
import os
import threading
from typing import Optional

from pushcdn_tpu.native import _build_lib, _BUILD_DIR, _REPO

_SRC = os.path.join(_REPO, "native", "io_uring.cpp")
_LIB_PATH = os.path.join(_BUILD_DIR, "libpushcdn_uring.so")

_lock = threading.Lock()
_lib: Optional[ctypes.CDLL] = None
_lib_tried = False

# probe() results (cached once per process)
_probe_lock = threading.Lock()
_probe_result: Optional[int] = None

PROBE_ZC = 2  # bitmask bit: kernel supports IORING_OP_SEND_ZC

_u64 = ctypes.c_ulonglong
_u64p = ctypes.POINTER(_u64)
_i32p = ctypes.POINTER(ctypes.c_int)
_u32p = ctypes.POINTER(ctypes.c_uint)


def _compile() -> Optional[ctypes.CDLL]:
    lib = _build_lib(_SRC, _LIB_PATH, ctypes.CDLL)
    if lib is None:
        return None
    P = ctypes.c_void_p
    lib.pcu_probe.restype = ctypes.c_long
    lib.pcu_probe.argtypes = []
    lib.pcu_create.restype = P
    lib.pcu_create.argtypes = [ctypes.c_uint, ctypes.c_uint, ctypes.c_uint,
                               _i32p]
    lib.pcu_destroy.restype = None
    lib.pcu_destroy.argtypes = [P]
    lib.pcu_ring_fd.restype = ctypes.c_int
    lib.pcu_ring_fd.argtypes = [P]
    lib.pcu_sq_entries.restype = ctypes.c_uint
    lib.pcu_sq_entries.argtypes = [P]
    lib.pcu_register_eventfd.restype = ctypes.c_int
    lib.pcu_register_eventfd.argtypes = [P, ctypes.c_int, ctypes.c_int]
    lib.pcu_register_buf_table.restype = ctypes.c_int
    lib.pcu_register_buf_table.argtypes = [P, ctypes.c_uint]
    lib.pcu_update_buf.restype = ctypes.c_int
    lib.pcu_update_buf.argtypes = [P, ctypes.c_uint, ctypes.c_void_p,
                                   ctypes.c_ulong]
    lib.pcu_pbuf_setup.restype = ctypes.c_int
    lib.pcu_pbuf_setup.argtypes = [P, ctypes.c_uint, ctypes.c_uint, _u64p]
    lib.pcu_pbuf_recycle.restype = None
    lib.pcu_pbuf_recycle.argtypes = [P, ctypes.c_ushort]
    lib.pcu_pbuf_buflen.restype = ctypes.c_uint
    lib.pcu_pbuf_buflen.argtypes = [P]
    lib.pcu_sq_space.restype = ctypes.c_int
    lib.pcu_sq_space.argtypes = [P]
    lib.pcu_prep_send.restype = ctypes.c_int
    lib.pcu_prep_send.argtypes = [P, ctypes.c_int, _u64, ctypes.c_uint,
                                  _u64, ctypes.c_uint, ctypes.c_uint]
    lib.pcu_prep_send_zc.restype = ctypes.c_int
    lib.pcu_prep_send_zc.argtypes = [P, ctypes.c_int, _u64, ctypes.c_uint,
                                     _u64, ctypes.c_uint, ctypes.c_uint,
                                     ctypes.c_int]
    lib.pcu_prep_write_fixed.restype = ctypes.c_int
    lib.pcu_prep_write_fixed.argtypes = [P, ctypes.c_int, _u64,
                                         ctypes.c_uint, ctypes.c_int, _u64,
                                         ctypes.c_uint]
    lib.pcu_prep_recv_multishot.restype = ctypes.c_int
    lib.pcu_prep_recv_multishot.argtypes = [P, ctypes.c_int, _u64]
    lib.pcu_prep_recv.restype = ctypes.c_int
    lib.pcu_prep_recv.argtypes = [P, ctypes.c_int, _u64, ctypes.c_uint, _u64]
    lib.pcu_prep_accept_multishot.restype = ctypes.c_int
    lib.pcu_prep_accept_multishot.argtypes = [P, ctypes.c_int, _u64]
    lib.pcu_prep_cancel.restype = ctypes.c_int
    lib.pcu_prep_cancel.argtypes = [P, _u64, _u64]
    lib.pcu_prep_shutdown.restype = ctypes.c_int
    lib.pcu_prep_shutdown.argtypes = [P, ctypes.c_int, ctypes.c_int, _u64]
    lib.pcu_submit.restype = ctypes.c_long
    lib.pcu_submit.argtypes = [P, ctypes.c_uint]
    lib.pcu_cq_overflowed.restype = ctypes.c_int
    lib.pcu_cq_overflowed.argtypes = [P]
    lib.pcu_flush_overflow.restype = ctypes.c_long
    lib.pcu_flush_overflow.argtypes = [P]
    lib.pcu_peek_cqes.restype = ctypes.c_int
    lib.pcu_peek_cqes.argtypes = [P, _u64p, _i32p, _u32p, ctypes.c_int]
    lib.pcu_telem_enable.restype = ctypes.c_int
    lib.pcu_telem_enable.argtypes = [P]
    lib.pcu_telem_enabled.restype = ctypes.c_int
    lib.pcu_telem_enabled.argtypes = [P]
    lib.pcu_telem_words.restype = ctypes.c_long
    lib.pcu_telem_words.argtypes = []
    lib.pcu_telem_snapshot.restype = ctypes.c_long
    lib.pcu_telem_snapshot.argtypes = [P, _u64p, ctypes.c_long]
    lib.pcu_telem_test_observe.restype = ctypes.c_int
    lib.pcu_telem_test_observe.argtypes = [P, ctypes.c_int, ctypes.c_int,
                                           _u64, _u64]
    lib.pcu_telem_test_count.restype = ctypes.c_int
    lib.pcu_telem_test_count.argtypes = [P, ctypes.c_int, ctypes.c_int,
                                         _u64]
    return lib


def _get() -> Optional[ctypes.CDLL]:
    global _lib, _lib_tried
    if _lib is None and not _lib_tried:
        with _lock:
            if _lib is None and not _lib_tried:
                _lib = _compile()
                _lib_tried = True
    return _lib


def probe() -> int:
    """Capability probe, cached per process.

    Returns a positive bitmask (bit0: io_uring usable, bit1
    (:data:`PROBE_ZC`): SEND_ZC supported) when the kernel grants a
    ring, ``-errno`` when denied (``-ENOSYS`` on old kernels,
    ``-EPERM`` under seccomp or ``io_uring_disabled``), and
    ``-ENOSYS`` when the native shim itself failed to build — the
    honest demotion paths for ``--io-impl auto``.
    """
    global _probe_result
    if _probe_result is None:
        with _probe_lock:
            if _probe_result is None:
                lib = _get()
                if lib is None:
                    _probe_result = -_errno.ENOSYS
                else:
                    _probe_result = int(lib.pcu_probe())
    return _probe_result


def probe_errname() -> str:
    """Human label for a failed probe ("ENOSYS", "EPERM", ...)."""
    rc = probe()
    if rc > 0:
        return "ok"
    return _errno.errorcode.get(-rc, f"errno {-rc}")


def available() -> bool:
    return probe() > 0


def zerocopy_supported() -> bool:
    return probe() > 0 and bool(probe() & PROBE_ZC)


# sqe_flags the engine uses (mirrors the shim's enums)
IOSQE_IO_LINK = 1 << 2
# cqe flags
CQE_F_BUFFER = 1 << 0
CQE_F_MORE = 1 << 1
CQE_F_NOTIF = 1 << 3
CQE_BUFFER_SHIFT = 16

# msg_flags
MSG_WAITALL = 0x100
MSG_NOSIGNAL = 0x4000

_CQ_BATCH = 512

# -- shm telemetry block layout (mirror of pcu_telem in io_uring.cpp) --
# The snapshot is a flat u64 payload (the seqlock word is stripped); the
# offsets below index into it. A pcu_hist is {count, sum_ns, bucket[64]}
# where bucket[k] counts durations in [2^(k-1), 2^k) ns (0 -> bucket 0).
TM_BUCKETS = 64
TM_STAGES = 4     # 0=plan 1=submit 2=wire 3=total
TM_CHAIN = 2      # 0=enter (io_uring_enter wall) 1=chain (submit->quiesce)
TM_CLASSES = 4    # 0=control 1=consensus 2=live 3=bulk
TM_PEERS = 64
TM_HIST_WORDS = 2 + TM_BUCKETS
TM_STAGE_OFF = 0
TM_CHAIN_OFF = TM_STAGE_OFF + TM_STAGES * TM_HIST_WORDS
TM_CLASS_DELAY_OFF = TM_CHAIN_OFF + TM_CHAIN * TM_HIST_WORDS
TM_CLASS_FRAMES_OFF = TM_CLASS_DELAY_OFF + TM_CLASSES * TM_HIST_WORDS
TM_CLASS_BYTES_OFF = TM_CLASS_FRAMES_OFF + TM_CLASSES
TM_PEER_FD_OFF = TM_CLASS_BYTES_OFF + TM_CLASSES
TM_PEER_FRAMES_OFF = TM_PEER_FD_OFF + TM_PEERS
TM_PEER_BYTES_OFF = TM_PEER_FRAMES_OFF + TM_PEERS
TM_PEER_USED_OFF = TM_PEER_BYTES_OFF + TM_PEERS
# frame-fate ledger (ISSUE 20): per-class pump-drop counters, appended
# at the end of pcu_telem so every prior snapshot offset stays stable
TM_DROP_FRAMES_OFF = TM_PEER_USED_OFF + 1
TM_WORDS = TM_DROP_FRAMES_OFF + TM_CLASSES

STAGE_NAMES = ("plan", "submit", "wire", "total")
CHAIN_NAMES = ("enter", "chain")
CLASS_NAMES = ("control", "consensus", "live", "bulk")


def _tm_hist(words, off):
    return {"count": int(words[off]), "sum_ns": int(words[off + 1]),
            "buckets": [int(words[off + 2 + k]) for k in range(TM_BUCKETS)]}


def parse_telemetry(words):
    """Decode a raw snapshot (sequence of TM_WORDS u64s) into dicts —
    shared by the /metrics pre-render hook and the tests so the layout
    is asserted in exactly one place."""
    if words is None or len(words) < TM_WORDS:
        return None
    out = {
        "stage": {STAGE_NAMES[i]:
                  _tm_hist(words, TM_STAGE_OFF + i * TM_HIST_WORDS)
                  for i in range(TM_STAGES)},
        "chain": {CHAIN_NAMES[i]:
                  _tm_hist(words, TM_CHAIN_OFF + i * TM_HIST_WORDS)
                  for i in range(TM_CHAIN)},
        "class_delay": {CLASS_NAMES[i]:
                        _tm_hist(words, TM_CLASS_DELAY_OFF
                                 + i * TM_HIST_WORDS)
                        for i in range(TM_CLASSES)},
        "class_frames": {CLASS_NAMES[i]:
                         int(words[TM_CLASS_FRAMES_OFF + i])
                         for i in range(TM_CLASSES)},
        "class_bytes": {CLASS_NAMES[i]: int(words[TM_CLASS_BYTES_OFF + i])
                        for i in range(TM_CLASSES)},
        "class_drop_frames": {CLASS_NAMES[i]:
                              int(words[TM_DROP_FRAMES_OFF + i])
                              for i in range(TM_CLASSES)},
    }
    used = min(int(words[TM_PEER_USED_OFF]), TM_PEERS)
    out["peers"] = [
        {"fd": int(words[TM_PEER_FD_OFF + i]),
         "frames": int(words[TM_PEER_FRAMES_OFF + i]),
         "bytes": int(words[TM_PEER_BYTES_OFF + i])}
        for i in range(used)
    ]
    return out


class RingError(OSError):
    pass


def _check(rc: int, what: str) -> int:
    if rc < 0:
        raise RingError(-rc, f"{what}: {os.strerror(-rc)}")
    return rc


class Ring:
    """One io_uring instance: SQ/CQ mmaps, a provided-buffer ring for
    multishot recv, and a sparse fixed-buffer table for registered
    pooled egress buffers. All methods are event-loop-thread only."""

    def __init__(self, entries: int = 1024, sqpoll: bool = False,
                 sq_thread_idle_ms: int = 50, pbuf_entries: int = 256,
                 pbuf_len: int = 64 * 1024, fixed_slots: int = 16):
        lib = _get()
        if lib is None:
            raise RingError(_errno.ENOSYS, "uring shim unavailable")
        self._lib = lib
        err = ctypes.c_int(0)
        self._h = lib.pcu_create(entries, 1 if sqpoll else 0,
                                 sq_thread_idle_ms, ctypes.byref(err))
        if not self._h:
            raise RingError(-err.value,
                            f"io_uring_setup: {os.strerror(-err.value)}")
        self.sqpoll = sqpoll
        self.sq_entries = int(lib.pcu_sq_entries(self._h))
        self.enters = 0  # counted io_uring_enter round-trips (bench row)
        self._cq_uds = (_u64 * _CQ_BATCH)()
        self._cq_ress = (ctypes.c_int * _CQ_BATCH)()
        self._cq_flags = (ctypes.c_uint * _CQ_BATCH)()
        base = _u64(0)
        _check(lib.pcu_pbuf_setup(self._h, pbuf_entries, pbuf_len,
                                  ctypes.byref(base)), "pbuf_setup")
        self.pbuf_base = int(base.value)
        self.pbuf_len = pbuf_len
        self.fixed_slots = 0
        if fixed_slots:
            # best-effort: fixed buffers are an optimization, not a
            # requirement (RLIMIT_MEMLOCK can deny the page pinning)
            if lib.pcu_register_buf_table(self._h, fixed_slots) == 0:
                self.fixed_slots = fixed_slots

    # -- lifecycle --

    def close(self) -> None:
        if self._h:
            self._lib.pcu_destroy(self._h)
            self._h = None

    def __del__(self):  # backstop; the engine closes explicitly
        try:
            self.close()
        except Exception:
            pass

    @property
    def closed(self) -> bool:
        return not self._h

    def fd(self) -> int:
        return int(self._lib.pcu_ring_fd(self._h))

    def register_eventfd(self, efd: int, async_only: bool = True) -> None:
        _check(self._lib.pcu_register_eventfd(
            self._h, efd, 1 if async_only else 0), "register_eventfd")

    def update_fixed(self, slot: int, addr: int, length: int) -> int:
        return int(self._lib.pcu_update_buf(self._h, slot, addr, length))

    # -- prep (each returns 0 or raises; -EBUSY triggers a submit+retry) --

    def _retry(self, rc: int, what: str) -> bool:
        if rc == -_errno.EBUSY:
            self.submit()
            return True
        _check(rc, what)
        return False

    def prep_send(self, fd: int, addr: int, length: int, ud: int,
                  sqe_flags: int = 0, msg_flags: int = MSG_NOSIGNAL) -> None:
        while self._retry(self._lib.pcu_prep_send(
                self._h, fd, addr, length, ud, sqe_flags, msg_flags),
                "prep_send"):
            pass

    def prep_send_zc(self, fd: int, addr: int, length: int, ud: int,
                     buf_index: int = -1, sqe_flags: int = 0,
                     msg_flags: int = MSG_NOSIGNAL) -> None:
        while self._retry(self._lib.pcu_prep_send_zc(
                self._h, fd, addr, length, ud, sqe_flags, msg_flags,
                buf_index), "prep_send_zc"):
            pass

    def prep_write_fixed(self, fd: int, addr: int, length: int,
                         buf_index: int, ud: int,
                         sqe_flags: int = 0) -> None:
        while self._retry(self._lib.pcu_prep_write_fixed(
                self._h, fd, addr, length, buf_index, ud, sqe_flags),
                "prep_write_fixed"):
            pass

    def prep_recv_multishot(self, fd: int, ud: int) -> None:
        while self._retry(self._lib.pcu_prep_recv_multishot(
                self._h, fd, ud), "prep_recv_multishot"):
            pass

    def prep_accept_multishot(self, fd: int, ud: int) -> None:
        while self._retry(self._lib.pcu_prep_accept_multishot(
                self._h, fd, ud), "prep_accept_multishot"):
            pass

    def prep_cancel(self, target_ud: int, ud: int) -> None:
        while self._retry(self._lib.pcu_prep_cancel(
                self._h, target_ud, ud), "prep_cancel"):
            pass

    def prep_shutdown(self, fd: int, how: int, ud: int) -> None:
        while self._retry(self._lib.pcu_prep_shutdown(
                self._h, fd, how, ud), "prep_shutdown"):
            pass

    # -- submit / drain --

    def submit(self, wait_nr: int = 0) -> int:
        rc = int(self._lib.pcu_submit(self._h, wait_nr))
        if rc == -_errno.EINTR:
            return 0
        rc = _check(rc, "io_uring_enter")
        # Informational tally (the bench's authoritative count is the
        # LD_PRELOAD interposer): no-op submits skip the syscall, and a
        # SQPOLL ring with an awake poller thread submits with zero.
        if (rc or wait_nr) and not self.sqpoll:
            self.enters += 1
        return rc

    def peek_cqes(self):
        """Drain pending CQEs → list of (user_data, res, flags)."""
        n = int(self._lib.pcu_peek_cqes(
            self._h, self._cq_uds, self._cq_ress, self._cq_flags,
            _CQ_BATCH))
        if n <= 0:
            if self._lib.pcu_cq_overflowed(self._h):
                self._lib.pcu_flush_overflow(self._h)
                self.enters += 1
                n = int(self._lib.pcu_peek_cqes(
                    self._h, self._cq_uds, self._cq_ress, self._cq_flags,
                    _CQ_BATCH))
                if n <= 0:
                    return []
            else:
                return []
        uds, ress, flags = self._cq_uds, self._cq_ress, self._cq_flags
        return [(uds[i], ress[i], flags[i]) for i in range(n)]

    # -- shm telemetry block (ISSUE 19) --

    def enable_telemetry(self) -> bool:
        """Attach the shm telemetry block (idempotent). Best-effort:
        returns False when the mmap is denied — telemetry is an
        observability plane, never a reason to fail the ring."""
        if not self._h:
            return False
        return int(self._lib.pcu_telem_enable(self._h)) == 0

    @property
    def telemetry_enabled(self) -> bool:
        return bool(self._h) and \
            bool(self._lib.pcu_telem_enabled(self._h))

    def telemetry_snapshot(self):
        """Torn-read-safe snapshot of the telemetry payload as a list of
        TM_WORDS ints, or None when telemetry is off / unreadable."""
        if not self._h:
            return None
        words = int(self._lib.pcu_telem_words())
        buf = (_u64 * words)()
        n = int(self._lib.pcu_telem_snapshot(self._h, buf, words))
        if n <= 0:
            return None
        return list(buf[:n])

    def telemetry_test_observe(self, kind: int, idx: int, ns: int,
                               n: int = 1) -> int:
        """Test hook: drive one histogram observation from Python
        (kind 0=stage 1=chain 2=class_delay)."""
        if not self._h:
            return -1
        return int(self._lib.pcu_telem_test_observe(
            self._h, kind, idx, ns, n))

    def telemetry_test_count(self, which: int, idx: int, n: int = 1) -> int:
        """Test hook: bump a flat per-class counter (which 0=class_frames
        1=fate_drop_frames) so the ledger fold is testable pump-free."""
        if not self._h:
            return -1
        return int(self._lib.pcu_telem_test_count(self._h, which, idx, n))

    def pbuf_read(self, bid: int, nbytes: int) -> bytes:
        """Copy a provided buffer's payload out (the one copy the recv
        path pays, matching the asyncio reader's chunk copy count)."""
        return ctypes.string_at(self.pbuf_base + bid * self.pbuf_len,
                                nbytes)

    def pbuf_recycle(self, bid: int) -> None:
        self._lib.pcu_pbuf_recycle(self._h, bid)
