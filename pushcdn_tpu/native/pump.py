"""ctypes binding for the fused data-plane pump (native/pump.cpp).

The pump library COMPOSES the two native layers below it: it is compiled
from a TU that includes ``io_uring.cpp`` and ``route_plan.cpp``, and at
runtime it operates on handles those libraries created — the transport
engine's ``Ring._h`` (a ``pcu_ring*``) and the planner's
``RoutePlanner._handle`` (a ``RouteTable*``). That interop is sound
because the structs carry all state (no library globals), every .so is
built from the same sources with the same flags, and allocation goes
through the shared libc — but it does mean THIS module must rebuild its
cache when *any* of the three sources change, so staleness is checked
against all of them (``_build_lib`` alone only checks one).

Policy (which peers engage, fencing, lease parking, submit scheduling)
lives in ``proto/transport/pump.py``; this module is the thin typed
surface plus per-instance scratch so the hot calls allocate nothing.
"""

from __future__ import annotations

import ctypes
import os
import threading
from typing import Optional

import numpy as np

from pushcdn_tpu.native import _build_lib, _BUILD_DIR, _REPO, _ptr

_SRC = os.path.join(_REPO, "native", "pump.cpp")
_DEPS = (_SRC,
         os.path.join(_REPO, "native", "io_uring.cpp"),
         os.path.join(_REPO, "native", "route_plan.cpp"))
_LIB_PATH = os.path.join(_BUILD_DIR, "libpushcdn_pump.so")

_lock = threading.Lock()
_lib: Optional[ctypes.CDLL] = None
_tried = False

_u64 = ctypes.c_ulonglong
_i64 = ctypes.c_longlong
_u64p = ctypes.POINTER(_u64)
_i64p = ctypes.POINTER(_i64)
_i32p = ctypes.POINTER(ctypes.c_int)
_u32p = ctypes.POINTER(ctypes.c_uint)
_longp = ctypes.POINTER(ctypes.c_long)

# route_chunk out_meta indices (mirrors the C comment block)
META_CONSUMED = 0
META_STOP = 1
META_N_RESID = 2
META_CHUNK_SLOT = 3
META_REFS = 4
META_SQES = 5
META_PAIRS = 6
META_USER_PAIRS = 7
META_BROKER_PAIRS = 8
META_RESID_UNMAPPED = 9
META_RESID_FENCED = 10
META_RESID_ERROR = 11
META_NO_CHUNK_SLOT = 12
META_RUNS = 13
META_PLAN_PAIRS = 14

# drain/inject event triple types
EV_PEER_IDLE = 1
EV_PEER_ERROR = 2
EV_PEER_QUIESCED = 3

STATS_KEYS = ("runs", "chains", "sqes", "cqes", "bytes", "frames",
              "errors", "short_repump", "engaged", "fenced",
              "chunk_slots_free", "queued_runs", "ev_lost")


def _compile() -> Optional[ctypes.CDLL]:
    try:
        if os.path.exists(_LIB_PATH):
            newest = max(os.path.getmtime(s) for s in _DEPS)
            if newest > os.path.getmtime(_LIB_PATH):
                os.remove(_LIB_PATH)  # _build_lib only watches pump.cpp
    except OSError:
        return None
    lib = _build_lib(_SRC, _LIB_PATH, ctypes.CDLL,
                     ("-I", os.path.join(_REPO, "native")))
    if lib is None:
        return None
    P = ctypes.c_void_p
    u8p = ctypes.POINTER(ctypes.c_uint8)
    lib.pushcdn_pump_create.restype = P
    lib.pushcdn_pump_create.argtypes = [P, ctypes.c_int, ctypes.c_int,
                                        ctypes.c_int, ctypes.c_long]
    lib.pushcdn_pump_destroy.restype = None
    lib.pushcdn_pump_destroy.argtypes = [P]
    lib.pushcdn_pump_add_peer.restype = ctypes.c_int
    lib.pushcdn_pump_add_peer.argtypes = [P, ctypes.c_int]
    lib.pushcdn_pump_set_fence.restype = None
    lib.pushcdn_pump_set_fence.argtypes = [P, ctypes.c_int, ctypes.c_int]
    lib.pushcdn_pump_peer_pending.restype = ctypes.c_long
    lib.pushcdn_pump_peer_pending.argtypes = [P, ctypes.c_int]
    lib.pushcdn_pump_peer_stats.restype = None
    lib.pushcdn_pump_peer_stats.argtypes = [P, ctypes.c_int, _i64p]
    lib.pushcdn_pump_drop_peer.restype = ctypes.c_int
    lib.pushcdn_pump_drop_peer.argtypes = [P, ctypes.c_int]
    lib.pushcdn_pump_take_released.restype = ctypes.c_long
    lib.pushcdn_pump_take_released.argtypes = [P, _i32p, ctypes.c_long]
    lib.pushcdn_pump_set_slots.restype = ctypes.c_int
    lib.pushcdn_pump_set_slots.argtypes = [P, _i32p, ctypes.c_long]
    lib.pushcdn_pump_route_chunk.restype = _i64
    lib.pushcdn_pump_route_chunk.argtypes = [
        P, P, u8p, _i64, _i64p, _i64p, _i64, _i64, ctypes.c_int,
        _i32p, _i32p, _i64, _i64p, u8p]
    lib.pushcdn_pump_drain.restype = ctypes.c_int
    lib.pushcdn_pump_drain.argtypes = [P, _u64p, _i32p, _u32p,
                                       ctypes.c_int, _i64p, ctypes.c_long,
                                       _longp, _longp]
    lib.pushcdn_pump_inject_cqe.restype = ctypes.c_int
    lib.pushcdn_pump_inject_cqe.argtypes = [P, ctypes.c_int, ctypes.c_int,
                                            _i64p, ctypes.c_long, _longp]
    lib.pushcdn_pump_stats.restype = None
    lib.pushcdn_pump_stats.argtypes = [P, _u64p]
    return lib


def _get() -> Optional[ctypes.CDLL]:
    global _lib, _tried
    if _lib is None and not _tried:
        with _lock:
            if _lib is None and not _tried:
                _lib = _compile()
                _tried = True
    return _lib


def available() -> bool:
    return _get() is not None


_CQ_BATCH = 512
_EV_CAP = 3 * 256  # 256 peer-state triples per drain: far above need


class NativePump:
    """One pump instance bound to one engine ring. Event-loop-thread only
    (the same affinity as the ``Ring`` it drives).

    Lifecycle contract the caller (``proto/transport/pump.py``) must
    keep: drain :meth:`take_released` after EVERY call that can release
    chunk slots (:meth:`drain`, :meth:`inject_cqe`, :meth:`drop_peer`)
    and before the next :meth:`route_chunk` — a freed slot is eligible
    for reuse, so an undrained release would alias the next chunk's
    lease parking.
    """

    __slots__ = ("_lib", "_h", "_ring", "pair_cap", "chunk_slots",
                 "_resid_peer", "_resid_frame", "_meta", "_uds", "_ress",
                 "_flagss", "_events", "_released", "_stats", "_pstats",
                 "_n_events", "_n_prepped", "_frame_cls")

    def __init__(self, lib, handle, ring, pair_cap: int, chunk_slots: int):
        self._lib = lib
        self._h = handle
        self._ring = ring
        self.pair_cap = pair_cap
        self.chunk_slots = chunk_slots
        self._resid_peer = np.zeros(pair_cap, np.int32)
        self._resid_frame = np.zeros(pair_cap, np.int32)
        self._meta = np.zeros(16, np.int64)
        self._uds = (_u64 * _CQ_BATCH)()
        self._ress = (ctypes.c_int * _CQ_BATCH)()
        self._flagss = (ctypes.c_uint * _CQ_BATCH)()
        self._events = (_i64 * _EV_CAP)()
        self._released = (ctypes.c_int * chunk_slots)()
        self._stats = (_u64 * 16)()
        self._pstats = (_i64 * 6)()
        self._n_events = ctypes.c_long(0)
        self._n_prepped = ctypes.c_long(0)
        self._frame_cls = np.zeros(1024, np.uint8)

    @classmethod
    def create(cls, ring, max_peers: int = 4096, chunk_slots: int = 64,
               sq_reserve: int = 64,
               pair_cap: int = 65536) -> Optional["NativePump"]:
        """Bind a pump to ``ring`` (a ``native.uring.Ring``). Returns
        None when the library is unavailable or creation fails.
        ``sq_reserve`` SQ entries are kept back from pumped chains so
        the Python engine can always prep its own control traffic."""
        lib = _get()
        if lib is None or ring is None or getattr(ring, "closed", True):
            return None
        h = lib.pushcdn_pump_create(ring._h, max_peers, chunk_slots,
                                    sq_reserve, pair_cap)
        if not h:
            return None
        return cls(lib, h, ring, pair_cap, chunk_slots)

    def destroy(self) -> None:
        if self._h:
            self._lib.pushcdn_pump_destroy(self._h)
            self._h = None

    @property
    def closed(self) -> bool:
        return not self._h

    # -- peers --

    def add_peer(self, fd: int) -> int:
        return int(self._lib.pushcdn_pump_add_peer(self._h, fd))

    def set_fence(self, pid: int, fenced: bool) -> None:
        self._lib.pushcdn_pump_set_fence(self._h, pid, 1 if fenced else 0)

    def peer_pending(self, pid: int) -> int:
        return int(self._lib.pushcdn_pump_peer_pending(self._h, pid))

    def peer_stats(self, pid: int) -> dict:
        self._lib.pushcdn_pump_peer_stats(self._h, pid, self._pstats)
        s = self._pstats
        return {"q_len": int(s[0]), "inflight": int(s[1]),
                "fenced": bool(s[2]), "err": int(s[3]),
                "dead": bool(s[4]), "in_use": bool(s[5])}

    def drop_peer(self, pid: int) -> int:
        """1 = slot freed now, 0 = frees when inflight CQEs quiesce."""
        return int(self._lib.pushcdn_pump_drop_peer(self._h, pid))

    def take_released(self) -> list:
        out = []
        while True:
            n = int(self._lib.pushcdn_pump_take_released(
                self._h, self._released, self.chunk_slots))
            out.extend(self._released[i] for i in range(n))
            if n < self.chunk_slots:
                return out

    def set_slots(self, slots: np.ndarray) -> bool:
        slots = np.ascontiguousarray(slots, np.int32)
        rc = self._lib.pushcdn_pump_set_slots(
            self._h, _ptr(slots, ctypes.c_int), len(slots))
        return rc == 0

    # -- hot path --

    def route_chunk(self, table_handle, buf, offs: np.ndarray,
                    lens: np.ndarray, start: int, mode: int):
        """Plan + pump one chunk. Returns ``(consumed, stop,
        resid_peers, resid_frames, meta)`` where the resid arrays are
        int32 VIEWS over instance scratch (consume before the next
        call) and ``meta`` is the int64[16] out_meta view.

        Per-frame flow classes land in the ``frame_classes`` scratch
        (absolute frame index; 255 = consumed, delivered to no one)."""
        arr = np.frombuffer(buf, np.uint8)
        count = len(offs) - start
        if len(self._frame_cls) < len(offs):
            self._frame_cls = np.zeros(
                max(len(offs), 2 * len(self._frame_cls)), np.uint8)
        consumed = self._lib.pushcdn_pump_route_chunk(
            self._h, table_handle, _ptr(arr, ctypes.c_uint8), len(arr),
            _ptr(offs, _i64), _ptr(lens, _i64), start, count, mode,
            _ptr(self._resid_peer, ctypes.c_int),
            _ptr(self._resid_frame, ctypes.c_int),
            self.pair_cap, _ptr(self._meta, _i64),
            _ptr(self._frame_cls, ctypes.c_uint8))
        meta = self._meta
        n_resid = int(meta[META_N_RESID])
        return (int(consumed), int(meta[META_STOP]),
                self._resid_peer[:n_resid], self._resid_frame[:n_resid],
                meta)

    @property
    def frame_classes(self) -> np.ndarray:
        """Per-frame flow classes from the last ``route_chunk`` (absolute
        frame index; only [start, start+consumed) meaningful)."""
        return self._frame_cls

    def drain(self):
        """Drain the ring's CQ through the pump. Returns ``(cqes,
        events, n_prepped)``: ``cqes`` is the non-pump completions as
        (user_data, res, flags) tuples for the engine's dispatcher,
        ``events`` the flat (type, pid, arg) triples, and ``n_prepped``
        the SQEs the chain sweep staged (schedule a submit when > 0).
        Mirrors ``Ring.peek_cqes``'s CQ-overflow flush."""
        cqes, events = [], []
        n_prepped = 0
        while True:
            n = int(self._lib.pushcdn_pump_drain(
                self._h, self._uds, self._ress, self._flagss, _CQ_BATCH,
                self._events, _EV_CAP, ctypes.byref(self._n_events),
                ctypes.byref(self._n_prepped)))
            n_prepped += int(self._n_prepped.value)
            ne = int(self._n_events.value)
            for i in range(0, ne, 3):
                events.append((int(self._events[i]),
                               int(self._events[i + 1]),
                               int(self._events[i + 2])))
            uds, ress, flagss = self._uds, self._ress, self._flagss
            cqes.extend((uds[i], ress[i], flagss[i]) for i in range(n))
            if n < _CQ_BATCH and ne < _EV_CAP:
                break
        ring = self._ring
        if not cqes and ring is not None and not ring.closed \
                and ring._lib.pcu_cq_overflowed(ring._h):
            ring._lib.pcu_flush_overflow(ring._h)
            ring.enters += 1
            n = int(self._lib.pushcdn_pump_drain(
                self._h, self._uds, self._ress, self._flagss, _CQ_BATCH,
                self._events, _EV_CAP, ctypes.byref(self._n_events),
                ctypes.byref(self._n_prepped)))
            n_prepped += int(self._n_prepped.value)
            ne = int(self._n_events.value)
            for i in range(0, ne, 3):
                events.append((int(self._events[i]),
                               int(self._events[i + 1]),
                               int(self._events[i + 2])))
            uds, ress, flagss = self._uds, self._ress, self._flagss
            cqes.extend((uds[i], ress[i], flagss[i]) for i in range(n))
        return cqes, events, n_prepped

    def inject_cqe(self, pid: int, res: int) -> list:
        """Test hook: feed one synthetic completion for peer ``pid``
        through the pump's accounting; returns the event triples."""
        rc = int(self._lib.pushcdn_pump_inject_cqe(
            self._h, pid, res, self._events, _EV_CAP,
            ctypes.byref(self._n_events)))
        if rc != 0:
            raise ValueError(f"inject_cqe: bad peer id {pid}")
        ne = int(self._n_events.value)
        return [(int(self._events[i]), int(self._events[i + 1]),
                 int(self._events[i + 2])) for i in range(0, ne, 3)]

    def stats(self) -> dict:
        self._lib.pushcdn_pump_stats(self._h, self._stats)
        return {k: int(self._stats[i]) for i, k in enumerate(STATS_KEYS)}
