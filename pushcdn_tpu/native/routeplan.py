"""ctypes binding for the batch route-plan kernel (native/route_plan.cpp).

The kernel is the cut-through routing plane's core: one C call scans a
``FrameChunk``'s frame headers in place, matches Broadcast topic bitmasks
against an interest-table snapshot and Direct recipients against a
DirectMap hash snapshot, and returns a flat (peer, frame) fan-out pair
list. A second call gathers one peer's frames into a wire-ready
length-delimited buffer. Snapshot lifecycle (when to rebuild, how peers
map to connections) is the caller's job — see
``pushcdn_tpu.broker.tasks.cutthrough``.

Same degradation contract as the rest of the package: ``RoutePlanner.create``
returns None when the library can't compile/load, and callers fall back to
the scalar routing path.
"""

from __future__ import annotations

import ctypes
import os
import threading
from typing import List, Optional, Tuple

import numpy as np

from pushcdn_tpu.native import _BUILD_DIR, _REPO, _build_lib

_SRC = os.path.join(_REPO, "native", "route_plan.cpp")
_LIB_PATH = os.path.join(_BUILD_DIR, "libpushcdn_routeplan.so")

_lock = threading.Lock()
_lib = None
_tried = False

MASK_WORDS = 4  # 4 x u64 = the full u8 topic space

# plan() stop reasons (mirrors route_plan.cpp)
STOP_END = 0       # whole range planned
STOP_RESIDUAL = 1  # next frame is control/malformed: scalar path owns it
STOP_CAPACITY = 2  # pair buffer full: call again from the returned index


def _compile():
    lib = _build_lib(_SRC, _LIB_PATH, ctypes.CDLL)
    if lib is None:
        return None
    u8p = ctypes.POINTER(ctypes.c_uint8)
    i32p = ctypes.POINTER(ctypes.c_int32)
    i64p = ctypes.POINTER(ctypes.c_int64)
    u64p = ctypes.POINTER(ctypes.c_uint64)
    lib.pushcdn_route_table_create.restype = ctypes.c_void_p
    lib.pushcdn_route_table_create.argtypes = []
    lib.pushcdn_route_table_destroy.restype = None
    lib.pushcdn_route_table_destroy.argtypes = [ctypes.c_void_p]
    lib.pushcdn_route_table_build.restype = ctypes.c_int32
    lib.pushcdn_route_table_build.argtypes = [
        ctypes.c_void_p, ctypes.c_int32, ctypes.c_int32,
        u64p, u64p, u8p, i64p, i32p, i32p, ctypes.c_int32]
    lib.pushcdn_route_table_apply.restype = ctypes.c_int32
    lib.pushcdn_route_table_apply.argtypes = [
        ctypes.c_void_p, i32p, u64p, ctypes.c_int32,
        u8p, i64p, i32p, i32p, ctypes.c_int32]
    lib.pushcdn_route_table_stats.restype = None
    lib.pushcdn_route_table_stats.argtypes = [ctypes.c_void_p, i64p]
    lib.pushcdn_route_table_set_classes.restype = ctypes.c_int32
    lib.pushcdn_route_table_set_classes.argtypes = [ctypes.c_void_p, u8p]
    lib.pushcdn_route_plan.restype = ctypes.c_int64
    lib.pushcdn_route_plan.argtypes = [
        ctypes.c_void_p, u8p, ctypes.c_int64, i64p, i64p,
        ctypes.c_int64, ctypes.c_int64, ctypes.c_int32,
        i32p, i32p, ctypes.c_int64, i64p, i32p, u8p]
    lib.pushcdn_route_gather.restype = ctypes.c_int64
    lib.pushcdn_route_gather.argtypes = [
        u8p, ctypes.c_int64, i64p, i64p, i32p, ctypes.c_int64,
        u8p, ctypes.c_int64]
    return lib


def _get():
    global _lib, _tried
    if _lib is None and not _tried:
        with _lock:
            if _lib is None and not _tried:
                _lib = _compile()
                _tried = True
    return _lib


def available() -> bool:
    return _get() is not None


def _ptr(a: np.ndarray, ctype):
    return a.ctypes.data_as(ctypes.POINTER(ctype))


def topic_mask(topics) -> np.ndarray:
    """Pack an iterable of u8 topics into the kernel's [4] u64 bitmask."""
    mask = np.zeros(MASK_WORDS, np.uint64)
    for t in topics:
        t = int(t)
        if 0 <= t <= 255:
            mask[t >> 6] |= np.uint64(1 << (t & 63))
    return mask


class RoutePlanner:
    """One routing-snapshot handle + reusable plan scratch buffers.

    Not thread-safe (the broker's event loop owns it); the snapshot is
    rebuilt by the caller whenever routing state changes — see
    ``cutthrough.RouteState``.
    """

    __slots__ = ("_lib", "_handle", "_pair_peer", "_pair_frame",
                 "_frame_cls", "n_users", "n_brokers")

    def __init__(self, lib, handle):
        self._lib = lib
        self._handle = handle
        self._pair_peer = np.zeros(4096, np.int32)
        self._pair_frame = np.zeros(4096, np.int32)
        self._frame_cls = np.zeros(1024, np.uint8)
        self.n_users = 0
        self.n_brokers = 0

    @classmethod
    def create(cls) -> Optional["RoutePlanner"]:
        lib = _get()
        if lib is None:
            return None
        handle = lib.pushcdn_route_table_create()
        if not handle:
            return None
        return cls(lib, handle)

    def __del__(self):
        handle, self._handle = getattr(self, "_handle", None), None
        if handle and self._lib is not None:
            try:
                self._lib.pushcdn_route_table_destroy(handle)
            except Exception:
                pass

    def build(self, n_users: int, n_brokers: int, valid_mask: np.ndarray,
              peer_masks: np.ndarray, direct_keys: List[bytes],
              direct_owners: np.ndarray) -> bool:
        """Install a snapshot: ``peer_masks`` is u64[P, 4] interest
        bitmasks (users first, then brokers); ``direct_keys[i]`` routes to
        peer ``direct_owners[i]``. Returns False on allocation failure
        (the caller must fall back to scalar routing)."""
        self.n_users = int(n_users)
        self.n_brokers = int(n_brokers)
        n = len(direct_keys)
        lens = np.fromiter(map(len, direct_keys), np.int32, count=n) \
            if n else np.zeros(1, np.int32)
        offs = np.zeros(max(n, 1), np.int64)
        if n:
            np.cumsum(lens[:-1], dtype=np.int64, out=offs[1:n])
        blob = b"".join(direct_keys)
        blob_arr = np.frombuffer(blob, np.uint8) if blob \
            else np.zeros(1, np.uint8)
        owners = np.ascontiguousarray(direct_owners, np.int32) \
            if n else np.zeros(1, np.int32)
        peer_masks = np.ascontiguousarray(peer_masks, np.uint64)
        valid_mask = np.ascontiguousarray(valid_mask, np.uint64)
        rc = self._lib.pushcdn_route_table_build(
            self._handle,
            self.n_users, self.n_brokers,
            _ptr(valid_mask, ctypes.c_uint64),
            _ptr(peer_masks, ctypes.c_uint64),
            _ptr(blob_arr, ctypes.c_uint8), _ptr(offs, ctypes.c_int64),
            _ptr(lens, ctypes.c_int32), _ptr(owners, ctypes.c_int32), n)
        return rc == 0

    def apply(self, upd_peers, upd_masks, direct_keys: List[bytes],
              direct_owners) -> bool:
        """Apply one delta batch IN PLACE (ISSUE 7): ``upd_peers[i]`` gets
        the absolute interest mask ``upd_masks[i]`` (u64[4]; zeros free the
        slot), and ``direct_keys[i]`` is upserted to peer
        ``direct_owners[i]`` (or tombstoned when the owner is ``-1``).
        O(delta) — the stored masks are the diff base. Returns False on
        allocation failure / out-of-range slot (the caller must fall back
        to a full rebuild)."""
        n_upd = len(upd_peers)
        peers = np.ascontiguousarray(upd_peers, np.int32) if n_upd \
            else np.zeros(1, np.int32)
        masks = np.ascontiguousarray(upd_masks, np.uint64) if n_upd \
            else np.zeros(MASK_WORDS, np.uint64)
        n = len(direct_keys)
        lens = np.fromiter(map(len, direct_keys), np.int32, count=n) \
            if n else np.zeros(1, np.int32)
        offs = np.zeros(max(n, 1), np.int64)
        if n:
            np.cumsum(lens[:-1], dtype=np.int64, out=offs[1:n])
        blob = b"".join(direct_keys)
        blob_arr = np.frombuffer(blob, np.uint8) if blob \
            else np.zeros(1, np.uint8)
        owners = np.ascontiguousarray(direct_owners, np.int32) \
            if n else np.zeros(1, np.int32)
        rc = self._lib.pushcdn_route_table_apply(
            self._handle,
            _ptr(peers, ctypes.c_int32), _ptr(masks, ctypes.c_uint64),
            n_upd,
            _ptr(blob_arr, ctypes.c_uint8), _ptr(offs, ctypes.c_int64),
            _ptr(lens, ctypes.c_int32), _ptr(owners, ctypes.c_int32), n)
        return rc == 0

    def stats(self) -> dict:
        """Occupancy/garbage counters (the compaction-policy inputs)."""
        out = np.zeros(8, np.int64)
        self._lib.pushcdn_route_table_stats(self._handle,
                                            _ptr(out, ctypes.c_int64))
        return {"n_users": int(out[0]), "n_brokers": int(out[1]),
                "live_subs": int(out[2]), "list_entries": int(out[3]),
                "dmap_live": int(out[4]), "dmap_tombstones": int(out[5]),
                "keys_blob_bytes": int(out[6]),
                "keys_blob_garbage": int(out[7])}

    def set_classes(self, classes: np.ndarray) -> bool:
        """Install the topic -> flow-class map (u8[256], values 0..3 per
        ``proto.flowclass``). Survives ``build``/``apply``: the taxonomy
        is deployment config, not routing state."""
        table = np.ascontiguousarray(classes, np.uint8)
        if table.shape != (256,):
            return False
        return self._lib.pushcdn_route_table_set_classes(
            self._handle, _ptr(table, ctypes.c_uint8)) == 0

    def _ensure_pairs(self, need: int) -> None:
        if len(self._pair_peer) < need:
            cap = max(need, 2 * len(self._pair_peer))
            self._pair_peer = np.zeros(cap, np.int32)
            self._pair_frame = np.zeros(cap, np.int32)

    def _ensure_classes(self, need: int) -> None:
        if len(self._frame_cls) < need:
            cap = max(need, 2 * len(self._frame_cls))
            self._frame_cls = np.zeros(cap, np.uint8)

    def plan(self, buf: bytes, offs: np.ndarray, lens: np.ndarray,
             start: int, mode: int
             ) -> Tuple[int, int, np.ndarray, np.ndarray]:
        """Plan frames [start, len(offs)) of one chunk buffer.

        Returns (consumed, stop_reason, peer_idx, frame_idx) where the
        pair arrays are views into reusable scratch (valid until the next
        call). ``mode`` 0 = user-origin, 1 = broker-origin.

        Per-frame flow classes land in the ``frame_classes`` scratch
        (absolute frame index; 255 = consumed but delivered to no one),
        valid for the same window as the pair views."""
        count = len(offs) - start
        n_peers = self.n_users + self.n_brokers
        # capacity for the worst case (every frame fans to every peer)
        # is overkill; size for one guaranteed frame of progress plus a
        # typical batch, and let STOP_CAPACITY loop handle the rest
        self._ensure_pairs(max(n_peers + 1, 4096))
        self._ensure_classes(len(offs))
        arr = np.frombuffer(buf, np.uint8) if buf else np.zeros(1, np.uint8)
        n_pairs = ctypes.c_int64(0)
        stop = ctypes.c_int32(0)
        consumed = self._lib.pushcdn_route_plan(
            self._handle, _ptr(arr, ctypes.c_uint8), len(buf),
            _ptr(offs, ctypes.c_int64), _ptr(lens, ctypes.c_int64),
            start, count, mode,
            _ptr(self._pair_peer, ctypes.c_int32),
            _ptr(self._pair_frame, ctypes.c_int32),
            len(self._pair_peer), ctypes.byref(n_pairs), ctypes.byref(stop),
            _ptr(self._frame_cls, ctypes.c_uint8))
        if consumed < 0:
            return 0, STOP_RESIDUAL, self._pair_peer[:0], self._pair_frame[:0]
        k = n_pairs.value
        return (int(consumed), int(stop.value),
                self._pair_peer[:k], self._pair_frame[:k])

    @property
    def frame_classes(self) -> np.ndarray:
        """Per-frame flow classes from the last ``plan`` call, indexed by
        absolute frame index (only [start, start+consumed) meaningful)."""
        return self._frame_cls

    def gather(self, buf: bytes, offs: np.ndarray, lens: np.ndarray,
               frame_idx: np.ndarray) -> Optional[bytearray]:
        """Length-delimit one peer's fan-out frames into a fresh buffer
        (one C call, one copy — the cut-through egress handoff for
        non-contiguous index runs)."""
        total = int(lens[frame_idx].sum()) + 4 * len(frame_idx)
        out = bytearray(total)
        arr = np.frombuffer(buf, np.uint8) if buf else np.zeros(1, np.uint8)
        out_ptr = (ctypes.c_uint8 * total).from_buffer(out)
        idx = np.ascontiguousarray(frame_idx, np.int32)
        wrote = self._lib.pushcdn_route_gather(
            _ptr(arr, ctypes.c_uint8), len(buf),
            _ptr(offs, ctypes.c_int64), _ptr(lens, ctypes.c_int64),
            _ptr(idx, ctypes.c_int32), len(idx),
            ctypes.cast(out_ptr, ctypes.POINTER(ctypes.c_uint8)), total)
        del out_ptr
        if wrote != total:
            return None
        return out
