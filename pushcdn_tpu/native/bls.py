"""ctypes bindings for the native BLS-over-BN254 library
(native/bls_bn254.cpp).

The reference's signature scheme is BLS over BN254 from jellyfish
(cdn-proto/src/crypto/signature.rs:113-175); the pairing arithmetic is
native there and native here. Compiled on first use with g++ (pybind11 is
not in this image, so the ABI is plain C via ctypes) and cached under
``.build/``. ``available()`` is False if compilation fails; callers fall
back to the Ed25519 scheme — the ``SignatureScheme`` seam makes the swap
invisible.
"""

from __future__ import annotations

import ctypes
import os
import subprocess
import threading
from typing import Optional

_REPO = os.path.dirname(os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
_SRC = os.path.join(_REPO, "native", "bls_bn254.cpp")
_INC = os.path.join(_REPO, "native", "bls_generated.inc")
_BUILD_DIR = os.path.join(_REPO, ".build")
_LIB_PATH = os.path.join(_BUILD_DIR, "libpushcdn_bls.so")

SK_LEN = 32
PK_LEN = 128
SIG_LEN = 64

_lock = threading.Lock()
_lib: Optional[ctypes.CDLL] = None
_tried = False


def _compile() -> Optional[ctypes.CDLL]:
    os.makedirs(_BUILD_DIR, exist_ok=True)
    src_mtime = max(os.path.getmtime(_SRC), os.path.getmtime(_INC))
    if not os.path.exists(_LIB_PATH) or src_mtime > os.path.getmtime(_LIB_PATH):
        cmd = ["g++", "-O3", "-shared", "-fPIC", _SRC, "-o", _LIB_PATH]
        try:
            subprocess.run(cmd, check=True, capture_output=True, timeout=180)
        except (subprocess.SubprocessError, OSError):
            return None
    try:
        lib = ctypes.CDLL(_LIB_PATH)
    except OSError:
        return None

    u8p = ctypes.POINTER(ctypes.c_uint8)
    lib.bls_keygen.restype = ctypes.c_int
    lib.bls_keygen.argtypes = [u8p, u8p, u8p]
    lib.bls_sign.restype = ctypes.c_int
    lib.bls_sign.argtypes = [u8p, ctypes.c_char_p, ctypes.c_longlong, u8p]
    lib.bls_verify.restype = ctypes.c_int
    lib.bls_verify.argtypes = [u8p, ctypes.c_char_p, ctypes.c_longlong, u8p]
    lib.bls_self_test.restype = ctypes.c_int
    lib.bls_self_test.argtypes = []
    return lib


def _get() -> Optional[ctypes.CDLL]:
    global _lib, _tried
    with _lock:
        if not _tried:
            _tried = True
            _lib = _compile()
        return _lib


def available() -> bool:
    return _get() is not None


def self_test() -> int:
    """0 = all pairing/scheme invariants hold (see bls_self_test)."""
    lib = _get()
    if lib is None:
        return -1
    return lib.bls_self_test()


def _buf(data: bytes):
    return (ctypes.c_uint8 * len(data)).from_buffer_copy(data)


def keygen(seed32: bytes) -> tuple[bytes, bytes]:
    """Deterministic (private_key, public_key) from a 32-byte seed."""
    lib = _get()
    assert lib is not None, "native BLS unavailable"
    assert len(seed32) == 32
    sk = (ctypes.c_uint8 * SK_LEN)()
    pk = (ctypes.c_uint8 * PK_LEN)()
    rc = lib.bls_keygen(_buf(seed32), sk, pk)
    if rc != 0:
        raise ValueError(f"bls_keygen failed: {rc}")
    return bytes(sk), bytes(pk)


def sign(sk: bytes, message: bytes) -> bytes:
    lib = _get()
    assert lib is not None, "native BLS unavailable"
    if len(sk) != SK_LEN:
        raise ValueError("bad secret key length")
    sig = (ctypes.c_uint8 * SIG_LEN)()
    rc = lib.bls_sign(_buf(sk), bytes(message), len(message), sig)
    if rc != 0:
        raise ValueError(f"bls_sign failed: {rc}")
    return bytes(sig)


def verify(pk: bytes, message: bytes, signature: bytes) -> bool:
    lib = _get()
    assert lib is not None, "native BLS unavailable"
    if len(pk) != PK_LEN or len(signature) != SIG_LEN:
        return False
    return lib.bls_verify(_buf(pk), bytes(message), len(message),
                          _buf(signature)) == 1
