"""ctypes bindings for the native BLS-over-BN254 library
(native/bls_bn254.cpp).

The reference's signature scheme is BLS over BN254 from jellyfish
(cdn-proto/src/crypto/signature.rs:113-175); the pairing arithmetic is
native there and native here. Compiled on first use with g++ (pybind11 is
not in this image, so the ABI is plain C via ctypes) and cached under
``.build/``. ``available()`` is False if compilation fails; callers fall
back to the Ed25519 scheme — the ``SignatureScheme`` seam makes the swap
invisible.
"""

from __future__ import annotations

import ctypes
import os
import subprocess
import threading
from typing import Optional

_REPO = os.path.dirname(os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
_SRC = os.path.join(_REPO, "native", "bls_bn254.cpp")
_INC = os.path.join(_REPO, "native", "bls_generated.inc")
_BUILD_DIR = os.path.join(_REPO, ".build")
_LIB_PATH = os.path.join(_BUILD_DIR, "libpushcdn_bls.so")

SK_LEN = 32
PK_LEN = 128
SIG_LEN = 64

_lock = threading.Lock()
_lib: Optional[ctypes.CDLL] = None
_tried = False


def _compile() -> Optional[ctypes.CDLL]:
    os.makedirs(_BUILD_DIR, exist_ok=True)
    # The cached .so may have been built with -march=native on a DIFFERENT
    # machine (repo on shared storage / baked into an image): loading it
    # here could die with an uncatchable SIGILL. Key the cache on a host
    # fingerprint as well as source mtime and rebuild on mismatch.
    import hashlib
    import platform
    try:
        with open("/proc/cpuinfo") as f:
            cpu_src = f.read()
    except OSError:
        cpu_src = platform.processor() or platform.machine()
    host_tag = hashlib.sha256(
        (platform.machine() + "\n" + cpu_src).encode()).hexdigest()[:16]
    tag_path = _LIB_PATH + ".hosttag"
    try:
        cached_tag = open(tag_path).read().strip()
    except OSError:
        cached_tag = ""
    src_mtime = max(os.path.getmtime(_SRC), os.path.getmtime(_INC))
    if not os.path.exists(_LIB_PATH) or \
            src_mtime > os.path.getmtime(_LIB_PATH) or cached_tag != host_tag:
        # -march=native is worth ~10% on the Montgomery ladder (adx/bmi2);
        # fall back to the portable build where the flag is unsupported
        base = ["g++", "-O3", "-shared", "-fPIC", _SRC, "-o", _LIB_PATH]
        for cmd in (base[:2] + ["-march=native"] + base[2:], base):
            try:
                subprocess.run(cmd, check=True, capture_output=True,
                               timeout=180)
                break
            except (subprocess.SubprocessError, OSError):
                continue
        else:
            return None
        try:
            with open(tag_path, "w") as f:
                f.write(host_tag)
        except OSError:
            pass
    try:
        lib = ctypes.CDLL(_LIB_PATH)
    except OSError:
        return None

    u8p = ctypes.POINTER(ctypes.c_uint8)
    lib.bls_keygen.restype = ctypes.c_int
    lib.bls_keygen.argtypes = [u8p, u8p, u8p]
    lib.bls_sign.restype = ctypes.c_int
    lib.bls_sign.argtypes = [u8p, ctypes.c_char_p, ctypes.c_longlong, u8p]
    lib.bls_verify.restype = ctypes.c_int
    lib.bls_verify.argtypes = [u8p, ctypes.c_char_p, ctypes.c_longlong, u8p]
    lib.bls_verify_cached.restype = ctypes.c_int
    lib.bls_verify_cached.argtypes = [
        u8p, ctypes.c_char_p, ctypes.c_longlong, u8p]
    lib.bls_verify_batch.restype = ctypes.c_int
    lib.bls_verify_batch.argtypes = [
        ctypes.c_int, u8p, ctypes.POINTER(ctypes.c_char_p),
        ctypes.POINTER(ctypes.c_longlong), u8p, u8p]
    lib.bls_verify_batch_cached.restype = ctypes.c_int
    lib.bls_verify_batch_cached.argtypes = lib.bls_verify_batch.argtypes
    lib.bls_pk_cache_stats.restype = None
    lib.bls_pk_cache_stats.argtypes = [ctypes.POINTER(ctypes.c_uint64)]
    lib.bls_pk_cache_configure.restype = ctypes.c_int
    lib.bls_pk_cache_configure.argtypes = [ctypes.c_longlong]
    lib.bls_pk_cache_clear.restype = None
    lib.bls_pk_cache_clear.argtypes = []
    lib.bls_self_test.restype = ctypes.c_int
    lib.bls_self_test.argtypes = []
    # PUSHCDN_BLS_PK_CACHE sizes the per-public-key Miller line-table LRU
    # (entries; ~17 KB each; 0 disables and the cached entrypoints take
    # the plain path). Default stays the library's 128 (~2.2 MB bound).
    env_cap = os.environ.get("PUSHCDN_BLS_PK_CACHE", "").strip()
    if env_cap:
        try:
            lib.bls_pk_cache_configure(int(env_cap))
        except ValueError:
            pass
    return lib


def _get() -> Optional[ctypes.CDLL]:
    global _lib, _tried
    with _lock:
        if not _tried:
            _tried = True
            _lib = _compile()
        return _lib


def available() -> bool:
    return _get() is not None


def loaded() -> bool:
    """True when the library is ALREADY loaded — never triggers the
    compile. For callers on latency-sensitive paths (the /metrics
    pre-render hook) that must observe, not provoke, the g++ build."""
    return _lib is not None


def self_test() -> int:
    """0 = all pairing/scheme invariants hold (see bls_self_test)."""
    lib = _get()
    if lib is None:
        return -1
    return lib.bls_self_test()


def _buf(data: bytes):
    return (ctypes.c_uint8 * len(data)).from_buffer_copy(data)


def keygen(seed32: bytes) -> tuple[bytes, bytes]:
    """Deterministic (private_key, public_key) from a 32-byte seed."""
    lib = _get()
    assert lib is not None, "native BLS unavailable"
    assert len(seed32) == 32
    sk = (ctypes.c_uint8 * SK_LEN)()
    pk = (ctypes.c_uint8 * PK_LEN)()
    rc = lib.bls_keygen(_buf(seed32), sk, pk)
    if rc != 0:
        raise ValueError(f"bls_keygen failed: {rc}")
    return bytes(sk), bytes(pk)


def sign(sk: bytes, message: bytes) -> bytes:
    lib = _get()
    assert lib is not None, "native BLS unavailable"
    if len(sk) != SK_LEN:
        raise ValueError("bad secret key length")
    sig = (ctypes.c_uint8 * SIG_LEN)()
    rc = lib.bls_sign(_buf(sk), bytes(message), len(message), sig)
    if rc != 0:
        raise ValueError(f"bls_sign failed: {rc}")
    return bytes(sig)


def verify_batch(items, seed32: bytes, cached: bool = True) -> bool:
    """Batch-verify ``[(pk, message, signature), ...]`` with one shared
    final exponentiation via random linear combination (bls_verify_batch).
    ``seed32`` seeds the per-item 128-bit weights — callers pass fresh
    randomness (os.urandom) so an adversary cannot target the
    combination. Falls back to False on malformed input.

    ``cached`` (default) routes through ``bls_verify_batch_cached``: each
    item's pk-side Miller loop replays that key's line table from the
    bounded LRU, and every item shares ONE squaring chain with the
    generator side — same accept/reject semantics, ~2x at batch size 8
    with warm tables."""
    lib = _get()
    assert lib is not None, "native BLS unavailable"
    assert len(seed32) == 32
    n = len(items)
    if n == 0:
        return True
    pks = bytearray()
    sigs = bytearray()
    msgs = []
    for pk, message, signature in items:
        if len(pk) != PK_LEN or len(signature) != SIG_LEN:
            return False
        pks += pk
        sigs += signature
        msgs.append(bytes(message))
    msg_arr = (ctypes.c_char_p * n)(*msgs)
    len_arr = (ctypes.c_longlong * n)(*(len(m) for m in msgs))
    fn = lib.bls_verify_batch_cached if cached else lib.bls_verify_batch
    return fn(
        n, _buf(bytes(pks)), msg_arr, len_arr, _buf(bytes(sigs)),
        _buf(seed32)) == 1


def verify(pk: bytes, message: bytes, signature: bytes) -> bool:
    lib = _get()
    assert lib is not None, "native BLS unavailable"
    if len(pk) != PK_LEN or len(signature) != SIG_LEN:
        return False
    return lib.bls_verify(_buf(pk), bytes(message), len(message),
                          _buf(signature)) == 1


def verify_cached(pk: bytes, message: bytes, signature: bytes) -> bool:
    """``verify`` through the per-public-key Miller line-table cache: a
    repeat connector's second and later verifications skip the pk-side
    Jacobian ladder, the G2 subgroup check, and the pk parse (the LRU key
    is the exact 128-byte encoding, validated before insert). Identical
    accept/reject semantics to :func:`verify` for every input —
    asserted by the in-library self-test including across LRU
    eviction/repopulation."""
    lib = _get()
    assert lib is not None, "native BLS unavailable"
    if len(pk) != PK_LEN or len(signature) != SIG_LEN:
        return False
    return lib.bls_verify_cached(_buf(pk), bytes(message), len(message),
                                 _buf(signature)) == 1


def pk_cache_stats() -> Optional[dict]:
    """Line-table cache counters, or None when the library is
    unavailable: hits/misses/evictions since start (or last clear),
    current entries, capacity, and resident table bytes."""
    lib = _get()
    if lib is None:
        return None
    out = (ctypes.c_uint64 * 6)()
    lib.bls_pk_cache_stats(out)
    return {"hits": int(out[0]), "misses": int(out[1]),
            "evictions": int(out[2]), "entries": int(out[3]),
            "capacity": int(out[4]), "bytes": int(out[5])}


def pk_cache_configure(capacity: int) -> None:
    """Resize the line-table LRU (entries, ~17 KB each; 0 disables —
    cached entrypoints then take the plain uncached path). Shrinking
    evicts least-recently-used tables immediately."""
    lib = _get()
    assert lib is not None, "native BLS unavailable"
    if lib.bls_pk_cache_configure(int(capacity)) != 0:
        raise ValueError(f"bad pk cache capacity {capacity!r}")


def pk_cache_clear() -> None:
    """Drop every cached table and zero the counters (test isolation)."""
    lib = _get()
    if lib is not None:
        lib.bls_pk_cache_clear()
