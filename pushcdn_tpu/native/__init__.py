"""ctypes bindings for the C++ framing hot loops (native/framing.cpp).

The library is compiled on first use (g++ is in the image; pybind11 is
not, so the ABI is plain C via ctypes) and cached under ``.build/``.
Everything degrades gracefully: ``available()`` is False if compilation
fails and callers fall back to the numpy/Python paths.
"""

from __future__ import annotations

import ctypes
import os
import subprocess
import threading
from typing import Optional, Tuple

import numpy as np

_REPO = os.path.dirname(os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
_SRC = os.path.join(_REPO, "native", "framing.cpp")
_BUILD_DIR = os.path.join(_REPO, ".build")
_LIB_PATH = os.path.join(_BUILD_DIR, "libpushcdn_framing.so")

_lock = threading.Lock()
_lib: Optional[ctypes.CDLL] = None
_tried = False


def _build_lib(src: str, lib_path: str, loader, extra_flags: tuple = ()):
    """Compile ``src`` to ``lib_path`` when stale and load it via
    ``loader`` (CDLL or PyDLL). Returns None on ANY failure — a missing
    source next to a cached .so, a compiler error, a load error — so
    callers always degrade to their Python fallback."""
    try:
        os.makedirs(_BUILD_DIR, exist_ok=True)
        if not os.path.exists(lib_path) or \
                os.path.getmtime(src) > os.path.getmtime(lib_path):
            cmd = ["g++", "-O3", "-shared", "-fPIC", *extra_flags,
                   src, "-o", lib_path]
            subprocess.run(cmd, check=True, capture_output=True, timeout=120)
        return loader(lib_path)
    except (subprocess.SubprocessError, OSError):
        return None


def _compile() -> Optional[ctypes.CDLL]:
    lib = _build_lib(_SRC, _LIB_PATH, ctypes.CDLL)
    if lib is None:
        return None

    u8p = ctypes.POINTER(ctypes.c_uint8)
    i32p = ctypes.POINTER(ctypes.c_int32)
    i64p = ctypes.POINTER(ctypes.c_int64)
    u32p = ctypes.POINTER(ctypes.c_uint32)

    lib.pushcdn_pack_frames.restype = ctypes.c_int32
    lib.pushcdn_pack_frames.argtypes = [
        u8p, i64p, i32p, i32p, u32p, i32p,
        ctypes.c_int32, ctypes.c_int32, ctypes.c_int32, ctypes.c_int32,
        u8p, i32p, i32p, u32p, i32p, u8p]
    lib.pushcdn_scan_frames.restype = ctypes.c_int64
    lib.pushcdn_scan_frames.argtypes = [
        u8p, ctypes.c_int64, ctypes.c_uint32,
        i64p, i32p, ctypes.c_int32, i32p, i32p]
    lib.pushcdn_encode_frames.restype = ctypes.c_int64
    lib.pushcdn_encode_frames.argtypes = [
        u8p, i64p, i32p, ctypes.c_int32, u8p, ctypes.c_int64]
    lib.pushcdn_encode_frames_ptrs.restype = ctypes.c_int64
    lib.pushcdn_encode_frames_ptrs.argtypes = [
        ctypes.POINTER(ctypes.c_char_p), i32p,
        ctypes.c_int32, u8p, ctypes.c_int64]
    lib.pushcdn_egress_count.restype = None
    lib.pushcdn_egress_count.argtypes = [
        u8p, ctypes.c_int32, ctypes.c_int32, i32p, i64p, i32p]
    lib.pushcdn_egress_fill.restype = ctypes.c_int64
    lib.pushcdn_egress_fill.argtypes = [
        u8p, ctypes.c_int32, ctypes.c_int32, i32p,
        ctypes.POINTER(ctypes.c_void_p), ctypes.c_int32, ctypes.c_int32,
        ctypes.c_int64, i64p, u8p, ctypes.c_int64]
    lib.pushcdn_egress_encode_fused.restype = ctypes.c_int64
    lib.pushcdn_egress_encode_fused.argtypes = [
        u8p, ctypes.c_int32, ctypes.c_int32, i32p,
        ctypes.POINTER(ctypes.c_void_p), ctypes.c_int32, ctypes.c_int32,
        ctypes.c_int64, i64p, i64p, i32p, u8p, ctypes.c_int64]
    return lib


def _get() -> Optional[ctypes.CDLL]:
    global _lib, _tried
    if _lib is None and not _tried:
        with _lock:
            if _lib is None and not _tried:
                _lib = _compile()
                _tried = True
    return _lib


def available() -> bool:
    return _get() is not None


# -- CPython-API batch decoder (native/pydecode.cpp) -------------------------
#
# A SEPARATE library from the framing CDLL: it is loaded via PyDLL so calls
# keep the GIL (the decoder builds Python objects), whereas the framing
# lib's plain-C calls release it.

_PYDECODE_SRC = os.path.join(_REPO, "native", "pydecode.cpp")
_pydecode_fn = None
_pydecode_tried = False


def _pydecode_lib_path() -> str:
    """The cached .so name is keyed on the interpreter ABI: unlike the
    plain-C framing lib, pydecode is a CPython-API library (tp_alloc, slot
    layouts), and loading a cache built against another interpreter's
    headers is undefined behavior — a Python minor upgrade must recompile,
    not reuse."""
    import sysconfig
    abi = sysconfig.get_config_var("SOABI") or "unknown-abi"
    return os.path.join(_BUILD_DIR, f"libpushcdn_pydecode-{abi}.so")


def _compile_pydecode():
    import sysconfig
    lib = _build_lib(_PYDECODE_SRC, _pydecode_lib_path(), ctypes.PyDLL,
                     ("-I", sysconfig.get_paths()["include"]))
    if lib is None:
        return None
    fn = lib.pushcdn_decode_frames_py
    fn.restype = ctypes.py_object
    fn.argtypes = [ctypes.py_object, ctypes.py_object, ctypes.py_object,
                   ctypes.c_ssize_t, ctypes.py_object, ctypes.py_object,
                   ctypes.py_object, ctypes.c_ssize_t]
    return fn


def pydecode():
    """The batch frame→Message decoder, or None when unavailable.

    Signature: ``fn(buf, offs, lens, start, Broadcast, Direct, fallback,
    zero_copy_min)`` → list of messages, or None when the inputs don't
    fit the C fast path (caller must then run the Python decoder). With
    ``zero_copy_min > 0``, hot payloads of at least that many bytes are
    memoryview slices over ``buf`` instead of owned copies
    (message.ZERO_COPY_MIN is the callers' threshold). Raises whatever
    ``fallback`` raises on malformed frames.
    """
    global _pydecode_fn, _pydecode_tried
    if _pydecode_fn is None and not _pydecode_tried:
        with _lock:
            if _pydecode_fn is None and not _pydecode_tried:
                _pydecode_fn = _compile_pydecode()
                _pydecode_tried = True
    return _pydecode_fn


def _ptr(a: np.ndarray, ctype):
    return a.ctypes.data_as(ctypes.POINTER(ctype))


def pack_frames_into(payloads: list[bytes], kinds: np.ndarray,
                     tmasks: np.ndarray, dests: np.ndarray,
                     out_frames: np.ndarray, out_kind: np.ndarray,
                     out_len: np.ndarray, out_tmask: np.ndarray,
                     out_dest: np.ndarray, out_valid: np.ndarray
                     ) -> Optional[int]:
    """Batch-pack payloads directly into caller-owned frame arrays via the
    C++ kernel (zero extra allocation on the pump path). Returns the number
    packed, or None if the native library is unavailable.

    ``tmasks``/``out_tmask`` may be 1-D (compact ≤32-topic masks) or 2-D
    ``[n, W]`` / ``[capacity, W]`` multi-word rows covering the full u8
    topic space — the two must agree on W.

    Preconditions (validated): metadata arrays as long as ``payloads``; no
    payload longer than a frame slot; out arrays contiguous with matching
    dtypes. ``out_valid`` must be uint8 (written 0/1). The out arrays may
    be sliced views starting at a ring's cursor (C-contiguous slices along
    axis 0), so a partially-filled ring can batch-pack into its tail.
    """
    lib = _get()
    if lib is None:
        return None
    n_in = len(payloads)
    if not (len(kinds) == len(tmasks) == len(dests) == n_in):
        raise ValueError("payloads/kinds/tmasks/dests length mismatch")
    words = 1 if out_tmask.ndim == 1 else out_tmask.shape[1]
    in_words = 1 if np.ndim(tmasks) == 1 else np.shape(tmasks)[1]
    if words != in_words:
        raise ValueError(
            f"tmasks width {in_words} != out_tmask width {words}")
    capacity, frame_bytes = out_frames.shape
    # lengths/offsets at C speed: map(len) + cumsum beat a Python loop by
    # ~400 ns/frame on the pump path
    lengths = np.fromiter(map(len, payloads), np.int32, count=n_in)
    if n_in and int(lengths.max(initial=0)) > frame_bytes:
        i = int(np.argmax(lengths > frame_bytes))
        raise ValueError(
            f"payload {i} is {lengths[i]} B > frame slot {frame_bytes} B; "
            "pre-filter oversized payloads to the host path")
    offsets = np.empty(n_in, np.int64)
    if n_in:
        offsets[0] = 0
        np.cumsum(lengths[:-1], dtype=np.int64, out=offsets[1:])
    blob = b"".join(payloads)
    blob_arr = np.frombuffer(blob, np.uint8) if blob else np.zeros(1, np.uint8)

    n = lib.pushcdn_pack_frames(
        _ptr(blob_arr, ctypes.c_uint8), _ptr(offsets, ctypes.c_int64),
        _ptr(lengths, ctypes.c_int32),
        _ptr(np.ascontiguousarray(kinds, np.int32), ctypes.c_int32),
        _ptr(np.ascontiguousarray(tmasks, np.uint32), ctypes.c_uint32),
        _ptr(np.ascontiguousarray(dests, np.int32), ctypes.c_int32),
        n_in, capacity, frame_bytes, words,
        _ptr(out_frames, ctypes.c_uint8), _ptr(out_kind, ctypes.c_int32),
        _ptr(out_len, ctypes.c_int32), _ptr(out_tmask, ctypes.c_uint32),
        _ptr(out_dest, ctypes.c_int32), _ptr(out_valid, ctypes.c_uint8))
    return int(n)


def scan_frames(buf: bytes, max_frame_len: int, max_frames: int = 4096
                ) -> Optional[Tuple[list[Tuple[int, int]], int, bool]]:
    """Find complete length-delimited frames in ``buf`` via the C++
    scanner. Returns ([(offset, length)...], consumed_bytes, error) or
    None if unavailable."""
    lib = _get()
    if lib is None:
        return None
    arr = np.frombuffer(buf, np.uint8) if buf else np.zeros(1, np.uint8)
    out_off = np.zeros(max_frames, np.int64)
    out_len = np.zeros(max_frames, np.int32)
    nframes = ctypes.c_int32(0)
    error = ctypes.c_int32(0)
    consumed = lib.pushcdn_scan_frames(
        _ptr(arr, ctypes.c_uint8), len(buf), max_frame_len,
        _ptr(out_off, ctypes.c_int64), _ptr(out_len, ctypes.c_int32),
        max_frames, ctypes.byref(nframes), ctypes.byref(error))
    frames = [(int(out_off[i]), int(out_len[i])) for i in range(nframes.value)]
    return frames, int(consumed), bool(error.value)


class FrameScanner:
    """Reusable scan state for one connection's reader loop: the (offset,
    length) output columns are allocated once and reused every chunk, and
    results come back as plain-int lists via one ``tolist()`` call — the
    per-frame Python cost of the wire scan is two list indexes.

    ``None``-safe construction: ``FrameScanner.create()`` returns None when
    the native library is unavailable (callers fall back to the Python
    struct scan).
    """

    __slots__ = ("_lib", "_off", "_len", "max_frames")

    def __init__(self, lib, max_frames: int):
        self._lib = lib
        self.max_frames = max_frames
        self._off = np.zeros(max_frames, np.int64)
        self._len = np.zeros(max_frames, np.int32)

    @classmethod
    def create(cls, max_frames: int = 8192) -> Optional["FrameScanner"]:
        lib = _get()
        return None if lib is None else cls(lib, max_frames)

    def scan(self, buf, max_frame_len: int):
        """Scan a ``bytearray``/``bytes`` carry buffer for complete frames.
        Returns (offsets, lengths, consumed, error) with offsets/lengths as
        plain-int lists pointing at payload starts."""
        blen = len(buf)
        if blen < 4:
            return (), (), 0, False
        arr = np.frombuffer(buf, np.uint8)  # zero-copy view
        nframes = ctypes.c_int32(0)
        error = ctypes.c_int32(0)
        consumed = self._lib.pushcdn_scan_frames(
            _ptr(arr, ctypes.c_uint8), blen, max_frame_len,
            _ptr(self._off, ctypes.c_int64), _ptr(self._len, ctypes.c_int32),
            self.max_frames, ctypes.byref(nframes), ctypes.byref(error))
        n = nframes.value
        return (self._off[:n].tolist(), self._len[:n].tolist(),
                int(consumed), bool(error.value))


class FrameEncoder:
    """Reusable writer-side batch encoder: length-delimits many payloads
    into one reusable output buffer with a single C call and a single copy
    (payload pointers are passed directly — no intermediate join)."""

    __slots__ = ("_lib", "_out", "_lens")

    def __init__(self, lib, capacity: int):
        self._lib = lib
        self._out = bytearray(capacity)
        self._lens = np.zeros(1024, np.int32)

    @classmethod
    def create(cls, capacity: int = 256 * 1024) -> Optional["FrameEncoder"]:
        lib = _get()
        return None if lib is None else cls(lib, capacity)

    def encode(self, payloads: list) -> Optional[memoryview]:
        """Encode ``payloads`` (bytes objects) as one length-delimited
        stream; returns a memoryview over the internal buffer (valid until
        the next call) or None when the batch doesn't fit."""
        n = len(payloads)
        if n > len(self._lens):
            self._lens = np.zeros(max(n, 2 * len(self._lens)), np.int32)
        lens = self._lens
        lens[:n] = np.fromiter(map(len, payloads), np.int32, count=n)
        total = int(lens[:n].sum()) + 4 * n
        if total > len(self._out):
            return None
        ptrs = (ctypes.c_char_p * n)(*payloads)
        out_ptr = (ctypes.c_uint8 * len(self._out)).from_buffer(self._out)
        wrote = self._lib.pushcdn_encode_frames_ptrs(
            ctypes.cast(ptrs, ctypes.POINTER(ctypes.c_char_p)),
            _ptr(lens, ctypes.c_int32), n,
            ctypes.cast(out_ptr, ctypes.POINTER(ctypes.c_uint8)), len(self._out))
        if wrote < 0:
            return None
        return memoryview(self._out)[:wrote]

    def encode_detached(self, payloads: list) -> Optional[bytearray]:
        """Encode ``payloads`` (bytes objects) into a FRESH exact-size
        bytearray the caller owns outright — the routing loops' pre-encode
        handoff: the batch becomes one ``PreEncoded`` writer entry, still
        one C call and one copy (the same count as the writer-side
        encoder), but flattening/probing moves off the writer task and
        the frames' pool permits release at encode time instead of after
        the wire flush. None when any payload is not ``bytes``."""
        n = len(payloads)
        if n == 0:
            return None
        if n > len(self._lens):
            self._lens = np.zeros(max(n, 2 * len(self._lens)), np.int32)
        lens = self._lens
        try:
            lens[:n] = np.fromiter(map(len, payloads), np.int32, count=n)
            ptrs = (ctypes.c_char_p * n)(*payloads)
        except TypeError:  # a non-bytes payload (memoryview/Bytes slipped in)
            return None
        total = int(lens[:n].sum()) + 4 * n
        out = bytearray(total)
        out_ptr = (ctypes.c_uint8 * total).from_buffer(out)
        wrote = self._lib.pushcdn_encode_frames_ptrs(
            ctypes.cast(ptrs, ctypes.POINTER(ctypes.c_char_p)),
            _ptr(lens, ctypes.c_int32), n,
            ctypes.cast(out_ptr, ctypes.POINTER(ctypes.c_uint8)), total)
        del out_ptr  # release the from_buffer export before handing out
        if wrote != total:
            return None
        return out


_shared_encoder: Optional[FrameEncoder] = None
_shared_encoder_tried = False


def shared_encoder() -> Optional[FrameEncoder]:
    """Process-wide :class:`FrameEncoder` for single-event-loop callers
    that only use :meth:`FrameEncoder.encode_detached` (no persistent
    output buffer is shared, so one instance serves every connection)."""
    global _shared_encoder, _shared_encoder_tried
    if _shared_encoder is None and not _shared_encoder_tried:
        _shared_encoder_tried = True
        _shared_encoder = FrameEncoder.create(capacity=1)
    return _shared_encoder


class _EgressLease:
    """Owns one pooled egress buffer; when the LAST reference to the lease
    drops (the :class:`EgressStreams` and every writer entry holding it),
    the buffer returns to the free pool instead of the allocator. This is
    what turns the per-step egress allocation — whose page-fault cost was
    ~2/3 of the engine's steady-state runtime — into a recycled buffer.

    Callers that hand stream views to asynchronous consumers (connection
    writers) must keep the lease alive alongside the view (the ``owner``
    seat on ``PreEncoded`` / ``send_encoded_nowait``); a view without its
    lease risks the pool recycling the buffer under a pending write."""

    __slots__ = ("_buf",)

    def __init__(self, buf: bytearray):
        self._buf = buf

    def __del__(self):
        buf = self._buf
        # drop buffers far above the (decaying) recent need instead of
        # pooling them: one anomalous spike step must not pin
        # spike-sized allocations for process lifetime
        if buf is not None and len(_EGRESS_POOL) < _EGRESS_POOL_MAX \
                and len(buf) <= 8 * _EGRESS_NEED_HW:
            _EGRESS_POOL.append(buf)


_EGRESS_POOL: list = []   # free bytearrays (bounded; newest last)
_EGRESS_POOL_MAX = 3
_EGRESS_NEED_HW = 1 << 20  # decaying high-water mark of real step sizes
# io_uring fixed-buffer hook: callbacks invoked once per pooled egress
# buffer (existing and future) so the engine can page-pin each buffer a
# single time at allocation instead of per send. Pool buffers are never
# resized in place (a too-small buffer rotates away and a fresh one is
# allocated), so a persistent registration stays valid for the buffer's
# whole life.
_EGRESS_REGISTRARS: list = []


def add_egress_registrar(fn) -> None:
    """Subscribe ``fn(buf)`` to every pooled egress buffer, replaying the
    current free pool immediately. ``fn`` must never raise."""
    _EGRESS_REGISTRARS.append(fn)
    for buf in list(_EGRESS_POOL):
        fn(buf)


def egress_pool_buffers() -> list:
    """Snapshot of the free egress pool (for fixed-buffer registration)."""
    return list(_EGRESS_POOL)


def _egress_note_need(nbytes: int) -> None:
    """Record a step's actual egress size (geometric decay: the
    high-water mark forgets a spike within ~tens of steps)."""
    global _EGRESS_NEED_HW
    _EGRESS_NEED_HW = max(nbytes, int(_EGRESS_NEED_HW * 0.9), 1 << 20)


def _egress_take(nbytes: int):
    """Take a pooled buffer of at least ``nbytes``, or allocate fresh.
    Returns (bytearray, lease). Lock-free on purpose: encode runs both on
    the event loop and in mesh-group worker threads, and the lease's
    ``__del__`` (which appends back) can fire inside any allocation's GC —
    so only GIL-atomic list ops are used, with a defensive retry."""
    pool = _EGRESS_POOL
    try:
        for _ in range(len(pool)):
            buf = pool.pop()
            if len(buf) >= nbytes:
                return buf, _EgressLease(buf)
            pool.insert(0, buf)  # too small for this step: rotate away
    except IndexError:  # raced another taker
        pass
    buf = bytearray(max(nbytes, 1 << 20))
    for fn in _EGRESS_REGISTRARS:
        fn(buf)
    return buf, _EgressLease(buf)


class EgressStreams:
    """One step's egress, encoded: per-user length-delimited streams laid
    out back-to-back in one buffer. ``users`` lists the slots with at least
    one delivery; ``stream(i)`` is the i-th listed user's bytes — already
    wire-framed, handed to the connection writer as-is (pass this object
    as the writer's ``owner`` so the pooled buffer outlives the flush)."""

    __slots__ = ("buf", "users", "offsets", "nbytes", "msgs", "total_msgs",
                 "lease")

    def __init__(self, buf, users, offsets, nbytes, msgs, lease=None):
        self.buf = buf
        self.users = users      # int list — user slots with deliveries
        self.offsets = offsets  # int64[U] stream starts (all slots)
        self.nbytes = nbytes    # int64[U] stream sizes (all slots)
        self.msgs = msgs        # int32[U] delivered count (all slots)
        self.total_msgs = int(msgs.sum())
        self.lease = lease      # pooled-buffer lease (None = plain alloc)

    def stream(self, slot: int) -> memoryview:
        off = int(self.offsets[slot])
        return memoryview(self.buf)[off:off + int(self.nbytes[slot])]


def egress_encode(deliver: np.ndarray, lengths: np.ndarray,
                  blocks: list) -> Optional[EgressStreams]:
    """Encode a delivery matrix into per-user wire streams via the C++
    engine (two passes: count → prefix-sum → fill). ``deliver`` is
    bool[U, N] (numpy bool_, row-major); ``lengths`` int32[N]; ``blocks``
    the per-shard frame tensors in gather order (each C-contiguous
    uint8[rows, frame_bytes], equal shapes) — frame n is row
    ``n % rows`` of block ``n // rows``. Returns None when the native
    library is unavailable (callers fall back to the per-frame path)."""
    lib = _get()
    if lib is None:
        return None
    U, N = deliver.shape
    rows = blocks[0].shape[0]
    stride = blocks[0].strides[0]  # row pitch (rows themselves contiguous)
    if rows * len(blocks) != N:
        raise ValueError(f"blocks cover {rows * len(blocks)} frames, "
                         f"deliver has {N}")
    for b in blocks:
        if b.shape[0] != rows or b.strides[0] != stride or b.strides[1] != 1:
            raise ValueError("egress blocks must share shape/stride with "
                             "byte-contiguous rows")
    if deliver.dtype == np.bool_ and deliver.flags.c_contiguous:
        deliver = deliver.view(np.uint8)  # free: bool_ is 1 byte/cell
    else:
        deliver = np.ascontiguousarray(deliver, np.uint8)
    lengths = np.ascontiguousarray(lengths, np.int32)
    per_bytes = np.zeros(U, np.int64)
    per_msgs = np.zeros(U, np.int32)
    offsets = np.zeros(U, np.int64)
    block_ptrs = (ctypes.c_void_p * len(blocks))(
        *(b.ctypes.data for b in blocks))

    # Fused single pass into a pooled buffer: count + prefix + fill in one
    # matrix walk, zero allocation in the steady state (the lease returns
    # the buffer once the streams and every pending writer entry drop).
    # A too-small buffer (first step, or a new high-water mark) sizes
    # exactly via the count pass and retries once.
    buf, lease = _egress_take(1)
    buf_np = np.frombuffer(buf, np.uint8)
    wrote = lib.pushcdn_egress_encode_fused(
        _ptr(deliver, ctypes.c_uint8), U, N, _ptr(lengths, ctypes.c_int32),
        block_ptrs, len(blocks), rows, stride,
        _ptr(offsets, ctypes.c_int64), _ptr(per_bytes, ctypes.c_int64),
        _ptr(per_msgs, ctypes.c_int32), _ptr(buf_np, ctypes.c_uint8),
        len(buf))
    if wrote < 0:
        lib.pushcdn_egress_count(
            _ptr(deliver, ctypes.c_uint8), U, N,
            _ptr(lengths, ctypes.c_int32),
            _ptr(per_bytes, ctypes.c_int64), _ptr(per_msgs, ctypes.c_int32))
        total = int(per_bytes.sum())
        del buf_np
        buf, lease = _egress_take(total)
        buf_np = np.frombuffer(buf, np.uint8)
        wrote = lib.pushcdn_egress_encode_fused(
            _ptr(deliver, ctypes.c_uint8), U, N,
            _ptr(lengths, ctypes.c_int32),
            block_ptrs, len(blocks), rows, stride,
            _ptr(offsets, ctypes.c_int64), _ptr(per_bytes, ctypes.c_int64),
            _ptr(per_msgs, ctypes.c_int32), _ptr(buf_np, ctypes.c_uint8),
            len(buf))
        if wrote != total:  # can't happen on one snapshot; stay safe
            return None
    _egress_note_need(int(wrote))
    users = np.nonzero(per_msgs)[0].tolist()
    return EgressStreams(buf, users, offsets, per_bytes, per_msgs,
                         lease=lease)


def encode_frames(payloads: list[bytes]) -> Optional[bytes]:
    """Batch-encode payloads as one length-delimited stream (writer-side
    batching: one buffer → one syscall). None if unavailable."""
    lib = _get()
    if lib is None:
        return None
    blob = b"".join(payloads)
    offsets = np.zeros(len(payloads), np.int64)
    lengths = np.zeros(len(payloads), np.int32)
    off = 0
    for i, p in enumerate(payloads):
        offsets[i] = off
        lengths[i] = len(p)
        off += len(p)
    blob_arr = np.frombuffer(blob, np.uint8) if blob else np.zeros(1, np.uint8)
    cap = len(blob) + 4 * len(payloads)
    out = np.zeros(cap, np.uint8)
    n = lib.pushcdn_encode_frames(
        _ptr(blob_arr, ctypes.c_uint8), _ptr(offsets, ctypes.c_int64),
        _ptr(lengths, ctypes.c_int32), len(payloads),
        _ptr(out, ctypes.c_uint8), cap)
    if n < 0:
        return None
    return out[:n].tobytes()
