"""DevicePlane — the bridge that puts the TPU router in the broker's hot
path.

The host broker (tasks/handlers.py) routes per-message with dict lookups;
with a ``DevicePlane`` attached, eligible messages (wire frames that fit a
frame slot) are instead **staged into the frame ring, routed in batched
jitted steps on the attached device, and delivered from the resulting
delivery matrix** (SURVEY.md §7 stage 7 → stage 8 "edge": the socket⇄HBM
pump). The wire frame travels verbatim through HBM, so receivers are
byte-identical with the host path. Oversized messages and control traffic
keep the host path.

Scope (round 1): one broker = one device shard (``routing_step_lanes_single``).
The host CRDT stays authoritative for cross-broker ownership; the device
plane handles the local fan-out — which is where the per-message Python
cost lives. Multi-shard meshes route via parallel.router's shard_map step.

Consistency design (single-writer, snapshot-per-step):

- The **host mirrors** (``_owned`` bool[U], ``_masks`` u32[U, 8]) are the
  source of truth, mutated only on the event loop by the Connections
  observer hooks. Each step SNAPSHOTS them together with ``take_batch()``
  (same event-loop tick), and the device ``RouterState`` is rebuilt from
  that snapshot — a registration or subscription racing the in-flight step
  simply lands in the next snapshot, never lost.
- **Slot quarantine**: a released user slot is not reusable until the step
  that might still carry frames addressed to it has completed — prevents a
  recycled slot from leaking one user's messages to another.
- **Failure = host fallback**: if a step raises, its staged frames are
  re-routed on the host path (users-only, matching what the device would
  have delivered) and the plane disables itself; staging then always
  returns False and the broker is a plain host broker again.

Flow per step:
  ingress: user_receive_loop → try_stage() → FrameRing (slot credits)
  compute: snapshot + take_batch → routing_step_lanes_single (jitted)
  egress:  deliver[u, f] → per-user non-blocking send of the frame bytes
"""

from __future__ import annotations

import asyncio
import logging
from dataclasses import dataclass
from typing import TYPE_CHECKING, List, Optional, Tuple

import numpy as np

from pushcdn_tpu.broker.staging import StageResult
from pushcdn_tpu.broker.tasks.senders import egress_delivery_rows
from pushcdn_tpu.parallel.crdt import ABSENT, CrdtState
from pushcdn_tpu.parallel.frames import (
    TOPIC_WORDS_FULL,
    FrameRing,
    UserSlots,
    mask_mirror_shape,
    mask_of_topics,
    mask_row_of,
    stage_best_fit,
)
from pushcdn_tpu.parallel.router import (
    IngressBatch,
    RouterState,
    routing_step_lanes_single,
)
from pushcdn_tpu.proto.error import Error
from pushcdn_tpu.proto.limiter import Bytes
from pushcdn_tpu.proto.message import (
    KIND_BROADCAST,
    KIND_DIRECT,
    Broadcast,
    Direct,
)

if TYPE_CHECKING:
    from pushcdn_tpu.broker.broker import Broker

logger = logging.getLogger("pushcdn.broker.device")


@dataclass
class DevicePlaneConfig:
    num_user_slots: int = 1024
    ring_slots: int = 1024
    frame_bytes: int = 2048
    # Size-bucketed lanes beyond the base (ring_slots × frame_bytes) ring
    # (SURVEY.md §7 hard-part #1): each entry is (frame_bytes, ring_slots).
    # A frame is staged into the smallest lane it fits, so 100 B acks don't
    # ride 32 KB-padded slots and 16 KB proposals still stay on device.
    extra_lanes: tuple = ((16384, 64),)
    # u32 words per topic mask: 8 covers the reference's whole u8 topic
    # space; 1 keeps compact masks (and the native batch packer) for
    # deployments with ≤32 topics
    topic_words: int = TOPIC_WORDS_FULL
    # batch window: how long the pump waits to coalesce ingress into one
    # step (the latency ↔ step-efficiency knob)
    batch_window_s: float = 0.001

    def lane_shapes(self):
        """All lanes as (frame_bytes, ring_slots), sorted ascending by
        frame width (best-fit staging walks this order)."""
        return sorted(((self.frame_bytes, self.ring_slots),)
                      + tuple(self.extra_lanes))


class DevicePlane:
    # single-shard plane: inter-broker fan-out stays on the host links
    # (the mesh-group plane overrides this — peers ride ICI)
    covers_brokers = False

    def __init__(self, broker: "Broker", config: DevicePlaneConfig = None):
        self.broker = broker
        self.config = config or DevicePlaneConfig()
        c = self.config
        self.slots = UserSlots(c.num_user_slots)
        self.rings = [FrameRing(slots=s, frame_bytes=f,
                                topic_words=c.topic_words)
                      for f, s in c.lane_shapes()]
        # host mirrors — the single source of truth for device state;
        # mask shape tracks the configured topic-space width
        self._owned = np.zeros(c.num_user_slots, bool)
        self._masks = np.zeros(
            mask_mirror_shape(c.num_user_slots, c.topic_words), np.uint32)
        self._quarantine: List[int] = []   # slots awaiting step completion
        # users the slot table couldn't hold: broadcasts must stay on the
        # host path while any exist (they'd miss device-only fan-out)
        self._unmirrored: set[bytes] = set()
        self.disabled = False
        # single-shard planes keep inter-broker traffic on host links, so
        # they never *need* overflow dialing — the attribute exists because
        # heartbeat fail-open logic reads it off any plane uniformly
        self.overflow_seen = False
        self._kick = asyncio.Event()
        self._task: Optional[asyncio.Task] = None
        self.steps = 0
        self.messages_routed = 0

    # ---- user lifecycle (Connections observer; event-loop only) ----------

    def on_user_added(self, public_key: bytes, topics) -> None:
        try:
            slot = self.slots.assign(public_key)
        except Error:
            # table full: this user is host-routed only; never fail the
            # registration over the mirror
            self._unmirrored.add(public_key)
            logger.warning("device user-slot table full; %d unmirrored users",
                           len(self._unmirrored))
            return
        self._owned[slot] = True
        self._masks[slot] = mask_row_of(topics, self.config.topic_words)

    def on_user_removed(self, public_key: bytes) -> None:
        self._unmirrored.discard(public_key)
        slot = self.slots.unmap(public_key)
        if slot is None:
            return
        self._owned[slot] = False
        self._masks[slot] = 0
        # the slot index stays quarantined until the next step completes —
        # in-flight frames may still address it
        self._quarantine.append(slot)

    def on_subscription_changed(self, public_key: bytes, topics) -> None:
        slot = self.slots.slot_of(public_key)
        if slot is None:
            return
        self._masks[slot] = mask_row_of(topics, self.config.topic_words)

    # ---- ingress ----------------------------------------------------------

    def try_stage(self, message, raw: Bytes) -> StageResult:
        """Stage a decoded message's WIRE FRAME for device routing.
        INELIGIBLE ⇒ host path (too big, unknown recipient, unmirrored
        users present); FULL ⇒ slot-credit backpressure, caller retries."""
        if self.disabled:
            return StageResult.INELIGIBLE
        frame = bytes(raw.data)
        if len(frame) > self.rings[-1].frame_bytes:
            return StageResult.INELIGIBLE
        if isinstance(message, Broadcast):
            if self._unmirrored:
                return StageResult.INELIGIBLE  # would miss unmirrored users
            if any(int(t) >= 32 * self.config.topic_words
                   for t in message.topics):
                return StageResult.INELIGIBLE  # beyond the configured space
            mask = mask_of_topics(message.topics, self.config.topic_words)
            if mask == 0:
                return StageResult.INELIGIBLE
            ok = stage_best_fit(self.rings, len(frame),
                                lambda r: r.push_broadcast(frame, mask))
        elif isinstance(message, Direct):
            slot = self.slots.slot_of(bytes(message.recipient))
            if slot is None:
                return StageResult.INELIGIBLE  # not mirrored (cross-broker)
            ok = stage_best_fit(self.rings, len(frame),
                                lambda r: r.push_direct(frame, slot))
        else:
            return StageResult.INELIGIBLE
        if ok:
            self._kick.set()
            return StageResult.STAGED
        return StageResult.FULL

    def stage_batch(self, items) -> List[StageResult]:
        """Stage a whole receive batch in one pass: classify each
        (message, raw) pair, group the eligible frames per size lane
        (best-fit with free-slot accounting), then pack each lane's group
        with ONE ``FrameRing.push_batch`` (one C call + one copy per
        lane) instead of a per-frame Python ``_put``. Returns a
        per-item ``StageResult`` aligned with ``items``; FULL items are
        the ring-backpressure leftovers the caller retries singly."""
        results = [StageResult.INELIGIBLE] * len(items)
        if self.disabled:
            return results
        # (ring -> [(item_idx, frame, kind, mask, dest), ...])
        groups: dict[int, list] = {}
        free = [r.free_slots for r in self.rings]
        widest = self.rings[-1].frame_bytes
        for idx, (message, raw) in enumerate(items):
            frame = bytes(raw.data)
            if len(frame) > widest:
                continue  # INELIGIBLE
            if isinstance(message, Broadcast):
                if self._unmirrored:
                    continue
                if any(int(t) >= 32 * self.config.topic_words
                       for t in message.topics):
                    continue
                mask = mask_of_topics(message.topics,
                                      self.config.topic_words)
                if mask == 0:
                    continue
                kind, dest = KIND_BROADCAST, -1
            elif isinstance(message, Direct):
                slot = self.slots.slot_of(bytes(message.recipient))
                if slot is None:
                    continue
                kind, mask, dest = KIND_DIRECT, 0, slot
            else:
                continue
            # best-fit with credit accounting (mirrors stage_best_fit)
            placed = False
            for li, ring in enumerate(self.rings):
                if len(frame) <= ring.frame_bytes and free[li] > 0:
                    free[li] -= 1
                    groups.setdefault(li, []).append(
                        (idx, frame, kind, mask, dest))
                    placed = True
                    break
            results[idx] = StageResult.STAGED if placed else StageResult.FULL
        staged_any = False
        for li, group in groups.items():
            n = self.rings[li].push_batch(
                [g[1] for g in group], [g[2] for g in group],
                [g[3] for g in group], [g[4] for g in group])
            staged_any = staged_any or n > 0
            for idx, *_ in group[n:]:  # raced-full leftovers
                results[idx] = StageResult.FULL
        if staged_any:
            self._kick.set()
        return results

    def covered_broker_idents(self) -> set:
        """Broker identifiers whose delivery this plane covers — none for
        the single-shard plane (host links handle all peers)."""
        return set()

    # ---- the pump ---------------------------------------------------------

    async def start(self) -> None:
        # compile the step off the hot path (first jit can take seconds)
        await asyncio.to_thread(self._warmup)
        self._task = asyncio.create_task(self._pump(), name="device-pump")

    def _warmup(self) -> None:
        empty = [r.take_batch() for r in self.rings]
        try:
            # compile the two common lane subsets off the hot path: all
            # lanes busy, and base-lane-only (steady state for small
            # messages); other subsets jit-compile on first use
            self._run_step(empty, self._owned.copy(), self._masks.copy(),
                           keep_idle_lanes=True)
            self._run_step(empty[:1], self._owned.copy(), self._masks.copy(),
                           keep_idle_lanes=True)
            self.steps -= 2  # warmup doesn't count
        except Exception:
            logger.exception("device-plane warmup step failed")
            self.disabled = True

    async def stop(self) -> None:
        if self._task is not None:
            self._task.cancel()
            try:
                await self._task
            except asyncio.CancelledError:
                pass
            except Exception:
                logger.exception("device pump died during stop")

    async def _pump(self) -> None:
        while True:
            await self._kick.wait()
            self._kick.clear()
            await asyncio.sleep(self.config.batch_window_s)  # coalesce
            if all(r.free_slots == r.slots for r in self.rings):
                continue
            # snapshot mirrors + all lane rings in ONE event-loop tick
            batches_np = [r.take_batch() for r in self.rings]
            owned = self._owned.copy()
            masks = self._masks.copy()
            quarantined, self._quarantine = self._quarantine, []
            try:
                lane_results = await asyncio.to_thread(
                    self._run_step, batches_np, owned, masks)
                for deliver, lengths, frames in lane_results:
                    self._egress(deliver, lengths, frames)
            except asyncio.CancelledError:
                raise
            except Exception:
                logger.exception(
                    "device routing step failed; re-routing the batch on "
                    "the host path and disabling the device plane")
                self.disabled = True
                # frames staged (and acked STAGED) while the failing step
                # ran in the worker thread sit in the fresh rings — drain
                # them too, or they'd be lost with no fallback
                late = [r.take_batch() for r in self.rings]
                await self._host_fallback(batches_np)
                await self._host_fallback(late)
                return
            finally:
                for slot in quarantined:  # safe to recycle now
                    self.slots.free_slot(slot)

    def _run_step(self, lane_batches, owned: np.ndarray, masks: np.ndarray,
                  keep_idle_lanes: bool = False):
        """Blocking device step (runs in a worker thread) against the
        snapshotted mirrors. All busy lanes ride one jitted program; idle
        lanes are dropped before the H2D transfer — an empty lane delivers
        nothing, so skipping it is semantically free, and each lane subset
        is its own (cached) jit specialization."""
        import jax.numpy as jnp
        state = RouterState(
            crdt=CrdtState(
                owners=jnp.asarray(np.where(owned, 0, ABSENT).astype(np.int32)),
                versions=jnp.asarray(owned.astype(np.uint32)),
                identities=jnp.asarray(
                    np.where(owned, 0, ABSENT).astype(np.int32)),
            ),
            topic_masks=jnp.asarray(masks))
        batches = tuple(
            IngressBatch(
                jnp.asarray(b.bytes_), jnp.asarray(b.kind),
                jnp.asarray(b.length), jnp.asarray(b.topic_mask),
                jnp.asarray(b.dest), jnp.asarray(b.valid))
            for b in lane_batches if keep_idle_lanes or b.valid.any())
        result = routing_step_lanes_single(state, batches)
        self.steps += 1
        return [(np.asarray(lane.deliver), np.asarray(lane.gathered_length),
                 np.asarray(lane.gathered_bytes)) for lane in result.lanes]

    def _egress(self, deliver, lengths, frames) -> None:
        """Walk the delivery matrix and queue the original wire frames to
        local user connections — non-blocking and grouped per user
        (senders.egress_delivery_rows), so one slow consumer cannot stall
        the pump (its overflow is handled by the failure-is-removal
        policy in the sender)."""
        users, frame_idx = np.nonzero(deliver)
        cache: dict[int, Bytes] = {}

        def frame_of(f: int) -> Bytes:
            raw = cache.get(f)
            if raw is None:
                raw = Bytes(frames[f, :lengths[f]].tobytes())
                cache[f] = raw
            return raw

        self.messages_routed += egress_delivery_rows(
            self.broker, self.slots, users, frame_idx, frame_of)
        for raw in cache.values():
            raw.release()

    async def _host_fallback(self, lane_batches) -> None:
        """Deliver batches the device failed to route, via the host path.
        Users-only on purpose: any broker-bound fan-out for these messages
        already ran on the host at staging time."""
        from pushcdn_tpu.broker.tasks.handlers import (
            handle_broadcast_message,
            handle_direct_message,
        )
        from pushcdn_tpu.proto.message import deserialize
        for b in lane_batches:
            for i in range(len(b.valid)):
                if not b.valid[i]:
                    continue
                raw = Bytes(b.bytes_[i, :b.length[i]].tobytes())
                try:
                    message = deserialize(raw.data)
                    if isinstance(message, Direct):
                        await handle_direct_message(
                            self.broker, bytes(message.recipient), raw,
                            to_user_only=True)
                    elif isinstance(message, Broadcast):
                        await handle_broadcast_message(
                            self.broker, list(message.topics), raw,
                            to_users_only=True)
                except Error:
                    pass
                finally:
                    raw.release()
