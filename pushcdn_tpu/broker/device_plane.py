"""DevicePlane — the bridge that puts the TPU router in the broker's hot
path.

The host broker (tasks/handlers.py) routes per-message with dict lookups;
with a ``DevicePlane`` attached, eligible messages (wire frames that fit a
frame slot) are instead **staged into the frame ring, routed in batched
jitted steps on the attached device, and delivered from the resulting
delivery matrix** (SURVEY.md §7 stage 7 → stage 8 "edge": the socket⇄HBM
pump). The wire frame travels verbatim through HBM, so receivers are
byte-identical with the host path. Oversized messages and control traffic
keep the host path.

Scope (round 1): one broker = one device shard (``routing_step_lanes_single``).
The host CRDT stays authoritative for cross-broker ownership; the device
plane handles the local fan-out — which is where the per-message Python
cost lives. Multi-shard meshes route via parallel.router's shard_map step.

Consistency design (single-writer, snapshot-per-step):

- The **host mirrors** (``_owned`` bool[U], ``_masks`` u32[U, 8]) are the
  source of truth, mutated only on the event loop by the Connections
  observer hooks. Each step SNAPSHOTS them together with ``take_batch()``
  (same event-loop tick), and the device ``RouterState`` is rebuilt from
  that snapshot — a registration or subscription racing the in-flight step
  simply lands in the next snapshot, never lost.
- **Slot quarantine**: a released user slot is not reusable until the step
  that might still carry frames addressed to it has completed — prevents a
  recycled slot from leaking one user's messages to another.
- **Failure = host fallback**: if a step raises, its staged frames are
  re-routed on the host path (users-only, matching what the device would
  have delivered) and the plane disables itself; staging then always
  returns False and the broker is a plain host broker again.

Flow per step:
  ingress: user_receive_loop → try_stage() → FrameRing (slot credits)
  compute: snapshot + take_batch → routing_step_lanes_single (jitted)
  egress:  deliver[u, f] → per-user non-blocking send of the frame bytes
"""

from __future__ import annotations

import asyncio
import logging
from dataclasses import dataclass
from typing import TYPE_CHECKING, List, Optional, Tuple

import numpy as np

from pushcdn_tpu.broker.pump_common import (
    CoalesceGate,
    RevCache,
    TopicMaskCache,
    effective_users,
)
from pushcdn_tpu.broker.staging import StageResult
from pushcdn_tpu.broker.tasks.senders import egress_delivery_rows
from pushcdn_tpu.parallel.crdt import ABSENT, CrdtState
from pushcdn_tpu.parallel.frames import (
    TOPIC_WORDS_FULL,
    FrameRing,
    UserSlots,
    mask_mirror_shape,
    mask_row_of,
    stage_best_fit,
)
from pushcdn_tpu.parallel.router import (
    IngressBatch,
    RouterState,
    routing_step_lanes_single,
)
from pushcdn_tpu.proto.error import Error
from pushcdn_tpu.proto.limiter import Bytes
from pushcdn_tpu.proto.message import (
    KIND_BROADCAST,
    KIND_DIRECT,
    Broadcast,
    Direct,
)

if TYPE_CHECKING:
    from pushcdn_tpu.broker.broker import Broker

logger = logging.getLogger("pushcdn.broker.device")


@dataclass
class DevicePlaneConfig:
    num_user_slots: int = 1024
    ring_slots: int = 1024
    frame_bytes: int = 2048
    # Size-bucketed lanes beyond the base (ring_slots × frame_bytes) ring
    # (SURVEY.md §7 hard-part #1): each entry is (frame_bytes, ring_slots).
    # A frame is staged into the smallest lane it fits, so 100 B acks don't
    # ride 32 KB-padded slots and 16 KB proposals still stay on device.
    extra_lanes: tuple = ((16384, 64),)
    # u32 words per topic mask: 8 covers the reference's whole u8 topic
    # space; 1 keeps compact masks (and the native batch packer) for
    # deployments with ≤32 topics
    topic_words: int = TOPIC_WORDS_FULL
    # Adaptive coalescing: a step fires immediately on a burst after idle
    # (latency regime) or when >= coalesce_min_frames are staged; a steady
    # trickle below the threshold waits batch_window_s to amortize step
    # dispatch.
    batch_window_s: float = 0.001
    coalesce_min_frames: int = 16
    # prefix-slice shapes for sparse traffic (one extra cached jit
    # specialization; collectives/D2H shrink ~ring/latency_slots x)
    latency_slots: int = 8
    # Depth-1 bypass: when the plane is COMPLETELY idle (no step in
    # flight, rings empty) and at most this many messages arrive in one
    # batch, route them on the host path immediately — the device's step
    # dispatch is a latency floor the sparse regime should never pay,
    # and the single-shard plane's host path covers exactly the same
    # local users. 0 disables (tests of staging mechanics do).
    bypass_max_items: int = 2
    # Delivery implementation: "auto" follows router.DELIVERY_IMPL (the
    # bench.py --delivery-impl switch, PUSHCDN_DELIVERY_IMPL env);
    # "ragged" forces the paged walk (ops.ragged_delivery — per-tick work
    # scales with fan-out, compact pairs feed egress_delivery_rows with
    # no bool[U,N] re-scan); "dense" forces the delivery-matrix kernels.
    delivery_impl: str = "auto"
    # page-pool capacity for the ragged interest index (PAGE-slot pages;
    # exhaustion falls the plane back to the dense step, never drops)
    ragged_max_pages: int = 1024
    # Relaxed-order pair extraction (ragged_pairs_grouped): a multi-topic
    # subscriber's same-tick frames arrive grouped per topic-mask instead
    # of in frame-staging order — per-topic FIFO holds, cross-topic order
    # within one tick does not (the same relaxation class as cross-LANE
    # reordering, which the size-bucketed rings already accept). Off by
    # default: the strict extractor keeps per-user order identical to the
    # dense plane at the cost of one radix sort over the tick's pairs.
    ragged_relaxed_order: bool = False

    def lane_shapes(self):
        """All lanes as (frame_bytes, ring_slots), sorted ascending by
        frame width (best-fit staging walks this order)."""
        return sorted(((self.frame_bytes, self.ring_slots),)
                      + tuple(self.extra_lanes))


class DevicePlane:
    # single-shard plane: inter-broker fan-out stays on the host links
    # (the mesh-group plane overrides this — peers ride ICI)
    covers_brokers = False

    def __init__(self, broker: "Broker", config: DevicePlaneConfig = None):
        self.broker = broker
        self.config = config or DevicePlaneConfig()
        c = self.config
        self.slots = UserSlots(c.num_user_slots)
        self.rings = [FrameRing(slots=s, frame_bytes=f,
                                topic_words=c.topic_words)
                      for f, s in c.lane_shapes()]
        # host mirrors — the single source of truth for device state;
        # mask shape tracks the configured topic-space width
        self._owned = np.zeros(c.num_user_slots, bool)
        self._masks = np.zeros(
            mask_mirror_shape(c.num_user_slots, c.topic_words), np.uint32)
        self._quarantine: List[int] = []   # slots awaiting step completion
        # users the slot table couldn't hold: broadcasts must stay on the
        # host path while any exist (they'd miss device-only fan-out)
        self._unmirrored: set[bytes] = set()
        # mirror revision: device state re-uploads only when it changed
        # (pump_common.RevCache holds the device copy)
        self._state_rev = 0
        self._state_cache = RevCache()
        self._tmask_cache = TopicMaskCache(c.topic_words)
        # cached device-side empty lane batches + byte stubs (frame bytes
        # never ride the device on the single-shard plane: the delivery
        # DECISION comes back, payloads egress from the host ring snapshot)
        self._idle_dev_lanes = {}
        self._byte_stubs = {}
        # ragged paged delivery (ISSUE 8): the incremental per-topic page
        # index is maintained from the same observer hooks as the mirrors;
        # per tick the pump packs a walk list and the step runs the paged
        # kernel instead of the U x N sweep. Resolved once at construction
        # (env > config > router.DELIVERY_IMPL).
        import os as _os
        impl = _os.environ.get("PUSHCDN_DELIVERY_IMPL", "") or \
            c.delivery_impl
        if impl == "auto":
            from pushcdn_tpu.parallel import router as _router
            impl = _router.DELIVERY_IMPL or "dense"
        self.delivery_impl = "ragged" if impl == "ragged" else "dense"
        self._ragged = None
        self._ragged_retry_below = 0  # rebuild-retry mark post-overflow
        if self.delivery_impl == "ragged":
            from pushcdn_tpu.ops.ragged_delivery import RaggedInterest
            self._ragged = RaggedInterest(
                32 * c.topic_words, max_pages=c.ragged_max_pages)
        self.ragged_steps = 0       # ticks routed through the paged walk
        self.ragged_fallbacks = 0   # ticks that fell back to dense
        self.disabled = False
        # single-shard planes keep inter-broker traffic on host links, so
        # they never *need* overflow dialing — the attribute exists because
        # heartbeat fail-open logic reads it off any plane uniformly
        self.overflow_seen = False
        self._kick = asyncio.Event()
        self._task: Optional[asyncio.Task] = None
        self._step_inflight = False
        self.steps = 0
        self.messages_routed = 0

    # ---- user lifecycle (Connections observer; event-loop only) ----------

    def _ragged_set_mask(self, slot: int, topics) -> None:
        """Mirror a mask change into the ragged page index (O(changed
        topics)). Pool exhaustion falls the plane back to the dense step
        — never a dropped delivery — and once membership shrinks to half
        the overflow-time population a ``rebuild()`` is attempted (rate-
        limited by halving the retry mark on failure) so the plane
        returns to the paged walk instead of staying dense forever."""
        if self._ragged is None:
            return
        from pushcdn_tpu.parallel.frames import mask_of_topics
        self._ragged.set_mask(
            slot, mask_of_topics(topics, self.config.topic_words)
            if topics else 0)
        if not self._ragged.overflowed:
            return
        if self.delivery_impl == "ragged":
            logger.warning(
                "ragged page pool exhausted (%d pages); device plane "
                "falling back to the dense delivery step",
                self.config.ragged_max_pages)
            self.delivery_impl = "dense"
            self._ragged_retry_below = max(len(self._ragged) // 2, 1)
        elif len(self._ragged) <= self._ragged_retry_below:
            if self._ragged.rebuild():
                logger.info("ragged page index rebuilt (%d users); "
                            "resuming paged delivery", len(self._ragged))
                self.delivery_impl = "ragged"
            else:  # still too big: wait for a further halving
                self._ragged_retry_below = max(len(self._ragged) // 2, 1)

    def on_user_added(self, public_key: bytes, topics) -> None:
        try:
            slot = self.slots.assign(public_key)
        except Error:
            # table full: this user is host-routed only; never fail the
            # registration over the mirror
            self._unmirrored.add(public_key)
            logger.warning("device user-slot table full; %d unmirrored users",
                           len(self._unmirrored))
            return
        self._owned[slot] = True
        self._masks[slot] = mask_row_of(topics, self.config.topic_words)
        self._ragged_set_mask(slot, topics)
        self._state_rev += 1

    def on_user_removed(self, public_key: bytes) -> None:
        self._unmirrored.discard(public_key)
        slot = self.slots.unmap(public_key)
        if slot is None:
            return
        self._owned[slot] = False
        self._masks[slot] = 0
        self._ragged_set_mask(slot, None)
        self._state_rev += 1
        # the slot index stays quarantined until the next step completes —
        # in-flight frames may still address it
        self._quarantine.append(slot)

    def on_subscription_changed(self, public_key: bytes, topics) -> None:
        slot = self.slots.slot_of(public_key)
        if slot is None:
            return
        self._masks[slot] = mask_row_of(topics, self.config.topic_words)
        self._ragged_set_mask(slot, topics)
        self._state_rev += 1

    # ---- ingress ----------------------------------------------------------

    def _idle_bypass(self, n_items: int) -> bool:
        """True when the latency regime should skip the device entirely:
        nothing staged, no step in flight, and the arriving batch is
        small — host-routing now beats waiting a step dispatch."""
        return (n_items <= self.config.bypass_max_items
                and not self._step_inflight
                and all(r.free_slots == r.slots for r in self.rings))

    def try_stage(self, message, raw: Bytes) -> StageResult:
        """Stage a decoded message's WIRE FRAME for device routing.
        INELIGIBLE ⇒ host path (too big, unknown recipient, unmirrored
        users present, or the depth-1 idle bypass); FULL ⇒ slot-credit
        backpressure, caller retries."""
        if self.disabled:
            return StageResult.INELIGIBLE
        if self._idle_bypass(1):
            return StageResult.INELIGIBLE
        frame = bytes(raw.data)
        if len(frame) > self.rings[-1].frame_bytes:
            return StageResult.INELIGIBLE
        if isinstance(message, Broadcast):
            if self._unmirrored:
                return StageResult.INELIGIBLE  # would miss unmirrored users
            mask, out_of_range = self._tmask_cache.resolve(message.topics)
            if out_of_range:
                return StageResult.INELIGIBLE  # beyond the configured space
            if mask == 0:
                return StageResult.INELIGIBLE
            ok = stage_best_fit(self.rings, len(frame),
                                lambda r: r.push_broadcast(frame, mask))
        elif isinstance(message, Direct):
            slot = self.slots.slot_of(bytes(message.recipient))
            if slot is None:
                return StageResult.INELIGIBLE  # not mirrored (cross-broker)
            ok = stage_best_fit(self.rings, len(frame),
                                lambda r: r.push_direct(frame, slot))
        else:
            return StageResult.INELIGIBLE
        if ok:
            self._kick.set()
            return StageResult.STAGED
        return StageResult.FULL

    def stage_batch(self, items) -> List[StageResult]:
        """Stage a whole receive batch in one pass: classify each
        (message, raw) pair, group the eligible frames per size lane
        (best-fit with free-slot accounting), then pack each lane's group
        with ONE ``FrameRing.push_batch`` (one C call + one copy per
        lane) instead of a per-frame Python ``_put``. Returns a
        per-item ``StageResult`` aligned with ``items``; FULL items are
        the ring-backpressure leftovers the caller retries singly."""
        results = [StageResult.INELIGIBLE] * len(items)
        if self.disabled or self._idle_bypass(len(items)):
            return results
        # (ring -> [(item_idx, frame, kind, mask, dest), ...])
        groups: dict[int, list] = {}
        free = [r.free_slots for r in self.rings]
        widest = self.rings[-1].frame_bytes
        for idx, (message, raw) in enumerate(items):
            frame = bytes(raw.data)
            if len(frame) > widest:
                continue  # INELIGIBLE
            if isinstance(message, Broadcast):
                if self._unmirrored:
                    continue
                mask, out_of_range = self._tmask_cache.resolve(
                    message.topics)
                if out_of_range or mask == 0:
                    continue
                kind, dest = KIND_BROADCAST, -1
            elif isinstance(message, Direct):
                slot = self.slots.slot_of(bytes(message.recipient))
                if slot is None:
                    continue
                kind, mask, dest = KIND_DIRECT, 0, slot
            else:
                continue
            # best-fit with credit accounting (mirrors stage_best_fit)
            placed = False
            for li, ring in enumerate(self.rings):
                if len(frame) <= ring.frame_bytes and free[li] > 0:
                    free[li] -= 1
                    groups.setdefault(li, []).append(
                        (idx, frame, kind, mask, dest))
                    placed = True
                    break
            results[idx] = StageResult.STAGED if placed else StageResult.FULL
        staged_any = False
        for li, group in groups.items():
            n = self.rings[li].push_batch(
                [g[1] for g in group], [g[2] for g in group],
                [g[3] for g in group], [g[4] for g in group])
            staged_any = staged_any or n > 0
            for idx, *_ in group[n:]:  # raced-full leftovers
                results[idx] = StageResult.FULL
        if staged_any:
            self._kick.set()
        return results

    def covered_broker_idents(self) -> set:
        """Broker identifiers whose delivery this plane covers — none for
        the single-shard plane (host links handle all peers)."""
        return set()

    # ---- the pump ---------------------------------------------------------

    async def start(self) -> None:
        # compile the step off the hot path (first jit can take seconds)
        await asyncio.to_thread(self._warmup)
        self._task = asyncio.create_task(self._pump(), name="device-pump")

    def _pack_walks(self, batches):
        """Pack one walk list per lane (event-loop only — the index is
        observer-mutated there). Returns None when any frame spilled
        (transient-page exhaustion) — the dense step covers that tick."""
        walks = []
        spilled = False
        for b in batches:
            w = self._ragged.pack(b.kind, b.topic_mask, b.dest, b.valid,
                                  page_round=64)
            walks.append(w)
            spilled = spilled or bool(w.spilled)
        # pack() snapshots the pool, so transient union/direct pages
        # recycle immediately (wraparound)
        self._ragged.release_transient()
        if spilled:
            self.ragged_fallbacks += 1
            return None
        return walks

    def _warmup(self) -> None:
        from pushcdn_tpu.parallel.frames import slice_batch
        empty = [r.take_batch() for r in self.rings]
        lat = [slice_batch(b, self.config.latency_slots) for b in empty]
        u0 = effective_users(0, self.config.num_user_slots)
        try:
            # compile the only two specializations the pump uses: all lanes
            # at full shapes (idle lanes ride cached device empties) and
            # the latency-sliced base lane; wider user buckets compile on
            # first growth past the mark
            walks = self._pack_walks(empty) \
                if self.delivery_impl == "ragged" else None
            self._run_step(empty, self._owned[:u0].copy(),
                           self._masks[:u0].copy(), walks=walks,
                           compile_only=True)
            self._run_step(lat[:1], self._owned[:u0].copy(),
                           self._masks[:u0].copy(),
                           walks=None if walks is None else walks[:1],
                           compile_only=True)
            self.steps -= 2  # warmup doesn't count
        except Exception:
            logger.exception("device-plane warmup step failed")
            self.disabled = True

    async def stop(self) -> None:
        if self._task is not None:
            self._task.cancel()
            try:
                await self._task
            except asyncio.CancelledError:
                pass
            except Exception:
                logger.exception("device pump died during stop")

    async def _pump(self) -> None:
        from pushcdn_tpu.broker.tasks.senders import egress_streams
        from pushcdn_tpu.parallel.frames import slice_batch
        c = self.config
        loop = asyncio.get_running_loop()
        gate = CoalesceGate(c.batch_window_s, c.coalesce_min_frames)
        while True:
            await self._kick.wait()
            self._kick.clear()
            await asyncio.sleep(0)  # let same-tick stagers land
            staged = sum(r.slots - r.free_slots for r in self.rings)
            wait = gate.wait_s(staged, loop.time())
            if wait:
                # steady trickle: coalesce one window; bursts after idle
                # (the latency regime) and saturated pipelines step now
                await asyncio.sleep(wait)
            if all(r.free_slots == r.slots for r in self.rings):
                continue
            lat = c.latency_slots
            small = (all(r.slots - r.free_slots <= lat
                         for r in self.rings[:1])
                     and all(r.free_slots == r.slots
                             for r in self.rings[1:]))
            # snapshot mirrors + all lane rings in ONE event-loop tick
            batches_np = [r.take_batch() for r in self.rings]
            if small:
                batches_np = [slice_batch(batches_np[0], lat)]
            u_eff = effective_users(self.slots.high_water,
                                    c.num_user_slots)
            owned = self._owned[:u_eff].copy()
            masks = self._masks[:u_eff].copy()
            rev = self._state_rev
            # pack the ragged walk in the SAME event-loop tick as the
            # snapshot (the page index is observer-mutated on the loop;
            # pack copies the referenced pool prefix). Overflow demotes
            # delivery_impl to "dense" (the index stays maintained for
            # the rebuild-retry path), so gate on the impl, not the index
            walks = self._pack_walks(batches_np) \
                if self.delivery_impl == "ragged" else None
            quarantined, self._quarantine = self._quarantine, []
            try:
                self._step_inflight = True
                try:
                    jobs = await asyncio.to_thread(
                        self._run_step, batches_np, owned, masks, rev,
                        walks)
                finally:
                    self._step_inflight = False
                gate.stepped(loop.time())
                for streams, d2, lengths, frames in jobs:
                    if streams is not None:
                        self.messages_routed += egress_streams(
                            self.broker, self.slots, streams)
                    else:
                        self._egress(d2, lengths, frames)
            except asyncio.CancelledError:
                raise
            except Exception:
                logger.exception(
                    "device routing step failed; re-routing the batch on "
                    "the host path and disabling the device plane")
                self.disabled = True
                # frames staged (and acked STAGED) while the failing step
                # ran in the worker thread sit in the fresh rings — drain
                # them too, or they'd be lost with no fallback
                late = [r.take_batch() for r in self.rings]
                await self._host_fallback(batches_np)
                await self._host_fallback(late)
                return
            finally:
                for slot in quarantined:  # safe to recycle now
                    self.slots.free_slot(slot)

    def _run_step(self, lane_batches, owned: np.ndarray, masks: np.ndarray,
                  state_rev=None, walks=None, compile_only: bool = False):
        """Blocking device step (runs in a worker thread) against the
        snapshotted mirrors. All lanes ride one jitted program; idle lanes
        reuse cached device-side empty batches (zero H2D, and the jit key
        never depends on the traffic mix). Frame BYTES never touch the
        device: zero-width stubs stand in for the byte tensors
        (gather_bytes=False), only the delivery matrix comes back, and
        egress encodes payloads from the host ring snapshots via the
        native engine. Returns per-lane egress jobs: (EgressStreams, -, -,
        -) on the native path or (None, deliver, lengths, frames) for the
        Python fallback.

        ``walks`` (one RaggedWalk per lane) switches to the ragged paged
        step: per-tick device work scales with fan-out and the step's
        compact (frame, receiver-run) output feeds
        ``senders.egress_delivery_rows`` directly — no bool[U, N] comes
        back and Python never re-scans one. ``compile_only`` runs every
        lane regardless of traffic (warmup) and returns no jobs."""
        import jax.numpy as jnp
        from pushcdn_tpu import native as native_mod

        def build_state():
            return RouterState(
                crdt=CrdtState(
                    owners=jnp.asarray(
                        np.where(owned, 0, ABSENT).astype(np.int32)),
                    versions=jnp.asarray(owned.astype(np.uint32)),
                    identities=jnp.asarray(
                        np.where(owned, 0, ABSENT).astype(np.int32)),
                ),
                topic_masks=jnp.asarray(masks))

        state = self._state_cache.get(state_rev, build_state)

        def stub(n):
            st = self._byte_stubs.get(n)
            if st is None:
                st = jnp.zeros((n, 0), jnp.uint8)
                self._byte_stubs[n] = st
            return st

        def to_dev(li, b, busy):
            key = (li, b.valid.shape[0])
            if not busy:
                cached = self._idle_dev_lanes.get(key)
                if cached is not None:
                    return cached
            dev = IngressBatch(
                stub(b.valid.shape[0]), jnp.asarray(b.kind),
                jnp.asarray(b.length), jnp.asarray(b.topic_mask),
                jnp.asarray(b.dest), jnp.asarray(b.valid))
            if not busy:
                self._idle_dev_lanes[key] = dev
            return dev

        busy = [bool(b.valid.any()) for b in lane_batches]

        if walks is not None:
            # ---- ragged paged step: one walk per lane ----
            from pushcdn_tpu.ops.ragged_delivery import (
                ragged_pairs,
                ragged_pairs_grouped,
            )
            from pushcdn_tpu.parallel.router import \
                routing_step_ragged_single
            jobs = []
            routed_ragged = False
            for li, (b, walk) in enumerate(zip(lane_batches, walks)):
                if not busy[li] and not compile_only:
                    continue  # an idle lane has no walk entries
                res = routing_step_ragged_single(
                    state, to_dev(li, b, busy[li]),
                    jnp.asarray(walk.pages), jnp.asarray(walk.walk_page),
                    jnp.asarray(walk.walk_frame))
                if compile_only:
                    res.counts.block_until_ready()
                    continue
                routed_ragged = True
                out_user = np.asarray(res.out_user)
                if self.config.ragged_relaxed_order:
                    # per-topic FIFO only (see the config knob's docs)
                    users, frame_idx = ragged_pairs_grouped(
                        out_user, walk,
                        num_users=self.config.num_user_slots)
                else:
                    # strict: per-user order identical to the dense plane
                    users, frame_idx = ragged_pairs(
                        out_user, walk.walk_frame,
                        num_users=self.config.num_user_slots)
                if len(users):
                    jobs.append((None, (users, frame_idx), b.length,
                                 b.bytes_))
            self.steps += 1
            if routed_ragged:  # warmup compile runs don't count as ticks
                self.ragged_steps += 1
            return jobs

        batches = tuple(to_dev(li, b, busy[li])
                        for li, b in enumerate(lane_batches))
        result = routing_step_lanes_single(state, batches,
                                           gather_bytes=False)
        self.steps += 1
        jobs = []
        for li, lane in enumerate(result.lanes):
            if not busy[li]:
                continue  # an idle lane can't deliver: skip its D2H
            deliver = np.asarray(lane.deliver)
            if not deliver.any():
                continue
            b = lane_batches[li]
            streams = native_mod.egress_encode(deliver, b.length, [b.bytes_])
            if streams is not None:
                jobs.append((streams, None, None, None))
            else:
                jobs.append((None, deliver, b.length, b.bytes_))
        return jobs

    def _egress(self, deliver, lengths, frames) -> None:
        """Queue delivered wire frames to local user connections —
        non-blocking and grouped per user (senders.egress_delivery_rows),
        so one slow consumer cannot stall the pump (its overflow is
        handled by the failure-is-removal policy in the sender).
        ``deliver`` is either the dense bool[U, N] matrix (scanned here —
        the Python-fallback path) or the ragged step's compact
        ``(users, frame_idx)`` pair listing, consumed as-is."""
        if isinstance(deliver, tuple):
            users, frame_idx = deliver
        else:
            users, frame_idx = np.nonzero(deliver)
        cache: dict[int, Bytes] = {}

        def frame_of(f: int) -> Bytes:
            raw = cache.get(f)
            if raw is None:
                raw = Bytes(frames[f, :lengths[f]].tobytes())
                cache[f] = raw
            return raw

        self.messages_routed += egress_delivery_rows(
            self.broker, self.slots, users, frame_idx, frame_of)
        for raw in cache.values():
            raw.release()

    async def _host_fallback(self, lane_batches) -> None:
        """Deliver batches the device failed to route, via the host path.
        Users-only on purpose: any broker-bound fan-out for these messages
        already ran on the host at staging time."""
        from pushcdn_tpu.broker.tasks.handlers import (
            handle_broadcast_message,
            handle_direct_message,
        )
        from pushcdn_tpu.proto.message import deserialize
        for b in lane_batches:
            for i in range(len(b.valid)):
                if not b.valid[i]:
                    continue
                raw = Bytes(b.bytes_[i, :b.length[i]].tobytes())
                try:
                    message = deserialize(raw.data)
                    if isinstance(message, Direct):
                        await handle_direct_message(
                            self.broker, bytes(message.recipient), raw,
                            to_user_only=True)
                    elif isinstance(message, Broadcast):
                        await handle_broadcast_message(
                            self.broker, list(message.topics), raw,
                            to_users_only=True)
                except Error:
                    pass
                finally:
                    raw.release()
