"""Deterministic single-broker test harness.

Capability parity with cdn-broker/src/tests/mod.rs:45-412 (NOT test-gated —
the reference exposes it to benches too; our bench.py reuses it the same
way): build one *real* ``Broker`` over the **Memory** transport with an
**Embedded** (temp-file SQLite) discovery, then *inject* fake users and
fake peer brokers directly into ``Connections`` — spawning real receive
loops but skipping auth (inject_users mod.rs:258-300, inject_brokers
mod.rs:308-389). Peer broker state (their topics, the users they own) is
seeded with hand-built sync payloads exactly like the reference seeds rkyv
messages (mod.rs:356-382).

The injected entities' *remote* connection ends act as the test's hands:
``send_message_as`` publishes from an entity; ``assert_received`` /
``assert_silence`` check exact delivery sets and the absence of duplicates
with short timeouts (mod.rs:45-107).
"""

from __future__ import annotations

import asyncio
import itertools
import os
import tempfile
from dataclasses import dataclass, field
from typing import List, Optional, Sequence, Tuple

from pushcdn_tpu.broker.broker import Broker, BrokerConfig
from pushcdn_tpu.broker.connections import SubscriptionStatus
from pushcdn_tpu.broker.tasks.handlers import broker_receive_loop, user_receive_loop
from pushcdn_tpu.broker.versioned_map import VersionedMap
from pushcdn_tpu.proto.crypto.signature import DEFAULT_SCHEME
from pushcdn_tpu.proto.def_ import testing_run_def
from pushcdn_tpu.proto.message import Message, deserialize, serialize
from pushcdn_tpu.proto.transport.base import Connection
from pushcdn_tpu.proto.transport.memory import gen_testing_connection_pair
from pushcdn_tpu.proto.util import AbortOnDropHandle

_UNIQUE = itertools.count()


@dataclass
class TestUser:
    __test__ = False  # not a pytest class despite the reference-parity name
    public_key: bytes
    remote: Connection  # the end the test drives


@dataclass
class TestBroker:
    __test__ = False
    identifier: str
    remote: Connection


@dataclass
class TestDefinition:
    __test__ = False
    """Declarative scenario (parity ``TestDefinition``, mod.rs):
    ``connected_users[i]`` = topic list of injected user i;
    ``connected_brokers[j]`` = (topics, owned-user-keys) of injected peer j.
    """

    connected_users: Sequence[Sequence[int]] = ()
    connected_brokers: Sequence[Tuple[Sequence[int], Sequence[bytes]]] = ()
    # e.g. "127.0.0.1:0" to exercise the observability endpoint
    # (/healthz, /readyz, /debug/topology) against an injected broker
    metrics_bind_endpoint: Optional[str] = None
    # route the injected USER links over real loopback TCP instead of the
    # Memory pair — the io-impl (asyncio vs io_uring) A/B seam: the whole
    # forwarding path then crosses real sockets on both ends while the
    # broker internals stay identical
    tcp_users: bool = False
    # widen the topic space (wildcard/durable scenarios) or shrink the
    # byte pool (pool-pressure scenarios); None = harness defaults
    topics: Optional[object] = None
    pool_bytes: Optional[int] = None

    async def run(self) -> "TestRun":
        uid = next(_UNIQUE)
        db = os.path.join(tempfile.mkdtemp(prefix="pushcdn-test-"),
                          "discovery.sqlite")
        pool_kw = ({"global_memory_pool_size": self.pool_bytes}
                   if self.pool_bytes is not None else {})
        config = BrokerConfig(
            run_def=testing_run_def(topics=self.topics),
            keypair=DEFAULT_SCHEME.generate_keypair(seed=uid),
            discovery_endpoint=db,
            public_advertise_endpoint=f"test-pub-{uid}",
            public_bind_endpoint=f"test-pub-{uid}",
            private_advertise_endpoint=f"test-priv-{uid}",
            private_bind_endpoint=f"test-priv-{uid}",
            metrics_bind_endpoint=self.metrics_bind_endpoint,
            # keep periodic tasks out of the way for determinism
            heartbeat_interval_s=3600, sync_interval_s=3600,
            whitelist_interval_s=3600,
            **pool_kw,
        )
        broker = await Broker.new(config)
        await broker.start()
        run = TestRun(broker=broker)
        if self.tcp_users:
            await run.inject_users_tcp(self.connected_users)
        else:
            await run.inject_users(self.connected_users)
        await run.inject_brokers(self.connected_brokers)
        return run


@dataclass
class TestRun:
    __test__ = False
    broker: Broker
    connected_users: List[TestUser] = field(default_factory=list)
    connected_brokers: List[TestBroker] = field(default_factory=list)
    tcp_listener: Optional[object] = None  # set by inject_users_tcp

    async def inject_users(self, user_topics) -> None:
        """Parity inject_users (mod.rs:258-300): real receive loops, no auth."""
        for i, topics in enumerate(user_topics):
            key = f"user-{i}".encode()
            local, remote = await gen_testing_connection_pair(self.broker.limiter)
            task = asyncio.create_task(
                user_receive_loop(self.broker, key, local))
            self.broker.connections.add_user(key, local, list(topics),
                                             AbortOnDropHandle(task))
            self.connected_users.append(TestUser(key, remote))

    async def inject_users_tcp(self, user_topics) -> None:
        """``inject_users`` over real loopback TCP: the broker side accepts
        and finalizes with the broker limiter (exactly what the public
        accept loop does after auth), then spawns the same
        ``user_receive_loop``. The Tcp protocol resolves ``--io-impl``
        per process, so these links exercise whichever data plane
        (asyncio or io_uring) is selected."""
        from pushcdn_tpu.proto.transport.tcp import Tcp
        listener = await Tcp.bind("127.0.0.1:0")
        self.tcp_listener = listener
        port = listener.bound_port
        for i, topics in enumerate(user_topics):
            key = f"user-{i}".encode()
            accept_t = asyncio.create_task(listener.accept())
            remote = await Tcp.connect(f"127.0.0.1:{port}",
                                       limiter=self.broker.limiter)
            local = await (await accept_t).finalize(self.broker.limiter)
            task = asyncio.create_task(
                user_receive_loop(self.broker, key, local))
            self.broker.connections.add_user(key, local, list(topics),
                                             AbortOnDropHandle(task))
            self.connected_users.append(TestUser(key, remote))

    async def inject_brokers(self, broker_defs) -> None:
        """Parity inject_brokers (mod.rs:308-389): register a fake peer and
        seed its state with hand-built sync payloads."""
        for j, (topics, owned_users) in enumerate(broker_defs):
            ident = f"testbrokerpub-{j}:0/testbrokerpriv-{j}:0"
            local, remote = await gen_testing_connection_pair(self.broker.limiter)
            task = asyncio.create_task(
                broker_receive_loop(self.broker, ident, local))
            self.broker.connections.add_broker(ident, local,
                                               AbortOnDropHandle(task))
            # seed topic interest (hand-built TopicSync, mod.rs:356-382)
            if topics:
                m = VersionedMap(local_identity=ident)
                for t in topics:
                    m.insert(int(t), int(SubscriptionStatus.SUBSCRIBED))
                self.broker.connections.apply_topic_sync(
                    ident, VersionedMap.serialize_entries(m.full()))
            # seed direct-map ownership (hand-built UserSync)
            if owned_users:
                m = VersionedMap(local_identity=ident)
                for u in owned_users:
                    m.insert(bytes(u), ident)
                self.broker.connections.apply_user_sync(
                    VersionedMap.serialize_entries(m.full()))
            self.connected_brokers.append(TestBroker(ident, remote))

    # -- assertion helpers (parity send_message_as!/assert_received!) -------

    async def send_message_as(self, entity, message: Message) -> None:
        await entity.remote.send_message(message, flush=True)

    async def assert_received(self, entity, expected: Message,
                              timeout: float = 0.25) -> None:
        """The entity receives exactly ``expected`` (payload-compared)."""
        raw = await asyncio.wait_for(entity.remote.recv_raw(), timeout)
        got = deserialize(raw.data)
        assert serialize(got) == serialize(expected), (
            f"{_name(entity)} got {got!r}, want {expected!r}")
        raw.release()

    async def assert_silence(self, entity, timeout: float = 0.1) -> None:
        """The entity receives NOTHING within ``timeout`` (duplicate /
        mis-delivery detection, mod.rs assert_received! absence mode)."""
        try:
            raw = await asyncio.wait_for(entity.remote.recv_raw(), timeout)
        except (asyncio.TimeoutError, Exception) as exc:
            if isinstance(exc, asyncio.TimeoutError):
                return
            return  # connection closed also counts as silence
        got = deserialize(raw.data)
        raise AssertionError(f"{_name(entity)} unexpectedly received {got!r}")

    async def shutdown(self) -> None:
        for u in self.connected_users:
            u.remote.close()
        for b in self.connected_brokers:
            b.remote.close()
        if self.tcp_listener is not None:
            await self.tcp_listener.close()
        await self.broker.stop()

    # index helpers (parity at_index!)
    def user(self, i: int) -> TestUser:
        return self.connected_users[i]

    def peer(self, j: int) -> TestBroker:
        return self.connected_brokers[j]


def _name(entity) -> str:
    if isinstance(entity, TestUser):
        return f"user {entity.public_key!r}"
    return f"broker {entity.identifier}"
