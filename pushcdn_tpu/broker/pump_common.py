"""Shared device-pump machinery for the single-shard plane and the mesh
group (two reviews flagged the hand-synced copies of these heuristics —
one home keeps them in lockstep):

- the adaptive coalescing gate (step immediately on bursts-after-idle and
  saturated pipelines; wait one window for a steady sub-threshold
  trickle),
- the user-table slice mark (round the slot high-water up to a bucket so
  delivery matrices, their D2H, and the egress scans pay for the actual
  population, while the jit key only moves once per bucket),
- the revision-keyed device-state cache (steady state pays zero H2D for
  the user table).
"""

from __future__ import annotations

from typing import Any, Callable, Optional

from pushcdn_tpu.parallel.frames import mask_of_topics

# user-table slice granularity (jit keys move once per bucket)
U_ROUND = 64


def effective_users(high_water: int, capacity: int,
                    round_to: int = U_ROUND) -> int:
    """Slice mark for the user table: ``high_water`` rounded up to a
    bucket, clamped to capacity, at least one bucket."""
    return min(capacity, max(round_to,
                             -(-high_water // round_to) * round_to))


class CoalesceGate:
    """The latency/step-efficiency knob as one decision point.

    A step fires immediately when staged traffic reaches
    ``coalesce_min_frames`` OR when the pump has been idle (a burst after
    quiet pays no window at all); a steady trickle below the threshold
    waits one ``batch_window_s`` to amortize step dispatch.
    """

    __slots__ = ("batch_window_s", "coalesce_min_frames", "last_step_t")

    def __init__(self, batch_window_s: float, coalesce_min_frames: int):
        self.batch_window_s = batch_window_s
        self.coalesce_min_frames = coalesce_min_frames
        self.last_step_t = -1e9

    def wait_s(self, staged: int, now: float) -> float:
        """Seconds to coalesce before stepping (0 = step now)."""
        if staged and staged < self.coalesce_min_frames and \
                now - self.last_step_t < 4 * self.batch_window_s:
            return self.batch_window_s
        return 0.0

    def stepped(self, now: float) -> None:
        self.last_step_t = now


class RevCache:
    """Revision-keyed single-entry cache for device-resident state: the
    builder runs only when the revision moved (mirror mutations bump it),
    so unchanged user tables cost zero H2D per step."""

    __slots__ = ("_rev", "_value")

    def __init__(self):
        self._rev: Optional[int] = None
        self._value: Any = None

    def get(self, rev: Optional[int], build: Callable[[], Any]) -> Any:
        """Return the cached value when ``rev`` matches; otherwise build,
        and cache iff ``rev`` is not None (warmup passes None so its
        throwaway state never masks the first real upload)."""
        if rev is not None and rev == self._rev and self._value is not None:
            return self._value
        value = build()
        if rev is not None:
            self._rev = rev
            self._value = value
        return value


class TopicMaskCache:
    """Per-plane memo of topic-list -> (mask, any_out_of_range): consensus
    traffic repeats a handful of topic sets per deployment, and the
    per-message mask_of_topics loop + range scan showed up in the ingest
    profile. Bounds/eviction come from the shared BoundedTopicMemo
    policy (proto.topic)."""

    __slots__ = ("words", "_memo")

    def __init__(self, topic_words: int):
        from pushcdn_tpu.proto.topic import BoundedTopicMemo
        self.words = topic_words
        self._memo = BoundedTopicMemo()

    def resolve(self, topics):
        limit = 32 * self.words

        def compute(key):
            return (mask_of_topics(key, self.words),
                    any(int(t) >= limit for t in key))

        return self._memo.get(topics, compute)
