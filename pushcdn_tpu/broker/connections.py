"""``Connections`` — the broker's entire routing state plane.

Capability parity with cdn-broker/src/connections/mod.rs:34-388 and
connections/broadcast/mod.rs:19-55:

- users map: ``UserPublicKey → (Connection, AbortOnDropHandle)``;
- brokers map: ``BrokerIdentifier → (Connection, AbortOnDropHandle)`` plus a
  per-peer ``TopicSyncMap`` tracking that peer's advertised topics;
- ``DirectMap``: the global "which broker owns this user" CRDT
  (``VersionedMap[UserPublicKey, str, str]``, connections/direct/mod.rs:14);
- ``BroadcastMap``: RelationalMaps for local users and peer brokers, our own
  ``TopicSyncMap`` advertisement, and previous-topic-set delta tracking;
- interest queries, sync generation/application, double-connect eviction
  ("user connected elsewhere", connections/mod.rs:154-162).

Locking: one ``asyncio`` world — Connections is only touched from the
broker's event loop, which gives the same "one RwLock" discipline as the
reference (cdn-broker/src/lib.rs:98) for free. Methods are synchronous;
I/O (closing evicted connections) is delegated to abort handles.

Broker identifiers are carried as **strings** (``BrokerIdentifier``'s
canonical "pub/priv" form) inside CRDT payloads so the codec stays scalar.
"""

from __future__ import annotations

import asyncio
import enum
import logging
import os
from collections import deque
from dataclasses import dataclass, field
from typing import Deque, Dict, List, Optional, Set, Tuple

from pushcdn_tpu.broker.relational_map import RelationalMap
from pushcdn_tpu.broker.versioned_map import VersionedMap
from pushcdn_tpu.proto import ledger as ledger_mod
from pushcdn_tpu.proto.transport.base import Connection
from pushcdn_tpu.proto.util import AbortOnDropHandle, mnemonic

logger = logging.getLogger("pushcdn.broker")

UserPublicKey = bytes
Topic = int

# How long a migration-evicted user's connection stays in ``parting``
# (sendable for already-routed deliveries AND for late directs that
# raced the eviction — see route_direct's parting chase) before the
# deferred flush-and-FIN. Must cover the UserSync propagation skew
# between mesh peers: a publisher's broker keeps forwarding to the old
# home until the out-versioned DirectMap row reaches it, which under
# load can lag by hundreds of ms. Kept just under the client's own
# drain backstop (PUSHCDN_MIGRATE_DRAIN_S, default 2 s) so the broker
# FINs first and the client's drain ends on EOF, not on its timer.
PARTING_GRACE_S = float(os.environ.get("PUSHCDN_PARTING_GRACE_S",
                                       "1.5") or 1.5)


class SubscriptionStatus(enum.IntEnum):
    """Value type of the topic-sync CRDT (broadcast/mod.rs SubscriptionStatus)."""

    UNSUBSCRIBED = 0
    SUBSCRIBED = 1


@dataclass
class UserHandle:
    connection: Connection
    abort_handle: Optional[AbortOnDropHandle] = None


@dataclass
class BrokerHandle:
    connection: Connection
    abort_handle: Optional[AbortOnDropHandle] = None
    # That peer's advertised topic set, as a CRDT we merge TopicSync into
    # (per-broker TopicSyncMap, connections/mod.rs:40-53).
    topic_sync_map: VersionedMap = None


# Bound on the typed route-delta log (ISSUE 7). A consumer that falls
# further behind than this rebuilds from scratch (version gap) instead of
# the log growing without bound; sized so steady churn never trims a
# snapshot that refreshes once per plan call.
ROUTE_LOG_MAX = int(os.environ.get("PUSHCDN_ROUTE_LOG_MAX", "8192") or 8192)


class Connections:
    """All routing state for one broker."""

    def __init__(self, identity: str):
        # identity = our BrokerIdentifier in canonical string form
        self.identity = identity
        # optional observer (the broker's DevicePlane mirrors user slots /
        # topic masks on device); duck-typed: on_user_added(key, topics),
        # on_user_removed(key), on_subscription_changed(key, topics)
        self.observer = None
        self.users: Dict[UserPublicKey, UserHandle] = {}
        self.brokers: Dict[str, BrokerHandle] = {}
        # Migration evictions (ISSUE 12): a user whose UserSync merge says
        # it now lives elsewhere leaves ``users`` immediately (routing must
        # follow the new owner) but its connection lingers here so
        # deliveries ALREADY routed to it — this very batch's egress, a
        # sibling shard's in-flight ring record — still flush to the old
        # connection the client is draining. A deferred soft_close empties
        # the writer, FINs, and drops the entry after PARTING_GRACE_S.
        self.parting: Dict[UserPublicKey, Connection] = {}
        # user → owning-broker CRDT (DirectMap, connections/direct/mod.rs:14)
        self.direct_map: VersionedMap = VersionedMap(local_identity=identity)
        # topic interest indexes (BroadcastMap, broadcast/mod.rs:19-55)
        self.user_topics: RelationalMap = RelationalMap()    # user -> topics
        self.broker_topics: RelationalMap = RelationalMap()  # peer -> topics
        # our own advertised-topics CRDT + previous snapshot for deltas
        self.our_topic_map: VersionedMap = VersionedMap(local_identity=identity)
        self._previous_local_topics: Set[Topic] = set()
        # Bumped by every mutation that can change an interest query's
        # answer; receive loops' per-batch interest caches validate against
        # it so a subscribe/sync landing from ANOTHER task mid-batch (the
        # batch awaits on egress/device backpressure) invalidates the cache
        # the same way the reference's per-message query would see it.
        self.interest_version = 0
        # ---- sharded data plane (ISSUE 6) ----
        # This process may be one of N worker shards presenting as ONE
        # broker identity. Siblings' users/mesh links are tracked here so
        # routing (scalar and cut-through) can hand their fan-out to the
        # shard rings; all four stay empty (and cost nothing) at N == 1.
        self.num_shards = 1
        self.shard_id = 0
        self.remote_user_shard: Dict[UserPublicKey, int] = {}   # key -> shard
        self.remote_broker_shard: Dict[str, int] = {}           # ident -> shard
        # control-plane delta emitter (ShardRuntime installs it): every
        # local routing-state mutation is mirrored to sibling shards as a
        # versioned delta via the parent hub
        self.shard_notifier = None
        # ---- typed route-delta log (ISSUE 7) ----
        # Every interest/DirectMap mutation appends a typed record naming
        # the entity whose routing contribution may have changed:
        #   ("user", key)     membership / shard residency / topic set
        #   ("broker", ident) link / shard residency / advertised topics
        #   ("dmap", key)     DirectMap ownership entry
        # Consumers (cutthrough.RouteState) re-resolve each named entity
        # against CURRENT state, so application is order-insensitive and
        # O(dirty entities) — the incremental alternative to the
        # O(users + brokers + DirectMap) snapshot rebuild. Records are
        # sequence-numbered; a consumer whose cursor predates
        # ``route_log_start`` has a version gap and must rebuild.
        self.route_log: Deque[tuple] = deque()
        self.route_log_start = 0     # seq of route_log[0]
        self.route_log_next = 0      # seq the next record gets

    def _notify_shards(self, event: tuple) -> None:
        if self.shard_notifier is not None:
            self.shard_notifier(event)

    def _log_route(self, kind: str, ident) -> None:
        """Append one typed route delta (and trim the log to its bound)."""
        self.route_log.append((kind, ident))
        self.route_log_next += 1
        if len(self.route_log) > ROUTE_LOG_MAX:
            self.route_log.popleft()
            self.route_log_start += 1

    # ---- users ------------------------------------------------------------

    def add_user(self, public_key: UserPublicKey, connection: Connection,
                 topics: List[Topic],
                 abort_handle: Optional[AbortOnDropHandle] = None) -> None:
        """Register a user: evict any same-broker double-connect, claim the
        user in the DirectMap, and apply initial subscriptions
        (connections/mod.rs add_user)."""
        existing = self.users.pop(public_key, None)
        if existing is not None:
            logger.info("user %s reconnected here; evicting old connection",
                        mnemonic(public_key))
            self._teardown(existing, "evicted by reconnect")
            self.user_topics.remove_key(public_key)
        # a user migrating here from a sibling shard (REUSEPORT lands the
        # reconnect on a different worker) sheds its remote record; the
        # ``user`` delta below makes the old shard evict its stale conn
        if self.remote_user_shard.pop(public_key, None) is not None:
            self.user_topics.remove_key(public_key)
        # elastic re-home arrival (ISSUE 12): the DirectMap still naming
        # ANOTHER broker as owner means this user just migrated here — the
        # insert below out-versions that claim, and the next UserSync delta
        # makes the old home evict its half of the connection
        prev_owner = self.direct_map.get(public_key)
        self.interest_version += 1
        self.users[public_key] = UserHandle(connection, abort_handle)
        if topics:
            self.user_topics.associate_key_with_values(public_key, topics)
        self.direct_map.insert(public_key, self.identity)
        if prev_owner is not None and prev_owner != self.identity:
            connection.flightrec.record("migrate-in", f"from {prev_owner}")
        self._log_route("user", public_key)
        self._log_route("dmap", public_key)
        if self.observer is not None:
            self.observer.on_user_added(public_key, topics)
        self._notify_shards(("user", public_key, list(topics)))
        logger.info("user %s connected (topics=%s)", mnemonic(public_key), topics)

    def remove_user(self, public_key: UserPublicKey,
                    reason: str = "disconnected") -> None:
        handle = self.users.pop(public_key, None)
        if handle is None:
            return
        self.interest_version += 1
        if reason == "user connected elsewhere":
            # elastic re-home (ISSUE 12): flush-then-close, never abort —
            # the client is still draining this connection. The interest
            # rows survive until the parting grace expires (``_part``
            # returns True and owns the deferred cleanup), so LATE
            # broadcasts — routed here by peers whose TopicSync view of
            # the new home still lags — chase the parting connection
            # instead of dropping into a zero-home window.
            deferred = self._part(public_key, handle)
        else:
            self._teardown(handle, reason)
            deferred = False
        if not deferred:
            self.user_topics.remove_key(public_key)
            self._log_route("user", public_key)
        # Release our DirectMap claim only if we still hold it — a newer
        # claim by another broker must not be clobbered.
        self.direct_map.remove_if_equals(public_key, self.identity)
        self._log_route("dmap", public_key)
        if self.observer is not None:
            self.observer.on_user_removed(public_key)
        self._notify_shards(("user_del", public_key))
        logger.info("user %s removed: %s", mnemonic(public_key), reason)

    def has_user(self, public_key: UserPublicKey) -> bool:
        return public_key in self.users

    def get_user_connection(self, public_key: UserPublicKey) -> Optional[Connection]:
        h = self.users.get(public_key)
        if h is not None:
            return h.connection
        # send-time fallback for deliveries routed before a migration
        # eviction landed mid-batch (see ``parting``); new routing
        # decisions never reach here — the interest indexes and the
        # DirectMap already point at the new home
        return self.parting.get(public_key)

    def _part(self, public_key: UserPublicKey, handle) -> bool:
        """Move a migration-evicted user's connection into ``parting``:
        the receive loop is aborted now (nothing further is accepted from
        the old connection), queued deliveries keep flushing to it, and a
        deferred ``soft_close`` drains the writer, FINs, and forgets the
        entry. Without this the egress batch that carried the eviction's
        own UserSync drops every delivery it had already routed to the
        user — a real delivered-message loss window under migration.

        Returns True when the deferred close task was scheduled and owns
        the user's interest-row cleanup (the rows stay live through the
        grace so late-routed broadcasts still reach the parting
        connection); False when everything was torn down synchronously
        and the caller must clean up now."""
        rec = getattr(handle.connection, "flightrec", None)
        if rec is not None:
            # routine under elastic drain — recorded, not dumped
            rec.record("removed", "user connected elsewhere (parting)")
        if handle.abort_handle is not None:
            handle.abort_handle.abort()
        conn = handle.connection
        self.parting[public_key] = conn

        async def _close_later():
            try:
                await asyncio.sleep(PARTING_GRACE_S)
                # the grace is over: whatever the flush below cannot get
                # onto the wire is a counted parting-expiry loss, not a
                # generic teardown (ISSUE 20)
                conn.ledger_drop_reason = "parting_expiry"
                await conn.soft_close()
            finally:
                if self.parting.get(public_key) is conn:
                    del self.parting[public_key]
                    # deferred interest cleanup (see remove_user): the
                    # grace is over — unless the user reconnected HERE
                    # meanwhile (their rows are live again), drop them.
                    # A superseding _part re-entered via the dict guard
                    # above owns its own cleanup.
                    if public_key not in self.users:
                        self.interest_version += 1
                        self.user_topics.remove_key(public_key)
                        self._log_route("user", public_key)

        try:
            asyncio.get_running_loop().create_task(_close_later())
        except RuntimeError:  # no loop (teardown from sync context)
            self.parting.pop(public_key, None)
            conn.close()
            return False
        return True

    @property
    def num_users(self) -> int:
        return len(self.users)

    # ---- brokers ----------------------------------------------------------

    def add_broker(self, identifier: str, connection: Connection,
                   abort_handle: Optional[AbortOnDropHandle] = None) -> None:
        existing = self.brokers.pop(identifier, None)
        if existing is not None:
            logger.info("broker %s reconnected; evicting old link", identifier)
            self._teardown(existing, "evicted by reconnect")
            self.broker_topics.remove_key(identifier)
        self.interest_version += 1
        self.remote_broker_shard.pop(identifier, None)  # now a live link
        # mesh links tag their connection for the conservation ledger:
        # writer dequeues on this link count relayed/mesh, not delivered —
        # and a (re)formed link opens a fresh per-link conservation epoch
        connection.ledger_peer = identifier
        ledger_mod.reset_link(identifier)
        self.brokers[identifier] = BrokerHandle(
            connection, abort_handle,
            topic_sync_map=VersionedMap(local_identity=identifier))
        # the new link also makes DirectMap entries owned by this peer
        # resolvable — RouteState's owner index re-resolves them off this
        # one record
        self._log_route("broker", identifier)
        self._notify_shards(("mesh_topics", identifier, []))
        logger.info("broker %s connected", identifier)

    def remove_broker(self, identifier: str, reason: str = "disconnected") -> None:
        handle = self.brokers.pop(identifier, None)
        if handle is None:
            return
        self._teardown(handle, reason)
        self.interest_version += 1
        self.broker_topics.remove_key(identifier)
        # Forget (locally, without tombstoning) every user the dead peer
        # owned — they will re-appear when they reconnect elsewhere
        # (remove_by_value_no_modify, versioned_map.rs).
        dropped = self.direct_map.remove_by_value_no_modify(identifier)
        self._log_route("broker", identifier)
        # per-dropped-key records, proportional to the actual forget work
        # (a mass drop that outruns the log bound falls back to a rebuild)
        for key in dropped:
            self._log_route("dmap", key)
        self._notify_shards(("mesh_broker_del", identifier))
        logger.info("broker %s removed (%s); forgot %d routed users",
                    identifier, reason, len(dropped))

    def has_broker(self, identifier: str) -> bool:
        return identifier in self.brokers

    def get_broker_connection(self, identifier: str) -> Optional[Connection]:
        h = self.brokers.get(identifier)
        return None if h is None else h.connection

    def all_broker_identifiers(self) -> List[str]:
        return list(self.brokers.keys())

    @property
    def num_brokers(self) -> int:
        return len(self.brokers)

    # ---- subscriptions ----------------------------------------------------

    def subscribe_user_to(self, public_key: UserPublicKey,
                          topics: List[Topic]) -> None:
        if public_key in self.users and topics:
            self.interest_version += 1
            self.users[public_key].connection.flightrec.record(
                "subscribe", topics)
            self.user_topics.associate_key_with_values(public_key, topics)
            self._log_route("user", public_key)
            if self.observer is not None:
                self.observer.on_subscription_changed(
                    public_key, self.user_topics.get_values_of_key(public_key))
            self._notify_shards((
                "user", public_key,
                list(self.user_topics.get_values_of_key(public_key))))

    def unsubscribe_user_from(self, public_key: UserPublicKey,
                              topics: List[Topic]) -> None:
        if topics:
            self.interest_version += 1
            handle = self.users.get(public_key)
            if handle is not None:
                handle.connection.flightrec.record("unsubscribe", topics)
            self.user_topics.dissociate_key_from_values(public_key, topics)
            self._log_route("user", public_key)
            if self.observer is not None:
                self.observer.on_subscription_changed(
                    public_key, self.user_topics.get_values_of_key(public_key))
            if handle is not None:
                self._notify_shards((
                    "user", public_key,
                    list(self.user_topics.get_values_of_key(public_key))))

    def subscribe_broker_to(self, identifier: str, topics: List[Topic]) -> None:
        if identifier in self.brokers and topics:
            self.interest_version += 1
            self.broker_topics.associate_key_with_values(identifier, topics)
            self._log_route("broker", identifier)

    def unsubscribe_broker_from(self, identifier: str,
                                topics: List[Topic]) -> None:
        if topics:
            self.interest_version += 1
            self.broker_topics.dissociate_key_from_values(identifier, topics)
            self._log_route("broker", identifier)

    # ---- sibling-shard delta application (ISSUE 6) -------------------------
    # Called by ShardRuntime.apply_event with state relayed from sibling
    # worker processes; these never re-emit to the shard bus (the parent
    # hub already fans deltas to every other worker).

    def add_remote_user_interest(self, public_key: UserPublicKey,
                                 shard: int, topics: List[Topic]) -> None:
        """ADDITIVE sibling-shard interest row (durable replay handover,
        ISSUE 14): the owner shard applying a ``durable_sub`` must see the
        user's interest BEFORE it snapshots the retention ring, ahead of
        the authoritative full-list "user" delta still in flight on the
        bus. Unlike :meth:`set_remote_user` this never clears existing
        associations (that would open a drop window for the user's other
        topics) and never evicts a local connection. A local user takes
        the ordinary subscribe path instead."""
        if public_key in self.users:
            self.subscribe_user_to(public_key, list(topics))
            return
        if not topics:
            return
        self.interest_version += 1
        self.remote_user_shard.setdefault(public_key, shard)
        self.user_topics.associate_key_with_values(public_key, list(topics))
        if self.shard_id == 0:
            self.direct_map.insert(public_key, self.identity)
            self._log_route("dmap", public_key)
        self._log_route("user", public_key)

    def set_remote_user(self, public_key: UserPublicKey, shard: int,
                        topics: List[Topic]) -> None:
        """A sibling shard owns (or re-announced) this user. Evicts any
        local connection for the same key — the cross-shard flavor of the
        double-connect kick (the user reconnected and SO_REUSEPORT landed
        them on another worker)."""
        if public_key in self.users:
            logger.info("user %s connected on shard %d; evicting local",
                        mnemonic(public_key), shard)
            self.remove_user(public_key,
                             reason=f"user connected on shard {shard}")
        self.interest_version += 1
        self.remote_user_shard[public_key] = shard
        self.user_topics.remove_key(public_key)
        if topics:
            self.user_topics.associate_key_with_values(public_key,
                                                       list(topics))
        if self.shard_id == 0:
            # shard 0 fronts the mesh: its DirectMap replica must claim
            # every shard's users so UserSync advertises the whole box
            self.direct_map.insert(public_key, self.identity)
        self._log_route("user", public_key)
        self._log_route("dmap", public_key)

    def remove_remote_user(self, public_key: UserPublicKey,
                           shard: int) -> None:
        """Sibling user disconnect. ``shard`` guards against reorder with
        a migration: a del from the OLD shard must not clobber the record
        the NEW shard's announcement just installed."""
        if self.remote_user_shard.get(public_key) != shard:
            return
        self.interest_version += 1
        del self.remote_user_shard[public_key]
        self.user_topics.remove_key(public_key)
        if self.shard_id == 0:
            self.direct_map.remove_if_equals(public_key, self.identity)
        self._log_route("user", public_key)
        self._log_route("dmap", public_key)

    def set_remote_broker(self, identifier: str, shard: int,
                          topics: List[Topic]) -> None:
        """Shard ``shard`` (0 — the mesh owner) holds a live link to this
        peer broker; record its advertised topics so broadcasts here plan
        fan-out through the ring to the link-owning shard."""
        if identifier in self.brokers:
            return  # we hold the live link ourselves
        self.interest_version += 1
        self.remote_broker_shard[identifier] = shard
        self.broker_topics.remove_key(identifier)
        if topics:
            self.broker_topics.associate_key_with_values(identifier,
                                                         list(topics))
        self._log_route("broker", identifier)

    def remove_remote_broker(self, identifier: str) -> None:
        self.interest_version += 1
        self.remote_broker_shard.pop(identifier, None)
        self.broker_topics.remove_key(identifier)
        # same local forget as remove_broker: users the dead peer owned
        # reappear when they reconnect elsewhere. The dropped claims get
        # per-key records — the peer may ALSO hold a live local link (no
        # slot transition for the owner index to re-resolve through)
        dropped = self.direct_map.remove_by_value_no_modify(identifier)
        self._log_route("broker", identifier)
        for key in dropped:
            self._log_route("dmap", key)

    @property
    def num_users_global(self) -> int:
        """Users across ALL shards of this broker (what shard 0 reports
        to discovery so the marshal's load balancing sees the box)."""
        return len(self.users) + len(self.remote_user_shard)

    # ---- routing queries --------------------------------------------------

    def get_broker_identifier_of_user(self,
                                      public_key: UserPublicKey) -> Optional[str]:
        """DirectMap lookup (connections/mod.rs:69)."""
        return self.direct_map.get(public_key)

    def get_interested_by_topic(self, topics: List[Topic], to_users_only: bool
                                ) -> Tuple[List[UserPublicKey], List[str]]:
        """Who should receive a broadcast on ``topics``
        (connections/mod.rs:94-124). ``to_users_only=True`` is the
        loop-prevention rule for broker-originated broadcasts."""
        users = list(self.user_topics.get_keys_by_values(topics))
        if to_users_only:
            return users, []
        return users, list(self.broker_topics.get_keys_by_values(topics))

    # ---- sync generation (parity tasks/broker/sync.rs + mod.rs:205-237) ---

    def get_full_user_sync(self) -> bytes:
        return VersionedMap.serialize_entries(self.direct_map.full())

    def get_partial_user_sync(self) -> Optional[bytes]:
        delta = self.direct_map.diff()
        if not delta:
            return None
        return VersionedMap.serialize_entries(delta)

    def _refresh_our_topics(self) -> None:
        """Fold the current local-interest topic set into our topic CRDT
        (set-difference vs previous snapshot, connections/mod.rs:205-237)."""
        current: Set[Topic] = set(self.user_topics.values())
        for t in current - self._previous_local_topics:
            self.our_topic_map.insert(t, int(SubscriptionStatus.SUBSCRIBED))
        for t in self._previous_local_topics - current:
            self.our_topic_map.insert(t, int(SubscriptionStatus.UNSUBSCRIBED))
        self._previous_local_topics = current

    def get_full_topic_sync(self) -> bytes:
        self._refresh_our_topics()
        return VersionedMap.serialize_entries(self.our_topic_map.full())

    def get_partial_topic_sync(self) -> Optional[bytes]:
        self._refresh_our_topics()
        delta = self.our_topic_map.diff()
        if not delta:
            return None
        return VersionedMap.serialize_entries(delta)

    # ---- sync application -------------------------------------------------

    def apply_user_sync(self, payload,
                        from_sibling: bool = False) -> List[UserPublicKey]:
        """Merge a peer's DirectMap delta. Returns local users to EVICT
        because the merge says they are now owned elsewhere — the
        double-connect kick across brokers (connections/mod.rs:154-162).

        ``from_sibling=True`` marks a payload relayed by a sibling shard
        (the mesh links live on shard 0; it forwards every merge): applied
        identically but not re-emitted to the shard bus."""
        incoming = VersionedMap.deserialize_entries(payload)
        changed = self.direct_map.merge(incoming)
        if changed:
            # DirectMap mutations change Direct-routing answers: bump the
            # version so route snapshots (cut-through plan tables, batch
            # interest caches) can't serve a pre-merge owner. The scalar
            # interest caches key only on topic queries, which a DirectMap
            # merge can't affect, so the extra bump is conservative there.
            self.interest_version += 1
            if not from_sibling:
                self._notify_shards(("usersync", bytes(payload)))
        evict: List[UserPublicKey] = []
        for key, _old, new in changed:
            self._log_route("dmap", key)
            if new is not None and new != self.identity and key in self.users:
                evict.append(key)
            if new is not None and new != self.identity:
                # a user the mesh now places on ANOTHER broker can't be a
                # sibling-shard resident either: drop the stale record so
                # routing stops ring-forwarding to a shard that lost it
                if self.remote_user_shard.pop(key, None) is not None:
                    self.user_topics.remove_key(key)
                    self._log_route("user", key)
        for key in evict:
            logger.info("user %s connected elsewhere (%s); evicting",
                        mnemonic(key), self.direct_map.get(key))
            self.remove_user(key, reason="user connected elsewhere")
        return evict

    def apply_topic_sync(self, from_broker: str, payload) -> None:
        """Merge a peer's advertised-topic delta into its per-broker map and
        mirror the result into the broker interest index
        (connections/mod.rs:165-191)."""
        handle = self.brokers.get(from_broker)
        if handle is None:
            return
        handle.connection.flightrec.record("topic-sync",
                                           f"{len(payload)} B")
        incoming = VersionedMap.deserialize_entries(payload)
        changed = handle.topic_sync_map.merge(incoming)
        for topic, _old, new in changed:
            if new == int(SubscriptionStatus.SUBSCRIBED):
                self.subscribe_broker_to(from_broker, [int(topic)])
            else:
                self.unsubscribe_broker_from(from_broker, [int(topic)])
        if changed:
            self._notify_shards((
                "mesh_topics", from_broker,
                list(self.broker_topics.get_values_of_key(from_broker))))

    # ---- teardown ---------------------------------------------------------

    # removal reasons that mean "something went wrong" — they arm the
    # connection's flight recorder so its trail hits the diagnostics log
    _ABNORMAL_REASONS = frozenset(
        ("send failed", "user connected elsewhere"))

    @classmethod
    def _teardown(cls, handle, reason: str = "disconnected") -> None:
        rec = getattr(handle.connection, "flightrec", None)
        if rec is not None:
            rec.record("removed", reason,
                       abnormal=reason in cls._ABNORMAL_REASONS)
            rec.maybe_dump(reason)
        if reason == "send failed":
            # failure-is-removal: frames the writer drains now take the
            # send_failed fate, not the generic teardown one (ISSUE 20)
            handle.connection.ledger_drop_reason = "send_failed"
        if handle.abort_handle is not None:
            handle.abort_handle.abort()
        try:
            handle.connection.close()
        except Exception:
            pass

    def remove_all(self) -> None:
        for key in list(self.users):
            self.remove_user(key, "broker shutdown")
        for ident in list(self.brokers):
            self.remove_broker(ident, "broker shutdown")
        for conn in self.parting.values():
            conn.close()  # shutdown outruns the deferred soft_close
        self.parting.clear()
