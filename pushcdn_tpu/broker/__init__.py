"""The broker: routing state (CRDT maps) + task runtime.

Capability parity with the reference's ``cdn-broker`` crate (SURVEY.md §2b):
a state plane (``connections``: users map, brokers map, DirectMap CRDT,
broadcast subscription indexes) and a task plane (heartbeat, sync,
whitelist, user listener, broker listener + one receive loop per
connection), supervised fail-fast.

TPU lowering: the same routing state also exists as a *vectorized twin*
(owner-table and topic-bitmask tensors, pushcdn_tpu.parallel) so the data
plane can route entirely on-device over a broker mesh.
"""

