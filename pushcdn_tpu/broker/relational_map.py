"""``RelationalMap`` — a bidirectional multimap key ⇄ topics for O(1)-ish
interest lookups.

Capability parity with cdn-broker/src/connections/broadcast/relational_map.rs:14-116:
forward index (key → topic set) and inverse index (topic → key set) kept in
lockstep; used both for local users and for peer brokers.

TPU twin: on-device this is the per-connection topic **bitmask tensor**
(connections × topic-bits), where "who is interested in topic t" is a
vectorized mask test instead of a hash lookup (pushcdn_tpu.parallel).
"""

from __future__ import annotations

from typing import Dict, Generic, Hashable, Iterable, List, Set, TypeVar

K = TypeVar("K", bound=Hashable)
T = TypeVar("T", bound=Hashable)


class RelationalMap(Generic[K, T]):
    def __init__(self):
        self._forward: Dict[K, Set[T]] = {}
        self._inverse: Dict[T, Set[K]] = {}

    def associate_key_with_values(self, key: K, values: Iterable[T]) -> None:
        fwd = self._forward.setdefault(key, set())
        for v in values:
            fwd.add(v)
            self._inverse.setdefault(v, set()).add(key)

    def dissociate_key_from_values(self, key: K, values: Iterable[T]) -> None:
        fwd = self._forward.get(key)
        if fwd is None:
            return
        for v in values:
            fwd.discard(v)
            inv = self._inverse.get(v)
            if inv is not None:
                inv.discard(key)
                if not inv:
                    del self._inverse[v]
        if not fwd:
            del self._forward[key]

    def remove_key(self, key: K) -> Set[T]:
        """Drop ``key`` entirely; returns the values it was associated with."""
        fwd = self._forward.pop(key, set())
        for v in fwd:
            inv = self._inverse.get(v)
            if inv is not None:
                inv.discard(key)
                if not inv:
                    del self._inverse[v]
        return fwd

    def get_values_of_key(self, key: K) -> Set[T]:
        return set(self._forward.get(key, ()))

    def get_keys_by_value(self, value: T) -> Set[K]:
        return set(self._inverse.get(value, ()))

    def get_keys_by_values(self, values: Iterable[T]) -> Set[K]:
        """Union of interested keys over ``values`` (the broadcast interest
        query, connections/mod.rs:94-124)."""
        out: Set[K] = set()
        for v in values:
            out |= self._inverse.get(v, set())
        return out

    def keys(self) -> List[K]:
        return list(self._forward.keys())

    def values(self) -> List[T]:
        """All values with ≥1 associated key — O(distinct values), straight
        off the inverse index (used for 'which topics have local interest')."""
        return list(self._inverse.keys())

    def __contains__(self, key: K) -> bool:
        return key in self._forward

    def __len__(self) -> int:
        return len(self._forward)

    def check_invariants(self) -> bool:
        """Test hook: forward and inverse indexes agree exactly (parity with
        the invariant tests at relational_map.rs:119-347)."""
        for k, vs in self._forward.items():
            for v in vs:
                if k not in self._inverse.get(v, set()):
                    return False
        for v, ks in self._inverse.items():
            if not ks:
                return False
            for k in ks:
                if v not in self._forward.get(k, set()):
                    return False
        return True
