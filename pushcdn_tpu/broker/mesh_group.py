"""MeshBrokerGroup — N broker shards whose inter-broker traffic rides the
device mesh instead of host links.

This is the BASELINE.json north star wired into the broker runtime: each
broker in the group is one shard of a ``jax.sharding.Mesh`` over the
``"brokers"`` axis; the group pump coalesces every shard's staged frames
and runs ONE jitted ``shard_map`` routing step per tick, in which

- the inter-broker hop is the step's ``all_gather`` over ICI (replacing
  the reference's per-peer TCP writes, SURVEY.md §2e row 1-2),
- cross-shard direct routing is delivery-iff-owner (one hop, loop-free by
  construction),
- broadcast interest is the topic-bitmask kernel against the global user
  table.

Host TCP/memory broker links remain as the **fallback plane**: brokers in
a group still heartbeat/dial each other, and if a device step ever fails
the staged batches are re-routed over those links and the group disables
itself (fail-open to the reference's architecture).

Consistency: one process = one source of truth. The group owns the GLOBAL
user-slot table and mirrors (owner shard, claim version, topic mask per
slot), mutated only on the event loop via each shard's observer facade
(:class:`MeshShardPlane`). Steps snapshot mirrors + all rings in one tick
(same discipline as the single-shard DevicePlane). In-group double
connects are authoritative at claim time: the previous owning shard's
session is kicked immediately ("user connected elsewhere"). On a real
multi-host pod each host would hold only its shard's claims and the
in-step CRDT merge would do the convergence — the device program is the
same either way (it already property-matches the host VersionedMap).
"""

from __future__ import annotations

import asyncio
import logging
from dataclasses import dataclass
from typing import TYPE_CHECKING, Dict, List, Optional

import numpy as np

from pushcdn_tpu.broker.tasks.senders import egress_delivery_rows
from pushcdn_tpu.parallel.crdt import ABSENT, CrdtState
from pushcdn_tpu.parallel.frames import (
    TOPIC_WORDS_FULL,
    DirectBuckets,
    FrameRing,
    UserSlots,
    mask_mirror_shape,
    mask_of_topics,
    mask_row_of,
    stage_best_fit,
)
from pushcdn_tpu.parallel.router import (
    BROKER_AXIS,
    DirectIngress,
    IngressBatch,
    RouterState,
    make_mesh_lane_step,
)
from pushcdn_tpu.proto.error import Error
from pushcdn_tpu.proto.limiter import Bytes
from pushcdn_tpu.proto.message import Broadcast, Direct

if TYPE_CHECKING:
    from pushcdn_tpu.broker.broker import Broker

logger = logging.getLogger("pushcdn.broker.meshgroup")


@dataclass
class MeshGroupConfig:
    num_user_slots: int = 1024
    ring_slots: int = 256          # per shard per step (broadcast all_gather)
    direct_bucket_slots: int = 64  # per shard per DESTINATION per step
    frame_bytes: int = 2048
    # Size-bucketed lanes beyond the base lane (SURVEY.md §7 hard-part #1):
    # (frame_bytes, ring_slots, direct_bucket_slots) per entry. Frames stage
    # into the smallest lane they fit, so big proposals ride ICI without
    # padding every small ack to the widest slot.
    extra_lanes: tuple = ((16384, 32, 8),)
    # u32 words per topic mask: 8 covers the reference's whole u8 topic
    # space; 1 keeps compact masks for deployments with ≤32 topics
    topic_words: int = TOPIC_WORDS_FULL
    batch_window_s: float = 0.001

    def lane_shapes(self):
        """All lanes as (frame_bytes, ring_slots, direct_bucket_slots),
        ascending by frame width."""
        return sorted(
            ((self.frame_bytes, self.ring_slots, self.direct_bucket_slots),)
            + tuple(self.extra_lanes))


class MeshShardPlane:
    """Per-broker facade: the Connections observer + staging interface for
    one shard. Duck-compatible with DevicePlane where handlers.py cares."""

    covers_brokers = True  # staged broadcasts reach mesh peers over ICI

    def __init__(self, group: "MeshBrokerGroup", shard: int):
        self.group = group
        self.shard = shard

    # Connections observer protocol --------------------------------------
    def on_user_added(self, public_key: bytes, topics) -> None:
        self.group.claim_user(self.shard, public_key, topics)

    def on_user_removed(self, public_key: bytes) -> None:
        self.group.release_user(self.shard, public_key)

    def on_subscription_changed(self, public_key: bytes, topics) -> None:
        self.group.update_mask(self.shard, public_key, topics)

    # staging -------------------------------------------------------------
    def try_stage(self, message, raw: Bytes):
        return self.group.try_stage(self.shard, message, raw)

    def stage_batch(self, items):
        return self.group.stage_batch(self.shard, items)

    def covered_broker_idents(self) -> set:
        """Identifiers of the group's member brokers — the mesh step covers
        delivery to them, so the host path must not also forward (but MUST
        still forward to interested OUT-of-group brokers)."""
        return self.group.member_idents()

    # lifecycle (driven by the owning broker's start/stop)
    async def start(self) -> None:
        await self.group.ensure_started()

    async def stop(self) -> None:
        await self.group.on_shard_stopped(self.shard)

    @property
    def disabled(self) -> bool:
        return self.group.disabled

    @property
    def overflow_seen(self) -> bool:
        return self.group.overflow_seen

    @property
    def steps(self) -> int:
        return self.group.steps

    @property
    def messages_routed(self) -> int:
        return self.group.messages_routed


class MeshBrokerGroup:
    def __init__(self, mesh, config: MeshGroupConfig = None):
        self.mesh = mesh
        self.config = config or MeshGroupConfig()
        c = self.config
        self.num_shards = mesh.devices.size
        self.step_fn = make_mesh_lane_step(mesh)
        self.brokers: List[Optional["Broker"]] = [None] * self.num_shards
        # lane_rings[lane][shard] — size-bucketed broadcast staging
        self.lane_rings = [
            [FrameRing(slots=s, frame_bytes=f, topic_words=c.topic_words)
             for _ in range(self.num_shards)]
            for f, s, _d in c.lane_shapes()]
        # direct frames go into per-destination-shard buckets and cross the
        # mesh with one all_to_all per lane (router.DirectIngress) instead
        # of riding the broadcast all_gather to every shard
        self.lane_buckets = [
            [DirectBuckets(self.num_shards, capacity=d, frame_bytes=f)
             for _ in range(self.num_shards)]
            for f, _s, d in c.lane_shapes()]
        # global user table + mirrors (single source of truth)
        self.slots = UserSlots(c.num_user_slots)
        self._owner = np.full(c.num_user_slots, ABSENT, np.int32)
        self._claim_version = np.zeros(c.num_user_slots, np.uint32)
        # mask shape tracks the configured topic-space width
        self._masks = np.zeros(
            mask_mirror_shape(c.num_user_slots, c.topic_words), np.uint32)
        self._quarantine: List[int] = []
        # users the slot table couldn't hold, keyed to their shard so a
        # dead shard's entries can be swept (a crash fires no releases)
        self._unmirrored: Dict[bytes, int] = {}
        # dynamic membership over the static mesh (hard-part #3): a stopped
        # shard is masked dead in-step rather than re-forming the mesh
        self._liveness = np.zeros(self.num_shards, bool)
        self.disabled = False
        # set when traffic falls outside what the mesh step can carry —
        # heartbeats then form host links even in mesh-only deployments
        self.overflow_seen = False
        self._kick = asyncio.Event()
        self._task: Optional[asyncio.Task] = None
        self._started = False
        self._state_dirty = False  # forces a step with no staged traffic
        self.steps = 0
        self.messages_routed = 0

    # ---- wiring ----------------------------------------------------------

    def attach(self, broker: "Broker", shard: int) -> MeshShardPlane:
        """Make ``broker`` shard ``shard`` of this group (call after
        Broker.new, before Broker.start)."""
        plane = MeshShardPlane(self, shard)
        self.brokers[shard] = broker
        self._liveness[shard] = True
        broker.device_plane = plane
        broker.connections.observer = plane
        self._member_idents = None  # recompute lazily
        return plane

    def member_idents(self) -> set:
        idents = getattr(self, "_member_idents", None)
        if idents is None:
            idents = {str(b.identity) for b in self.brokers if b is not None}
            self._member_idents = idents
        return idents

    async def ensure_started(self) -> None:
        if not self._started:
            self._started = True
            # compile the step off the hot path: the first jitted shard_map
            # trace can take seconds; rings must not saturate behind it
            await asyncio.to_thread(self._warmup)
            self._task = asyncio.create_task(self._pump(), name="mesh-group-pump")

    def _warmup(self) -> None:
        # empty, right shapes: [lane][shard]
        batches = [[r.take_batch() for r in rings] for rings in self.lane_rings]
        directs = [[b.take_batch() for b in bkts] for bkts in self.lane_buckets]
        try:
            # compile the two common lane subsets: everything busy, and
            # base-lane-only (steady state for small messages)
            self._run_step(batches, directs, self._owner.copy(),
                           self._claim_version.copy(), self._masks.copy(),
                           keep_idle_lanes=True)
            self._run_step(batches[:1], directs[:1], self._owner.copy(),
                           self._claim_version.copy(), self._masks.copy(),
                           keep_idle_lanes=True)
            self.steps -= 2  # warmup doesn't count
        except Exception:
            logger.exception("mesh-group warmup step failed")
            self.disabled = True

    async def on_shard_stopped(self, shard: int) -> None:
        self.brokers[shard] = None
        self._liveness[shard] = False
        self._member_idents = None
        # Release every slot the dead shard still owned: a crashed broker
        # never fires per-user removals, and without this sweep directs to
        # its users would be acked STAGED and dropped at the tombstone
        # (and the slot table would leak). With the mapping gone,
        # try_stage sees an unknown recipient and overflows to the host
        # path — the same "failure is an I/O error, route around it"
        # posture as the reference.
        for slot in np.nonzero(self._owner == shard)[0]:
            key = self.slots.key_of(int(slot))
            if key is not None:
                self.slots.unmap(key)
            self._owner[slot] = ABSENT
            self._claim_version[slot] += 1
            self._masks[slot] = 0
            self._quarantine.append(int(slot))
        # unmirrored users of the dead shard would otherwise pin every
        # broadcast to the host path forever
        for key in [k for k, s in self._unmirrored.items() if s == shard]:
            del self._unmirrored[key]
        # wake the pump even with no staged traffic: the tombstoned release
        # must reach the device CRDT, already-staged frames to the dead
        # shard must be flushed (dropped at the tombstone), and the
        # quarantined slots must return to the free list
        self._state_dirty = True
        self._kick.set()
        if all(b is None for b in self.brokers) and self._task is not None:
            self._task.cancel()
            try:
                await self._task
            except asyncio.CancelledError:
                pass
            except Exception:
                logger.exception("mesh-group pump died during stop")
            self._task = None
            self._started = False

    # ---- mirrors (event-loop only) ---------------------------------------

    def claim_user(self, shard: int, public_key: bytes, topics) -> None:
        try:
            slot = self.slots.assign(public_key)
        except Error:
            self._unmirrored[public_key] = shard
            logger.warning("mesh-group slot table full; %d unmirrored",
                           len(self._unmirrored))
            return
        prev = int(self._owner[slot])
        if prev != ABSENT and prev != shard:
            # in-group double connect: kick the old session immediately
            # (the host CRDT handles out-of-group brokers)
            old = self.brokers[prev]
            if old is not None and old.connections.has_user(public_key):
                logger.info("user connected elsewhere in group (shard %d -> %d)",
                            prev, shard)
                old.connections.remove_user(
                    public_key, reason="user connected elsewhere")
                # removal via the old shard's observer released the slot;
                # re-assign for the new owner (the freed slot is quarantined
                # until the next step, so a full table can fail here too)
                try:
                    slot = self.slots.assign(public_key)
                except Error:
                    self._unmirrored[public_key] = shard
                    logger.warning(
                        "mesh-group slot table full after in-group kick; "
                        "%d unmirrored", len(self._unmirrored))
                    return
        self._owner[slot] = shard
        self._claim_version[slot] += 1
        self._masks[slot] = mask_row_of(topics, self.config.topic_words)

    def release_user(self, shard: int, public_key: bytes) -> None:
        self._unmirrored.pop(public_key, None)
        slot = self.slots.slot_of(public_key)
        if slot is None or int(self._owner[slot]) != shard:
            return  # not ours (already taken over by another shard)
        self.slots.unmap(public_key)
        self._owner[slot] = ABSENT
        self._claim_version[slot] += 1
        self._masks[slot] = 0
        self._quarantine.append(slot)

    def update_mask(self, shard: int, public_key: bytes, topics) -> None:
        slot = self.slots.slot_of(public_key)
        if slot is not None and int(self._owner[slot]) == shard:
            self._masks[slot] = mask_row_of(topics, self.config.topic_words)

    # ---- staging ----------------------------------------------------------

    def _overflow(self):
        """Traffic the mesh step can't carry must ride host links: flag it
        and wake every member's heartbeat so those links form promptly."""
        from pushcdn_tpu.broker.staging import StageResult
        if not self.overflow_seen:
            self.overflow_seen = True
            logger.info("mesh-group overflow traffic; host links requested")
        for b in self.brokers:
            if b is not None:
                b.host_links_kick.set()
        return StageResult.INELIGIBLE

    def try_stage(self, shard: int, message, raw: Bytes):
        from pushcdn_tpu.broker.staging import StageResult
        if self.disabled:
            return StageResult.INELIGIBLE
        frame = bytes(raw.data)
        if len(frame) > self.lane_rings[-1][shard].frame_bytes:
            return self._overflow()
        if isinstance(message, Broadcast):
            if self._unmirrored:
                return self._overflow()
            if any(int(t) >= 32 * self.config.topic_words
                   for t in message.topics):
                return self._overflow()
            mask = mask_of_topics(message.topics, self.config.topic_words)
            if mask == 0:
                return StageResult.INELIGIBLE  # no valid topics: no-op send
            ok = stage_best_fit(
                [rings[shard] for rings in self.lane_rings], len(frame),
                lambda r: r.push_broadcast(frame, mask))
        elif isinstance(message, Direct):
            slot = self.slots.slot_of(bytes(message.recipient))
            if slot is None:
                # outside the group: legitimately the host path's job
                return self._overflow()
            owner = int(self._owner[slot])
            if owner == ABSENT:
                return self._overflow()
            # one-hop ICI path: bucket by owner shard for the all_to_all
            ok = stage_best_fit(
                [bkts[shard] for bkts in self.lane_buckets], len(frame),
                lambda b: b.push(owner, frame, slot))
        else:
            return StageResult.INELIGIBLE
        if ok:
            self._kick.set()
            return StageResult.STAGED
        return StageResult.FULL

    def stage_batch(self, shard: int, items):
        """Batch staging for one member shard: broadcasts are grouped per
        size lane and packed with ONE ``FrameRing.push_batch`` per lane
        (the C framing kernel, multi-word masks included); directs keep
        the per-frame owner-bucket push (each lands in a different
        [dest][slot] cell, so there is no contiguous batch to pack).
        Returns per-item ``StageResult``s aligned with ``items``."""
        from pushcdn_tpu.broker.staging import StageResult
        results = [StageResult.INELIGIBLE] * len(items)
        if self.disabled:
            return results
        groups: dict[int, list] = {}
        rings = [lane[shard] for lane in self.lane_rings]
        free = [r.free_slots for r in rings]
        widest = rings[-1].frame_bytes
        staged_any = False
        for idx, (message, raw) in enumerate(items):
            frame = bytes(raw.data)
            if len(frame) > widest:
                self._overflow()
                continue
            if isinstance(message, Broadcast):
                if self._unmirrored or any(
                        int(t) >= 32 * self.config.topic_words
                        for t in message.topics):
                    self._overflow()
                    continue
                mask = mask_of_topics(message.topics,
                                      self.config.topic_words)
                if mask == 0:
                    continue  # no valid topics: no-op send
                placed = False
                for li, ring in enumerate(rings):
                    if len(frame) <= ring.frame_bytes and free[li] > 0:
                        free[li] -= 1
                        groups.setdefault(li, []).append((idx, frame, mask))
                        placed = True
                        break
                results[idx] = (StageResult.STAGED if placed
                                else StageResult.FULL)
            elif isinstance(message, Direct):
                slot = self.slots.slot_of(bytes(message.recipient))
                owner = ABSENT if slot is None else int(self._owner[slot])
                if slot is None or owner == ABSENT:
                    self._overflow()
                    continue
                ok = stage_best_fit(
                    [bkts[shard] for bkts in self.lane_buckets], len(frame),
                    lambda b: b.push(owner, frame, slot))
                results[idx] = (StageResult.STAGED if ok
                                else StageResult.FULL)
                staged_any = staged_any or ok
        from pushcdn_tpu.proto.message import KIND_BROADCAST
        for li, group in groups.items():
            n = rings[li].push_batch(
                [g[1] for g in group],
                [KIND_BROADCAST] * len(group),
                [g[2] for g in group],
                [-1] * len(group))
            staged_any = staged_any or n > 0
            for idx, *_ in group[n:]:
                results[idx] = StageResult.FULL
        if staged_any:
            self._kick.set()
        return results

    # ---- the pump ---------------------------------------------------------

    async def _pump(self) -> None:
        while True:
            await self._kick.wait()
            self._kick.clear()
            await asyncio.sleep(self.config.batch_window_s)
            if not self._state_dirty and \
                    all(r.free_slots == r.slots
                        for rings in self.lane_rings for r in rings) and \
                    all(b.total_used == 0
                        for bkts in self.lane_buckets for b in bkts):
                continue
            self._state_dirty = False
            # one-tick snapshot: all lanes' rings + buckets + mirrors
            batches = [[r.take_batch() for r in rings]
                       for rings in self.lane_rings]
            directs = [[b.take_batch() for b in bkts]
                       for bkts in self.lane_buckets]
            owner = self._owner.copy()
            versions = self._claim_version.copy()
            masks = self._masks.copy()
            liveness = self._liveness.copy()
            quarantined, self._quarantine = self._quarantine, []
            try:
                lanes, direct_lanes = await asyncio.to_thread(
                    self._run_step, batches, directs, owner, versions, masks,
                    liveness)
                for deliver, lengths, frames in lanes:
                    self._egress(deliver, lengths, frames)
                for deliver, lengths, frames in direct_lanes:
                    self._egress(deliver, lengths, frames)
            except asyncio.CancelledError:
                raise
            except Exception:
                logger.exception(
                    "mesh-group step failed; re-routing batches over host "
                    "links and disabling the group")
                self.disabled = True
                # frames staged (and acked as STAGED) while the failing step
                # ran in the worker thread sit in the fresh rings — drain
                # them too, or they'd be lost with no fallback
                late = [[r.take_batch() for r in rings]
                        for rings in self.lane_rings]
                late_d = [[b.take_batch() for b in bkts]
                          for bkts in self.lane_buckets]
                for lane in batches + late:
                    await self._host_fallback(lane)
                for lane in directs + late_d:
                    await self._host_fallback_direct(lane)
                return
            finally:
                for slot in quarantined:
                    self.slots.free_slot(slot)

    def _run_step(self, batches, directs, owner, versions, masks,
                  liveness=None, keep_idle_lanes: bool = False):
        """Blocking multi-shard device step (worker thread). ``batches`` and
        ``directs`` are [lane][shard] host snapshots; busy lanes ride ONE
        jitted shard_map program with one shared CRDT merge. Lanes idle on
        EVERY shard are dropped before the H2D transfer (an empty lane
        delivers nothing; each lane subset is its own cached jit
        specialization), so an idle wide lane costs no ICI traffic."""
        import jax.numpy as jnp
        B = self.num_shards
        if not keep_idle_lanes:
            batches = [lane for lane in batches
                       if any(b.valid.any() for b in lane)]
            directs = [lane for lane in directs
                       if any(d.valid.any() for d in lane)]
        # every shard's state row is the (shared) global view; on real
        # multi-host pods these rows diverge and the in-step merge converges
        # them — the device program is identical
        owners_b = np.broadcast_to(owner, (B,) + owner.shape)
        versions_b = np.broadcast_to(versions, (B,) + versions.shape)
        ids_b = owners_b  # conflict identity = owning shard index
        masks_b = np.broadcast_to(masks, (B,) + masks.shape)
        state = RouterState(
            crdt=CrdtState(jnp.asarray(owners_b), jnp.asarray(versions_b),
                           jnp.asarray(ids_b)),
            topic_masks=jnp.asarray(masks_b))
        lane_batches = tuple(
            IngressBatch(
                jnp.asarray(np.stack([b.bytes_ for b in lane])),
                jnp.asarray(np.stack([b.kind for b in lane])),
                jnp.asarray(np.stack([b.length for b in lane])),
                jnp.asarray(np.stack([b.topic_mask for b in lane])),
                jnp.asarray(np.stack([b.dest for b in lane])),
                jnp.asarray(np.stack([b.valid for b in lane])))
            for lane in batches)
        lane_directs = tuple(
            DirectIngress(
                jnp.asarray(np.stack([d.bytes_ for d in lane])),
                jnp.asarray(np.stack([d.length for d in lane])),
                jnp.asarray(np.stack([d.dest for d in lane])),
                jnp.asarray(np.stack([d.valid for d in lane])))
            for lane in directs)
        live = (np.ones(B, bool) if liveness is None else liveness)
        result = self.step_fn(state, lane_batches, lane_directs,
                              jnp.asarray(np.broadcast_to(live, (B, B))))
        self.steps += 1
        lanes = [(np.asarray(l.deliver), np.asarray(l.gathered_length),
                  np.asarray(l.gathered_bytes)) for l in result.lanes]
        direct_lanes = [(np.asarray(l.deliver), np.asarray(l.gathered_length),
                         np.asarray(l.gathered_bytes))
                        for l in result.direct_lanes]
        return lanes, direct_lanes

    def _egress(self, deliver, lengths, frames) -> None:
        for shard in range(self.num_shards):
            broker = self.brokers[shard]
            if broker is None:
                continue
            users, frame_idx = np.nonzero(deliver[shard])
            cache: Dict[int, Bytes] = {}

            def frame_of(f: int) -> Bytes:
                raw = cache.get(f)
                if raw is None:
                    raw = Bytes(
                        frames[shard, f, :lengths[shard, f]].tobytes())
                    cache[f] = raw
                return raw

            self.messages_routed += egress_delivery_rows(
                broker, self.slots, users, frame_idx, frame_of)
            for raw in cache.values():
                raw.release()

    async def _host_fallback(self, batches) -> None:
        """Re-route every staged frame over the host plane (brokers keep
        their TCP/memory mesh links as backup)."""
        from pushcdn_tpu.broker.tasks.handlers import (
            handle_broadcast_message,
            handle_direct_message,
        )
        from pushcdn_tpu.proto.message import deserialize
        members = self.member_idents()
        for shard, b in enumerate(batches):
            broker = self.brokers[shard]
            if broker is None:
                continue
            # Staged broadcasts were ALREADY forwarded to interested
            # out-of-group brokers at staging time (the stage-time exclude
            # set covers only group members) — re-forwarding here would
            # deliver those subscribers a second copy. The fallback only
            # owes what the failed step owed: local users + group members.
            out_of_group = frozenset(
                ident for ident in broker.connections.all_broker_identifiers()
                if ident not in members)
            for i in range(len(b.valid)):
                if not b.valid[i]:
                    continue
                raw = Bytes(b.bytes_[i, :b.length[i]].tobytes())
                try:
                    message = deserialize(raw.data)
                    if isinstance(message, Direct):
                        await handle_direct_message(
                            broker, bytes(message.recipient), raw,
                            to_user_only=False)
                    elif isinstance(message, Broadcast):
                        await handle_broadcast_message(
                            broker, list(message.topics), raw,
                            to_users_only=False,
                            exclude_brokers=out_of_group)
                except Error:
                    pass
                finally:
                    raw.release()

    async def _host_fallback_direct(self, directs) -> None:
        """Re-route staged direct-bucket frames over the host plane (the
        recipient is in the wire frame; bucket geometry doesn't matter)."""
        from pushcdn_tpu.broker.tasks.handlers import handle_direct_message
        from pushcdn_tpu.proto.message import deserialize
        for shard, d in enumerate(directs):
            broker = self.brokers[shard]
            if broker is None:
                continue
            dests, idx = np.nonzero(d.valid)
            for b_dest, i in zip(dests.tolist(), idx.tolist()):
                raw = Bytes(d.bytes_[b_dest, i, :d.length[b_dest, i]].tobytes())
                try:
                    message = deserialize(raw.data)
                    if isinstance(message, Direct):
                        await handle_direct_message(
                            broker, bytes(message.recipient), raw,
                            to_user_only=False)
                except Error:
                    pass
                finally:
                    raw.release()

