"""MeshBrokerGroup — N broker shards whose inter-broker traffic rides the
device mesh instead of host links.

This is the BASELINE.json north star wired into the broker runtime: each
broker in the group is one shard of a ``jax.sharding.Mesh`` over the
``"brokers"`` axis; the group pump coalesces every shard's staged frames
and runs ONE jitted ``shard_map`` routing step per tick, in which

- the inter-broker hop is the step's ``all_gather`` over ICI (replacing
  the reference's per-peer TCP writes, SURVEY.md §2e row 1-2),
- cross-shard direct routing is delivery-iff-owner (one hop, loop-free by
  construction),
- broadcast interest is the topic-bitmask kernel against the global user
  table.

Host TCP/memory broker links remain as the **fallback plane**: brokers in
a group still heartbeat/dial each other, and if a device step ever fails
the staged batches are re-routed over those links and the group disables
itself (fail-open to the reference's architecture).

Consistency: one process = one source of truth. The group owns the GLOBAL
user-slot table and mirrors (owner shard, claim version, topic mask per
slot), mutated only on the event loop via each shard's observer facade
(:class:`MeshShardPlane`). Steps snapshot mirrors + all rings in one tick
(same discipline as the single-shard DevicePlane). In-group double
connects are authoritative at claim time: the previous owning shard's
session is kicked immediately ("user connected elsewhere"). On a real
multi-host pod each host would hold only its shard's claims and the
in-step CRDT merge would do the convergence — the device program is the
same either way (it already property-matches the host VersionedMap).
"""

from __future__ import annotations

import asyncio
import logging
from dataclasses import dataclass
from typing import TYPE_CHECKING, Dict, List, Optional

import numpy as np

from pushcdn_tpu.broker.pump_common import (
    CoalesceGate,
    RevCache,
    TopicMaskCache,
    effective_users,
)
from pushcdn_tpu.broker.tasks.senders import (
    egress_delivery_rows,
    egress_streams,
)
from pushcdn_tpu.parallel.crdt import ABSENT, CrdtState
from pushcdn_tpu.parallel.frames import (
    TOPIC_WORDS_FULL,
    DirectBuckets,
    FrameRing,
    UserSlots,
    mask_mirror_shape,
    mask_row_of,
    slice_batch,
    slice_direct_batch,
    stage_best_fit,
)
from pushcdn_tpu.parallel.router import (
    BROKER_AXIS,
    DirectIngress,
    IngressBatch,
    RouterState,
    make_mesh_lane_step,
)
from pushcdn_tpu.proto.error import Error
from pushcdn_tpu.proto.limiter import Bytes
from pushcdn_tpu.proto.message import Broadcast, Direct

if TYPE_CHECKING:
    from pushcdn_tpu.broker.broker import Broker

logger = logging.getLogger("pushcdn.broker.meshgroup")


@dataclass
class MeshGroupConfig:
    num_user_slots: int = 1024
    ring_slots: int = 256          # per shard per step (broadcast all_gather)
    direct_bucket_slots: int = 64  # per shard per DESTINATION per step
    frame_bytes: int = 2048
    # Size-bucketed lanes beyond the base lane (SURVEY.md §7 hard-part #1):
    # (frame_bytes, ring_slots, direct_bucket_slots) per entry. Frames stage
    # into the smallest lane they fit, so big proposals ride ICI without
    # padding every small ack to the widest slot.
    extra_lanes: tuple = ((16384, 32, 8),)
    # u32 words per topic mask: 8 covers the reference's whole u8 topic
    # space; 1 keeps compact masks for deployments with ≤32 topics
    topic_words: int = TOPIC_WORDS_FULL
    # Adaptive coalescing: a step fires immediately when staged traffic is
    # at least ``coalesce_min_frames`` OR the pump has been idle (burst
    # start — the latency regime pays no window at all); a steady trickle
    # below the threshold waits ``batch_window_s`` to amortize step cost.
    batch_window_s: float = 0.001
    coalesce_min_frames: int = 16
    # When everything staged fits in the first ``latency_slots`` slots of
    # every ring/bucket, the step runs on prefix-sliced shapes — a separate
    # (cached) jit specialization whose collectives move ~1/16th the bytes,
    # cutting sparse-traffic step latency several-fold.
    latency_slots: int = 8
    # Single-host groups skip the frame-byte collectives entirely: all
    # shards' staged frames live in this process, so only the delivery
    # DECISION rides the mesh; egress reads payloads from the host ring
    # snapshots (router.routing_step_lanes gather_bytes docs). Multi-host
    # deployments set this True.
    gather_frame_bytes: bool = False
    # One sharding-aware collective per tick: every gathered leaf (CRDT
    # state, lane metadata, direct buckets — frame bytes too when
    # ``gather_frame_bytes``) is packed into one u32 buffer and moved by a
    # single all_gather, the all_to_all folded in as gather+local-slice
    # (router._routing_step_lanes_fused). Off restores the per-array
    # collective schedule — the right call for byte-gathering multi-host
    # pods where the fused form pays B-fold redundancy on direct payloads.
    fused_collective: bool = True

    def lane_shapes(self):
        """All lanes as (frame_bytes, ring_slots, direct_bucket_slots),
        ascending by frame width."""
        return sorted(
            ((self.frame_bytes, self.ring_slots, self.direct_bucket_slots),)
            + tuple(self.extra_lanes))


class MeshShardPlane:
    """Per-broker facade: the Connections observer + staging interface for
    one shard. Duck-compatible with DevicePlane where handlers.py cares."""

    covers_brokers = True  # staged broadcasts reach mesh peers over ICI

    def __init__(self, group: "MeshBrokerGroup", shard: int):
        self.group = group
        self.shard = shard

    # Connections observer protocol --------------------------------------
    def on_user_added(self, public_key: bytes, topics) -> None:
        self.group.claim_user(self.shard, public_key, topics)

    def on_user_removed(self, public_key: bytes) -> None:
        self.group.release_user(self.shard, public_key)

    def on_subscription_changed(self, public_key: bytes, topics) -> None:
        self.group.update_mask(self.shard, public_key, topics)

    # staging -------------------------------------------------------------
    def try_stage(self, message, raw: Bytes):
        return self.group.try_stage(self.shard, message, raw)

    def stage_batch(self, items):
        return self.group.stage_batch(self.shard, items)

    def covered_broker_idents(self) -> set:
        """Identifiers of the group's member brokers — the mesh step covers
        delivery to them, so the host path must not also forward (but MUST
        still forward to interested OUT-of-group brokers)."""
        return self.group.member_idents()

    # lifecycle (driven by the owning broker's start/stop)
    async def start(self) -> None:
        await self.group.ensure_started()

    async def stop(self) -> None:
        await self.group.on_shard_stopped(self.shard)

    @property
    def disabled(self) -> bool:
        return self.group.disabled

    @property
    def overflow_seen(self) -> bool:
        return self.group.overflow_seen

    @property
    def steps(self) -> int:
        return self.group.steps

    @property
    def messages_routed(self) -> int:
        return self.group.messages_routed


class MeshBrokerGroup:
    def __init__(self, mesh, config: MeshGroupConfig = None):
        self.mesh = mesh
        self.config = config or MeshGroupConfig()
        c = self.config
        self.num_shards = mesh.devices.size
        self.step_fn = make_mesh_lane_step(
            mesh, gather_bytes=self.config.gather_frame_bytes,
            fused=self.config.fused_collective)
        # every step input is placed PRE-SHARDED over the broker axis:
        # jit would otherwise silently reshard device-0-resident arrays
        # inside every call (~0.5 ms/array on an 8-device CPU mesh)
        from jax.sharding import NamedSharding, PartitionSpec
        self._sharding = NamedSharding(mesh, PartitionSpec(BROKER_AXIS))
        self.brokers: List[Optional["Broker"]] = [None] * self.num_shards
        # lane_rings[lane][shard] — size-bucketed broadcast staging
        self.lane_rings = [
            [FrameRing(slots=s, frame_bytes=f, topic_words=c.topic_words)
             for _ in range(self.num_shards)]
            for f, s, _d in c.lane_shapes()]
        # direct frames go into per-destination-shard buckets and cross the
        # mesh with one all_to_all per lane (router.DirectIngress) instead
        # of riding the broadcast all_gather to every shard
        self.lane_buckets = [
            [DirectBuckets(self.num_shards, capacity=d, frame_bytes=f)
             for _ in range(self.num_shards)]
            for f, _s, d in c.lane_shapes()]
        # global user table + mirrors (single source of truth)
        self.slots = UserSlots(c.num_user_slots)
        self._owner = np.full(c.num_user_slots, ABSENT, np.int32)
        self._claim_version = np.zeros(c.num_user_slots, np.uint32)
        # mask shape tracks the configured topic-space width
        self._masks = np.zeros(
            mask_mirror_shape(c.num_user_slots, c.topic_words), np.uint32)
        self._quarantine: List[int] = []
        # users the slot table couldn't hold, keyed to their shard so a
        # dead shard's entries can be swept (a crash fires no releases)
        self._unmirrored: Dict[bytes, int] = {}
        # dynamic membership over the static mesh (hard-part #3): a stopped
        # shard is masked dead in-step rather than re-forming the mesh
        self._liveness = np.zeros(self.num_shards, bool)
        # mirror revision: bumped on any owner/mask/liveness mutation; the
        # step thread re-uploads device state only when it changed (steady
        # state pays zero H2D for the user table)
        self._state_rev = 0
        self._state_cache = RevCache()  # (RouterState, liveness) on device
        self._tmask_cache = TopicMaskCache(c.topic_words)
        # cached device-side EMPTY lane batches: an idle lane re-uses its
        # device arrays, paying zero stack/H2D per step (keying the jit
        # cache on lane SUBSETS instead would recompile per traffic mix)
        self._idle_dev_lanes: Dict = {}
        self.disabled = False
        # set when traffic falls outside what the mesh step can carry —
        # heartbeats then form host links even in mesh-only deployments
        self.overflow_seen = False
        self._kick = asyncio.Event()
        self._task: Optional[asyncio.Task] = None
        self._started = False
        self._state_dirty = False  # forces a step with no staged traffic
        self.steps = 0
        self.messages_routed = 0
        # collectives traced by the most recently COMPILED step
        # specialization (router.trace_collectives delta around the call):
        # the counted one-collective-per-tick invariant, asserted by the
        # mesh dryrun tier. None until a step has traced in this process.
        self.collectives_last_trace: Optional[int] = None

    # ---- wiring ----------------------------------------------------------

    def attach(self, broker: "Broker", shard: int) -> MeshShardPlane:
        """Make ``broker`` shard ``shard`` of this group (call after
        Broker.new, before Broker.start)."""
        plane = MeshShardPlane(self, shard)
        self.brokers[shard] = broker
        self._liveness[shard] = True
        self._state_rev += 1
        broker.device_plane = plane
        broker.connections.observer = plane
        self._member_idents = None  # recompute lazily
        return plane

    def member_idents(self) -> set:
        idents = getattr(self, "_member_idents", None)
        if idents is None:
            idents = {str(b.identity) for b in self.brokers if b is not None}
            self._member_idents = idents
        return idents

    async def ensure_started(self) -> None:
        if not self._started:
            self._started = True
            # compile the step off the hot path: the first jitted shard_map
            # trace can take seconds; rings must not saturate behind it
            await asyncio.to_thread(self._warmup)
            self._task = asyncio.create_task(self._pump(), name="mesh-group-pump")

    def _warmup(self) -> None:
        # empty, right shapes: [lane][shard]
        batches = [[r.take_batch() for r in rings] for rings in self.lane_rings]
        directs = [[b.take_batch() for b in bkts] for bkts in self.lane_buckets]
        lat = self.config.latency_slots
        small = [[slice_batch(b, lat) for b in lane] for lane in batches]
        small_d = [[slice_direct_batch(d, lat) for d in lane]
                   for lane in directs]
        u0 = effective_users(0, self.config.num_user_slots)
        try:
            # compile the ONLY two specializations the pump needs at first
            # population (u_eff = first user bucket): all lanes at full
            # shapes (idle lanes ride cached device-side empties, so
            # traffic mix never changes the jit key), and the latency-
            # sliced base lanes (sparse traffic); wider user buckets
            # compile on first growth past the mark
            self._run_step(batches, directs, self._owner[:u0].copy(),
                           self._claim_version[:u0].copy(),
                           self._masks[:u0].copy())
            self._run_step(small[:1], small_d[:1], self._owner[:u0].copy(),
                           self._claim_version[:u0].copy(),
                           self._masks[:u0].copy())
            self.steps -= 2  # warmup doesn't count
        except Exception:
            logger.exception("mesh-group warmup step failed")
            self.disabled = True

    async def on_shard_stopped(self, shard: int) -> None:
        self.brokers[shard] = None
        self._liveness[shard] = False
        self._state_rev += 1
        self._member_idents = None
        # Release every slot the dead shard still owned: a crashed broker
        # never fires per-user removals, and without this sweep directs to
        # its users would be acked STAGED and dropped at the tombstone
        # (and the slot table would leak). With the mapping gone,
        # try_stage sees an unknown recipient and overflows to the host
        # path — the same "failure is an I/O error, route around it"
        # posture as the reference.
        for slot in np.nonzero(self._owner == shard)[0]:
            key = self.slots.key_of(int(slot))
            if key is not None:
                self.slots.unmap(key)
            self._owner[slot] = ABSENT
            self._claim_version[slot] += 1
            self._masks[slot] = 0
            self._quarantine.append(int(slot))
        # unmirrored users of the dead shard would otherwise pin every
        # broadcast to the host path forever
        for key in [k for k, s in self._unmirrored.items() if s == shard]:
            del self._unmirrored[key]
        # wake the pump even with no staged traffic: the tombstoned release
        # must reach the device CRDT, already-staged frames to the dead
        # shard must be flushed (dropped at the tombstone), and the
        # quarantined slots must return to the free list
        self._state_dirty = True
        self._kick.set()
        if all(b is None for b in self.brokers) and self._task is not None:
            self._task.cancel()
            try:
                await self._task
            except asyncio.CancelledError:
                pass
            except Exception:
                logger.exception("mesh-group pump died during stop")
            self._task = None
            self._started = False

    # ---- mirrors (event-loop only) ---------------------------------------

    def claim_user(self, shard: int, public_key: bytes, topics) -> None:
        try:
            slot = self.slots.assign(public_key)
        except Error:
            self._unmirrored[public_key] = shard
            logger.warning("mesh-group slot table full; %d unmirrored",
                           len(self._unmirrored))
            return
        prev = int(self._owner[slot])
        if prev != ABSENT and prev != shard:
            # in-group double connect: kick the old session immediately
            # (the host CRDT handles out-of-group brokers)
            old = self.brokers[prev]
            if old is not None and old.connections.has_user(public_key):
                logger.info("user connected elsewhere in group (shard %d -> %d)",
                            prev, shard)
                old.connections.remove_user(
                    public_key, reason="user connected elsewhere")
                # removal via the old shard's observer released the slot;
                # re-assign for the new owner (the freed slot is quarantined
                # until the next step, so a full table can fail here too)
                try:
                    slot = self.slots.assign(public_key)
                except Error:
                    self._unmirrored[public_key] = shard
                    logger.warning(
                        "mesh-group slot table full after in-group kick; "
                        "%d unmirrored", len(self._unmirrored))
                    return
        self._owner[slot] = shard
        self._claim_version[slot] += 1
        self._masks[slot] = mask_row_of(topics, self.config.topic_words)
        self._state_rev += 1

    def release_user(self, shard: int, public_key: bytes) -> None:
        self._unmirrored.pop(public_key, None)
        slot = self.slots.slot_of(public_key)
        if slot is None or int(self._owner[slot]) != shard:
            return  # not ours (already taken over by another shard)
        self.slots.unmap(public_key)
        self._owner[slot] = ABSENT
        self._claim_version[slot] += 1
        self._masks[slot] = 0
        self._quarantine.append(slot)
        self._state_rev += 1

    def update_mask(self, shard: int, public_key: bytes, topics) -> None:
        slot = self.slots.slot_of(public_key)
        if slot is not None and int(self._owner[slot]) == shard:
            self._masks[slot] = mask_row_of(topics, self.config.topic_words)
            self._state_rev += 1

    # ---- staging ----------------------------------------------------------

    def _overflow(self):
        """Traffic the mesh step can't carry must ride host links: flag it
        and wake every member's heartbeat so those links form promptly."""
        from pushcdn_tpu.broker.staging import StageResult
        if not self.overflow_seen:
            self.overflow_seen = True
            logger.info("mesh-group overflow traffic; host links requested")
        for b in self.brokers:
            if b is not None:
                b.host_links_kick.set()
        return StageResult.INELIGIBLE

    def _direct_route_info(self, recipient: bytes):
        """Resolve a direct recipient to (device slot, owner shard), or
        None when the mesh can't carry it (unknown/absent recipient — the
        host path's job). The multi-host group overrides this with the
        statically partitioned slot space + the discovery directory."""
        slot = self.slots.slot_of(recipient)
        if slot is None:
            return None
        owner = int(self._owner[slot])
        if owner == ABSENT:
            return None
        return slot, owner

    def try_stage(self, shard: int, message, raw: Bytes):
        from pushcdn_tpu.broker.staging import StageResult
        if self.disabled:
            return StageResult.INELIGIBLE
        frame = bytes(raw.data)
        if len(frame) > self.lane_rings[-1][shard].frame_bytes:
            return self._overflow()
        if isinstance(message, Broadcast):
            if self._unmirrored:
                return self._overflow()
            mask, out_of_range = self._tmask_cache.resolve(message.topics)
            if out_of_range:
                return self._overflow()
            if mask == 0:
                return StageResult.INELIGIBLE  # no valid topics: no-op send
            ok = stage_best_fit(
                [rings[shard] for rings in self.lane_rings], len(frame),
                lambda r: r.push_broadcast(frame, mask))
        elif isinstance(message, Direct):
            info = self._direct_route_info(bytes(message.recipient))
            if info is None:
                # outside the group: legitimately the host path's job
                return self._overflow()
            slot, owner = info
            # one-hop ICI path: bucket by owner shard for the all_to_all
            ok = stage_best_fit(
                [bkts[shard] for bkts in self.lane_buckets], len(frame),
                lambda b: b.push(owner, frame, slot))
        else:
            return StageResult.INELIGIBLE
        if ok:
            self._kick.set()
            return StageResult.STAGED
        return StageResult.FULL

    def stage_batch(self, shard: int, items):
        """Batch staging for one member shard: broadcasts are grouped per
        size lane and packed with ONE ``FrameRing.push_batch`` per lane
        (the C framing kernel, multi-word masks included); directs keep
        the per-frame owner-bucket push (each lands in a different
        [dest][slot] cell, so there is no contiguous batch to pack).
        Returns per-item ``StageResult``s aligned with ``items``."""
        from pushcdn_tpu.broker.staging import StageResult
        results = [StageResult.INELIGIBLE] * len(items)
        if self.disabled:
            return results
        groups: dict[int, list] = {}
        rings = [lane[shard] for lane in self.lane_rings]
        free = [r.free_slots for r in rings]
        widest = rings[-1].frame_bytes
        staged_any = False
        for idx, (message, raw) in enumerate(items):
            frame = bytes(raw.data)
            if len(frame) > widest:
                self._overflow()
                continue
            if isinstance(message, Broadcast):
                if self._unmirrored:  # short-circuit before mask work
                    self._overflow()
                    continue
                mask, out_of_range = self._tmask_cache.resolve(
                    message.topics)
                if out_of_range:
                    self._overflow()
                    continue
                if mask == 0:
                    continue  # no valid topics: no-op send
                placed = False
                for li, ring in enumerate(rings):
                    if len(frame) <= ring.frame_bytes and free[li] > 0:
                        free[li] -= 1
                        groups.setdefault(li, []).append((idx, frame, mask))
                        placed = True
                        break
                results[idx] = (StageResult.STAGED if placed
                                else StageResult.FULL)
            elif isinstance(message, Direct):
                info = self._direct_route_info(bytes(message.recipient))
                if info is None:
                    self._overflow()
                    continue
                slot, owner = info
                ok = stage_best_fit(
                    [bkts[shard] for bkts in self.lane_buckets], len(frame),
                    lambda b: b.push(owner, frame, slot))
                results[idx] = (StageResult.STAGED if ok
                                else StageResult.FULL)
                staged_any = staged_any or ok
        from pushcdn_tpu.proto.message import KIND_BROADCAST
        for li, group in groups.items():
            n = rings[li].push_batch(
                [g[1] for g in group],
                [KIND_BROADCAST] * len(group),
                [g[2] for g in group],
                [-1] * len(group))
            staged_any = staged_any or n > 0
            for idx, *_ in group[n:]:
                results[idx] = StageResult.FULL
        if staged_any:
            self._kick.set()
        return results

    # ---- the pump ---------------------------------------------------------

    def _staged_total(self) -> int:
        return (sum(r.slots - r.free_slots
                    for rings in self.lane_rings for r in rings)
                + sum(b.total_used
                      for bkts in self.lane_buckets for b in bkts))

    async def _pump(self) -> None:
        c = self.config
        loop = asyncio.get_running_loop()
        gate = CoalesceGate(c.batch_window_s, c.coalesce_min_frames)
        while True:
            await self._kick.wait()
            self._kick.clear()
            # one yield so every stager woken in this tick lands first
            await asyncio.sleep(0)
            staged = self._staged_total()
            wait = gate.wait_s(staged, loop.time())
            if wait:
                # steady trickle below the coalesce threshold: wait one
                # window. A burst after idle (latency regime) and a
                # saturated pipeline both step immediately.
                await asyncio.sleep(wait)
                staged = self._staged_total()
            if not self._state_dirty and staged == 0:
                continue
            self._state_dirty = False
            # prefix-slice to the latency shapes when everything staged
            # fits the base lanes' first ``latency_slots`` slots and the
            # extra lanes are idle (collectives then move ~ring/lat× fewer
            # bytes; one extra cached jit specialization)
            lat = c.latency_slots
            small = (all(r.slots - r.free_slots <= lat
                         for r in self.lane_rings[0])
                     and all(b.max_used <= lat
                             for b in self.lane_buckets[0])
                     and all(r.free_slots == r.slots
                             for rings in self.lane_rings[1:] for r in rings)
                     and all(b.total_used == 0
                             for bkts in self.lane_buckets[1:] for b in bkts))
            # one-tick snapshot: all lanes' rings + buckets + mirrors
            batches = [[r.take_batch() for r in rings]
                       for rings in self.lane_rings]
            directs = [[b.take_batch() for b in bkts]
                       for bkts in self.lane_buckets]
            if small:
                batches = [[slice_batch(b, lat) for b in batches[0]]]
                directs = [[slice_direct_batch(d, lat) for d in directs[0]]]
            # slice the user table to its high-water mark (rounded up so
            # the jit key only moves every ``u_round`` users): delivery
            # matrices, their D2H, and the egress scans all shrink with the
            # actual population instead of paying for empty slots
            u_eff = effective_users(self.slots.high_water,
                                    self.config.num_user_slots)
            owner = self._owner[:u_eff].copy()
            versions = self._claim_version[:u_eff].copy()
            masks = self._masks[:u_eff].copy()
            liveness = self._liveness.copy()
            rev = self._state_rev
            quarantined, self._quarantine = self._quarantine, []
            try:
                egress_jobs = await asyncio.to_thread(
                    self._run_step, batches, directs, owner, versions, masks,
                    liveness, rev)
                gate.stepped(loop.time())
                for shard, streams, d2, lengths, frames in egress_jobs:
                    broker = self.brokers[shard]
                    if broker is None:
                        continue
                    if streams is not None:
                        self.messages_routed += egress_streams(
                            broker, self.slots, streams)
                    else:
                        self._egress_py(broker, d2, lengths, frames)
            except asyncio.CancelledError:
                raise
            except Exception:
                logger.exception(
                    "mesh-group step failed; re-routing batches over host "
                    "links and disabling the group")
                self.disabled = True
                # frames staged (and acked as STAGED) while the failing step
                # ran in the worker thread sit in the fresh rings — drain
                # them too, or they'd be lost with no fallback
                late = [[r.take_batch() for r in rings]
                        for rings in self.lane_rings]
                late_d = [[b.take_batch() for b in bkts]
                          for bkts in self.lane_buckets]
                for lane in batches + late:
                    await self._host_fallback(lane)
                for lane in directs + late_d:
                    await self._host_fallback_direct(lane)
                return
            finally:
                for slot in quarantined:
                    self.slots.free_slot(slot)

    def _run_step(self, batches, directs, owner, versions, masks,
                  liveness=None, state_rev=None):
        """Blocking multi-shard device step (worker thread). ``batches`` and
        ``directs`` are [lane][shard] host snapshots; busy lanes ride ONE
        jitted shard_map program with one shared CRDT merge. Lanes idle on
        EVERY shard ride cached device-side empty batches (zero stack/H2D
        per step) so the jit key never depends on the traffic mix.

        The device user table is re-uploaded only when ``state_rev`` moved
        (steady state pays zero H2D for state), and egress payloads come
        from the HOST snapshots when ``gather_frame_bytes`` is off — the
        step returns per-shard egress jobs, each either a native
        :class:`native.EgressStreams` (encoded right here, off the event
        loop) or the Python-fallback (deliver, lengths, frames) triple."""
        import jax
        from pushcdn_tpu import native as native_mod
        B = self.num_shards
        put = lambda a: jax.device_put(a, self._sharding)
        live = (np.ones(B, bool) if liveness is None else liveness)

        def build_state():
            # every shard's state row is the (shared) global view; on real
            # multi-host pods these rows diverge and the in-step merge
            # converges them — the device program is identical
            owners_b = np.broadcast_to(owner, (B,) + owner.shape)
            versions_b = np.broadcast_to(versions, (B,) + versions.shape)
            masks_b = np.broadcast_to(masks, (B,) + masks.shape)
            return (RouterState(
                crdt=CrdtState(put(owners_b),
                               put(versions_b),
                               put(owners_b)),  # identity = shard
                topic_masks=put(masks_b)),
                put(np.broadcast_to(live, (B, B))))

        state, live_dev = self._state_cache.get(state_rev, build_state)
        def put_rows(key, rows, busy_rows):
            """Assemble the [B, ...] byte tensor per device: busy shards
            H2D their own block; idle shards reuse a cached device-side
            zero block (their ``valid`` masks are False, so stale content
            can never deliver). Stack+upload cost is ∝ TRAFFIC, not lane
            geometry — with one busy shard this moves 1/B of the bytes a
            full-stack would."""
            devices = self.mesh.devices.reshape(-1)
            shards = []
            zero_key = ("z", key, rows[0].shape)
            zeros = self._idle_dev_lanes.get(zero_key)
            if zeros is None:
                zeros = [
                    jax.device_put(np.zeros((1,) + rows[0].shape, np.uint8),
                                   d) for d in devices]
                self._idle_dev_lanes[zero_key] = zeros
            for i, row in enumerate(rows):
                if busy_rows[i]:
                    shards.append(jax.device_put(row[None], devices[i]))
                else:
                    shards.append(zeros[i])
            return jax.make_array_from_single_device_arrays(
                (len(rows),) + rows[0].shape, self._sharding, shards)

        def lane_to_dev(key, lane, busy):
            """H2D one lane; an idle lane reuses its cached device-side
            empty batch (zero stack/copy), keyed by (kind, index, shape)."""
            if not busy:
                cached = self._idle_dev_lanes.get(key)
                if cached is not None:
                    return cached
            if key[0] == "b":
                dev = IngressBatch(
                    put_rows(key, [b.bytes_ for b in lane],
                             [bool(b.valid.any()) for b in lane]),
                    put(np.stack([b.kind for b in lane])),
                    put(np.stack([b.length for b in lane])),
                    put(np.stack([b.topic_mask for b in lane])),
                    put(np.stack([b.dest for b in lane])),
                    put(np.stack([b.valid for b in lane])))
            else:
                dev = DirectIngress(
                    put_rows(key, [d.bytes_ for d in lane],
                             [bool(d.valid.any()) for d in lane]),
                    put(np.stack([d.length for d in lane])),
                    put(np.stack([d.dest for d in lane])),
                    put(np.stack([d.valid for d in lane])))
            if not busy:
                self._idle_dev_lanes[key] = dev
            return dev

        busy_b = [any(b.valid.any() for b in lane) for lane in batches]
        busy_d = [any(d.valid.any() for d in lane) for lane in directs]
        lane_batches = tuple(
            lane_to_dev(("b", li, lane[0].valid.shape[0]), lane, busy_b[li])
            for li, lane in enumerate(batches))
        lane_directs = tuple(
            lane_to_dev(("d", li, lane[0].valid.shape[1]), lane, busy_d[li])
            for li, lane in enumerate(directs))
        from pushcdn_tpu.parallel import router as router_mod
        before = router_mod.trace_collectives()
        result = self.step_fn(state, lane_batches, lane_directs, live_dev)
        traced = router_mod.trace_collectives() - before
        if traced:  # this call compiled a fresh specialization
            self.collectives_last_trace = traced
        self.steps += 1
        # ---- egress prep: decisions from the mesh, payloads from host ----
        # (idle lanes can't deliver: skip their D2H entirely)
        jobs = []
        for li, l in enumerate(result.lanes):
            if not busy_b[li]:
                continue
            deliver = np.asarray(l.deliver)          # bool[B, U, N]
            if self.config.gather_frame_bytes:
                lengths = np.asarray(l.gathered_length[0])
                blocks = [np.asarray(l.gathered_bytes[0])]
                per_shard = None
            else:
                lane = batches[li]
                lengths = np.concatenate([b.length for b in lane])
                blocks = [b.bytes_ for b in lane]
                per_shard = None
            jobs.append((deliver, lengths, blocks, per_shard))
        for li, l in enumerate(result.direct_lanes):
            if not busy_d[li]:
                continue
            deliver = np.asarray(l.deliver)          # bool[B, U, B*C]
            if self.config.gather_frame_bytes:
                # all_to_all output DIFFERS per shard (unlike the broadcast
                # all_gather): each shard's received bytes/lengths must pair
                # with that shard's own delivery mask
                lengths = np.asarray(l.gathered_length)   # [B, B*C]
                blocks = np.asarray(l.gathered_bytes)     # [B, B*C, F]
                jobs.append((deliver, lengths, blocks, "per-shard"))
            else:
                # the all_to_all transposes buckets: shard j receives, from
                # each source shard, that source's bucket FOR j
                lane = directs[li]
                jobs.append((deliver, None, None, lane))
        out = []
        for deliver, lengths, blocks, direct_lane in jobs:
            for shard in range(B):
                if self.brokers[shard] is None:
                    continue
                d2 = deliver[shard]
                if not d2.any():
                    continue
                if direct_lane == "per-shard":
                    s_lengths = lengths[shard]
                    s_blocks = [blocks[shard]]
                elif direct_lane is not None:
                    s_lengths = np.concatenate(
                        [direct_lane[src].length[shard] for src in range(B)])
                    s_blocks = [direct_lane[src].bytes_[shard]
                                for src in range(B)]
                else:
                    s_lengths, s_blocks = lengths, blocks
                streams = native_mod.egress_encode(d2, s_lengths, s_blocks)
                if streams is not None:
                    out.append((shard, streams, None, None, None))
                else:  # no native library: per-frame Python fallback
                    out.append((shard, None, d2, s_lengths,
                                np.concatenate(s_blocks)))
        return out

    def _egress_py(self, broker, deliver2, lengths, frames) -> None:
        """Per-frame fallback egress for one shard (native lib absent)."""
        users, frame_idx = np.nonzero(deliver2)
        cache: Dict[int, Bytes] = {}

        def frame_of(f: int) -> Bytes:
            raw = cache.get(f)
            if raw is None:
                raw = Bytes(frames[f, :lengths[f]].tobytes())
                cache[f] = raw
            return raw

        self.messages_routed += egress_delivery_rows(
            broker, self.slots, users, frame_idx, frame_of)
        for raw in cache.values():
            raw.release()

    async def _host_fallback(self, batches) -> None:
        """Re-route every staged frame over the host plane (brokers keep
        their TCP/memory mesh links as backup)."""
        from pushcdn_tpu.broker.tasks.handlers import (
            handle_broadcast_message,
            handle_direct_message,
        )
        from pushcdn_tpu.proto.message import deserialize
        members = self.member_idents()
        for shard, b in enumerate(batches):
            broker = self.brokers[shard]
            if broker is None:
                continue
            # Staged broadcasts were ALREADY forwarded to interested
            # out-of-group brokers at staging time (the stage-time exclude
            # set covers only group members) — re-forwarding here would
            # deliver those subscribers a second copy. The fallback only
            # owes what the failed step owed: local users + group members.
            out_of_group = frozenset(
                ident for ident in broker.connections.all_broker_identifiers()
                if ident not in members)
            for i in range(len(b.valid)):
                if not b.valid[i]:
                    continue
                raw = Bytes(b.bytes_[i, :b.length[i]].tobytes())
                try:
                    message = deserialize(raw.data)
                    if isinstance(message, Direct):
                        await handle_direct_message(
                            broker, bytes(message.recipient), raw,
                            to_user_only=False)
                    elif isinstance(message, Broadcast):
                        await handle_broadcast_message(
                            broker, list(message.topics), raw,
                            to_users_only=False,
                            exclude_brokers=out_of_group)
                except Error:
                    pass
                finally:
                    raw.release()

    async def _host_fallback_direct(self, directs) -> None:
        """Re-route staged direct-bucket frames over the host plane (the
        recipient is in the wire frame; bucket geometry doesn't matter)."""
        from pushcdn_tpu.broker.tasks.handlers import handle_direct_message
        from pushcdn_tpu.proto.message import deserialize
        for shard, d in enumerate(directs):
            broker = self.brokers[shard]
            if broker is None:
                continue
            dests, idx = np.nonzero(d.valid)
            for b_dest, i in zip(dests.tolist(), idx.tolist()):
                raw = Bytes(d.bytes_[b_dest, i, :d.length[b_dest, i]].tobytes())
                try:
                    message = deserialize(raw.data)
                    if isinstance(message, Direct):
                        await handle_direct_message(
                            broker, bytes(message.recipient), raw,
                            to_user_only=False)
                except Error:
                    pass
                finally:
                    raw.release()

