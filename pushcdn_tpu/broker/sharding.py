"""Sharded data plane: N worker OS processes presenting as ONE broker
(ISSUE 6 tentpole).

Topology:

- the **parent** process supervises: it creates the shared-memory handoff
  rings (one per directed shard pair, ``shardring.py``), relays
  control-plane deltas between workers (the hub stamps a total order), and
  serves the aggregated observability endpoint (``/metrics`` with a
  ``shard`` label, ``/healthz``+``/readyz``+``/debug/topology`` merged
  across workers);
- **worker shard 0** owns the mesh: it binds the private endpoint, runs
  heartbeat/sync/whitelist, and fronts discovery for the whole box
  (reporting ``num_users_global``);
- **every worker** binds the public endpoint with ``SO_REUSEPORT`` (the
  kernel spreads accepted users across workers); where the platform lacks
  it, the parent binds once and passes accepted fds over a unix socketpair
  with ``sendmsg``/SCM_RIGHTS (:class:`FdHandoffListener`).

Data plane: each worker runs the existing cut-through drain against a
per-shard route snapshot whose peer space covers the WHOLE box (sibling
users + mesh links by owning shard). Fan-out to a peer on another worker
is handed off as pre-encoded wire chunks + per-peer index lists over the
shard rings — no re-serialization, no per-frame Python on the receiving
side ("RPC Considered Harmful" applied to our own interior boundary).
Ring-full degrades to a *counted* relay through the parent's control
socket (never blocks the drain); an epoch/ack handshake re-orders the
return to the ring so per-(origin→peer) frame order survives the
degraded window.

Control plane: subscribe/DirectMap mutations flow worker→parent→workers
as versioned deltas (``Connections.shard_notifier`` emits, the hub relays,
``ShardRuntime.apply_event`` applies); each application bumps
``interest_version`` so cut-through snapshots rebuild exactly like any
local mutation.
"""

from __future__ import annotations

import asyncio
import json
import logging
import os
import pickle
import signal as signal_mod
import socket
import struct
import time
from typing import Dict, List, Optional, Tuple

import numpy as np

from pushcdn_tpu.broker import shardring
from pushcdn_tpu.proto import flowclass
from pushcdn_tpu.proto import health as health_mod
from pushcdn_tpu.proto import ledger as ledger_mod
from pushcdn_tpu.proto import metrics as metrics_mod
from pushcdn_tpu.proto.util import mnemonic

logger = logging.getLogger("pushcdn.broker.shard")

_FRAME_LEN = struct.Struct(">I")

DEFAULT_RING_BYTES = int(os.environ.get("PUSHCDN_SHARD_RING_BYTES",
                                        str(4 * 1024 * 1024)))

# per-worker cap on the parent hub's outbound control-socket buffer: the
# relay budget bounds per-producer relay bytes, but broadcast deltas
# (connect/subscribe storms) are unbudgeted, so a worker that stops
# draining its control socket must be cut loose before it grows the
# parent heap without bound
HUB_MAX_BUFFER = int(os.environ.get("PUSHCDN_SHARD_HUB_MAX_BUFFER",
                                    str(32 * 1024 * 1024)))


def shards_from_env(flag_value: Optional[int]) -> int:
    if flag_value is not None:
        return max(int(flag_value), 1)
    raw = os.environ.get("PUSHCDN_SHARDS", "").strip()
    if raw:
        try:
            return max(int(raw), 1)
        except ValueError:
            pass
    return 1


# ---------------------------------------------------------------------------
# control-plane buses
# ---------------------------------------------------------------------------

class LocalBus:
    """In-process bus (tests, benches): deltas apply synchronously to the
    sibling runtimes in publish order — the same total order the parent
    hub provides across processes."""

    def __init__(self):
        self.runtimes: Dict[int, "ShardRuntime"] = {}
        self.version = 0

    def register(self, runtime: "ShardRuntime") -> None:
        self.runtimes[runtime.shard_id] = runtime

    def publish(self, origin: int, event: tuple) -> None:
        self.version += 1
        if event[0] == "relay":
            target = self.runtimes.get(event[1])
            if target is not None:
                target.apply_event(origin, event)
            return
        if event[0] == "relay_ack":
            target = self.runtimes.get(event[1])
            if target is not None:
                target.apply_event(origin, event)
            return
        for shard, rt in self.runtimes.items():
            if shard != origin:
                rt.apply_event(origin, event)


class SocketBus:
    """Worker end of the parent control socket: length-prefixed pickled
    frames. ``publish`` enqueues synchronously (Connections mutators are
    sync); a writer task drains; a reader task applies parent relays."""

    def __init__(self, runtime: "ShardRuntime", sock: socket.socket):
        self.runtime = runtime
        self._sock = sock
        self._out: asyncio.Queue = asyncio.Queue()
        self._reader: Optional[asyncio.StreamReader] = None
        self._writer: Optional[asyncio.StreamWriter] = None

    def publish(self, origin: int, event: tuple) -> None:
        self._out.put_nowait(pickle.dumps(event,
                                          protocol=pickle.HIGHEST_PROTOCOL))

    async def run(self) -> None:
        """Reader+writer over the control socket; exits (and thus fails
        the broker fast) if the parent goes away."""
        self._sock.setblocking(False)
        reader, writer = await asyncio.open_connection(sock=self._sock)
        self._reader, self._writer = reader, writer

        async def _send_loop():
            while True:
                blob = await self._out.get()
                writer.write(_FRAME_LEN.pack(len(blob)) + blob)
                await writer.drain()

        send_task = asyncio.create_task(_send_loop(), name="shard-bus-send")
        try:
            while True:
                hdr = await reader.readexactly(4)
                (n,) = _FRAME_LEN.unpack(hdr)
                blob = await reader.readexactly(n)
                origin, event = pickle.loads(blob)
                self.runtime.apply_event(origin, event)
        finally:
            send_task.cancel()


# ---------------------------------------------------------------------------
# worker-side runtime
# ---------------------------------------------------------------------------

class ShardRuntime:
    """One worker's shard plumbing: ring writers/readers + notify fds +
    the control bus, attached to a live :class:`Broker`."""

    def __init__(self, broker, shard_id: int, num_shards: int,
                 rings_out: Dict[int, shardring.RingWriter],
                 rings_in: Dict[int, shardring.RingReader],
                 notify_rx: Optional[socket.socket]):
        self.broker = broker
        self.shard_id = shard_id
        self.num_shards = num_shards
        self.rings_out = rings_out
        self.rings_in = rings_in
        self.notify_rx = notify_rx
        self.bus = None  # set via set_bus
        self._notify_event = asyncio.Event()
        self._reader_installed = False
        # ring-full degradation state per destination: once a push fails
        # we stay on the relay path until the ring is drained AND the last
        # relay epoch is acked — the handshake that keeps per-peer frame
        # order across the degraded window
        self._fallback: Dict[int, bool] = {}
        self._relay_epoch: Dict[int, int] = {}
        self._acked_epoch: Dict[int, int] = {}
        # unacked relayed bytes per destination, by epoch: the relay path
        # is NOT allowed to grow without bound when the consumer stays
        # slow — past the budget, records are SHED (counted), which keeps
        # "never block the drain" from becoming unbounded memory
        self._relay_unacked: Dict[int, Dict[int, int]] = {}
        # consumer side: one lock per ORIGIN serializes the ring drain
        # with relay delivery, so a relay task can never overtake ring
        # records (or another relay) from the same producer mid-dispatch
        self._origin_locks: Dict[int, asyncio.Lock] = {}
        # readers abandoned by the poison guard: out of rings_in (never
        # drained again) but still closed with the runtime
        self._poisoned_readers: List[shardring.RingReader] = []
        self.relay_fallbacks = 0
        self.relay_shed = 0
        self.deltas_applied = 0
        self._sync_kick_pending = False

    def _origin_lock(self, origin: int) -> asyncio.Lock:
        lock = self._origin_locks.get(origin)
        if lock is None:
            lock = self._origin_locks[origin] = asyncio.Lock()
        return lock

    # -- wiring --------------------------------------------------------------

    def set_bus(self, bus) -> None:
        self.bus = bus

    def attach(self) -> None:
        """Install on the broker + its Connections (call before traffic)."""
        conns = self.broker.connections
        conns.num_shards = self.num_shards
        conns.shard_id = self.shard_id
        conns.shard_notifier = self._emit
        self.broker.shard_runtime = self
        if self.notify_rx is not None:
            asyncio.get_running_loop().add_reader(
                self.notify_rx.fileno(), self._notify_event.set)
            self._reader_installed = True

    def close(self) -> None:
        if self._reader_installed and self.notify_rx is not None:
            try:
                asyncio.get_event_loop().remove_reader(
                    self.notify_rx.fileno())
            except Exception:
                pass
        conns = getattr(self.broker, "connections", None)
        if conns is not None and conns.shard_notifier is self._emit:
            conns.shard_notifier = None
        for w in self.rings_out.values():
            w.close()
        for r in self.rings_in.values():
            r.close()
        for r in self._poisoned_readers:
            r.close()

    def _emit(self, event: tuple) -> None:
        if self.bus is not None:
            self.bus.publish(self.shard_id, event)

    # -- control-plane delta application ------------------------------------

    def apply_event(self, origin: int, event: tuple) -> None:
        kind = event[0]
        conns = self.broker.connections
        # data-plane relay traffic and unknown events must NOT inflate
        # the interest-delta counters: during a ring-full window the
        # relay+ack chatter would otherwise read as a subscription storm
        if kind == "relay":
            asyncio.ensure_future(self._deliver_relay(origin, event[2],
                                                      event[3]))
            return
        if kind == "relay_ack":
            epoch = event[2]
            self._acked_epoch[origin] = max(
                self._acked_epoch.get(origin, 0), epoch)
            unacked = self._relay_unacked.get(origin)
            if unacked:
                for e in [e for e in unacked if e <= epoch]:
                    del unacked[e]
            return
        if kind in ("durable_pub", "durable_retain", "durable_sub"):
            # durable-topic data plane (ISSUE 14): owner-shard retention /
            # replay traffic — like relay, kept out of the interest-delta
            # counters
            durable = getattr(self.broker, "durable", None)
            if durable is not None:
                durable.apply_shard_event(event)
            return
        if kind not in ("user", "user_del", "usersync", "mesh_topics",
                        "mesh_broker_del"):
            logger.warning("unknown shard delta %r from shard %d",
                           kind, origin)
            return
        self.deltas_applied += 1
        metrics_mod.SHARD_DELTAS_APPLIED.inc()
        if kind == "user":
            conns.set_remote_user(event[1], origin, event[2])
            self._kick_mesh_sync()
        elif kind == "user_del":
            conns.remove_remote_user(event[1], origin)
            self._kick_mesh_sync()
        elif kind == "usersync":
            conns.apply_user_sync(event[1], from_sibling=True)
        elif kind == "mesh_topics":
            conns.set_remote_broker(event[1], origin, event[2])
        elif kind == "mesh_broker_del":
            conns.remove_remote_broker(event[1])

    def _kick_mesh_sync(self) -> None:
        """Shard 0 pushes partial syncs promptly when sibling membership
        changes (strong consistency across the mesh — the same semantics
        a local user connect gets from the listener). Kicks COALESCE: a
        delta storm (thousands of sibling connects applied in one bus
        drain) schedules one push task, not one per delta — the pending
        flag clears before the CRDT diff is computed, so a delta landing
        after that point just schedules the next push."""
        if self.shard_id != 0 or not self.broker.connections.brokers:
            return
        if self._sync_kick_pending:
            return
        self._sync_kick_pending = True
        from pushcdn_tpu.broker.tasks import sync as sync_task

        async def _push():
            self._sync_kick_pending = False
            try:
                await sync_task.partial_user_sync(self.broker)
                await sync_task.partial_topic_sync(self.broker)
            except Exception:
                logger.debug("sibling-delta partial sync failed",
                             exc_info=True)
        asyncio.ensure_future(_push())

    # -- cross-shard egress ---------------------------------------------------

    def _enter_fallback(self, dst: int) -> None:
        if not self._fallback.get(dst):
            self._fallback[dst] = True
            logger.warning("shard ring %d->%d full; relaying via control "
                           "plane until drained", self.shard_id, dst)

    def _ring_usable(self, dst: int) -> bool:
        w = self.rings_out.get(dst)
        if w is not None and w.poisoned:
            return False  # consumer abandoned it: relay for good
        if not self._fallback.get(dst, False):
            return True
        if w is None:
            return False
        # leave the degraded mode only once the consumer fully drained the
        # ring AND acked the last relay epoch (order barrier)
        if w.head == w.tail and self._acked_epoch.get(dst, 0) \
                >= self._relay_epoch.get(dst, 0):
            self._fallback[dst] = False
            return True
        return False

    def handoff(self, dst: int, frames: List, peers: List[tuple],
                prefixed: bool = False) -> None:
        """Scalar-path handoff: ``frames[i]`` are frame buffers, peers
        carry frame-index lists (EgressBatch._flush_shards)."""
        if self._ring_usable(dst):
            w = self.rings_out.get(dst)
            if w is not None and w.try_push(frames, peers,
                                            prefixed=prefixed):
                metrics_mod.SHARD_HANDOFF_RING.inc()
                metrics_mod.SHARD_HANDOFF_FRAMES_RING.inc(len(frames))
                # the frames are the sibling shard's responsibility now
                # (informational fate — class unresolved at this layer)
                ledger_mod.record_fate("relayed", "shard_ring",
                                       flowclass.CLASS_NONE, len(frames))
                return
            self._enter_fallback(dst)
        entries = []
        for kind, ident, idx in peers:
            if prefixed:
                stream = b"".join(bytes(frames[i]) for i in idx)
            else:
                stream = b"".join(
                    _FRAME_LEN.pack(len(frames[i])) + bytes(frames[i])
                    for i in idx)
            entries.append((kind, bytes(ident), stream, len(idx)))
        self._relay(dst, entries, n_frames=len(frames))

    def handoff_chunk(self, buf, offs, lens,
                      per_shard: Dict[int, List[tuple]]) -> None:
        """Cut-through handoff: copy the union of each shard's referenced
        wire frames straight from the pooled chunk into the ring record
        (one pass, already length-delimited — offs/lens are the chunk's
        payload table, the wire slice includes the 4-byte prefix)."""
        for dst, peers in per_shard.items():
            idx_arrays = [np.asarray(idx) for _k, _i, idx in peers]
            # per-peer idx arrays arrive sorted-unique (grouped from a
            # stable argsort), so the single-peer union IS the array
            union = np.unique(np.concatenate(idx_arrays)) \
                if len(idx_arrays) > 1 else idx_arrays[0]
            mv = memoryview(buf)
            frames = [mv[int(offs[i]) - 4: int(offs[i]) + int(lens[i])]
                      for i in union.tolist()]
            remapped = [
                (kind, ident,
                 np.searchsorted(union, np.asarray(idx)).tolist())
                for kind, ident, idx in peers]
            self.handoff(dst, frames, remapped, prefixed=True)

    # unacked relay budget per destination: past this, doubly-degraded
    # traffic (ring full AND the relay pipeline backed up) is SHED with a
    # counter instead of growing the control-plane queues without bound
    _RELAY_MAX_BYTES = int(os.environ.get(
        "PUSHCDN_SHARD_RELAY_MAX_BYTES", str(8 * 1024 * 1024)))

    def _relay(self, dst: int, entries: List[tuple],
               n_frames: int = 0) -> None:
        size = sum(len(e[2]) for e in entries)
        unacked = self._relay_unacked.setdefault(dst, {})
        if sum(unacked.values()) + size > self._RELAY_MAX_BYTES:
            # overload shedding: the consumer is behind on BOTH channels;
            # dropping here (counted) is the bounded alternative to
            # stalling the drain or OOMing the control plane
            self.relay_shed += 1
            metrics_mod.SHARD_HANDOFF_SHED.inc()
            metrics_mod.SHARD_HANDOFF_FRAMES_SHED.inc(n_frames)
            ledger_mod.record_fate("dropped", "relay_shed",
                                   flowclass.CLASS_NONE, n_frames)
            return
        self.relay_fallbacks += 1
        metrics_mod.SHARD_HANDOFF_FALLBACK.inc()
        metrics_mod.SHARD_HANDOFF_FRAMES_FALLBACK.inc(n_frames)
        epoch = self._relay_epoch.get(dst, 0) + 1
        self._relay_epoch[dst] = epoch
        unacked[epoch] = size
        self._emit(("relay", dst, entries, epoch))

    async def _deliver_relay(self, origin: int, entries: List[tuple],
                             epoch: int) -> None:
        """Apply a sibling's ring-full relay: under the per-origin lock
        (serialized with the ring-drain task and with other relays from
        the same producer — an unserialized relay could overtake ring
        records mid-dispatch and invert per-peer frame order), drain our
        inbound ring from that origin FIRST (those records predate the
        relay — FIFO per producer), then enqueue the relayed streams,
        then ack the epoch so the producer may return to the ring."""
        async with self._origin_lock(origin):
            reader = self.rings_in.get(origin)
            if reader is not None:
                await self._drain_reader(origin, reader)
            for kind, ident, stream, n in entries:
                await self._egress_one(kind, ident, stream, owner=None,
                                       n_frames=n)
            self._emit(("relay_ack", origin, epoch))

    # -- ring drain ----------------------------------------------------------

    async def _egress_one(self, kind: int, ident: bytes, data, owner,
                          n_frames: int) -> None:
        broker = self.broker
        conns = broker.connections
        if kind == shardring.KIND_USER:
            conn = conns.get_user_connection(ident)
        else:
            conn = conns.get_broker_connection(ident.decode())
        if conn is None:
            # peer left since the origin planned: drop (parity)
            ledger_mod.record_fate("dropped", "no_route",
                                   flowclass.CLASS_NONE, n_frames)
            return
        (metrics_mod.EGRESS_FRAMES_USER if kind == shardring.KIND_USER
         else metrics_mod.EGRESS_FRAMES_BROKER).inc(n_frames)
        try:
            # class volume was counted at the ORIGIN shard's routing
            # decision (pair-level, before the handoff); nbytes=0 keeps
            # the sibling's writer from counting the stream twice
            await conn.send_encoded(data, owner, nbytes=0, count=n_frames)
        except asyncio.CancelledError:
            raise
        except Exception as exc:
            if kind == shardring.KIND_USER:
                logger.info("shard egress to user %s failed (%r); removing",
                            mnemonic(ident), exc)
                conns.remove_user(ident, reason="send failed")
            else:
                logger.info("shard egress to broker %s failed (%r); "
                            "removing", ident.decode(), exc)
                conns.remove_broker(ident.decode(), reason="send failed")
            broker.update_metrics()

    async def _dispatch(self, rec: shardring.RingRecord) -> None:
        try:
            for kind, ident, idx in rec.peers:
                data = rec.stream_for(idx)
                owner = rec.lease() if isinstance(data, memoryview) \
                    else None
                await self._egress_one(kind, ident, data, owner,
                                       n_frames=len(idx))
        finally:
            rec.release()

    # consecutive no-progress retries on one uncommitted/corrupt record
    # before the ring is declared poisoned (a mid-write window is
    # microseconds; seconds of stall mean the producer died mid-push or
    # the slot is corrupt, and spinning would starve every other ring
    # and relay behind this origin's lock forever)
    _RING_POISON_RETRIES = 4000

    async def _drain_reader(self, src: int,
                            reader: shardring.RingReader) -> None:
        stalled = 0
        while True:
            recs = reader.drain(64)
            if not recs:
                if reader.backlog > 0:
                    # torn record mid-write: give the producer a beat
                    metrics_mod.SHARD_RING_TORN.inc()
                    stalled += 1
                    if stalled >= self._RING_POISON_RETRIES:
                        logger.error(
                            "ring %d->%d poisoned: record never committed "
                            "after %d retries; abandoning the ring (the "
                            "producer degrades to the counted relay path)",
                            src, self.shard_id, stalled)
                        metrics_mod.SHARD_RING_POISONED.inc()
                        # flag the header FIRST: the producer's next
                        # try_push fails over to the relay, so a stalled-
                        # then-resumed producer can't keep feeding (and
                        # counting path=ring deliveries into) a ring
                        # nobody will ever drain again
                        reader.poison()
                        if self.rings_in.pop(src, None) is not None:
                            self._poisoned_readers.append(reader)
                        return
                    await asyncio.sleep(0.0005)
                    continue
                return
            stalled = 0
            for rec in recs:
                await self._dispatch(rec)

    async def run_ring_drain(self) -> None:
        """The consumer task: woken by the notify socket, drains whole
        records from every inbound ring into the egress writers."""
        ev = self._notify_event
        rx = self.notify_rx
        while True:
            await ev.wait()
            ev.clear()
            if rx is not None:
                try:
                    while True:
                        if not rx.recv(4096):
                            break
                except (BlockingIOError, InterruptedError):
                    pass
            # list(): a poisoned ring may be dropped mid-iteration
            for src, reader in list(self.rings_in.items()):
                async with self._origin_lock(src):
                    await self._drain_reader(src, reader)

    def wake(self) -> None:
        """In-process producers (tests/benches on one loop) can nudge the
        consumer directly instead of through the notify socket."""
        self._notify_event.set()

    def stats(self) -> dict:
        return {
            "shard": self.shard_id,
            # the worker's OS pid: chaos drivers (scripts/local_cluster.py
            # --chaos) kill a specific shard worker through the merged
            # /debug/topology instead of guessing at child-process order
            "pid": os.getpid(),
            "num_shards": self.num_shards,
            "remote_users": len(self.broker.connections.remote_user_shard),
            "remote_brokers":
                len(self.broker.connections.remote_broker_shard),
            "deltas_applied": self.deltas_applied,
            "relay_fallbacks": self.relay_fallbacks,
            "relay_shed": self.relay_shed,
            "rings": shardring.stats_dict(self.rings_out, self.rings_in),
        }


# ---------------------------------------------------------------------------
# in-process harness (equivalence tests, benches)
# ---------------------------------------------------------------------------

def attach_inprocess_shards(brokers: list,
                            ring_bytes: int = 256 * 1024) -> list:
    """Wire already-constructed in-process brokers into a sharded group
    on ONE event loop: real shared-memory rings + notify sockets, a
    LocalBus for the control plane. Returns the runtimes; caller owns
    spawning ``run_ring_drain`` tasks and closing. The ring shm names are
    unlinked on close via the returned runtimes' ``_owned_rings``."""
    n = len(brokers)
    bus = LocalBus()
    names: Dict[Tuple[int, int], str] = {}
    for i in range(n):
        for j in range(n):
            if i != j:
                names[(i, j)] = shardring.create_ring(ring_bytes)
    notify = {i: shardring.notify_pair() for i in range(n)}
    runtimes = []
    for i, broker in enumerate(brokers):
        writers = {j: shardring.RingWriter(names[(i, j)], ring_bytes,
                                           notify_sock=notify[j][1])
                   for j in range(n) if j != i}
        readers = {j: shardring.RingReader(names[(j, i)], ring_bytes)
                   for j in range(n) if j != i}
        rt = ShardRuntime(broker, i, n, writers, readers, notify[i][0])
        rt.set_bus(bus)
        bus.register(rt)
        rt._owned_rings = list(names.values()) if i == 0 else []
        runtimes.append(rt)
    return runtimes


def detach_inprocess_shards(runtimes: list) -> None:
    for rt in runtimes:
        tx_socks = [w._notify for w in rt.rings_out.values()
                    if w._notify is not None]
        rt.close()
        if rt.notify_rx is not None:
            rt.notify_rx.close()
        for s in tx_socks:
            try:
                s.close()
            except OSError:
                pass
    for rt in runtimes:
        for name in getattr(rt, "_owned_rings", ()):
            shardring.unlink_ring(name)


# ---------------------------------------------------------------------------
# worker bootstrap from an IPC spec (inherited fds + shm names)
# ---------------------------------------------------------------------------

def runtime_from_spec(broker, spec: dict) -> ShardRuntime:
    shard = int(spec["shard"])
    num = int(spec["num_shards"])
    writers = {}
    for dst, (name, cap) in spec["rings_out"].items():
        tx_fd = spec["notify_tx"][str(dst)]
        tx = socket.socket(socket.AF_UNIX, socket.SOCK_DGRAM,
                           fileno=int(tx_fd))
        tx.setblocking(False)
        writers[int(dst)] = shardring.RingWriter(name, int(cap),
                                                 notify_sock=tx)
    readers = {int(src): shardring.RingReader(name, int(cap))
               for src, (name, cap) in spec["rings_in"].items()}
    rx = socket.socket(socket.AF_UNIX, socket.SOCK_DGRAM,
                       fileno=int(spec["notify_rx_fd"]))
    rx.setblocking(False)
    runtime = ShardRuntime(broker, shard, num, writers, readers, rx)
    control = socket.socket(socket.AF_UNIX, socket.SOCK_STREAM,
                            fileno=int(spec["control_fd"]))
    runtime.set_bus(SocketBus(runtime, control))
    return runtime


# ---------------------------------------------------------------------------
# SO_REUSEPORT fallback: parent accepts, workers adopt fds (SCM_RIGHTS)
# ---------------------------------------------------------------------------

def reuseport_available() -> bool:
    return hasattr(socket, "SO_REUSEPORT") and \
        os.environ.get("PUSHCDN_SHARD_ACCEPT", "").strip() != "handoff"


class FdHandoffListener:
    """Worker-side ``Listener``: accepted sockets arrive as SCM_RIGHTS fds
    over a unix socketpair from the parent's acceptor."""

    def __init__(self, handoff_sock: socket.socket):
        self._sock = handoff_sock
        self._sock.setblocking(False)
        self._accept_q: asyncio.Queue = asyncio.Queue()
        self._closed = False
        loop = asyncio.get_running_loop()
        loop.add_reader(self._sock.fileno(), self._on_readable)

    def _on_readable(self) -> None:
        try:
            while True:
                msg, fds, _flags, _addr = socket.recv_fds(self._sock, 16, 8)
                if not msg and not fds:
                    break
                for fd in fds:
                    self._accept_q.put_nowait(fd)
        except (BlockingIOError, InterruptedError):
            pass
        except OSError:
            self._accept_q.put_nowait(None)

    async def accept(self):
        from pushcdn_tpu.proto.error import ErrorKind, bail
        from pushcdn_tpu.proto.transport.tcp import _TcpUnfinalized
        while True:
            fd = await self._accept_q.get()
            if fd is None or self._closed:
                bail(ErrorKind.CONNECTION, "listener closed")
            sock = socket.socket(fileno=fd)
            sock.setblocking(False)
            try:
                reader, writer = await asyncio.open_connection(sock=sock)
            except OSError:
                sock.close()
                continue
            return _TcpUnfinalized(reader, writer)

    async def close(self) -> None:
        self._closed = True
        try:
            asyncio.get_event_loop().remove_reader(self._sock.fileno())
        except Exception:
            pass
        self._sock.close()
        self._accept_q.put_nowait(None)


class FdHandoffAcceptor:
    """Parent-side acceptor (only when SO_REUSEPORT is unavailable):
    binds the public endpoint once and deals accepted fds round-robin."""

    def __init__(self, endpoint: str, worker_socks: List[socket.socket]):
        from pushcdn_tpu.proto.error import parse_endpoint
        host, port = parse_endpoint(endpoint)
        self._listen = socket.create_server((host, port), backlog=512,
                                            reuse_port=False)
        self._listen.setblocking(False)
        self._workers = worker_socks
        for s in worker_socks:
            # a full handoff buffer must RAISE so the round-robin can try
            # the next worker — a blocking send_fds would freeze the whole
            # parent loop behind one wedged worker
            s.setblocking(False)
        self._next = 0
        self.handoff_retries = 0
        self.handoff_drops = 0
        loop = asyncio.get_running_loop()
        loop.add_reader(self._listen.fileno(), self._on_accept)

    def _on_accept(self) -> None:
        try:
            while True:
                sock, _addr = self._listen.accept()
                try:
                    delivered = False
                    for _ in range(len(self._workers)):
                        target = self._workers[self._next
                                               % len(self._workers)]
                        self._next += 1
                        try:
                            socket.send_fds(target, [b"\x01"],
                                            [sock.fileno()])
                            delivered = True
                            break
                        except OSError:
                            # this worker's handoff buffer is full
                            # (accept burst) or its pair died: try the
                            # next worker in the rotation
                            self.handoff_retries += 1
                    if not delivered:
                        self.handoff_drops += 1
                        logger.warning(
                            "fd handoff: no worker took the accepted "
                            "connection; dropping it (%d dropped total)",
                            self.handoff_drops)
                finally:
                    sock.close()  # worker owns its dup'd fd now
        except (BlockingIOError, InterruptedError):
            pass
        except OSError:
            pass

    def close(self) -> None:
        try:
            asyncio.get_event_loop().remove_reader(self._listen.fileno())
        except Exception:
            pass
        self._listen.close()


# ---------------------------------------------------------------------------
# parent supervisor
# ---------------------------------------------------------------------------

class _WorkerHandle:
    def __init__(self, shard: int, spec: dict, parent_control: socket.socket,
                 parent_fds: List[int], child_fds: List[int]):
        self.shard = shard
        self.spec = spec
        self.parent_control = parent_control
        self.parent_fds = parent_fds  # fds the parent keeps
        self.child_fds = child_fds    # fds passed to (and owned by) child
        self.proc = None
        self.metrics_port: Optional[int] = None


def build_worker_ipc(num_shards: int,
                     ring_bytes: int = DEFAULT_RING_BYTES
                     ) -> Tuple[List[_WorkerHandle], List[str]]:
    """Create rings + notify + control plumbing for ``num_shards``
    workers. Returns (handles, ring_names) — the parent unlinks the ring
    shm at teardown. Partial failure (fd exhaustion at high shard
    counts, shm creation errors) cleans up everything already created —
    leaked /dev/shm segments outlive the process."""
    names: Dict[Tuple[int, int], str] = {}
    notify: Dict[int, Tuple[socket.socket, socket.socket]] = {}
    handles: List[_WorkerHandle] = []
    try:
        return _build_worker_ipc(num_shards, ring_bytes, names, notify,
                                 handles)
    except BaseException:
        for nm in names.values():
            shardring.unlink_ring(nm)
        for rx, tx in notify.values():
            for s in (rx, tx):
                try:
                    s.close()
                except OSError:
                    pass
        for h in handles:
            for s in (h.parent_control, getattr(h, "_child_ctl", None)):
                if s is not None:
                    try:
                        s.close()
                    except OSError:
                        pass
        raise


def _build_worker_ipc(num_shards: int, ring_bytes: int,
                      names: Dict[Tuple[int, int], str],
                      notify: Dict[int, Tuple[socket.socket,
                                              socket.socket]],
                      handles: List[_WorkerHandle]
                      ) -> Tuple[List[_WorkerHandle], List[str]]:
    for i in range(num_shards):
        for j in range(num_shards):
            if i != j:
                names[(i, j)] = shardring.create_ring(ring_bytes)
    for i in range(num_shards):
        notify[i] = shardring.notify_pair()
    for i in range(num_shards):
        parent_ctl, child_ctl = socket.socketpair(socket.AF_UNIX,
                                                  socket.SOCK_STREAM)
        # the child end must survive until create_subprocess_exec dups it
        child_fds = [child_ctl.fileno(), notify[i][0].fileno()]
        notify_tx = {}
        for j in range(num_shards):
            if j != i:
                notify_tx[str(j)] = notify[j][1].fileno()
                child_fds.append(notify[j][1].fileno())
        spec = {
            "shard": i,
            "num_shards": num_shards,
            "control_fd": child_ctl.fileno(),
            "notify_rx_fd": notify[i][0].fileno(),
            "notify_tx": notify_tx,
            "rings_out": {str(j): [names[(i, j)], ring_bytes]
                          for j in range(num_shards) if j != i},
            "rings_in": {str(j): [names[(j, i)], ring_bytes]
                         for j in range(num_shards) if j != i},
        }
        handle = _WorkerHandle(i, spec, parent_ctl,
                               parent_fds=[parent_ctl.fileno()],
                               child_fds=sorted(set(child_fds)))
        handle._child_ctl = child_ctl
        handles.append(handle)
    # keep python socket objects alive on the handles (prevent GC close)
    # until the children have inherited them; close_child_ends() after
    for i, h in enumerate(handles):
        h._keep = (notify[i][0], [notify[j][1] for j in range(num_shards)
                                  if j != i])
    return handles, list(names.values())


def close_child_ends(handles: List["_WorkerHandle"]) -> None:
    """After every worker spawned: the parent drops its copies of the
    child-side fds (workers own the inherited dups)."""
    for h in handles:
        for sock in (getattr(h, "_child_ctl", None),
                     getattr(h, "_accept_child", None)):
            if sock is not None:
                try:
                    sock.close()
                except OSError:
                    pass
        keep = getattr(h, "_keep", None)
        if keep is not None:
            rx, txs = keep
            try:
                rx.close()
            except OSError:
                pass
            for t in txs:
                try:
                    t.close()
                except OSError:
                    pass
        h._keep = None


async def _http_get(host: str, port: int, path: str,
                    timeout: float = 2.0) -> Tuple[int, bytes]:
    reader, writer = await asyncio.wait_for(
        asyncio.open_connection(host, port), timeout)
    try:
        writer.write(f"GET {path} HTTP/1.0\r\nHost: {host}\r\n\r\n"
                     .encode())
        await writer.drain()
        raw = await asyncio.wait_for(reader.read(-1), timeout)
    finally:
        writer.close()
        try:
            await writer.wait_closed()
        except Exception:
            pass
    head, _, body = raw.partition(b"\r\n\r\n")
    status = int(head.split(b" ", 2)[1]) if b" " in head else 502
    return status, body


def _inject_shard_label(text: str, shard: int) -> str:
    """Rewrite a worker's Prometheus exposition, adding shard="i" to every
    sample line (HELP/TYPE pass through; the aggregator dedupes those)."""
    out = []
    for line in text.splitlines():
        if not line or line.startswith("#"):
            out.append(line)
            continue
        brace = line.find("{")
        space = line.find(" ")
        if brace != -1 and (space == -1 or brace < space):
            # label values may legally contain spaces (and escaped
            # quotes), so the sample-name boundary is the LAST '}' —
            # never the first space
            close = line.rfind("}")
            if close <= brace:
                out.append(line)  # malformed: pass through untouched
                continue
            fam = line[:brace]
            labels = line[brace + 1:close]
            rest = line[close + 1:].lstrip()
            sep = "," if labels else ""
            out.append(f'{fam}{{shard="{shard}"{sep}{labels}}} {rest}')
        else:
            name, _, rest = line.partition(" ")
            out.append(f'{name}{{shard="{shard}"}} {rest}')
    return "\n".join(out)


class ShardSupervisor:
    """The parent process: spawns/reaps workers, relays control deltas,
    serves the aggregated observability endpoint."""

    def __init__(self, num_shards: int, metrics_endpoint: Optional[str],
                 worker_argv, ring_bytes: int = DEFAULT_RING_BYTES,
                 acceptor_endpoint: Optional[str] = None):
        """``worker_argv(shard, spec_json, metrics_endpoint)`` builds one
        worker's command line. ``acceptor_endpoint`` non-None switches to
        the fd-handoff accept path (platforms without SO_REUSEPORT)."""
        self.num_shards = num_shards
        self.metrics_endpoint = metrics_endpoint
        self.worker_argv = worker_argv
        self.ring_bytes = ring_bytes
        self.acceptor_endpoint = acceptor_endpoint
        self.handles: List[_WorkerHandle] = []
        self.ring_names: List[str] = []
        # initialized here (not in start()) so stop() is safe to call on
        # a supervisor whose start() failed partway
        self._hub_writers: Dict[int, asyncio.StreamWriter] = {}
        self._hub_tasks: List[asyncio.Task] = []
        self._server = None
        self._acceptor = None
        self._version = 0
        self._draining = False
        self.hub_disconnects = 0
        # the disconnect bound must exceed the aggregate LEGAL relay
        # volume toward one destination — (num_shards-1) producers, each
        # allowed _RELAY_MAX_BYTES unacked — or a slow-but-still-draining
        # worker at high shard counts would be killed by design-legal
        # traffic; HUB_MAX_BUFFER is the headroom for the unbudgeted
        # broadcast deltas on top of that
        self._hub_buffer_cap = HUB_MAX_BUFFER + \
            max(0, num_shards - 1) * ShardRuntime._RELAY_MAX_BYTES

    # -- control hub ---------------------------------------------------------

    def _hub_send(self, writers: Dict[int, asyncio.StreamWriter],
                  dst: int, frame: bytes) -> None:
        """Forward one control frame with a bounded write buffer. The hub
        never awaits drain (one slow worker must not stall the whole
        control plane), so the bound is enforced by disconnect: a worker
        whose buffered control traffic exceeds the cap has stopped
        draining its socket — cut the link so it fails fast (its
        SocketBus reader exits the worker) and the reaper notices."""
        w = writers.get(dst)
        if w is None:
            return
        transport = w.transport
        if transport is not None and \
                transport.get_write_buffer_size() + len(frame) \
                > self._hub_buffer_cap:
            self.hub_disconnects += 1
            logger.error(
                "control hub buffer to shard %d exceeded %d B; dropping "
                "the link so the wedged worker fails fast",
                dst, self._hub_buffer_cap)
            writers.pop(dst, None)
            try:
                # abort, not close(): close() flushes buffered data
                # first, i.e. waits for the very drain that will never
                # happen — the peer must see the connection DIE now
                transport.abort()
            except Exception:
                pass
            return
        w.write(frame)

    async def _hub_loop(self, handle: _WorkerHandle,
                        writers: Dict[int, asyncio.StreamWriter]) -> None:
        handle.parent_control.setblocking(False)
        reader, writer = await asyncio.open_connection(
            sock=handle.parent_control)
        writers[handle.shard] = writer
        try:
            while True:
                hdr = await reader.readexactly(4)
                (n,) = _FRAME_LEN.unpack(hdr)
                blob = await reader.readexactly(n)
                event = pickle.loads(blob)
                self._version += 1
                out = pickle.dumps((handle.shard, event),
                                   protocol=pickle.HIGHEST_PROTOCOL)
                frame = _FRAME_LEN.pack(len(out)) + out
                if event[0] in ("relay", "relay_ack"):
                    self._hub_send(writers, int(event[1]), frame)
                    continue
                for shard in list(writers):
                    if shard != handle.shard:
                        self._hub_send(writers, shard, frame)
        except (asyncio.IncompleteReadError, ConnectionError, OSError):
            pass  # worker exited; the reaper notices

    # -- aggregated observability -------------------------------------------

    async def _fetch_all(self, path: str) -> Dict[int, Tuple[int, bytes]]:
        async def one(h):
            try:
                return await _http_get("127.0.0.1", h.metrics_port, path)
            except Exception as exc:
                return 503, json.dumps(
                    {"status": "unhealthy",
                     "checks": {"reachable": {
                         "ok": False, "detail": f"worker shard "
                         f"{h.shard} unreachable: {exc!r}"}},
                     "draining": False, "ts": time.time()}).encode()
        results = await asyncio.gather(*(one(h) for h in self.handles))
        return {h.shard: r for h, r in zip(self.handles, results)}

    async def _render(self, path: str) -> Tuple[int, str, str]:
        """(status, content_type, body) for the parent endpoint."""
        if path.startswith("/metrics"):
            parts = []
            seen_meta = set()
            for shard, (status, body) in (await self._fetch_all(
                    "/metrics")).items():
                if status != 200:
                    parts.append(f"# shard {shard} unreachable\n")
                    continue
                labeled = _inject_shard_label(body.decode(errors="replace"),
                                              shard)
                lines = []
                for line in labeled.splitlines():
                    if line.startswith("#"):
                        if line in seen_meta:
                            continue
                        seen_meta.add(line)
                    lines.append(line)
                parts.append("\n".join(lines) + "\n")
            parts.append(f"# HELP cdn_shard_workers worker shard count\n"
                         f"# TYPE cdn_shard_workers gauge\n"
                         f"cdn_shard_workers {len(self.handles)}\n")
            parts.append(
                f"# HELP cdn_shard_hub_disconnects workers dropped for "
                f"control-hub write-buffer overflow\n"
                f"# TYPE cdn_shard_hub_disconnects counter\n"
                f"cdn_shard_hub_disconnects {self.hub_disconnects}\n")
            if self._acceptor is not None:
                parts.append(
                    f"# HELP cdn_shard_accept_drops accepted connections "
                    f"dropped because no worker took the fd handoff\n"
                    f"# TYPE cdn_shard_accept_drops counter\n"
                    f"cdn_shard_accept_drops "
                    f"{self._acceptor.handoff_drops}\n"
                    f"# HELP cdn_shard_accept_retries fd handoffs retried "
                    f"on another worker\n"
                    f"# TYPE cdn_shard_accept_retries counter\n"
                    f"cdn_shard_accept_retries "
                    f"{self._acceptor.handoff_retries}\n")
            return 200, "text/plain; version=0.0.4; charset=utf-8", \
                "".join(parts)
        if path.startswith("/healthz") or path.startswith("/readyz"):
            which = "/healthz" if path.startswith("/healthz") else "/readyz"
            per = await self._fetch_all(which)
            checks = {}
            ok = True
            for shard, (status, body) in sorted(per.items()):
                try:
                    doc = json.loads(body)
                    for name, c in doc.get("checks", {}).items():
                        checks[f"shard{shard}:{name}"] = c
                except ValueError:
                    checks[f"shard{shard}:parse"] = {
                        "ok": False, "detail": "unparseable worker body"}
                if status != 200:
                    ok = False
            alive = all(h.proc is not None and h.proc.returncode is None
                        for h in self.handles)
            checks["workers"] = {
                "ok": alive,
                "detail": f"{sum(1 for h in self.handles if h.proc and h.proc.returncode is None)}"
                          f"/{len(self.handles)} workers alive"}
            ok = ok and alive
            if which == "/readyz" and self._draining:
                checks["draining"] = {"ok": False, "detail": "drain latch"}
                ok = False
            body = json.dumps({
                "status": "ok" if ok else "unhealthy",
                "checks": checks,
                "draining": self._draining,
                "shards": self.num_shards,
                "ts": time.time(),
            }, separators=(",", ":")) + "\n"
            return (200 if ok else 503), "application/json", body
        if path.startswith("/debug/topology"):
            per = await self._fetch_all("/debug/topology")
            shards = {}
            for shard, (status, body) in sorted(per.items()):
                try:
                    shards[shard] = json.loads(body) if status == 200 \
                        else None
                except ValueError:
                    shards[shard] = None
            base = shards.get(0) or {}
            merged = dict(base)
            merged["num_users"] = sum(
                (t or {}).get("num_users", 0) for t in shards.values())
            users = []
            for shard, t in sorted(shards.items()):
                for u in (t or {}).get("users", []):
                    users.append({**u, "shard": shard})
            merged["users"] = users
            merged["shards"] = {
                str(s): ((t or {}).get("shard_runtime")
                         or {"unreachable": t is None})
                for s, t in sorted(shards.items())}
            merged["draining"] = self._draining or any(
                (t or {}).get("draining") for t in shards.values())
            return 200, "application/json", \
                json.dumps(merged, separators=(",", ":")) + "\n"
        return 404, "text/plain", "not found\n"

    async def _serve(self, reader: asyncio.StreamReader,
                     writer: asyncio.StreamWriter) -> None:
        try:
            line = await asyncio.wait_for(reader.readline(), 5.0)
            parts = line.decode(errors="replace").split()
            if len(parts) < 2 or parts[0] != "GET":
                status, ctype, body = 405, "text/plain", "GET only\n"
            else:
                while True:  # drain headers
                    h = await asyncio.wait_for(reader.readline(), 5.0)
                    if h in (b"\r\n", b"\n", b""):
                        break
                status, ctype, body = await self._render(parts[1])
            payload = body.encode()
            writer.write(
                f"HTTP/1.0 {status} X\r\nContent-Type: {ctype}\r\n"
                f"Content-Length: {len(payload)}\r\n\r\n".encode()
                + payload)
            await writer.drain()
        except Exception:
            pass
        finally:
            writer.close()
            try:
                await writer.wait_closed()
            except Exception:
                pass

    # -- lifecycle -----------------------------------------------------------

    async def start(self) -> None:
        from pushcdn_tpu.proto.error import parse_endpoint
        self.handles, self.ring_names = build_worker_ipc(
            self.num_shards, self.ring_bytes)
        if self.acceptor_endpoint:
            # fd-handoff path: one extra socketpair per worker
            for h in self.handles:
                parent_sock, child_sock = socket.socketpair(
                    socket.AF_UNIX, socket.SOCK_STREAM)
                h.spec["accept_fd"] = child_sock.fileno()
                h.child_fds.append(child_sock.fileno())
                h._accept_parent = parent_sock
                h._accept_child = child_sock
            self._acceptor = FdHandoffAcceptor(
                self.acceptor_endpoint,
                [h._accept_parent for h in self.handles])
        mhost, mport = (None, None)
        if self.metrics_endpoint:
            mhost, mport = parse_endpoint(self.metrics_endpoint)
        for h in self.handles:
            worker_metrics = None
            if mport is not None:
                h.metrics_port = mport + 1 + h.shard
                worker_metrics = f"{mhost}:{h.metrics_port}"
            argv = self.worker_argv(h.shard, json.dumps(h.spec),
                                    worker_metrics)
            h.proc = await asyncio.create_subprocess_exec(
                *argv, pass_fds=tuple(h.child_fds),
                stdout=None, stderr=None)
            logger.info("shard worker %d up (pid %d)", h.shard, h.proc.pid)
        close_child_ends(self.handles)
        if self.metrics_endpoint:
            self._server = await asyncio.start_server(
                self._serve, mhost, mport)
        self._hub_tasks = [
            asyncio.create_task(self._hub_loop(h, self._hub_writers),
                                name=f"shard-hub-{h.shard}")
            for h in self.handles]

    def signal_workers(self, sig=signal_mod.SIGTERM) -> None:
        for h in self.handles:
            if h.proc is not None and h.proc.returncode is None:
                try:
                    h.proc.send_signal(sig)
                except ProcessLookupError:
                    pass

    def begin_drain(self) -> None:
        """Readiness flips false on the parent AND every shard first; the
        workers then serve out PUSHCDN_DRAIN_GRACE_S before their
        listeners close; the parent reaps them before its own endpoint
        goes away (bin/common.install_drain_signals drives this)."""
        self._draining = True
        health_mod.set_draining("shard supervisor drain")
        self.signal_workers(signal_mod.SIGTERM)

    async def wait_any_worker_exit(self) -> int:
        waits = [asyncio.create_task(h.proc.wait()) for h in self.handles]
        done, pending = await asyncio.wait(
            waits, return_when=asyncio.FIRST_COMPLETED)
        for p in pending:
            p.cancel()
        return done.pop().result()

    async def reap(self, timeout: float) -> None:
        try:
            await asyncio.wait_for(
                asyncio.gather(*(h.proc.wait() for h in self.handles
                                 if h.proc is not None)), timeout)
        except asyncio.TimeoutError:
            self.signal_workers(signal_mod.SIGKILL)
            await asyncio.gather(*(h.proc.wait() for h in self.handles
                                   if h.proc is not None),
                                 return_exceptions=True)

    async def stop(self) -> None:
        for t in self._hub_tasks:
            t.cancel()
        if self._hub_tasks:
            await asyncio.gather(*self._hub_tasks, return_exceptions=True)
        if self._acceptor is not None:
            self._acceptor.close()
        if self._server is not None:
            self._server.close()
            await self._server.wait_closed()
        for name in self.ring_names:
            shardring.unlink_ring(name)
