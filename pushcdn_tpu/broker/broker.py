"""Broker bootstrap and fail-fast supervision.

Capability parity with cdn-broker/src/lib.rs:43-319: config → ``local_ip``
substitution, discovery client, dual listeners (public = users, private =
peer brokers), optional metrics endpoint; ``start`` spawns the five
long-lived tasks (heartbeat, sync, whitelist, user listener, broker
listener) and the process dies if any of them exits (lib.rs:302-318).
"""

from __future__ import annotations

import asyncio
import logging
import os
import socket
import time
from dataclasses import dataclass
from typing import TYPE_CHECKING, Optional

from pushcdn_tpu.broker import metrics as broker_metrics
from pushcdn_tpu.broker.connections import Connections
from pushcdn_tpu.broker.tasks import heartbeat as heartbeat_task
from pushcdn_tpu.broker.tasks import listeners as listener_tasks
from pushcdn_tpu.broker.tasks import sync as sync_task
from pushcdn_tpu.broker.tasks import whitelist as whitelist_task
from pushcdn_tpu.proto import health as health_mod
from pushcdn_tpu.proto import ledger as ledger_mod
from pushcdn_tpu.proto import metrics as metrics_mod
from pushcdn_tpu.proto.crypto.signature import KeyPair
from pushcdn_tpu.proto.crypto.tls import Certificate, generate_cert_from_ca, load_ca
from pushcdn_tpu.proto.def_ import RunDef
from pushcdn_tpu.proto.discovery.base import BrokerIdentifier
from pushcdn_tpu.proto.error import Error, ErrorKind, bail
from pushcdn_tpu.proto.limiter import Limiter
from pushcdn_tpu.proto.util import mnemonic

if TYPE_CHECKING:  # import only for annotations (runtime import would cycle)
    from pushcdn_tpu.broker.device_plane import DevicePlaneConfig

logger = logging.getLogger("pushcdn.broker")

GIB = 1024 * 1024 * 1024


def _substitute_local_ip(endpoint: str) -> str:
    """Replace the magic host ``local_ip`` with this machine's primary
    address (parity cdn-broker/src/lib.rs:157-168)."""
    if not endpoint.startswith("local_ip"):
        return endpoint
    s = socket.socket(socket.AF_INET, socket.SOCK_DGRAM)
    try:
        s.connect(("8.8.8.8", 80))  # no traffic sent; just picks a route
        ip = s.getsockname()[0]
    except OSError:
        ip = "127.0.0.1"
    finally:
        s.close()
    return endpoint.replace("local_ip", ip, 1)


@dataclass
class BrokerConfig:
    """Parity ``Config<R>`` (cdn-broker/src/lib.rs:43-96)."""

    run_def: RunDef
    keypair: KeyPair
    discovery_endpoint: str
    public_advertise_endpoint: str
    public_bind_endpoint: str
    private_advertise_endpoint: str
    private_bind_endpoint: str
    metrics_bind_endpoint: Optional[str] = None
    ca_cert_path: Optional[str] = None
    ca_key_path: Optional[str] = None
    # attach the TPU device plane: eligible messages route on-device in
    # batched jitted steps (broker/device_plane.py); None = host-only
    device_plane: Optional["DevicePlaneConfig"] = None
    # 1 GiB default pool (binaries/broker.rs:67-72)
    global_memory_pool_size: int = GIB
    # operational cadences (heartbeat.rs:39,107; sync.rs:142; whitelist.rs)
    heartbeat_interval_s: float = 10.0
    sync_interval_s: float = 10.0
    whitelist_interval_s: float = 60.0
    membership_ttl_s: float = 60.0
    auth_timeout_s: float = 5.0
    # /readyz discovery check: re-probe the store at most this often (the
    # heartbeat's own successes/failures refresh the cache for free)
    discovery_probe_ttl_s: float = 5.0
    # False = register in discovery but never dial host broker links
    # (deployments whose inter-broker plane is the device mesh only)
    form_mesh: bool = True
    # ---- sharded data plane (ISSUE 6) ----
    # worker-shard role: shard 0 owns the private (mesh) listener and the
    # heartbeat/sync/whitelist control tasks; other workers run only the
    # user data plane. reuse_port spreads accepted users across workers.
    shard_index: int = 0
    num_shards: int = 1
    bind_private: bool = True
    reuse_port: bool = False
    # fd-handoff fallback (no SO_REUSEPORT): adopt accepted sockets from
    # the parent over this inherited unix-socketpair fd instead of binding
    accept_handoff_fd: Optional[int] = None


class Broker:
    """One broker process (parity ``Broker``/``Inner``, lib.rs:98-319)."""

    def __init__(self, config: BrokerConfig):
        self.config = config
        self.run_def = config.run_def
        self.identity: BrokerIdentifier = None       # set in new()
        self.discovery = None
        self.limiter: Limiter = None
        self.connections: Connections = None
        self.certificate: Optional[Certificate] = None
        self.user_listener = None
        self.broker_listener = None
        self.admission = None  # AdmissionControl, set in new()
        self._tasks: list[asyncio.Task] = []
        self._stopped = asyncio.Event()
        # set by the device plane when overflow traffic needs host links
        # before the next scheduled heartbeat tick
        self.host_links_kick = asyncio.Event()
        self._metrics_server = None
        self.device_plane = None
        self.shard_runtime = None  # ShardRuntime when this is one of N workers
        self.durable = None  # DurableTopics, set in new() (ISSUE 14)
        self.seen_dialing: set[str] = set()  # peers we're currently dialing
        # readiness state (ISSUE 5): listeners-bound latch, cached
        # discovery probe (refreshed by the heartbeat task and, past the
        # TTL, by an active probe from the /readyz handler), and the peer
        # count discovery last reported (the solo-vs-partitioned signal)
        self.listeners_bound = False
        self._discovery_probe: tuple = (False, "not probed yet")
        self._discovery_probe_at: Optional[float] = None
        self.last_peer_count: Optional[int] = None
        # elastic membership (ISSUE 12): set by begin_drain; the heartbeat
        # task checks it to deregister instead of re-advertising, and the
        # re-homer refuses to run twice
        self.draining = False

    @classmethod
    async def new(cls, config: BrokerConfig) -> "Broker":
        self = cls(config)
        c = config

        public_adv = _substitute_local_ip(c.public_advertise_endpoint)
        private_adv = _substitute_local_ip(c.private_advertise_endpoint)
        self.identity = BrokerIdentifier(public_adv, private_adv)

        self.discovery = await self.run_def.discovery.new(
            c.discovery_endpoint, identity=self.identity,
            global_permits=self.run_def.global_permits)

        ca_cert, ca_key = load_ca(c.ca_cert_path, c.ca_key_path)
        self.certificate = generate_cert_from_ca(ca_cert, ca_key)

        self.limiter = Limiter(global_pool_bytes=c.global_memory_pool_size)
        self.connections = Connections(str(self.identity))
        # admission control (ISSUE 7): connection budgets + subscribe-rate
        # shedding; env-configured, disabled by default
        from pushcdn_tpu.broker.admission import AdmissionControl
        self.admission = AdmissionControl(self)
        # durable topics (ISSUE 14): retention rings + replay subscribe +
        # wildcard namespace; env-configured, retention disabled by default
        # (wildcard SubscribeFrom works either way)
        from pushcdn_tpu.broker.retention import DurableTopics
        self.durable = DurableTopics.from_env(self)

        # The observability endpoint comes up BEFORE the listeners bind:
        # /readyz must be probe-able (and false) during startup, so an
        # orchestrator never routes to a broker that can't accept yet.
        if c.metrics_bind_endpoint:
            self._metrics_server = await metrics_mod.serve_metrics(
                c.metrics_bind_endpoint)
            self.register_observability()
            # CI/test hook: hold the listener binds open for a beat so an
            # external prober can observe the not-ready-before-bind state
            # (scripts/local_cluster.py uses this to prove the readiness
            # lifecycle end to end)
            delay = float(os.environ.get("PUSHCDN_BIND_DELAY_S", "") or 0)
            if delay > 0:
                await asyncio.sleep(delay)

        try:
            # public listener carries users, private carries peer brokers
            # (lib.rs:190-212)
            if c.accept_handoff_fd is not None:
                # sharded fd-handoff fallback: adopt accepted sockets from
                # the parent acceptor instead of binding (no SO_REUSEPORT)
                import socket as socket_mod

                from pushcdn_tpu.broker.sharding import FdHandoffListener
                self.user_listener = FdHandoffListener(socket_mod.socket(
                    socket_mod.AF_UNIX, socket_mod.SOCK_STREAM,
                    fileno=c.accept_handoff_fd))
            elif c.reuse_port:
                self.user_listener = await self.run_def.user_def.protocol.bind(
                    _substitute_local_ip(c.public_bind_endpoint),
                    certificate=self.certificate, reuse_port=True)
            else:
                self.user_listener = await self.run_def.user_def.protocol.bind(
                    _substitute_local_ip(c.public_bind_endpoint),
                    certificate=self.certificate)
            if c.bind_private:
                self.broker_listener = await self.run_def.broker_def.protocol.bind(
                    _substitute_local_ip(c.private_bind_endpoint),
                    certificate=self.certificate)
            self.listeners_bound = True

            if c.device_plane is not None:
                from pushcdn_tpu.broker.device_plane import DevicePlane
                self.device_plane = DevicePlane(self, c.device_plane)
                self.connections.observer = self.device_plane
        except BaseException:
            # a failed bootstrap (port in use) must not strand a live
            # metrics server answering /readyz for a broker that never
            # existed, nor leave its checks in the process registries
            if self.user_listener is not None:
                try:
                    await self.user_listener.close()
                except Exception:
                    pass
            if self._metrics_server is not None:
                self._metrics_server.close()
                await self._metrics_server.wait_closed()
                self._metrics_server = None
                self.unregister_observability()
            raise

        logger.info("broker %s ready (users on %s, brokers on %s)",
                    self.identity, c.public_bind_endpoint, c.private_bind_endpoint)
        return self

    # -- observability plane (ISSUE 5) --------------------------------------

    def register_observability(self) -> None:
        """Register this broker's readiness checks + /debug/topology on
        the process-global health/metrics registries (one broker per
        process owns the endpoint; in-process test brokers without a
        metrics server never register)."""
        health_mod.register_readiness("listeners", self._check_listeners)
        health_mod.register_readiness("discovery", self._check_discovery)
        health_mod.register_readiness("mesh", self._check_mesh)
        health_mod.register_readiness("admission", self._check_admission)
        health_mod.register_readiness("conservation",
                                      ledger_mod.LEDGER.conservation_check)
        metrics_mod.register_debug_route("/debug/topology",
                                         self._topology_route)
        metrics_mod.register_debug_route("/debug/ledger",
                                         ledger_mod.ledger_route)
        metrics_mod.register_debug_route("/drain", self._drain_route)

    def unregister_observability(self) -> None:
        for name in ("listeners", "discovery", "mesh", "admission",
                     "conservation"):
            health_mod.unregister(name)
        metrics_mod.unregister_debug_route("/debug/topology")
        metrics_mod.unregister_debug_route("/debug/ledger")
        metrics_mod.unregister_debug_route("/drain")

    def _check_listeners(self):
        if not self.listeners_bound:
            return False, "listeners not bound yet"
        return True, "user + broker listeners bound"

    def _check_admission(self):
        """Not ready while the admission plane is actively shedding —
        the load balancer steers new connections away until the box has
        gone PUSHCDN_SHED_READY_S without refusing work."""
        if self.admission is None:
            return True, "admission control not configured"
        return self.admission.readiness_check()

    def note_discovery_probe(self, ok: bool, detail: str) -> None:
        """Cache a discovery-store contact outcome (the heartbeat task
        reports its own successes/failures here, so steady-state /readyz
        never pays an extra round-trip)."""
        self._discovery_probe = (ok, detail)
        self._discovery_probe_at = time.monotonic()

    async def _check_discovery(self):
        now = time.monotonic()
        if (self._discovery_probe_at is not None
                and now - self._discovery_probe_at
                < self.config.discovery_probe_ttl_s):
            return self._discovery_probe
        # cache expired: active probe (bounded — a hung store must not
        # wedge the /readyz handler)
        try:
            async with asyncio.timeout(2.0):
                peers = await self.discovery.get_other_brokers()
            self.last_peer_count = len(peers)
            self.note_discovery_probe(True, f"ok ({len(peers)} peers)")
        except Exception as exc:
            self.note_discovery_probe(False, f"probe failed: {exc!r}")
        return self._discovery_probe

    def _check_mesh(self):
        """Ready when the mesh has ≥1 live peer link, or being solo is
        intentional: discovery reports no other brokers (we ARE the
        deployment), or the inter-broker plane is the device mesh
        (form_mesh=False), or this is a non-zero worker shard (the mesh
        links live on shard 0)."""
        if self.config.num_shards > 1 and self.config.shard_index != 0:
            return True, "mesh links owned by shard 0"
        n = self.connections.num_brokers
        if n >= 1:
            return True, f"{n} peer links"
        if not self.config.form_mesh:
            return True, "device-mesh inter-broker plane (no host links)"
        if self.last_peer_count == 0:
            return True, "intentionally solo (no other brokers registered)"
        if self.last_peer_count is None:
            return False, "no peer links and discovery not consulted yet"
        return (False, f"0 peer links but discovery reports "
                       f"{self.last_peer_count} other brokers")

    def begin_drain(self, reason: str = "shutdown") -> None:
        """Flip /readyz to 503 (and record the ready-flip flight-recorder
        event) BEFORE any listener closes — the load balancer stops
        routing here while in-flight traffic still drains."""
        self.draining = True
        health_mod.set_draining(reason)

    async def _drain_route(self, params: dict) -> dict:
        """``GET /drain``: operator-triggered elastic drain (ISSUE 12) —
        same sequence SIGTERM runs: flip /readyz, leave discovery, then
        actively re-home every connected user to the live peers. Returns
        the re-home summary so the operator sees migrated/orphaned counts
        without tailing logs."""
        from pushcdn_tpu.broker import rehome as rehome_mod
        already = self.draining
        self.begin_drain("operator /drain")
        summary = await rehome_mod.rehome_users(self)
        summary["was_draining"] = already
        return summary

    def _topology_route(self, params: dict) -> dict:
        return self.topology_snapshot()

    def topology_snapshot(self, max_users: int = 256) -> dict:
        """The live mesh as one JSON-able dict (``GET /debug/topology``):
        peer links with writer-queue backpressure, per-connection
        subscribe counts, interest-table summary, and the cut-through
        snapshot's age/churn state."""
        conns = self.connections
        peers = []
        for ident, handle in conns.brokers.items():
            depth, in_flight = handle.connection.queue_stats()
            peers.append({
                "id": ident,
                "writer_queue_depth": depth,
                "bytes_in_flight": in_flight,
                "topics": len(conns.broker_topics.get_values_of_key(ident)),
            })
        users = []
        for key, handle in conns.users.items():
            if len(users) >= max_users:
                break
            depth, in_flight = handle.connection.queue_stats()
            users.append({
                "key": mnemonic(key),
                "topics": len(conns.user_topics.get_values_of_key(key)),
                "writer_queue_depth": depth,
                "bytes_in_flight": in_flight,
            })
        topic_cardinality = {
            str(t): len(conns.user_topics.get_keys_by_value(t))
            for t in sorted(set(conns.user_topics.values()))}
        state = getattr(self, "_route_state", None)
        runtime = self.shard_runtime
        return {
            "shard_runtime": runtime.stats() if runtime is not None else None,
            "identity": str(self.identity),
            "draining": health_mod.draining() is not None,
            "interest_version": conns.interest_version,
            "num_users": conns.num_users,
            "num_brokers": conns.num_brokers,
            "peers": sorted(peers, key=lambda p: p["id"]),
            "users": users,
            "users_truncated": max(conns.num_users - len(users), 0),
            "interest": {
                "topic_cardinality": topic_cardinality,
                "broker_topics": len(set(conns.broker_topics.values())),
                "direct_map_size": len(conns.direct_map),
            },
            "cutthrough": state.summary() if state is not None else None,
            "admission": (self.admission.summary()
                          if self.admission is not None else None),
            "durable": (self.durable.stats()
                        if self.durable is not None and self.durable.enabled
                        else None),
        }

    # -- supervision --------------------------------------------------------

    async def start(self) -> None:
        """Spawn the five supervised tasks (lib.rs:269-318). A non-zero
        worker shard runs only the user data plane (+ whitelist for its
        own users); shard 0 / unsharded brokers run the full set."""
        if self.device_plane is not None:
            await self.device_plane.start()
        metrics_mod.PRE_RENDER_HOOKS.append(self.update_metrics)
        spawn = asyncio.create_task
        self._tasks = [
            spawn(listener_tasks.run_user_listener_task(self),
                  name="user-listener"),
            spawn(whitelist_task.run_whitelist_task(self), name="whitelist"),
            # continuous conservation auditor + SLO burn engine (ISSUE 20)
            spawn(metrics_mod.supervised(
                lambda: ledger_mod.run_auditor(
                    my_ident=self.connections.identity),
                "ledger-auditor"),
                name="ledger-auditor"),
        ]
        if self.config.bind_private:
            # heartbeat rides supervised(): a transient discovery outage
            # (store locked, network blip) must not fail-fast the whole
            # broker — readiness already degrades via note_discovery_probe,
            # each death lands in the supervised-tasks flight recorder, and
            # the task resumes once the store answers again
            self._tasks += [
                spawn(metrics_mod.supervised(
                    lambda: heartbeat_task.run_heartbeat_task(self),
                    "heartbeat"),
                    name="heartbeat"),
                spawn(sync_task.run_sync_task(self), name="sync"),
                spawn(listener_tasks.run_broker_listener_task(self),
                      name="broker-listener"),
            ]
        if self.shard_runtime is not None:
            self._tasks.append(spawn(self.shard_runtime.run_ring_drain(),
                                     name="shard-ring-drain"))
            bus = self.shard_runtime.bus
            if bus is not None and hasattr(bus, "run"):
                self._tasks.append(spawn(bus.run(), name="shard-bus"))

    async def run_until_failure(self) -> None:
        """Fail-fast: the first core task to exit brings the broker down
        (parity select! at lib.rs:302-318)."""
        await self.start()
        done, _pending = await asyncio.wait(
            self._tasks, return_when=asyncio.FIRST_COMPLETED)
        task = done.pop()
        exc = task.exception()
        await self.stop()
        if exc is not None:
            raise Error(ErrorKind.CONNECTION,
                        f"core task {task.get_name()!r} died: {exc!r}", exc)
        bail(ErrorKind.CONNECTION, f"core task {task.get_name()!r} exited")

    async def stop(self) -> None:
        # readiness flips false FIRST — before any listener closes — so a
        # prober sees "draining" rather than a connection refusal (only
        # the endpoint-owning broker touches the process-global latch)
        if self._metrics_server is not None:
            self.begin_drain("broker stop")
        self._stopped.set()
        if self.update_metrics in metrics_mod.PRE_RENDER_HOOKS:
            metrics_mod.PRE_RENDER_HOOKS.remove(self.update_metrics)
        if self.device_plane is not None:
            await self.device_plane.stop()
        for t in self._tasks:
            t.cancel()
        if self._tasks:
            await asyncio.gather(*self._tasks, return_exceptions=True)
        self.connections.remove_all()
        if self.durable is not None:
            self.durable.close()
        if self.shard_runtime is not None:
            self.shard_runtime.close()
            self.shard_runtime = None
        for listener in (self.user_listener, self.broker_listener):
            if listener is not None:
                try:
                    await listener.close()
                except Exception:
                    pass
        self.listeners_bound = False
        if self.discovery is not None:
            await self.discovery.close()
        if self._metrics_server is not None:
            self._metrics_server.close()
            await self._metrics_server.wait_closed()
            self._metrics_server = None
            # leave the process-global registries clean for the next
            # owner (in-process restarts, test isolation)
            self.unregister_observability()
            health_mod.clear_draining()
        broker_metrics.NUM_USERS_CONNECTED.set(0)
        broker_metrics.NUM_BROKERS_CONNECTED.set(0)
        logger.info("broker %s stopped", self.identity)

    # -- convenience (used by tasks) ---------------------------------------

    def update_metrics(self) -> None:
        """Refresh the process-global gauges; runs on connection events
        AND as a metrics pre-render hook, so device-plane counters that
        move per pump step are current at scrape time without any
        hot-loop pushes."""
        broker_metrics.NUM_USERS_CONNECTED.set(self.connections.num_users)
        broker_metrics.NUM_BROKERS_CONNECTED.set(self.connections.num_brokers)
        plane = self.device_plane
        if plane is not None:
            broker_metrics.DEVICE_STEPS.set(plane.steps)
            broker_metrics.DEVICE_MESSAGES_ROUTED.set(plane.messages_routed)
