"""Broker bootstrap and fail-fast supervision.

Capability parity with cdn-broker/src/lib.rs:43-319: config → ``local_ip``
substitution, discovery client, dual listeners (public = users, private =
peer brokers), optional metrics endpoint; ``start`` spawns the five
long-lived tasks (heartbeat, sync, whitelist, user listener, broker
listener) and the process dies if any of them exits (lib.rs:302-318).
"""

from __future__ import annotations

import asyncio
import logging
import socket
from dataclasses import dataclass
from typing import TYPE_CHECKING, Optional

from pushcdn_tpu.broker import metrics as broker_metrics
from pushcdn_tpu.broker.connections import Connections
from pushcdn_tpu.broker.tasks import heartbeat as heartbeat_task
from pushcdn_tpu.broker.tasks import listeners as listener_tasks
from pushcdn_tpu.broker.tasks import sync as sync_task
from pushcdn_tpu.broker.tasks import whitelist as whitelist_task
from pushcdn_tpu.proto import metrics as metrics_mod
from pushcdn_tpu.proto.crypto.signature import KeyPair
from pushcdn_tpu.proto.crypto.tls import Certificate, generate_cert_from_ca, load_ca
from pushcdn_tpu.proto.def_ import RunDef
from pushcdn_tpu.proto.discovery.base import BrokerIdentifier
from pushcdn_tpu.proto.error import Error, ErrorKind, bail
from pushcdn_tpu.proto.limiter import Limiter

if TYPE_CHECKING:  # import only for annotations (runtime import would cycle)
    from pushcdn_tpu.broker.device_plane import DevicePlaneConfig

logger = logging.getLogger("pushcdn.broker")

GIB = 1024 * 1024 * 1024


def _substitute_local_ip(endpoint: str) -> str:
    """Replace the magic host ``local_ip`` with this machine's primary
    address (parity cdn-broker/src/lib.rs:157-168)."""
    if not endpoint.startswith("local_ip"):
        return endpoint
    s = socket.socket(socket.AF_INET, socket.SOCK_DGRAM)
    try:
        s.connect(("8.8.8.8", 80))  # no traffic sent; just picks a route
        ip = s.getsockname()[0]
    except OSError:
        ip = "127.0.0.1"
    finally:
        s.close()
    return endpoint.replace("local_ip", ip, 1)


@dataclass
class BrokerConfig:
    """Parity ``Config<R>`` (cdn-broker/src/lib.rs:43-96)."""

    run_def: RunDef
    keypair: KeyPair
    discovery_endpoint: str
    public_advertise_endpoint: str
    public_bind_endpoint: str
    private_advertise_endpoint: str
    private_bind_endpoint: str
    metrics_bind_endpoint: Optional[str] = None
    ca_cert_path: Optional[str] = None
    ca_key_path: Optional[str] = None
    # attach the TPU device plane: eligible messages route on-device in
    # batched jitted steps (broker/device_plane.py); None = host-only
    device_plane: Optional["DevicePlaneConfig"] = None
    # 1 GiB default pool (binaries/broker.rs:67-72)
    global_memory_pool_size: int = GIB
    # operational cadences (heartbeat.rs:39,107; sync.rs:142; whitelist.rs)
    heartbeat_interval_s: float = 10.0
    sync_interval_s: float = 10.0
    whitelist_interval_s: float = 60.0
    membership_ttl_s: float = 60.0
    auth_timeout_s: float = 5.0
    # False = register in discovery but never dial host broker links
    # (deployments whose inter-broker plane is the device mesh only)
    form_mesh: bool = True


class Broker:
    """One broker process (parity ``Broker``/``Inner``, lib.rs:98-319)."""

    def __init__(self, config: BrokerConfig):
        self.config = config
        self.run_def = config.run_def
        self.identity: BrokerIdentifier = None       # set in new()
        self.discovery = None
        self.limiter: Limiter = None
        self.connections: Connections = None
        self.certificate: Optional[Certificate] = None
        self.user_listener = None
        self.broker_listener = None
        self._tasks: list[asyncio.Task] = []
        self._stopped = asyncio.Event()
        # set by the device plane when overflow traffic needs host links
        # before the next scheduled heartbeat tick
        self.host_links_kick = asyncio.Event()
        self._metrics_server = None
        self.device_plane = None
        self.seen_dialing: set[str] = set()  # peers we're currently dialing

    @classmethod
    async def new(cls, config: BrokerConfig) -> "Broker":
        self = cls(config)
        c = config

        public_adv = _substitute_local_ip(c.public_advertise_endpoint)
        private_adv = _substitute_local_ip(c.private_advertise_endpoint)
        self.identity = BrokerIdentifier(public_adv, private_adv)

        self.discovery = await self.run_def.discovery.new(
            c.discovery_endpoint, identity=self.identity,
            global_permits=self.run_def.global_permits)

        ca_cert, ca_key = load_ca(c.ca_cert_path, c.ca_key_path)
        self.certificate = generate_cert_from_ca(ca_cert, ca_key)

        self.limiter = Limiter(global_pool_bytes=c.global_memory_pool_size)
        self.connections = Connections(str(self.identity))

        # public listener carries users, private carries peer brokers
        # (lib.rs:190-212)
        self.user_listener = await self.run_def.user_def.protocol.bind(
            _substitute_local_ip(c.public_bind_endpoint),
            certificate=self.certificate)
        self.broker_listener = await self.run_def.broker_def.protocol.bind(
            _substitute_local_ip(c.private_bind_endpoint),
            certificate=self.certificate)

        if c.device_plane is not None:
            from pushcdn_tpu.broker.device_plane import DevicePlane
            self.device_plane = DevicePlane(self, c.device_plane)
            self.connections.observer = self.device_plane

        if c.metrics_bind_endpoint:
            self._metrics_server = await metrics_mod.serve_metrics(
                c.metrics_bind_endpoint)
        logger.info("broker %s ready (users on %s, brokers on %s)",
                    self.identity, c.public_bind_endpoint, c.private_bind_endpoint)
        return self

    # -- supervision --------------------------------------------------------

    async def start(self) -> None:
        """Spawn the five supervised tasks (lib.rs:269-318)."""
        if self.device_plane is not None:
            await self.device_plane.start()
        metrics_mod.PRE_RENDER_HOOKS.append(self.update_metrics)
        spawn = asyncio.create_task
        self._tasks = [
            spawn(heartbeat_task.run_heartbeat_task(self), name="heartbeat"),
            spawn(sync_task.run_sync_task(self), name="sync"),
            spawn(whitelist_task.run_whitelist_task(self), name="whitelist"),
            spawn(listener_tasks.run_user_listener_task(self), name="user-listener"),
            spawn(listener_tasks.run_broker_listener_task(self), name="broker-listener"),
        ]

    async def run_until_failure(self) -> None:
        """Fail-fast: the first core task to exit brings the broker down
        (parity select! at lib.rs:302-318)."""
        await self.start()
        done, _pending = await asyncio.wait(
            self._tasks, return_when=asyncio.FIRST_COMPLETED)
        task = done.pop()
        exc = task.exception()
        await self.stop()
        if exc is not None:
            raise Error(ErrorKind.CONNECTION,
                        f"core task {task.get_name()!r} died: {exc!r}", exc)
        bail(ErrorKind.CONNECTION, f"core task {task.get_name()!r} exited")

    async def stop(self) -> None:
        self._stopped.set()
        if self.update_metrics in metrics_mod.PRE_RENDER_HOOKS:
            metrics_mod.PRE_RENDER_HOOKS.remove(self.update_metrics)
        if self.device_plane is not None:
            await self.device_plane.stop()
        for t in self._tasks:
            t.cancel()
        if self._tasks:
            await asyncio.gather(*self._tasks, return_exceptions=True)
        self.connections.remove_all()
        for listener in (self.user_listener, self.broker_listener):
            if listener is not None:
                try:
                    await listener.close()
                except Exception:
                    pass
        if self.discovery is not None:
            await self.discovery.close()
        if self._metrics_server is not None:
            self._metrics_server.close()
            await self._metrics_server.wait_closed()
            self._metrics_server = None
        broker_metrics.NUM_USERS_CONNECTED.set(0)
        broker_metrics.NUM_BROKERS_CONNECTED.set(0)
        logger.info("broker %s stopped", self.identity)

    # -- convenience (used by tasks) ---------------------------------------

    def update_metrics(self) -> None:
        """Refresh the process-global gauges; runs on connection events
        AND as a metrics pre-render hook, so device-plane counters that
        move per pump step are current at scrape time without any
        hot-loop pushes."""
        broker_metrics.NUM_USERS_CONNECTED.set(self.connections.num_users)
        broker_metrics.NUM_BROKERS_CONNECTED.set(self.connections.num_brokers)
        plane = self.device_plane
        if plane is not None:
            broker_metrics.DEVICE_STEPS.set(plane.steps)
            broker_metrics.DEVICE_MESSAGES_ROUTED.set(plane.messages_routed)
