"""Shared-memory handoff ring for the sharded data plane (ISSUE 6).

One **SPSC** (single-producer / single-consumer) byte ring per *directed*
shard pair, backed by ``multiprocessing.shared_memory``. The producer is
the origin shard's drain (cut-through ``_send_plan`` or the scalar
``EgressBatch`` flush); the consumer is the destination shard's ring-drain
task. A record carries **already-encoded wire bytes** (u32-BE
length-delimited frames, exactly what arrived on the origin's socket)
plus a compact per-peer frame-index list — the "RPC Considered Harmful"
rule applied to our own interior boundary: the bytes the data plane
already holds in transmittable form cross the process boundary verbatim,
never re-serialized. The consumer slices per-peer streams out of the
record (zero-copy ``memoryview`` for contiguous index runs) and hands
them straight to the egress writers via ``PreEncoded``; a
:class:`SlotLease` rides each writer entry's ``owner`` seat so the ring
slot is reclaimed only after the LAST pending flush drops it (the
shard-pair analog of ``proto.limiter.BytesLease``).

Layout (offsets in bytes, all integers little-endian):

- header (64 B): ``u64 head`` (producer cursor, absolute, monotonic),
  ``u64 tail`` (consumer cursor), ``u64 dropped`` (producer-side
  ring-full fallbacks), ``u64 seq`` (next record sequence number),
  ``u64 poisoned`` (consumer abandoned the ring — a record never
  committed; the producer must stop pushing and use the relay);
- data region: records are contiguous (never wrap mid-record — a record
  that would cross the end is preceded by a PAD record covering the
  remainder).

Record: ``u32 total_len`` (header+body, 8-aligned), ``u32 commit``
(``COMMIT_FLAG | (seq & 0x7fffffff)``, written LAST — a reader seeing
anything else under an advanced ``head`` has caught a torn write and
backs off), then the body::

    u32 n_frames   u32 n_peers
    frame table:   n_frames x (u32 off, u32 len)      # off into payload
    peer table:    n_peers  x (u8 kind, u8 pad, u16 ident_len,
                               u32 n_idx, ident bytes, n_idx x u32)
    payload:       wire bytes (each frame u32-BE length-prefixed)

``try_push`` never blocks: a full ring returns False and bumps
``dropped`` — the caller's contract is a *counted* fallback to the
control-plane relay path, not a stalled drain.
"""

from __future__ import annotations

import socket
import struct
from collections import deque
from multiprocessing import shared_memory
from typing import Dict, List, Optional, Sequence, Tuple

_HDR = struct.Struct("<QQQQ")          # head, tail, dropped, seq
_REC = struct.Struct("<II")            # total_len, commit
_BODY = struct.Struct("<II")           # n_frames, n_peers
_FRAME = struct.Struct("<II")          # off, len
_PEER = struct.Struct("<BBHI")         # kind, pad, ident_len, n_idx
HEADER_BYTES = 64

COMMIT_FLAG = 0x8000_0000
PAD_MAGIC = 0x7F7F_7F7F                # commit word of a PAD record

KIND_USER = 0
KIND_BROKER = 1

DEFAULT_CAPACITY = 4 * 1024 * 1024


class RingRecord:
    """One drained record: per-peer targets over a shared payload view.

    ``peers`` is ``[(kind, ident, idx_list)]``; :meth:`stream_for` builds
    the wire stream for one peer — a zero-copy memoryview of the shm
    payload when the peer's frame indices form a consecutive increasing
    run (frames are stored back-to-back in table order, so consecutive
    indices ARE contiguous bytes), else one gather copy in idx order.
    """

    __slots__ = ("peers", "payload", "frame_offs", "frame_lens", "_lease")

    def __init__(self, peers, payload, frame_offs, frame_lens, lease):
        self.peers = peers
        self.payload = payload          # memoryview into the shm slot
        self.frame_offs = frame_offs
        self.frame_lens = frame_lens
        self._lease = lease

    def stream_for(self, idx: Sequence[int]):
        first, last = idx[0], idx[-1]
        n = len(idx)
        # zero-copy only for a STRICTLY consecutive run (first, first+1,
        # ..., last): frames sit back-to-back in table order, so such a
        # run is one byte span. The O(1) span test alone is NOT enough —
        # a same-span permutation like [0, 2, 1, 3] (emitted when a peer
        # shares frames first indexed by an earlier peer in the batch)
        # must gather in idx order, or the slice would silently reorder
        # this peer's frames. n <= 2 needs no scan (span == n pins both
        # elements); longer runs confirm with one C-level range compare
        # instead of a per-frame Python loop.
        if last - first + 1 == n and (
                n <= 2 or list(idx) == list(range(first, last + 1))):
            return self.payload[self.frame_offs[first]:
                                self.frame_offs[last] + self.frame_lens[last]]
        return b"".join(
            bytes(self.payload[self.frame_offs[i]:
                               self.frame_offs[i] + self.frame_lens[i]])
            for i in idx)

    def lease(self) -> "LeaseRef":
        """One keep-alive reference for a pending flush (rides the writer
        entry's ``owner`` seat; releases on GC like ``BytesLease``)."""
        return LeaseRef(self._lease)

    def release(self) -> None:
        """The consumer's own reference: call once dispatch is done (the
        peers' pending flushes keep their own :meth:`lease` refs). Also
        drops the payload view so the shm segment can close even while
        this record object is still referenced (stream slices taken via
        :meth:`stream_for` are independent views and stay valid)."""
        self._lease.drop()
        try:
            self.payload.release()
        except BufferError:
            pass


class SlotLease:
    """Refcounted keep-alive for one consumed record's shm bytes: the
    consumer holds one reference while dispatching; every pending egress
    flush holds one more. When the LAST drops, the owning reader is told
    the slot is done and advances ``tail`` over the done prefix
    (reclamation is in-order — the ring is a FIFO)."""

    __slots__ = ("reader", "end_cursor", "refs", "done")

    def __init__(self, reader: "RingReader", end_cursor: int):
        self.reader = reader
        self.end_cursor = end_cursor
        self.refs = 1
        self.done = False

    def drop(self) -> None:
        self.refs -= 1
        if self.refs <= 0 and not self.done:
            self.done = True
            self.reader._reclaim()

    def __del__(self):
        # GC backstop (e.g. a RingRecord discarded before release())
        if not self.done:
            self.done = True
            try:
                self.reader._reclaim()
            except Exception:
                pass


class LeaseRef:
    """One holder's reference on a :class:`SlotLease` — dropped when this
    object is garbage-collected (it rides ``PreEncoded.owner``, whose
    entry the writer drops right after the flush completes)."""

    __slots__ = ("_lease",)

    def __init__(self, lease: SlotLease):
        lease.refs += 1
        self._lease = lease

    def __del__(self):
        lease, self._lease = self._lease, None
        if lease is not None:
            try:
                lease.drop()
            except Exception:
                pass


def _align8(n: int) -> int:
    return (n + 7) & ~7


class _RingBase:
    def __init__(self, shm: shared_memory.SharedMemory, capacity: int,
                 owns: bool):
        self.shm = shm
        self.capacity = capacity
        self._owns = owns
        self.buf = shm.buf

    # -- header accessors (aligned 8-byte fields; x86 keeps these single
    # stores, and the commit-word protocol catches any torn read anyway) --

    def _get(self, off: int) -> int:
        return int.from_bytes(self.buf[off:off + 8], "little")

    def _set(self, off: int, value: int) -> None:
        self.buf[off:off + 8] = value.to_bytes(8, "little")

    @property
    def head(self) -> int:
        return self._get(0)

    @property
    def tail(self) -> int:
        return self._get(8)

    @property
    def dropped(self) -> int:
        return self._get(16)

    @property
    def poisoned(self) -> bool:
        return self._get(32) != 0

    def poison(self) -> None:
        """Consumer-side: mark the ring abandoned so the producer's next
        ``try_push`` fails over to the relay instead of silently feeding
        a ring nobody drains (a stalled-then-resumed producer would
        otherwise count path=ring deliveries that vanish)."""
        self._set(32, 1)

    def close(self) -> None:
        self.buf = None
        try:
            self.shm.close()
        except Exception:
            pass
        if self._owns:
            try:
                self.shm.unlink()
            except Exception:
                pass


def ring_capacity(capacity: int) -> int:
    """Clamp a requested capacity to the ring's alignment contract (a
    multiple of 8 — record totals and pads are 8-aligned so a record
    header can never straddle the wrap point)."""
    return max(capacity & ~7, 4096)


def create_ring(capacity: int = DEFAULT_CAPACITY) -> str:
    """Allocate one ring's shared memory (parent does this once per
    directed shard pair); returns the shm name both ends attach by."""
    capacity = ring_capacity(capacity)
    shm = shared_memory.SharedMemory(create=True,
                                     size=HEADER_BYTES + capacity)
    shm.buf[:HEADER_BYTES] = bytes(HEADER_BYTES)
    # the creator handle is closed immediately; writer/reader re-attach
    # by name. unlink stays the supervisor's job (unlink_ring).
    shm.close()
    return shm.name


def unlink_ring(name: str) -> None:
    try:
        shm = shared_memory.SharedMemory(name=name)
        shm.close()
        shm.unlink()
    except Exception:
        pass


class RingWriter(_RingBase):
    """The producer end (exactly one per directed pair, owned by the
    origin shard's event loop — never call from two tasks concurrently
    without external ordering; the broker's single loop provides it)."""

    def __init__(self, name: str, capacity: int,
                 notify_sock: Optional[socket.socket] = None):
        shm = shared_memory.SharedMemory(name=name)
        super().__init__(shm, ring_capacity(capacity), owns=False)
        self._notify = notify_sock
        self.records_pushed = 0
        self.frames_pushed = 0
        self.bytes_pushed = 0

    def note_dropped(self) -> None:
        self._set(16, self.dropped + 1)

    def try_push(self, frames: List, peers: List[Tuple[int, bytes,
                                                       Sequence[int]]],
                 prefixed: bool = False) -> bool:
        """Write one record. ``frames`` are frame buffers — raw payloads
        (the writer adds the u32-BE wire prefix) or, with
        ``prefixed=True``, already wire-framed slices copied verbatim.
        ``peers[i] = (kind, ident_bytes, frame_index_list)``. Returns
        False (and counts the drop) when the ring lacks space — the
        caller falls back to the control-plane relay."""
        if self.poisoned:
            self.note_dropped()
            return False
        n_frames = len(frames)
        n_peers = len(peers)
        flens = [len(f) + (0 if prefixed else 4) for f in frames]
        payload_len = sum(flens)
        peer_bytes = sum(_PEER.size + len(p[1]) + 4 * len(p[2])
                         for p in peers)
        body = _BODY.size + _FRAME.size * n_frames + peer_bytes + payload_len
        total = _align8(_REC.size + body)
        head, tail = self.head, self.tail
        cap = self.capacity
        avail = cap - (head - tail)
        pos = head % cap
        to_end = cap - pos
        # capacity and every record length are multiples of 8, so a
        # needed pad is always >= _REC.size — the PAD header always fits
        pad = to_end if total > to_end else 0
        if total + pad > avail:
            self.note_dropped()
            return False
        buf = self.buf
        base = HEADER_BYTES
        if pad:
            _REC.pack_into(buf, base + pos, pad, PAD_MAGIC)
            head += pad
            pos = 0
        start = base + pos
        seq = self._get(24)
        off = start + _REC.size
        _BODY.pack_into(buf, off, n_frames, n_peers)
        off += _BODY.size
        # frame table
        fo = 0
        for ln in flens:
            _FRAME.pack_into(buf, off, fo, ln)
            fo += ln
            off += _FRAME.size
        # peer table
        for kind, ident, idx in peers:
            _PEER.pack_into(buf, off, kind, 0, len(ident), len(idx))
            off += _PEER.size
            buf[off:off + len(ident)] = ident
            off += len(ident)
            for i in idx:
                buf[off:off + 4] = int(i).to_bytes(4, "little")
                off += 4
        # payload
        if prefixed:
            for f in frames:
                ln = len(f)
                buf[off:off + ln] = f
                off += ln
        else:
            for f in frames:
                ln = len(f)
                buf[off:off + 4] = ln.to_bytes(4, "big")
                off += 4
                buf[off:off + ln] = f
                off += ln
        # commit word LAST, then publish head — a reader that sees the
        # advanced head before the commit store has landed detects the
        # torn state from the commit word and retries
        _REC.pack_into(buf, start, total, 0)
        buf[start + 4:start + 8] = (COMMIT_FLAG
                                    | (seq & 0x7FFF_FFFF)).to_bytes(
                                        4, "little")
        self._set(24, seq + 1)
        self._set(0, head + total)
        if self.poisoned:
            # the consumer abandoned the ring while we were mid-push:
            # the record just committed will never be drained (orphaned
            # but harmless) — report failure so the caller relays
            # instead of counting a path=ring delivery that vanishes
            self.note_dropped()
            return False
        self.records_pushed += 1
        self.frames_pushed += n_frames
        self.bytes_pushed += payload_len
        if self._notify is not None:
            # notify EVERY push, not just empty->nonempty transitions:
            # "empty" judged via tail races the consumer's lease-deferred
            # reclamation (tail lags while an egress flush pins the oldest
            # slot), and a push in that window would otherwise never wake
            # the consumer again. The consumer drains the socket wholesale
            # per wakeup; a full buffer (EAGAIN) means wakeups are already
            # pending, so dropping the byte is safe.
            try:
                self._notify.send(b"\x01")
            except (BlockingIOError, OSError):
                pass
        return True


class RingReader(_RingBase):
    """The consumer end. :meth:`drain` parses committed records into
    :class:`RingRecord` views; slots are reclaimed in order as their
    leases drop (:class:`SlotLease`)."""

    def __init__(self, name: str, capacity: int):
        shm = shared_memory.SharedMemory(name=name)
        super().__init__(shm, ring_capacity(capacity), owns=False)
        self._cursor = self.tail      # private read cursor (>= tail)
        self._pending: deque = deque()  # SlotLeases in ring order
        self.torn_reads = 0
        self.records_drained = 0

    def _reclaim(self) -> None:
        advanced = False
        while self._pending and self._pending[0].done:
            lease = self._pending.popleft()
            self._set(8, lease.end_cursor)
            advanced = True
        if advanced and not self._pending:
            # fully drained: tail == cursor
            pass

    def drain(self, max_records: int = 64) -> List[RingRecord]:
        """Parse up to ``max_records`` committed records. A torn record
        (head advanced but commit word not yet visible / corrupted) stops
        the drain — counted, retried on the next wakeup."""
        out: List[RingRecord] = []
        buf = self.buf
        base = HEADER_BYTES
        cap = self.capacity
        while len(out) < max_records:
            head = self.head
            cur = self._cursor
            if cur >= head:
                break
            pos = cur % cap
            total, commit = _REC.unpack_from(buf, base + pos)
            if commit == PAD_MAGIC:
                self._cursor = cur + total
                # pads reclaim immediately when they're the oldest
                lease = SlotLease(self, self._cursor)
                lease.done = True
                self._pending.append(lease)
                self._reclaim()
                continue
            if not (commit & COMMIT_FLAG) or total < _REC.size \
                    or total > cap or pos + total > cap:
                self.torn_reads += 1
                break
            start = base + pos + _REC.size
            try:
                n_frames, n_peers = _BODY.unpack_from(buf, start)
                off = start + _BODY.size
                frame_offs = [0] * n_frames
                frame_lens = [0] * n_frames
                for i in range(n_frames):
                    frame_offs[i], frame_lens[i] = _FRAME.unpack_from(
                        buf, off)
                    off += _FRAME.size
                peers = []
                for _ in range(n_peers):
                    kind, _pad, ident_len, n_idx = _PEER.unpack_from(
                        buf, off)
                    off += _PEER.size
                    ident = bytes(buf[off:off + ident_len])
                    off += ident_len
                    idx = [int.from_bytes(buf[off + 4 * k:off + 4 * k + 4],
                                          "little") for k in range(n_idx)]
                    off += 4 * n_idx
                    peers.append((kind, ident, idx))
                payload_start = off
                payload_end = base + pos + total
                if payload_start > payload_end or any(
                        o + ln > payload_end - payload_start
                        for o, ln in zip(frame_offs, frame_lens)) or any(
                        i >= n_frames for _, _, idx in peers for i in idx):
                    raise ValueError("corrupt record")
            except (struct.error, ValueError):
                self.torn_reads += 1
                break
            self._cursor = cur + total
            lease = SlotLease(self, self._cursor)
            self._pending.append(lease)
            out.append(RingRecord(
                peers, memoryview(buf)[payload_start:payload_end],
                frame_offs, frame_lens, lease))
            self.records_drained += 1
        return out

    @property
    def backlog(self) -> int:
        return self.head - self._cursor


def notify_pair() -> Tuple[socket.socket, socket.socket]:
    """(rx, tx) non-blocking datagram pair: producers send one byte per
    empty→nonempty transition; the consumer's event loop watches rx."""
    rx, tx = socket.socketpair(socket.AF_UNIX, socket.SOCK_DGRAM)
    rx.setblocking(False)
    tx.setblocking(False)
    return rx, tx


def stats_dict(writers: Dict[int, RingWriter],
               readers: Dict[int, RingReader]) -> dict:
    """Operator-facing ring summary for /debug/topology."""
    return {
        "out": {str(dst): {"records": w.records_pushed,
                           "frames": w.frames_pushed,
                           "bytes": w.bytes_pushed,
                           "dropped": w.dropped,
                           "backlog_bytes": w.head - w.tail}
                for dst, w in writers.items()},
        "in": {str(src): {"records": r.records_drained,
                          "torn_reads": r.torn_reads,
                          "backlog_bytes": r.backlog}
               for src, r in readers.items()},
    }
