"""``VersionedMap`` — the eventually-consistent map underlying all routing
state, plus its wire codec.

Capability parity with cdn-broker/src/connections/versioned_map.rs:28-269:

- per-key ``u64`` version, bumped on every local modification;
- removals are **tombstones** (a versioned ``None``) so deletes propagate;
- local modifications are tracked so :meth:`diff` emits only deltas
  (versioned_map.rs:168-194);
- :meth:`merge` is last-writer-wins by version with ties broken by a
  **totally ordered conflict identity** (the modifying party), and returns
  the set of keys whose value actually changed so callers can evict
  (versioned_map.rs:201-269 — "user connected elsewhere" kicks);
- ``remove_if_equals`` / ``remove_by_value_no_modify`` for cleanup paths.

The wire codec replaces the reference's rkyv archives (sync payloads nested
inside the Message envelope, tasks/broker/sync.rs:24-40) with a compact
tag-length-value encoding of (key, version, identity, value) records.

TPU twin: ``pushcdn_tpu.parallel.crdt`` vectorizes exactly this merge —
per-key ``argmax`` over the (version, identity) pair — and is property-
tested for equivalence against this class.
"""

from __future__ import annotations

import struct
from dataclasses import dataclass
from typing import Callable, Dict, Generic, Hashable, List, Optional, Set, Tuple, TypeVar

from pushcdn_tpu.proto.error import ErrorKind, bail

K = TypeVar("K", bound=Hashable)
V = TypeVar("V")
C = TypeVar("C")  # conflict identity; must be totally ordered

_U64 = struct.Struct("<Q")
_U32 = struct.Struct("<I")


# --- generic scalar codec for keys/values/identities -----------------------
# Supports the types routing state actually uses: bytes (user public keys),
# str (broker identifiers), int (topics / subscription status), None
# (tombstones), and flat tuples of those.

_T_NONE, _T_INT, _T_BYTES, _T_STR, _T_TUPLE = 0, 1, 2, 3, 4


# Nesting bound for tuple values, enforced on BOTH sides: the analog of
# capnp's traversal limit (the reference's envelope format caps recursion
# depth by construction). Decode-side it stops a hostile peer's
# nested-tuple bomb from escaping as RecursionError; encode-side it fails
# fast with Error(SERIALIZE) so an over-nested local value can't ship a
# payload every peer would reject as malformed.
_MAX_VALUE_DEPTH = 32


def encode_value(v, out: bytearray, depth: int = 0) -> None:
    if depth >= _MAX_VALUE_DEPTH and isinstance(v, tuple):
        # symmetric with the decode-side traversal limit: fail fast at the
        # write site with Error(SERIALIZE) instead of shipping a payload
        # every peer would reject (and disconnect us) as malformed
        bail(ErrorKind.SERIALIZE,
             "versioned-map value nesting exceeds traversal limit")
    if v is None:
        out.append(_T_NONE)
    elif isinstance(v, bool):
        bail(ErrorKind.SERIALIZE, "bool not supported in versioned-map codec")
    elif isinstance(v, int):
        if not 0 <= v <= 0xFFFFFFFFFFFFFFFF:
            bail(ErrorKind.SERIALIZE,
                 f"int {v} out of u64 range in versioned-map codec")
        out.append(_T_INT)
        out += _U64.pack(v)
    elif isinstance(v, (bytes, bytearray, memoryview)):
        b = bytes(v)
        out.append(_T_BYTES)
        out += _U32.pack(len(b))
        out += b
    elif isinstance(v, str):
        b = v.encode("utf-8")
        out.append(_T_STR)
        out += _U32.pack(len(b))
        out += b
    elif isinstance(v, tuple):
        out.append(_T_TUPLE)
        out += _U32.pack(len(v))
        for item in v:
            encode_value(item, out, depth + 1)
    else:
        bail(ErrorKind.SERIALIZE,
             f"type {type(v).__name__} not supported in versioned-map codec")


def decode_value(view: memoryview, off: int,
                 depth: int = 0) -> Tuple[object, int]:
    tag = view[off]
    off += 1
    if tag == _T_NONE:
        return None, off
    if tag == _T_INT:
        (v,) = _U64.unpack_from(view, off)
        return v, off + 8
    if tag in (_T_BYTES, _T_STR):
        (n,) = _U32.unpack_from(view, off)
        off += 4
        raw = bytes(view[off:off + n])
        if len(raw) != n:
            bail(ErrorKind.DESERIALIZE, "truncated scalar in versioned-map codec")
        return (raw if tag == _T_BYTES else raw.decode("utf-8")), off + n
    if tag == _T_TUPLE:
        if depth >= _MAX_VALUE_DEPTH:
            bail(ErrorKind.DESERIALIZE,
                 "versioned-map value nesting exceeds traversal limit")
        (n,) = _U32.unpack_from(view, off)
        off += 4
        items = []
        for _ in range(n):
            item, off = decode_value(view, off, depth + 1)
            items.append(item)
        return tuple(items), off
    bail(ErrorKind.DESERIALIZE, f"unknown scalar tag {tag} in versioned-map codec")


@dataclass
class VersionedValue(Generic[V, C]):
    """One entry: ``value is None`` ⇒ tombstone (versioned_map.rs
    `VersionedValue`)."""

    value: Optional[V]
    version: int
    identity: C  # who made this modification (conflict tie-breaker)

    def dominates(self, other: "VersionedValue") -> bool:
        """Last-writer-wins by version; ties broken by ordered identity."""
        if self.version != other.version:
            return self.version > other.version
        return self.identity > other.identity


class VersionedMap(Generic[K, V, C]):
    """The CRDT map. Not thread-safe by itself — the broker guards all
    routing state behind one lock (parity: single
    ``parking_lot::RwLock<Connections>``, cdn-broker/src/lib.rs:98)."""

    def __init__(self, local_identity: C):
        self.local_identity = local_identity
        self._entries: Dict[K, VersionedValue[V, C]] = {}
        self._modified: Set[K] = set()

    # -- local modification (bumps version, tracks for diff) ----------------

    def insert(self, key: K, value: V) -> None:
        prev = self._entries.get(key)
        version = (prev.version + 1) if prev is not None else 1
        self._entries[key] = VersionedValue(value, version, self.local_identity)
        self._modified.add(key)

    def remove(self, key: K) -> Optional[V]:
        """Tombstone ``key`` (propagates); returns the removed value."""
        prev = self._entries.get(key)
        if prev is None or prev.value is None:
            return None
        self._entries[key] = VersionedValue(None, prev.version + 1,
                                            self.local_identity)
        self._modified.add(key)
        return prev.value

    def remove_if_equals(self, key: K, value: V) -> bool:
        """Remove only if the live value equals ``value`` — used when
        cleaning up our own claim without clobbering a newer one
        (versioned_map.rs `remove_if_equals`)."""
        prev = self._entries.get(key)
        if prev is not None and prev.value == value:
            self.remove(key)
            return True
        return False

    def remove_by_value_no_modify(self, value: V) -> List[K]:
        """Drop every entry whose value equals ``value`` WITHOUT tombstoning
        or marking modified — forgetting a dead peer's claims locally while
        letting the authoritative owner re-assert (versioned_map.rs
        `remove_by_value_no_modify`)."""
        doomed = [k for k, vv in self._entries.items() if vv.value == value]
        for k in doomed:
            del self._entries[k]
            self._modified.discard(k)
        return doomed

    # -- reads --------------------------------------------------------------

    def get(self, key: K) -> Optional[V]:
        vv = self._entries.get(key)
        return None if vv is None else vv.value

    def keys(self) -> List[K]:
        return [k for k, vv in self._entries.items() if vv.value is not None]

    def items(self) -> List[Tuple[K, V]]:
        return [(k, vv.value) for k, vv in self._entries.items()
                if vv.value is not None]

    def __len__(self) -> int:
        return sum(1 for vv in self._entries.values() if vv.value is not None)

    def __contains__(self, key: K) -> bool:
        return self.get(key) is not None

    # -- sync ---------------------------------------------------------------

    def diff(self) -> Dict[K, VersionedValue[V, C]]:
        """Entries modified locally since the previous diff; clears the
        tracking set (versioned_map.rs:168-194)."""
        out = {k: self._entries[k] for k in self._modified if k in self._entries}
        self._modified.clear()
        return out

    def full(self) -> Dict[K, VersionedValue[V, C]]:
        """Everything, tombstones included — sent when a broker (re)connects
        (full sync, tasks/broker/handler.rs:98-117)."""
        return dict(self._entries)

    def merge(self, incoming: Dict[K, VersionedValue[V, C]]) -> List[Tuple[K, Optional[V], Optional[V]]]:
        """Apply a remote delta. Returns ``(key, old_value, new_value)`` for
        every key whose *live value* changed, so the caller can react (the
        broker evicts local users whose DirectMap owner moved elsewhere,
        connections/mod.rs:154-162)."""
        changed: List[Tuple[K, Optional[V], Optional[V]]] = []
        for key, vv in incoming.items():
            local = self._entries.get(key)
            if local is None or vv.dominates(local):
                self._entries[key] = vv
                old = None if local is None else local.value
                if old != vv.value:
                    changed.append((key, old, vv.value))
        return changed

    def purge_tombstones(self) -> int:
        """Compact: drop tombstoned entries (the reference's purge test,
        versioned_map.rs:272-377). Safe between stable syncs; a peer that
        re-sends an older live entry will be re-tombstoned by whichever
        replica still knows better."""
        doomed = [k for k, vv in self._entries.items() if vv.value is None]
        for k in doomed:
            del self._entries[k]
            self._modified.discard(k)
        return len(doomed)

    # -- wire codec (replaces rkyv; parity sync.rs:24-40) -------------------

    @staticmethod
    def serialize_entries(entries: Dict[K, VersionedValue[V, C]]) -> bytes:
        out = bytearray()
        out += _U32.pack(len(entries))
        for k, vv in entries.items():
            encode_value(k, out)
            out += _U64.pack(vv.version)
            encode_value(vv.identity, out)
            encode_value(vv.value, out)
        return bytes(out)

    @staticmethod
    def deserialize_entries(payload) -> Dict[K, VersionedValue[V, C]]:
        """Raises ``Error(DESERIALIZE)`` on any truncated/malformed payload
        so the broker receive loop's disconnect-the-peer policy applies."""
        try:
            view = memoryview(payload)
            (n,) = _U32.unpack_from(view, 0)
            off = 4
            out: Dict[K, VersionedValue] = {}
            for _ in range(n):
                k, off = decode_value(view, off)
                (version,) = _U64.unpack_from(view, off)
                off += 8
                identity, off = decode_value(view, off)
                value, off = decode_value(view, off)
                out[k] = VersionedValue(value, version, identity)
            return out
        except (struct.error, IndexError, UnicodeDecodeError) as exc:
            bail(ErrorKind.DESERIALIZE, "malformed versioned-map payload", exc)
