"""Durable topics: per-topic retention rings, replay subscribe, last-value
cache, and wildcard interest (ISSUE 14).

The broker today is fire-and-forget pub/sub: a consensus node that rejoins
mid-view gets silence until the next broadcast. This module closes that gap
end to end:

- **Retention rings** — a configured subset of topics (``PUSHCDN_RETAIN_*``)
  keeps the last N broadcasts per topic in a bounded ring (count / bytes /
  age). On the scalar ingress path a retained entry holds a zero-copy
  ``Bytes.clone()`` of the arriving frame — the same lease-recycled permit
  accounting the egress fan-out uses — so retention never copies and never
  fights the pool for new allocations. Every entry carries a per-topic
  **monotone sequence number** stamped at ingress (seqs start at 1; the wire
  itself is unchanged — only replayed ``Retained`` frames carry them).

- **Pool-deadlock immunity** — retention registers a *reclaimer* on the
  broker's :class:`~pushcdn_tpu.proto.limiter.MemoryPool`: the moment an
  allocation would block, retained leases are materialized to owned heap
  bytes and their permits released, synchronously. Retention can therefore
  ALWAYS give back every permit it holds without blocking, so "block the
  reader, not the router" can never become "wedge the reader behind idle
  leases". The pooled share is additionally clamped to a quarter of pool
  capacity.

- **Replay subscribe + last-value cache** — ``SubscribeFrom{topic, seq}``
  registers the subscription and replays every retained frame with
  ``seq >= from_seq`` as ``Retained`` frames through the normal writer-queue
  path. ``seq == SEQ_LAST`` replays only the last-value-cache entry (one
  per topic, surviving ring eviction); ``seq == SEQ_LIVE`` subscribes
  without replay. The replay→live handover is **gap-free and dup-free** by
  construction: the subscription registration, the retained-ring snapshot,
  and the replay enqueue happen in ONE synchronous block on the broker's
  event loop, while every live route decision (interest query → egress
  append) and its matching retention stamp are likewise one synchronous
  block. So a broadcast either (a) routed before the SubscribeFrom — user
  not yet subscribed, frame retained, hence in the snapshot: replayed,
  exactly once; or (b) routed after — user subscribed (live delivery), and
  its seq exceeds everything in the snapshot: not replayed. Per-connection
  writer queues are FIFO, so the wire order is replay then live.
  (A SubscribeFrom from a user that is ALREADY subscribed may duplicate
  frames still in flight to it — the guarantee is scoped to the rejoin
  flow, where the subscription starts absent.)

- **Sharded brokers** — each durable topic's ring lives with its OWNER
  shard (``topic % num_shards``). A durable broadcast ingressing elsewhere
  is relayed to the owner verbatim (``durable_pub`` on the shard bus), and
  the owner makes the interest snapshot AND the retention stamp in one
  synchronous block, then routes through a single FIFO drainer task — so
  the per-user order of replay vs. live is pinned by the drainer queue. A
  ``SubscribeFrom`` at the user's shard relays ``durable_sub`` to the owner
  *before* the local subscribe delta, and the owner adds the interest row
  itself (additive — see ``Connections.add_remote_user_interest``) before
  snapshotting. Sequence numbers are broker-local (a rejoin to a DIFFERENT
  broker should use ``seq=0`` or ``SEQ_LAST``); durable frames whose topic
  sets span multiple owner shards are retained at every owner but fanned
  out only by the lowest topic's owner.

- **Wildcard interest** — hierarchical names (``consensus.view.3``) bind
  onto wire topics via :class:`~pushcdn_tpu.proto.topic.TopicNamespace`;
  a pattern (``consensus.view.*``) riding ``SubscribeFrom.pattern``
  compiles to the covered topic set and subscribes through the plain
  ``Connections.subscribe_user_to`` path, so the interest bitmask, the
  native route-plan table, the RaggedInterest page index, and the sharded
  deltas all see ordinary per-topic updates — wildcard plan output is
  bit-identical to the equivalent explicit subscription. A *watch* keeps
  the union live: later ``bind``/``unbind`` calls incrementally subscribe/
  unsubscribe the pattern's users (same shape as RaggedInterest page
  maintenance).

Environment knobs::

    PUSHCDN_RETAIN_TOPICS   comma list / ranges of retained topics ("0,3,8-11")
    PUSHCDN_RETAIN_COUNT    per-topic ring entry bound        (default 1024)
    PUSHCDN_RETAIN_BYTES    per-topic ring byte bound         (default 4 MiB)
    PUSHCDN_RETAIN_AGE_S    per-entry age bound, 0 = none     (default 0)
    PUSHCDN_TOPIC_NAMES     namespace seed: "name=topic,name=topic"
"""

from __future__ import annotations

import asyncio
import logging
import os
import struct
import time
import weakref
from collections import deque
from typing import TYPE_CHECKING, Iterable, List, Optional

from pushcdn_tpu.proto import flowclass
from pushcdn_tpu.proto import ledger as ledger_mod
from pushcdn_tpu.proto import metrics as metrics_mod
from pushcdn_tpu.proto import trace as trace_mod
from pushcdn_tpu.proto.error import Error
from pushcdn_tpu.proto.limiter import Bytes
from pushcdn_tpu.proto.message import (
    KIND_BROADCAST,
    KIND_RETAINED,
    SEQ_LAST,
    SEQ_LIVE,
    deserialize,
    deserialize_owned,
)
from pushcdn_tpu.proto.topic import TopicNamespace
from pushcdn_tpu.proto.util import mnemonic

if TYPE_CHECKING:
    from pushcdn_tpu.broker.broker import Broker

logger = logging.getLogger("pushcdn.broker")

_LEN = struct.Struct(">I")
_U64 = struct.Struct("<Q")

# -- retention observability (ISSUE 19 tentpole 3) ---------------------------
# Live stores refresh their ring/replay gauges from a /metrics pre-render
# hook (retain/evict hot paths only bump plain counters); eviction-reason
# children are cached so the evict loop pays one inc, no label lookup.
_LIVE_STORES: "weakref.WeakSet[DurableTopics]" = weakref.WeakSet()
_EVICT_REASON = {r: metrics_mod.RETENTION_EVICTIONS.labels(reason=r)
                 for r in ("bytes", "entries", "age")}
_REPLAY_LAG_TOP_K = 8
_replay_lag_live: set = set()


def _refresh_retention_metrics() -> None:
    rings: dict = {}
    ring_bytes: dict = {}
    lags: list = []
    for store in list(_LIVE_STORES):
        for t, ring in store._rings.items():
            key = str(t)
            rings[key] = rings.get(key, 0) + len(ring.entries)
            ring_bytes[key] = ring_bytes.get(key, 0) + ring.nbytes
        lags.extend(store._replay_lags())
    for key, n in rings.items():
        metrics_mod.RETENTION_RING_ENTRIES.labels(topic=key).set(n)
        metrics_mod.RETENTION_RING_BYTES.labels(topic=key).set(
            ring_bytes[key])
    lags.sort(key=lambda kv: (-kv[1], kv[0]))
    shown, other = set(), 0
    for name, lag in lags:
        if len(shown) < _REPLAY_LAG_TOP_K:
            metrics_mod.REPLAY_LAG.labels(subscriber=name).set(lag)
            shown.add(name)
        else:
            other += lag
    metrics_mod.REPLAY_LAG.labels(subscriber="other").set(other)
    for name in _replay_lag_live - shown:
        metrics_mod.REPLAY_LAG.labels(subscriber=name).set(0)
    _replay_lag_live.clear()
    _replay_lag_live.update(shown)


metrics_mod.PRE_RENDER_HOOKS.append(_refresh_retention_metrics)


def _parse_topic_set(spec: str) -> frozenset:
    """``"0,3,8-11"`` → {0, 3, 8, 9, 10, 11}."""
    out = set()
    for part in spec.split(","):
        part = part.strip()
        if not part:
            continue
        if "-" in part:
            lo, hi = part.split("-", 1)
            out.update(range(int(lo), int(hi) + 1))
        else:
            out.add(int(part))
    return frozenset(out)


class _Entry:
    """One retained broadcast: the payload plus (optionally) a ``Bytes``
    clone of the arriving frame whose pool permit it keeps alive. While
    ``owner`` is held the payload may be a zero-copy view into the owner's
    buffer; :meth:`materialize` converts to owned heap bytes and releases
    the permit — synchronously, so the pool reclaimer can always drain."""

    __slots__ = ("seq", "payload", "owner", "nbytes", "t")

    def __init__(self, seq: int, payload, owner: Optional[Bytes],
                 nbytes: int, t: float):
        self.seq = seq
        self.payload = payload
        self.owner = owner
        self.nbytes = nbytes
        self.t = t

    def materialize(self) -> int:
        """Copy the payload out of the leased buffer and release the pool
        permit; returns the pooled byte count given back (0 if already
        owned)."""
        owner, self.owner = self.owner, None
        if owner is None:
            return 0
        self.payload = bytes(self.payload)
        owner.release()
        return self.nbytes

    def drop(self) -> int:
        """Release the lease without keeping the payload (ring eviction of
        a non-LVC entry); returns the pooled bytes given back."""
        owner, self.owner = self.owner, None
        if owner is None:
            return 0
        owner.release()
        return self.nbytes


class _Ring:
    __slots__ = ("topic", "entries", "next_seq", "nbytes", "last",
                 "last_detached")

    def __init__(self, topic: int):
        self.topic = topic
        self.entries: deque = deque()
        self.next_seq = 1          # seqs count up from 1 (0 = "everything")
        self.nbytes = 0
        self.last: Optional[_Entry] = None  # LVC slot, survives eviction
        self.last_detached = False  # True once `last` was ring-evicted


class DurableTopics:
    """Per-broker durable-topic subsystem (see module docstring). One
    instance per broker process; always constructed (wildcard subscribe
    works without retention), ``enabled`` iff any topic is retained."""

    def __init__(self, broker: "Broker",
                 topics: Iterable[int] = (),
                 max_count: int = 1024,
                 max_bytes: int = 4 * 1024 * 1024,
                 max_age_s: float = 0.0):
        self.broker = broker
        self.topics = frozenset(int(t) for t in topics)
        self.max_count = max(1, int(max_count))
        self.max_bytes = max(1, int(max_bytes))
        self.max_age_s = float(max_age_s)
        space = broker.run_def.topics if broker.run_def is not None else None
        self.namespace = TopicNamespace(space)
        self._rings = {t: _Ring(t) for t in self.topics}
        # pooled-lease accounting: entries still holding a Bytes clone, in
        # retain order (reclaim materializes oldest-first)
        self._pooled: deque = deque()
        self._pooled_bytes = 0
        limiter = getattr(broker, "limiter", None)
        self._pool = limiter.pool if limiter is not None else None
        # retention may pin at most a quarter of the pool with idle leases
        self._pool_budget = (self._pool.capacity // 4
                             if self._pool is not None else 0)
        self._reclaimer_installed = False
        if self._pool is not None and self.topics:
            self._pool.add_reclaimer(self._reclaim)
            self._reclaimer_installed = True
        # wildcard watches: user key -> {pattern -> namespace watch handle}
        self._watches: dict = {}
        # sharded ordered fan-out (owner side): one FIFO drainer pins the
        # per-user order of replay vs. live batches
        self._fanout_q: Optional[asyncio.Queue] = None
        self._drain_task: Optional[asyncio.Task] = None
        # counters (surfaced via /debug/topology)
        self.retained_frames = 0
        self.replayed_frames = 0
        self.evicted_entries = 0
        self.evictions_by_reason: dict = {}
        self.materialized_entries = 0
        self.pool_reclaims = 0
        self.relayed_pubs = 0
        # replay-lag tracking: subscriber mnemonic -> [weakref(conn)|None,
        # entries handed over at its most recent replay]. The pre-render
        # hook publishes these top-K and retires entries whose writer
        # queue drained (replay reached the kernel = caught up).
        self._replay_track: dict = {}
        _LIVE_STORES.add(self)

    # -- construction --------------------------------------------------------

    @classmethod
    def from_env(cls, broker: "Broker") -> "DurableTopics":
        topics = _parse_topic_set(os.environ.get("PUSHCDN_RETAIN_TOPICS", ""))
        d = cls(
            broker, topics,
            max_count=int(os.environ.get("PUSHCDN_RETAIN_COUNT", "1024")),
            max_bytes=int(os.environ.get("PUSHCDN_RETAIN_BYTES",
                                         str(4 * 1024 * 1024))),
            max_age_s=float(os.environ.get("PUSHCDN_RETAIN_AGE_S", "0")))
        names = os.environ.get("PUSHCDN_TOPIC_NAMES", "")
        for pair in names.split(","):
            pair = pair.strip()
            if not pair or "=" not in pair:
                continue
            name, topic = pair.rsplit("=", 1)
            try:
                d.namespace.bind(name.strip(), int(topic))
            except ValueError as exc:
                logger.warning("PUSHCDN_TOPIC_NAMES entry %r ignored: %s",
                               pair, exc)
        # the bound names imply the flow-class taxonomy ("consensus.*",
        # "bulk.*", ...): publish the compiled topic -> class table for
        # the scalar senders; the cut-through plane mirrors it into the
        # native planner on its next (re)build
        flowclass.install_table(flowclass.compile_table(d.namespace))
        return d

    @property
    def enabled(self) -> bool:
        return bool(self.topics)

    def owner_shard(self, topic: int) -> int:
        return topic % max(1, self.broker.connections.num_shards)

    def close(self) -> None:
        if self._reclaimer_installed and self._pool is not None:
            self._pool.remove_reclaimer(self._reclaim)
            self._reclaimer_installed = False
        if self._drain_task is not None:
            self._drain_task.cancel()
            self._drain_task = None
        for handles in self._watches.values():
            for h in handles.values():
                self.namespace.unwatch(h)
        self._watches.clear()
        for ring in self._rings.values():
            while ring.entries:
                self._evict_one(ring)
            if ring.last is not None:
                ring.last.drop()
                ring.last = None
        self._pooled.clear()
        self._pooled_bytes = 0

    def stats(self) -> dict:
        return {
            "topics": sorted(self.topics),
            "bindings": len(self.namespace.bindings()),
            "retained_frames": self.retained_frames,
            "replayed_frames": self.replayed_frames,
            "evicted_entries": self.evicted_entries,
            "materialized_entries": self.materialized_entries,
            "pool_reclaims": self.pool_reclaims,
            "relayed_pubs": self.relayed_pubs,
            "pooled_bytes": self._pooled_bytes,
            "ring_entries": {t: len(r.entries)
                             for t, r in self._rings.items()},
            "ring_bytes": {t: r.nbytes for t, r in self._rings.items()},
            "next_seq": {t: r.next_seq for t, r in self._rings.items()},
            "evictions_by_reason": dict(self.evictions_by_reason),
            "replay_lag": dict(self._replay_lags()),
        }

    # -- retention rings -----------------------------------------------------

    def _evict_one(self, ring: _Ring, reason: Optional[str] = None) -> None:
        e = ring.entries.popleft()
        ring.nbytes -= e.nbytes
        self.evicted_entries += 1
        if reason is not None:  # None = teardown drain, not an eviction
            self.evictions_by_reason[reason] = \
                self.evictions_by_reason.get(reason, 0) + 1
            _EVICT_REASON[reason].inc()
            # the retained COPY's terminal fate (ISSUE 20; the original
            # frame's delivery fate was counted on its own path)
            ledger_mod.record_fate("dropped", "retention_evict",
                                   flowclass.BULK)
        if ring.last is e:
            # the LVC slot outlives the ring — but must not pin a pool
            # permit indefinitely: one bounded copy per topic
            self._pooled_bytes -= e.materialize()
            ring.last_detached = True
        else:
            self._pooled_bytes -= e.drop()

    def _age_evict(self, ring: _Ring, now: float) -> None:
        if self.max_age_s > 0:
            horizon = now - self.max_age_s
            while ring.entries and ring.entries[0].t < horizon:
                self._evict_one(ring, "age")

    def _retain(self, dtopics: List[int], payload,
                raw: Optional[Bytes]) -> None:
        """Stamp + store one broadcast under each durable topic it names.
        ``raw`` (the arriving frame's ``Bytes``) makes the entry a
        zero-copy lease; ``None`` stores owned bytes (chunk path, relayed
        frames)."""
        now = time.monotonic()
        nbytes = len(payload)
        for t in dtopics:
            ring = self._rings[t]
            seq = ring.next_seq
            ring.next_seq = seq + 1
            owner = raw.clone() if raw is not None else None
            entry = _Entry(seq, payload, owner, nbytes, now)
            if owner is not None:
                self._pooled.append(entry)
                self._pooled_bytes += nbytes
            ring.entries.append(entry)
            ring.nbytes += nbytes
            if ring.last_detached and ring.last is not None:
                ring.last.drop()  # displaced LVC copy (already owned bytes)
            ring.last = entry
            ring.last_detached = False
            self.retained_frames += 1
            self._age_evict(ring, now)
            while (len(ring.entries) > self.max_count
                   or ring.nbytes > self.max_bytes):
                self._evict_one(ring,
                                "entries"
                                if len(ring.entries) > self.max_count
                                else "bytes")
        # pooled clamp: retention's idle leases may not crowd the pool
        while self._pooled_bytes > self._pool_budget and self._pooled:
            self._materialize_oldest()

    def _materialize_oldest(self) -> bool:
        while self._pooled:
            e = self._pooled.popleft()
            if e.owner is None:
                continue  # already evicted/materialized elsewhere
            self._pooled_bytes -= e.materialize()
            self.materialized_entries += 1
            return True
        return False

    def _reclaim(self, deficit: int) -> None:
        """MemoryPool pressure hook (runs synchronously on the event loop
        while a reader is about to block): release every permit retention
        holds, oldest first, until the pool can satisfy the waiter. Pure
        copies + releases — can never block, so retained leases can never
        deadlock permit reclamation."""
        if not self._pooled:
            return
        self.pool_reclaims += 1
        pool = self._pool
        while self._pooled:
            if pool is not None and pool.available >= deficit >= 0:
                break
            if not self._materialize_oldest():
                break

    def snapshot(self, topic: int, from_seq: int) -> List[_Entry]:
        """The replay set for one topic at this instant. ``SEQ_LIVE`` →
        nothing; ``SEQ_LAST`` → the last-value-cache entry; otherwise every
        retained entry with ``seq >= from_seq``, oldest first."""
        ring = self._rings.get(topic)
        if ring is None or from_seq == SEQ_LIVE:
            return []
        self._age_evict(ring, time.monotonic())
        if from_seq == SEQ_LAST:
            return [ring.last] if ring.last is not None else []
        return [e for e in ring.entries if e.seq >= from_seq]

    @staticmethod
    def _prefixed_retained(topic: int, e: _Entry) -> bytes:
        """One ``Retained`` wire frame, u32-BE length-prefixed for the
        pre-encoded writer path."""
        frame = b"".join((bytes((KIND_RETAINED, topic)),
                          _U64.pack(e.seq), e.payload))
        return _LEN.pack(len(frame)) + frame

    # -- ingress (publish side) ----------------------------------------------

    def on_publish(self, pruned, message, raw: Bytes,
                   to_users_only: bool) -> bool:
        """Called at broadcast ingress (scalar loops + cut-through
        residuals) with the pruned topic list. Returns True when the
        caller should route the frame normally; False when the durable
        subsystem took over the fan-out (sharded mode: the owner shard
        stamps, retains, and routes through its ordered drainer — local
        routing must be skipped so frames are neither dropped nor
        duplicated)."""
        if not self.topics:
            return True
        dt = [t for t in pruned if t in self.topics]
        if not dt:
            return True
        conns = self.broker.connections
        if conns.num_shards <= 1:
            # unsharded: stamp + lease in the SAME synchronous block as the
            # caller's route decision — the handover invariant
            self._retain(dt, message.message, raw)
            return True
        # sharded: rings live with their owner shards. The lowest topic's
        # owner fans out; any other owner retains only (multi-owner durable
        # frames stay single-delivery).
        frame = bytes(raw.data)
        owners = {self.owner_shard(t) for t in dt}
        route_owner = self.owner_shard(min(dt))
        me = conns.shard_id
        for o in sorted(owners):
            if o == me:
                continue
            if o == route_owner:
                self._emit(("durable_pub", o, frame, to_users_only))
            else:
                self._emit(("durable_retain", o, frame))
            self.relayed_pubs += 1
        if me in owners:
            if me == route_owner:
                self._apply_durable_pub(frame, to_users_only)
            else:
                self._retain_owned_topics(frame)
        return False

    def retain_from_chunk(self, buf, offs, lens, pos: int,
                          consumed: int) -> None:
        """Cut-through seam (unsharded only — ``cutthrough.acquire`` routes
        sharded durable brokers scalar): after ``plan()`` returns and
        BEFORE the first egress await, stamp every consumed broadcast that
        names a durable topic. Payloads are copied out — a lease here
        would pin the whole pooled chunk for the ring's lifetime."""
        if not self.topics:
            return
        mv = memoryview(buf)
        space = self.broker.run_def.topics
        for i in range(pos, pos + consumed):
            o, ln = int(offs[i]), int(lens[i])
            if ln < 2 or (mv[o] & 0x7F) != KIND_BROADCAST:
                continue
            try:
                m = deserialize(mv[o:o + ln])
            except Error:
                continue  # plan stops on malformed frames; defensive
            pruned, _bad = space.prune(m.topics)
            dt = [t for t in pruned if t in self.topics]
            if dt:
                self._retain(dt, bytes(m.message), None)

    # -- subscribe side ------------------------------------------------------

    def handle_subscribe_from(self, public_key, msg, conn) -> bool:
        """Process one ``SubscribeFrom`` (user-origin, scalar loops + the
        cut-through residual twin). Registration, ring snapshot, and
        replay enqueue run in this one synchronous block — the handover
        invariant. Returns False when the sender must be disconnected
        (unknown explicit topic — ``Subscribe`` parity — or a replay
        enqueue failing against its own writer queue)."""
        conns = self.broker.connections
        space = self.broker.run_def.topics
        if msg.pattern:
            topics = [t for t in self.namespace.match(msg.pattern)
                      if t in space.valid]
            self._watch_pattern(public_key, msg.pattern)
        else:
            pruned, bad = space.prune([msg.topic])
            if bad:
                return False  # unknown topic ⇒ disconnect (Subscribe parity)
            topics = list(pruned)
        if not topics:
            return True  # nothing bound yet; a pattern watch keeps it live
        if conns.num_shards <= 1:
            conns.subscribe_user_to(public_key, topics)
            if msg.seq != SEQ_LIVE:
                for t in topics:
                    if t in self.topics:
                        if not self._replay_local(conn, public_key, t,
                                                  msg.seq):
                            return False
            return True
        # sharded: the owner adds the interest row itself (durable_sub
        # applies BEFORE the local subscribe's "user" delta — bus order),
        # snapshots, and replays through its ordered drainer
        me = conns.shard_id
        durable = ([t for t in topics if t in self.topics]
                   if msg.seq != SEQ_LIVE else [])
        for t in durable:
            if self.owner_shard(t) != me:
                self._emit(("durable_sub", t, msg.seq,
                            bytes(public_key), me))
        conns.subscribe_user_to(public_key, topics)
        for t in durable:
            if self.owner_shard(t) == me:
                self._apply_durable_sub(t, msg.seq, public_key, me)
        return True

    def _replay_local(self, conn, public_key, topic: int,
                      from_seq: int) -> bool:
        """Unsharded replay: ONE pre-encoded writer entry for the whole
        retained range, enqueued without awaiting so the snapshot and the
        enqueue stay in the same synchronous block."""
        entries = self.snapshot(topic, from_seq)
        if not entries:
            return True
        stream = b"".join(self._prefixed_retained(topic, e)
                          for e in entries)
        try:
            conn.send_encoded_nowait(stream, None, cls=flowclass.BULK,
                                     nframes=len(entries))
        except Exception as exc:
            logger.info("replay to user %s failed (%r); disconnecting",
                        mnemonic(public_key), exc)
            return False
        self.replayed_frames += len(entries)
        self._track_replay(public_key, conn, len(entries))
        return True

    def _track_replay(self, public_key, conn, entries: int) -> None:
        self._replay_track[mnemonic(public_key)] = \
            [weakref.ref(conn) if conn is not None else None, entries]

    def _replay_lags(self) -> list:
        """(subscriber, lag) pairs for the pre-render hook: a tracked
        replay counts as lagging while its connection's writer queue is
        still draining; once empty (or the conn died) the subscriber has
        caught up and the entry retires."""
        out = []
        for name, (ref, entries) in list(self._replay_track.items()):
            conn = ref() if ref is not None else None
            if conn is None:
                del self._replay_track[name]
                continue
            try:
                depth, _ = conn.queue_stats()
            except Exception:
                depth = 0
            if depth <= 0:
                del self._replay_track[name]
                continue
            out.append((name, entries))
        return out

    def _watch_pattern(self, public_key, pattern: str) -> None:
        """Keep a wildcard subscription live: future ``bind``/``unbind``
        calls matching the pattern subscribe/unsubscribe this user through
        the plain per-topic interest path (mask unions maintained
        incrementally — the route planes never see the pattern)."""
        key = bytes(public_key)
        per_user = self._watches.setdefault(key, {})
        if pattern in per_user:
            return

        def on_add(name, topic, _key=key):
            conns = self.broker.connections
            if conns.has_user(_key):
                if topic in self.broker.run_def.topics.valid:
                    conns.subscribe_user_to(_key, [topic])
            else:
                self.unwatch_user(_key)  # user gone: lazy cleanup

        def on_remove(name, topic, _key=key):
            conns = self.broker.connections
            if conns.has_user(_key):
                conns.unsubscribe_user_from(_key, [topic])
            else:
                self.unwatch_user(_key)

        per_user[pattern] = self.namespace.watch(pattern, on_add=on_add,
                                                 on_remove=on_remove)

    def unwatch_user(self, public_key) -> None:
        for h in self._watches.pop(bytes(public_key), {}).values():
            self.namespace.unwatch(h)

    # -- sharded owner plane -------------------------------------------------

    def _emit(self, event: tuple) -> None:
        runtime = self.broker.shard_runtime
        if runtime is not None:
            runtime._emit(event)

    def apply_shard_event(self, event: tuple) -> None:
        """Dispatch one durable event off the shard bus (data plane — the
        caller keeps these out of the interest-delta counters)."""
        kind = event[0]
        me = self.broker.connections.shard_id
        if kind == "durable_pub":
            _, owner, frame, to_users_only = event
            if owner == me:
                self._apply_durable_pub(frame, to_users_only)
        elif kind == "durable_retain":
            _, owner, frame = event
            if owner == me:
                self._retain_owned_topics(frame)
        elif kind == "durable_sub":
            _, topic, from_seq, key, user_shard = event
            if self.owner_shard(topic) == me:
                self._apply_durable_sub(topic, from_seq, key, user_shard)

    def _decode_pub(self, frame: bytes):
        try:
            msg = deserialize_owned(frame)
        except Error:
            return None, ()
        pruned, _bad = self.broker.run_def.topics.prune(msg.topics)
        me = self.broker.connections.shard_id
        dt = [t for t in pruned if t in self.topics
              and self.owner_shard(t) == me]
        return msg, (pruned, dt)

    def _retain_owned_topics(self, frame: bytes) -> None:
        msg, info = self._decode_pub(frame)
        if msg is not None and info[1]:
            self._retain(info[1], msg.message, None)

    def _apply_durable_pub(self, frame: bytes, to_users_only: bool) -> None:
        """Owner side of a durable broadcast: retention stamp + interest
        snapshot in ONE synchronous block, fan-out through the ordered
        drainer (queue FIFO pins per-user replay-vs-live order)."""
        msg, info = self._decode_pub(frame)
        if msg is None:
            return
        pruned, dt = info
        if dt:
            self._retain(dt, msg.message, None)
        users, brokers = self.broker.connections.get_interested_by_topic(
            list(pruned), to_users_only)
        tr = getattr(msg, "trace", None)
        if tr is not None:
            trace_mod.emit("ingress", tr, "durable-owner")
            if users or brokers:
                trace_mod.emit("plan", tr, "durable-owner")
                trace_mod.emit("egress", tr, "durable-drainer")
            else:
                trace_mod.emit("plan", tr, "dropped")
        if users or brokers:
            self._queue(("pub", frame, tuple(users), tuple(brokers)))

    def _apply_durable_sub(self, topic: int, from_seq: int, key,
                           user_shard: int) -> None:
        """Owner side of a replay subscribe: interest row + ring snapshot +
        replay enqueue, one synchronous block. The row is added additively
        here (ahead of the authoritative "user" delta already in flight on
        the bus) so no later durable pub can miss the user."""
        conns = self.broker.connections
        if not conns.has_user(key):
            conns.add_remote_user_interest(key, user_shard, [topic])
        entries = self.snapshot(topic, from_seq)
        if not entries:
            return
        frames = [self._prefixed_retained(topic, e) for e in entries]
        self.replayed_frames += len(frames)
        self._queue(("replay", bytes(key), user_shard, frames))

    def _queue(self, item: tuple) -> None:
        if self._fanout_q is None:
            self._fanout_q = asyncio.Queue()
        self._fanout_q.put_nowait(item)
        if self._drain_task is None or self._drain_task.done():
            self._drain_task = asyncio.ensure_future(self._drain_fanout())

    async def _drain_fanout(self) -> None:
        while True:
            item = await self._fanout_q.get()
            try:
                await self._drain_one(item)
            except asyncio.CancelledError:
                raise
            except Exception:
                logger.exception("durable fan-out failed")

    async def _drain_one(self, item: tuple) -> None:
        from pushcdn_tpu.broker import shardring
        from pushcdn_tpu.broker.tasks.handlers import EgressBatch
        broker = self.broker
        conns = broker.connections
        if item[0] == "pub":
            _, frame, users, brokers = item
            raw = Bytes(frame)
            cls = flowclass.frame_class(frame)
            egress = EgressBatch(broker)
            for u in users:
                if u in conns.users or u in conns.parting:
                    egress.to_user(u, raw)
                else:
                    shard = conns.remote_user_shard.get(u)
                    if shard is not None:
                        egress.to_shard(shard, shardring.KIND_USER, u, raw)
            for b in brokers:
                if b in conns.brokers:
                    egress.to_broker(b, raw, cls=cls)
                else:
                    shard = conns.remote_broker_shard.get(b)
                    if shard is not None:
                        egress.to_shard(shard, shardring.KIND_BROKER, b,
                                        raw, cls=cls)
            await egress.flush()
        else:  # ("replay", key, user_shard, prefixed_frames)
            _, key, user_shard, frames = item
            if key in conns.users:
                conn = conns.get_user_connection(key)
                if conn is None:
                    return
                try:
                    await conn.send_encoded(b"".join(frames), None,
                                            cls=flowclass.BULK,
                                            nframes=len(frames))
                    self._track_replay(key, conn, len(frames))
                except asyncio.CancelledError:
                    raise
                except Exception as exc:
                    logger.info("replay to user %s failed (%r); removing",
                                mnemonic(key), exc)
                    conns.remove_user(key, reason="send failed")
            elif broker.shard_runtime is not None:
                broker.shard_runtime.handoff(
                    user_shard, frames,
                    [(shardring.KIND_USER, key, list(range(len(frames))))],
                    prefixed=True)
