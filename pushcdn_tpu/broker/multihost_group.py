"""MultiHostBrokerGroup — the mesh broker group assembled across OS
processes: one SPMD deployment, zero host broker links.

This is SURVEY.md §2e scaled past one machine (ref mesh formation
cdn-broker/src/tasks/broker/heartbeat.rs:69-103, replaced wholesale):
every host process joins the jax.distributed runtime, builds the SAME
global broker mesh (parallel/multihost.py), attaches its brokers to its
LOCAL shards, and executes the routing step COLLECTIVELY — the
all_gather/all_to_all hops ride ICI inside a slice and DCN across hosts.
Inter-broker bytes never touch a socket this code owns.

Differences from the single-host :class:`MeshBrokerGroup`:

- **Lockstep stepping.** Collectives must be entered by every process the
  same number of times with the same shapes, so the pump runs at a fixed
  cadence (``batch_window_s``) and EVERY tick steps, traffic or not; the
  adaptive coalescing/latency-slicing/u_eff tricks are disabled (they key
  the jit cache on local state, which diverges across hosts). A tiny
  collective stop barrier runs before each step so every host leaves the
  loop on the same iteration — no process can strand a peer inside a
  collective.
- **Statically partitioned slot space.** Shard ``i`` owns user slots
  ``[i*K, (i+1)*K)`` (K = num_user_slots / num_shards): a slot's owner
  shard is ``slot // K`` by construction, so no host ever needs another
  host's allocator. Claims still carry versions and converge through the
  in-step CRDT merge exactly as on one host — each host authors only its
  own shards' state rows; the gather assembles the global view on device.
- **Frame bytes ride the collectives** (``gather_frame_bytes=True``): a
  remote shard's payload exists nowhere locally except via the step, and
  egress is host-local — each host encodes and flushes only to clients of
  its own shards, from its addressable output shards.
- **pk -> slot rendezvous via discovery.** Directs need the recipient's
  device slot; cross-host that mapping travels through the discovery
  registry's user-slot directory (heartbeat-style TTL re-publication,
  eventually consistent like the reference's 10 s UserSync gossip). A
  cross-host double-connect resolves through the same directory: the
  newer claim wins and the older host kicks its session on refresh.
"""

from __future__ import annotations

import asyncio
import logging
import time
from typing import Dict, List, Optional

import numpy as np

from pushcdn_tpu.broker.mesh_group import (
    MeshBrokerGroup,
    MeshGroupConfig,
)
from pushcdn_tpu.parallel.crdt import ABSENT, CrdtState
from pushcdn_tpu.parallel.frames import UserSlots, mask_row_of
from pushcdn_tpu.parallel.multihost import local_shard_indices
from pushcdn_tpu.parallel.router import BROKER_AXIS, RouterState
from pushcdn_tpu.proto.error import Error

logger = logging.getLogger("pushcdn.broker.multihost")


class PartitionedUserSlots(UserSlots):
    """Slot allocator over a static per-shard partition: ``assign`` is
    replaced by :meth:`assign_in_shard`, and freed slots return to their
    shard's own list (the inherited pump calls ``free_slot``)."""

    def __init__(self, capacity: int, num_shards: int,
                 local_shards: List[int]):
        super().__init__(capacity)
        self._free = []  # the global list is never used here
        self.slots_per_shard = capacity // num_shards
        K = self.slots_per_shard
        self.shard_free: Dict[int, List[int]] = {
            s: list(range((s + 1) * K - 1, s * K - 1, -1))
            for s in local_shards}

    def assign_in_shard(self, public_key: bytes, shard: int) -> int:
        existing = self.slot_of(public_key)
        if existing is not None:
            return existing
        free = self.shard_free.get(shard)
        if not free:
            from pushcdn_tpu.proto.error import ErrorKind, bail
            bail(ErrorKind.EXCEEDED_SIZE,
                 f"shard {shard} slot range full")
        slot = free.pop()
        self.assign_slot(public_key, slot)
        return slot

    def free_slot(self, slot: int) -> None:
        if self.key_of(slot) is None:
            shard = slot // self.slots_per_shard
            free = self.shard_free.get(shard)
            if free is not None and slot not in free:
                free.append(slot)


class MultiHostBrokerGroup(MeshBrokerGroup):
    def __init__(self, mesh, config: MeshGroupConfig = None,
                 discovery=None, directory_refresh_s: float = 0.5,
                 collective_timeout_s: float = 20.0):
        config = config or MeshGroupConfig()
        config.gather_frame_bytes = True  # bytes must cross hosts on-device
        super().__init__(mesh, config)
        self.local_shards = local_shard_indices(mesh)
        self.slots = PartitionedUserSlots(
            config.num_user_slots, self.num_shards, self.local_shards)
        self.slots_per_shard = self.slots.slots_per_shard
        # remote shards are live unless the control plane says otherwise
        self._liveness[:] = True
        self._state_rev += 1
        self.discovery = discovery
        self.directory_refresh_s = directory_refresh_s
        self._remote_slots: Dict[bytes, int] = {}   # directory mirror
        self._local_claim_ts: Dict[bytes, float] = {}
        self._dir_task: Optional[asyncio.Task] = None
        self._stop_requested = False
        self._stop_barrier = self._make_stop_barrier(mesh)
        # Watchdog bound on every collective tick: gloo's own failure
        # detection can take minutes on a silently-dead peer, and a
        # wedged or straggling host would otherwise gate the lockstep
        # pump forever. On breach the group fails CLOSED (disabled +
        # halt) in bounded time; the stuck collective thread is left to
        # die on gloo's schedule (it cannot be cancelled from Python).
        self.collective_timeout_s = collective_timeout_s

    # ---- collective stop barrier ----------------------------------------

    @staticmethod
    def _make_stop_barrier(mesh):
        import jax
        import jax.numpy as jnp
        from jax.sharding import PartitionSpec as P

        def per_shard(x):
            return jax.lax.psum(x[0], BROKER_AXIS)[None]

        from pushcdn_tpu.parallel.jax_compat import shard_map as _shard_map_compat
        sharded = _shard_map_compat(
            per_shard, mesh=mesh, in_specs=(P(BROKER_AXIS),),
            out_specs=P(BROKER_AXIS))
        return jax.jit(sharded)

    def _collective_stop(self, want_stop: bool) -> bool:
        """One tiny collective per tick: every host contributes its stop
        intent; all hosts see the same total and leave the loop on the
        same iteration."""
        import jax
        rows = {i: np.array([1 if want_stop else 0], np.int32)
                for i in self.local_shards}
        flags = self._make_global_rows(rows, (1,))
        out = self._stop_barrier(flags)
        shard0 = out.addressable_shards[0]
        return int(np.asarray(shard0.data)[0, 0]) > 0

    # ---- global array assembly (local shards only) ------------------------

    def _make_global_rows(self, rows: Dict[int, np.ndarray], row_shape):
        """Assemble a [B, ...] global array from THIS host's per-shard
        rows (jax.make_array_from_single_device_arrays: each process
        contributes only its addressable devices' blocks)."""
        import jax
        devices = self.mesh.devices.reshape(-1)
        shards = [jax.device_put(np.ascontiguousarray(rows[i])[None],
                                 devices[i])
                  for i in self.local_shards]
        return jax.make_array_from_single_device_arrays(
            (self.num_shards,) + tuple(row_shape), self._sharding, shards)

    # ---- user lifecycle ---------------------------------------------------

    def claim_user(self, shard: int, public_key: bytes, topics) -> None:
        existing = self.slots.slot_of(public_key)
        if existing is not None and \
                existing // self.slots_per_shard != shard:
            # same-host cross-shard reconnect: the slot//K owner-by-
            # construction invariant requires a slot in the NEW shard's
            # range — kick the old session (which releases its slot via
            # the observer) and fall through to a fresh assignment
            old_shard = existing // self.slots_per_shard
            old_broker = self.brokers[old_shard]
            if old_broker is not None and \
                    old_broker.connections.has_user(public_key):
                logger.info("user reconnected at another local shard "
                            "(%d -> %d); kicking", old_shard, shard)
                old_broker.connections.remove_user(
                    public_key, reason="user connected elsewhere")
            else:  # stale mapping with no live session
                self.release_user(old_shard, public_key)
        try:
            slot = self.slots.assign_in_shard(public_key, shard)
        except Error:
            self._unmirrored[public_key] = shard
            logger.warning("shard %d slot range full; %d unmirrored",
                           shard, len(self._unmirrored))
            return
        self._owner[slot] = shard
        self._claim_version[slot] += 1
        self._masks[slot] = mask_row_of(topics, self.config.topic_words)
        self._local_claim_ts[public_key] = time.time()
        self._state_rev += 1

    def release_user(self, shard: int, public_key: bytes) -> None:
        # only the host that believes it OWNS the claim may delete the
        # directory entry — after a cross-host double-connect kick the
        # entry already belongs to the winning host (the kick path clears
        # _local_claim_ts first), and deleting it would blackhole directs
        # until that host's next refresh
        owned = self._local_claim_ts.pop(public_key, None) is not None
        super().release_user(shard, public_key)
        if owned and self.discovery is not None:
            asyncio.ensure_future(
                self.discovery.drop_user_slots([public_key]))

    # ---- direct routing over the static partition -------------------------

    def _direct_route_info(self, recipient: bytes):
        slot = self.slots.slot_of(recipient)
        if slot is None:
            slot = self._remote_slots.get(recipient)
        if slot is None:
            return None
        return slot, slot // self.slots_per_shard

    # ---- directory refresh (heartbeat-style) ------------------------------

    async def _directory_loop(self) -> None:
        ttl = max(4 * self.directory_refresh_s, 2.0)
        while True:
            try:
                entries = {pk: (self.slots.slot_of(pk), ts)
                           for pk, ts in self._local_claim_ts.items()
                           if self.slots.slot_of(pk) is not None}
                if entries:
                    await self.discovery.publish_user_slots(entries, ttl)
                all_slots = await self.discovery.get_user_slots()
                remote = {}
                for pk, (slot, ts) in all_slots.items():
                    local_slot = self.slots.slot_of(pk)
                    if local_slot is None:
                        remote[pk] = slot
                    elif slot != local_slot and \
                            ts > self._local_claim_ts.get(pk, 0.0):
                        # cross-host double connect: the newer claim wins
                        # (the reference's CRDT kick, via the directory).
                        # ts is host wall-clock: hosts must be NTP-synced
                        # with skew below the reconnect gap — the same
                        # assumption the auth protocol's +-5 s signed-
                        # timestamp window already imposes on a deployment
                        shard = local_slot // self.slots_per_shard
                        broker = self.brokers[shard]
                        if broker is not None and \
                                broker.connections.has_user(pk):
                            logger.info(
                                "user connected on another host; kicking")
                            # the winner's directory entry must survive
                            # our release (see release_user)
                            self._local_claim_ts.pop(pk, None)
                            broker.connections.remove_user(
                                pk, reason="user connected elsewhere")
                self._remote_slots = remote
            except asyncio.CancelledError:
                raise
            except Exception:
                logger.exception("user-slot directory refresh failed")
            await asyncio.sleep(self.directory_refresh_s)

    # ---- the lockstep pump ------------------------------------------------

    async def ensure_started(self) -> None:
        if not self._started:
            self._started = True
            await asyncio.to_thread(self._warmup)
            self._task = asyncio.create_task(self._pump(),
                                             name="multihost-pump")
            if self.discovery is not None:
                self._dir_task = asyncio.create_task(
                    self._directory_loop(), name="multihost-directory")

    def _warmup(self) -> None:
        # the ONE specialization the lockstep pump uses (full shapes);
        # every host compiles it collectively here, so the first traffic
        # tick pays no compile rendezvous
        batches = [[r.take_batch() for r in rings]
                   for rings in self.lane_rings]
        directs = [[b.take_batch() for b in bkts]
                   for bkts in self.lane_buckets]
        try:
            self._run_step(batches, directs, self._owner.copy(),
                           self._claim_version.copy(), self._masks.copy(),
                           self._liveness.copy())
            self.steps -= 1
            # compile + first-rendezvous the stop barrier here too: its
            # first pump-tick call runs under the collective watchdog,
            # and paying jit compile inside that window could fail-close
            # a healthy group at startup on a contended host
            self._collective_stop(False)
        except Exception:
            logger.exception("multi-host warmup step failed")
            self.disabled = True

    async def on_shard_stopped(self, shard: int) -> None:
        # release local users of the stopped shard (same sweep as the
        # single-host group, restricted to its range)
        dropped = []
        for slot in np.nonzero(self._owner == shard)[0]:
            key = self.slots.key_of(int(slot))
            if key is not None:
                self.slots.unmap(key)
                if self._local_claim_ts.pop(key, None) is not None:
                    dropped.append(key)
            self._owner[slot] = ABSENT
            self._claim_version[slot] += 1
            self._masks[slot] = 0
            self._quarantine.append(int(slot))
        # a dead shard's unmirrored users must not pin broadcasts to the
        # (nonexistent cross-host) overflow path forever
        for key in [k for k, sh in self._unmirrored.items() if sh == shard]:
            del self._unmirrored[key]
        if dropped and self.discovery is not None:
            asyncio.ensure_future(self.discovery.drop_user_slots(dropped))
        self.brokers[shard] = None
        self._member_idents = None
        self._state_rev += 1
        # The collective stops only when THIS HOST fully retires (a single
        # broker of several restarting keeps the deployment running); a
        # retiring host necessarily stops the whole collective — SPMD
        # steps need every process.
        if any(self.brokers[s] is not None for s in self.local_shards):
            return
        self._stop_requested = True
        if self._dir_task is not None:
            self._dir_task.cancel()
            self._dir_task = None
        if self._task is not None:
            try:
                await asyncio.wait_for(self._task, timeout=10)
            except (asyncio.TimeoutError, asyncio.CancelledError):
                self._task.cancel()
            except Exception:
                logger.exception("multihost pump died during stop")
            self._task = None
            self._started = False

    async def _pump(self) -> None:
        c = self.config
        while True:
            await asyncio.sleep(c.batch_window_s)
            try:
                stop = await asyncio.wait_for(
                    asyncio.to_thread(self._collective_stop,
                                      self._stop_requested),
                    timeout=self.collective_timeout_s)
            except Exception as exc:  # CancelledError is BaseException
                logger.error(
                    "stop-barrier collective %s after %.0f s — peer host "
                    "dead or wedged; group disabled",
                    "timed out" if isinstance(exc, asyncio.TimeoutError)
                    else f"failed ({exc!r})", self.collective_timeout_s)
                self._fail_group("stop-barrier failure")
                return
            if stop:
                # a peer host retired: the collective is over everywhere.
                # Mark disabled so try_stage stops ACKing frames into rings
                # nothing will ever drain (they'd be silently blackholed).
                self.disabled = True
                self._halt_aux("peer host retired")
                return
            batches = [[r.take_batch() for r in rings]
                       for rings in self.lane_rings]
            directs = [[b.take_batch() for b in bkts]
                       for bkts in self.lane_buckets]
            owner = self._owner.copy()
            versions = self._claim_version.copy()
            masks = self._masks.copy()
            liveness = self._liveness.copy()
            quarantined, self._quarantine = self._quarantine, []
            try:
                from pushcdn_tpu.broker.tasks.senders import egress_streams
                jobs = await asyncio.wait_for(
                    asyncio.to_thread(
                        self._run_step, batches, directs, owner, versions,
                        masks, liveness),
                    timeout=self.collective_timeout_s)
                for shard, streams, d2, lengths, frames in jobs:
                    broker = self.brokers[shard]
                    if broker is None:
                        continue
                    if streams is not None:
                        self.messages_routed += egress_streams(
                            broker, self.slots, streams)
                    else:
                        self._egress_py(broker, d2, lengths, frames)
            except asyncio.CancelledError:
                raise
            except asyncio.TimeoutError:
                logger.error(
                    "multi-host step exceeded the %.0f s collective "
                    "watchdog — peer host dead or wedged; group disabled",
                    self.collective_timeout_s)
                self._fail_group("step watchdog breach", batches, directs)
                return
            except Exception:
                logger.exception("multi-host step failed; group disabled "
                                 "(no host fallback plane exists)")
                self._fail_group("step failure", batches, directs)
                # one last barrier so the peer hosts exit cleanly —
                # bounded: with a DEAD peer this barrier would otherwise
                # block until gloo's own (minutes-long) timeout
                try:
                    await asyncio.wait_for(
                        asyncio.to_thread(self._collective_stop, True),
                        timeout=self.collective_timeout_s)
                except Exception:
                    pass
                return
            finally:
                for slot in quarantined:
                    self.slots.free_slot(slot)

    def _fail_group(self, why: str, batches=None, directs=None) -> None:
        """Shared disable/halt path for every pump failure branch.
        ``batches``/``directs`` are the step's already-drained snapshots
        (their frames are the loss most certain to have happened)."""
        self.disabled = True
        self._stop_requested = True
        taken = 0
        if batches is not None:
            taken = (sum(int(b.valid.sum()) for lane in batches
                         for b in lane)
                     + sum(int(d.valid.sum()) for lane in directs
                           for d in lane))
        self._halt_aux(why, taken=taken)

    def _halt_aux(self, why: str, taken: int = 0) -> None:
        """Stop republishing claims and account for frames that were
        ACKed STAGED but will never be stepped (no cross-host fallback
        plane exists — log the loss rather than hide it). ``taken``
        counts frames already drained out of the rings for a step that
        then failed — the loss most certain to have happened."""
        if self._dir_task is not None:
            self._dir_task.cancel()
            self._dir_task = None
        stranded = self._staged_total() + taken
        if stranded:
            logger.warning(
                "multi-host group halted (%s) with %d staged frame(s) "
                "undeliverable — no host fallback plane exists", why,
                stranded)

    # ---- the collective step ---------------------------------------------

    def _run_step(self, batches, directs, owner, versions, masks,
                  liveness=None, state_rev=None):
        """One collective routing step: this host authors its local
        shards' state/lane rows, the step's collectives assemble the
        global view on device, and outputs are consumed from the
        addressable shards only (host-local egress)."""
        from pushcdn_tpu import native as native_mod
        B = self.num_shards
        live = (np.ones(B, bool) if liveness is None else liveness)

        state = RouterState(
            crdt=CrdtState(
                self._make_global_rows(
                    {i: owner for i in self.local_shards}, owner.shape),
                self._make_global_rows(
                    {i: versions for i in self.local_shards},
                    versions.shape),
                self._make_global_rows(
                    {i: owner for i in self.local_shards}, owner.shape)),
            topic_masks=self._make_global_rows(
                {i: masks for i in self.local_shards}, masks.shape))
        live_dev = self._make_global_rows(
            {i: live for i in self.local_shards}, live.shape)

        from pushcdn_tpu.parallel.router import DirectIngress, IngressBatch

        def gput(lane, attr):
            rows = {s: getattr(lane[s], attr) for s in self.local_shards}
            shape = next(iter(rows.values())).shape
            return self._make_global_rows(rows, shape)

        lane_batches = tuple(
            IngressBatch(gput(lane, "bytes_"), gput(lane, "kind"),
                         gput(lane, "length"), gput(lane, "topic_mask"),
                         gput(lane, "dest"), gput(lane, "valid"))
            for lane in batches)
        lane_directs = tuple(
            DirectIngress(gput(lane, "bytes_"), gput(lane, "length"),
                          gput(lane, "dest"), gput(lane, "valid"))
            for lane in directs)

        result = self.step_fn(state, lane_batches, lane_directs, live_dev)
        self.steps += 1

        # ---- host-local egress from addressable output shards ------------
        out = []
        for lanes in (result.lanes, result.direct_lanes):
            for l in lanes:
                d_sh = {sh.index[0].start: sh
                        for sh in l.deliver.addressable_shards}
                len_sh = {sh.index[0].start: sh
                          for sh in l.gathered_length.addressable_shards}
                byt_sh = {sh.index[0].start: sh
                          for sh in l.gathered_bytes.addressable_shards}
                for shard in self.local_shards:
                    if self.brokers[shard] is None:
                        continue
                    d2 = np.asarray(d_sh[shard].data)[0]
                    if not d2.any():
                        continue
                    # lazily pull the (large) gathered byte tensor ONLY
                    # for shards that actually deliver this tick — the
                    # lockstep pump fires every window, traffic or not
                    lengths = np.asarray(len_sh[shard].data)[0]
                    blocks = [np.asarray(byt_sh[shard].data)[0]]
                    streams = native_mod.egress_encode(d2, lengths, blocks)
                    if streams is not None:
                        out.append((shard, streams, None, None, None))
                    else:
                        out.append((shard, None, d2, lengths, blocks[0]))
        return out
