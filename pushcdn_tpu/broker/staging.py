"""Shared staging types for the device planes (dependency-light so the
host-only broker path never imports jax)."""

import enum


class StageResult(enum.Enum):
    """Outcome of try_stage — FULL is backpressure (retry), INELIGIBLE is
    a host-path message (don't)."""

    STAGED = "staged"
    INELIGIBLE = "ineligible"
    FULL = "full"
