"""Receive loops + the routing core (the #1 hot path).

Capability parity with cdn-broker/src/tasks/user/handler.rs:26-163 and
tasks/broker/handler.rs:31-272:

- ``user_receive_loop``: per-message recv-raw → deserialize (zero-copy) →
  hook → route ``Direct``/``Broadcast`` to users **and** brokers, or apply
  ``Subscribe``/``Unsubscribe`` locally; an invalid message disconnects the
  user (user/handler.rs:104-161).
- ``broker_receive_loop``: ``Direct`` → deliver to own user only
  (``to_user_only=True``); ``Broadcast`` → local users only (prevents
  re-forward loops); ``UserSync``/``TopicSync`` → CRDT merge
  (broker/handler.rs:121-193).
- ``handle_direct_message`` (broker/handler.rs:197-237): DirectMap lookup →
  self? send-to-user : send-to-broker (suppressed when ``to_user_only``).
- ``handle_broadcast_message`` (broker/handler.rs:240-272): interest query →
  fan-out. The serialized frame is forwarded **verbatim** (one deserialize
  per hop for dispatch; payload bytes shared via refcounted ``Bytes``).

Latency accounting: each frame's pool permit lives from socket-read to
last-fan-out-write; its lifetime feeds the LATENCY histogram
(limiter.AllocationPermit), mirroring the reference's latency proxy.
"""

from __future__ import annotations

import asyncio
import logging
import time
from typing import TYPE_CHECKING, List, Optional, Sequence

from pushcdn_tpu.broker.staging import StageResult
from pushcdn_tpu.proto import flowclass
from pushcdn_tpu.proto import ledger as ledger_mod
from pushcdn_tpu.proto import metrics as metrics_mod
from pushcdn_tpu.proto import trace as trace_mod
from pushcdn_tpu.proto.def_ import HookResult
from pushcdn_tpu.proto.error import Error
from pushcdn_tpu.proto.limiter import Bytes
from pushcdn_tpu.proto.message import (
    Broadcast,
    Direct,
    LedgerSync,
    Subscribe,
    SubscribeFrom,
    TopicSync,
    Unsubscribe,
    UserSync,
    deserialize,
)


def _ingress_class(message) -> int:
    """Frame-derived ledger class for ingress/link-recv accounting — the
    SAME rule senders use for the per-link tables (ISSUE 20): Broadcast →
    first-topic class, Direct → live, any other kind → control."""
    if isinstance(message, Broadcast):
        return flowclass.class_of_topics(message.topics)
    if isinstance(message, Direct):
        return flowclass.LIVE
    return flowclass.CONTROL
from pushcdn_tpu.proto.util import mnemonic

if TYPE_CHECKING:
    from pushcdn_tpu.broker.broker import Broker

logger = logging.getLogger("pushcdn.broker")


# ---------------------------------------------------------------------------
# routing core
# ---------------------------------------------------------------------------

class EgressBatch:
    """Per-wakeup egress accumulator: routing decisions append fan-out
    clones per peer; ``flush()`` hands each peer its whole batch with ONE
    ``send_raw_many`` (one queue entry, one writer wakeup). Per-peer frame
    order is the processing order, so per-(sender→receiver) ordering is
    identical to the per-frame path. Failure ⇒ removal semantics are the
    senders' (sender.rs:17-58).

    Lifecycle tracing: a routed TRACED message notes its context here
    (:meth:`note_trace`); ``flush()`` emits the ``egress`` span when the
    batch has been handed to every peer's writer queue. Deliberately NOT
    a wire-flush wait: forcing ``flush=True`` would let one backpressured
    peer head-of-line-block the sender's whole receive drain for up to
    the write timeout on every sampled message — the wire-side residence
    is observable via ``cdn_writer_queue_depth`` and the receiver's
    ``delivery`` span instead. ``appended`` counts fan-out clones routed
    into the batch, so span emission can tell a routed message from a
    dropped one (unknown recipient, no interest)."""

    __slots__ = ("broker", "users", "brokers", "shards", "appended",
                 "_traces")

    def __init__(self, broker: "Broker"):
        self.broker = broker
        self.users: dict = {}
        self.brokers: dict = {}
        # sharded data plane: {shard -> {(kind, ident) -> [clones]}} —
        # flushed as ONE handoff-ring record per shard (ISSUE 6)
        self.shards: dict = {}
        self.appended = 0
        self._traces: Optional[list] = None

    def note_trace(self, tr) -> None:
        """Remember a traced message routed into this batch; its egress
        span is emitted when the batch flushes."""
        if self._traces is None:
            self._traces = []
        self._traces.append((tr, time.monotonic()))

    def to_user(self, public_key: bytes, raw: Bytes) -> None:
        lst = self.users.get(public_key)
        if lst is None:
            lst = self.users[public_key] = []
        lst.append(raw.clone())
        self.appended += 1

    def to_broker(self, identifier: str, raw: Bytes,
                  cls: int = flowclass.LIVE) -> None:
        lst = self.brokers.get(identifier)
        if lst is None:
            lst = self.brokers[identifier] = []
        lst.append(raw.clone())
        self.appended += 1
        # per-link conservation table (ISSUE 20): counted at the routing
        # decision, where the per-frame class is exact on both ends
        ledger_mod.note_link_sent(identifier, cls)

    def to_shard(self, shard: int, kind: int, ident, raw: Bytes,
                 cls: int = flowclass.LIVE) -> None:
        """Queue a fan-out clone for a peer living on a sibling shard
        (``kind`` is shardring.KIND_USER/KIND_BROKER)."""
        targets = self.shards.get(shard)
        if targets is None:
            targets = self.shards[shard] = {}
        lst = targets.get((kind, ident))
        if lst is None:
            lst = targets[(kind, ident)] = []
        lst.append(raw.clone())
        self.appended += 1
        if kind == 1:  # shardring.KIND_BROKER: a mesh link via shard 0
            ledger_mod.note_link_sent(ident, cls)

    def release_all(self) -> None:
        for frames in self.users.values():
            for f in frames:
                f.release()
        self.users.clear()
        for frames in self.brokers.values():
            for f in frames:
                f.release()
        self.brokers.clear()
        for targets in self.shards.values():
            for frames in targets.values():
                for f in frames:
                    f.release()
        self.shards.clear()

    def _flush_shards(self) -> None:
        """Hand each sibling shard its batch as one ring record: every
        distinct frame's bytes written once, each peer carrying its
        frame-index list (no re-serialization at the boundary). Synchronous
        — ring-full degrades to the runtime's counted relay fallback."""
        runtime = self.broker.shard_runtime
        for shard, targets in self.shards.items():
            frames: list = []
            index_of: dict = {}
            peers = []
            for (kind, ident), clones in targets.items():
                idx = []
                for c in clones:
                    key = id(c.data)
                    i = index_of.get(key)
                    if i is None:
                        i = index_of[key] = len(frames)
                        frames.append(c.data)
                    idx.append(i)
                peers.append((kind,
                              ident if isinstance(ident, bytes)
                              else ident.encode(), idx))
            runtime.handoff(shard, frames, peers)
            for clones in targets.values():
                for c in clones:
                    c.release()
        self.shards.clear()

    @staticmethod
    async def _send_batch(conn, frames: list) -> None:
        """Hand one peer its whole batch. Small-frame batches pre-encode
        into ONE PreEncoded writer entry via the native batch encoder
        (verbatim flush, permits released here, no per-frame writer
        work); other shapes ride ``send_raw_many`` (the writer's own
        coalescer). Ownership rule either way: the frames are consumed —
        released here on the encode path, by the connection on the raw
        path."""
        # class volume was already counted at the routing decision
        # (route_direct/route_broadcast, one count per fan-out pair), so
        # the writer entries carry nframes=0/nbytes=0 and only observe
        # queue delay — same suppression the cut-through plan path uses
        if len(frames) < 2:  # depth-1: nothing to coalesce, skip probing
            await conn.send_raw_many(frames, nframes=0, nbytes=0)
            return
        from pushcdn_tpu.broker.tasks.senders import pre_encode_frames
        encoded = pre_encode_frames(frames)
        if encoded is not None:
            for f in frames:
                f.release()
            await conn.send_encoded(encoded, nbytes=0, count=len(frames))
        else:
            await conn.send_raw_many(frames, nframes=0, nbytes=0)

    async def flush(self) -> None:
        broker = self.broker
        traces, self._traces = self._traces, None
        try:
            if self.shards:
                # cross-shard handoff first: synchronous ring writes, so a
                # backpressured local peer below can't delay the sibling
                # (per-peer targets are disjoint — order across them is
                # not observable)
                self._flush_shards()
            # brokers first (reference fan-out order, handler.rs:240-272)
            while self.brokers:
                ident, frames = self.brokers.popitem()
                conn = broker.connections.get_broker_connection(ident)
                if conn is None:
                    for f in frames:
                        f.release()
                    continue
                metrics_mod.EGRESS_FRAMES_BROKER.inc(len(frames))
                try:
                    await self._send_batch(conn, frames)
                except asyncio.CancelledError:
                    raise
                except Exception as exc:
                    logger.info("send to broker %s failed (%r); removing",
                                ident, exc)
                    broker.connections.remove_broker(ident,
                                                     reason="send failed")
                    broker.update_metrics()
            while self.users:
                key, frames = self.users.popitem()
                conn = broker.connections.get_user_connection(key)
                if conn is None:
                    for f in frames:
                        f.release()
                    continue
                metrics_mod.EGRESS_FRAMES_USER.inc(len(frames))
                try:
                    await self._send_batch(conn, frames)
                except asyncio.CancelledError:
                    raise
                except Exception as exc:
                    logger.info("send to user %s failed (%r); removing",
                                mnemonic(key), exc)
                    broker.connections.remove_user(key, reason="send failed")
                    broker.update_metrics()
        except BaseException:
            # interrupted mid-flush (e.g. cancellation): the un-flushed
            # peers' clones must still return their pool permits
            self.release_all()
            raise
        if traces:
            # the whole batch is in the peers' writer queues: that handoff
            # IS the egress hop (wire residence is visible via
            # cdn_writer_queue_depth and the receiver's delivery span)
            now = time.monotonic()
            for tr, t0 in traces:
                trace_mod.emit("egress", tr,
                               f"writer-handoff {now - t0:.6f}s")


def _emit_staged_trace(message) -> None:
    """Span emission for a traced message the DEVICE plane accepted: the
    frame rides the staging ring and the device egress verbatim (flag +
    trace block intact — the receiver still emits ``delivery``), so the
    broker-side hops collapse to the stage handoff: ``plan`` = the stage
    decision, ``egress`` = handed to the device pump's egress (the pump
    itself is a batched jitted step with no per-message seam)."""
    tr = message.trace
    if tr is not None:
        trace_mod.emit("ingress", tr, "device")
        trace_mod.emit("plan", tr, "device-staged")
        trace_mod.emit("egress", tr, "device-staged")


def _emit_scalar_trace(message, egress: EgressBatch, before: int) -> None:
    """Span emission for a traced message routed by the scalar loops:
    ingress and plan collapse to adjacent instants (the scalar body is one
    synchronous block), the egress span completes at batch flush. One
    class-attribute load for the untraced 1023/1024. ``before`` is
    ``egress.appended`` captured before the route call — a message the
    route decision DROPPED (unknown recipient, no interest) gets its plan
    span tagged ``dropped`` and NO egress span, so a chain ending at
    ``plan`` means the broker itself dropped the message."""
    tr = message.trace
    if tr is not None:
        trace_mod.emit("ingress", tr, "scalar")
        if egress.appended > before:
            trace_mod.emit("plan", tr, "scalar")
            egress.note_trace(tr)
        else:
            trace_mod.emit("plan", tr, "dropped")


def route_direct(broker: "Broker", recipient: bytes, raw: Bytes,
                 to_user_only: bool, egress: EgressBatch) -> None:
    """One-hop direct routing decision (broker/handler.rs:197-237).

    Flow accounting mirrors the cut-through plan's semantics exactly: a
    delivered Direct counts ONE ``dir=in`` frame (class ``live``, like the
    plan's ``out_class``) and one ``dir=out`` count per fan-out pair,
    stamped at the routing decision before any connection lookup; a
    dropped Direct (unknown recipient) counts nothing (plan writes 255).
    """
    before = egress.appended
    _route_direct(broker, recipient, raw, to_user_only, egress)
    delta = egress.appended - before
    if delta:
        data = getattr(raw, "data", None)
        nb = (len(data) + 4) if data is not None else 4
        metrics_mod.CLASS_FRAMES_IN[flowclass.LIVE].inc()
        metrics_mod.CLASS_BYTES_IN[flowclass.LIVE].inc(nb)
        metrics_mod.CLASS_FRAMES_OUT[flowclass.LIVE].inc(delta)
        metrics_mod.CLASS_BYTES_OUT[flowclass.LIVE].inc(delta * nb)
    else:
        # unknown/stale recipient: the frame's terminal fate (ISSUE 20)
        ledger_mod.record_fate("dropped", "no_route", flowclass.LIVE)


def _route_direct(broker: "Broker", recipient: bytes, raw: Bytes,
                  to_user_only: bool, egress: EgressBatch) -> None:
    conns = broker.connections
    if conns.num_shards > 1:
        # sharded data plane: "our user" spans every worker shard of this
        # identity. A sibling's user rides the handoff ring (allowed even
        # for broker-origin frames — the sibling IS this broker); a mesh
        # owner reachable only via shard 0's links rides the ring too.
        # Precedence mirrors the unsharded path (and the cut-through
        # plan's dmap): the DirectMap owner wins, so a user the mesh
        # already re-homed elsewhere is forwarded even while the local
        # eviction delta is still in flight.
        from pushcdn_tpu.broker import shardring
        owner = conns.get_broker_identifier_of_user(recipient)
        if owner is not None and owner != conns.identity:
            if to_user_only:
                # one-hop rule: never re-forward. But a forwarded direct
                # that raced a migration eviction here (the sender's
                # DirectMap replica hadn't caught up yet) can still reach
                # the user over the ``parting`` connection the client is
                # draining — chasing it beats a silent delivered-loss.
                if recipient in conns.parting:
                    egress.to_user(recipient, raw)
                return
            if owner in conns.brokers:
                egress.to_broker(owner, raw)
            else:
                link_shard = conns.remote_broker_shard.get(owner)
                if link_shard is not None:
                    egress.to_shard(link_shard, shardring.KIND_BROKER,
                                    owner, raw)
            return
        # owner is this box — or absent from this worker's replica
        # (sibling users are mirrored into the DirectMap on shard 0
        # only): deliver locally, else hand off to the owning shard
        if recipient in conns.users:
            egress.to_user(recipient, raw)
            return
        shard = conns.remote_user_shard.get(recipient)
        if shard is not None:
            egress.to_shard(shard, shardring.KIND_USER, recipient, raw)
            return
        if recipient in conns.parting:  # evicted mid-flight: chase
            egress.to_user(recipient, raw)
        return  # unknown/stale user: drop
    owner = conns.get_broker_identifier_of_user(recipient)
    if owner == conns.identity:
        egress.to_user(recipient, raw)
    elif owner is None:
        # unknown user: drop — unless the old connection is still
        # parting after an eviction (the row may be gone entirely when
        # the user disconnected elsewhere before this frame landed)
        if recipient in conns.parting:
            egress.to_user(recipient, raw)
    elif not to_user_only:
        # forward one hop to the owning broker; the remote end delivers
        # with to_user_only=True so it can never bounce back
        egress.to_broker(owner, raw)
    else:
        # one-hop rule: never re-forward. A forwarded direct that raced
        # the migration eviction (sender's DirectMap replica was behind)
        # still reaches the user over the ``parting`` connection the
        # client is draining — chasing it beats a silent delivered-loss.
        if recipient in conns.parting:
            egress.to_user(recipient, raw)


def route_broadcast(broker: "Broker", topics: Sequence[int], raw: Bytes,
                    to_users_only: bool, egress: EgressBatch,
                    users_via_device: bool = False,
                    exclude_brokers: frozenset = frozenset(),
                    interest_cache: Optional[dict] = None,
                    raw_topics: Optional[Sequence[int]] = None) -> None:
    """Interest-driven fan-out decision (broker/handler.rs:240-272).

    ``users_via_device=True`` means the local-user fan-out was staged onto
    the device plane; only the inter-broker forwarding runs on the host.
    ``exclude_brokers`` are peers already covered by the device mesh
    (group members) — interested OUT-of-group brokers still get the frame.
    ``interest_cache`` memoizes the interest query per (topics, scope)
    within one receive batch; entries carry ``Connections.interest_version``
    so a subscription/membership/sync mutation from ANY task — including
    one landing while this batch awaits egress or device backpressure —
    invalidates them, keeping parity with the reference's per-message
    interest query.

    Flow accounting mirrors the cut-through plan: one ``dir=in`` frame per
    Broadcast with a non-empty (pruned) topic list — consumed even with
    zero interested peers, like the plan's ``out_class`` — and one
    ``dir=out`` count per fan-out pair, under the class of the FIRST
    topic byte of the frame AS SENT (``raw_topics``; the plan kernel
    reads that byte before pruning, and the scalar twin must agree).
    """
    before = egress.appended
    cls = flowclass.class_of_topics(
        raw_topics if raw_topics is not None else topics)
    _route_broadcast(broker, topics, raw, to_users_only, egress,
                     users_via_device=users_via_device,
                     exclude_brokers=exclude_brokers,
                     interest_cache=interest_cache, cls=cls)
    if topics:
        data = getattr(raw, "data", None)
        nb = (len(data) + 4) if data is not None else 4
        metrics_mod.CLASS_FRAMES_IN[cls].inc()
        metrics_mod.CLASS_BYTES_IN[cls].inc(nb)
        delta = egress.appended - before
        if delta:
            metrics_mod.CLASS_FRAMES_OUT[cls].inc(delta)
            metrics_mod.CLASS_BYTES_OUT[cls].inc(delta * nb)
        elif not users_via_device:
            # zero interested recipients: a counted (benign) fate
            ledger_mod.record_fate("dropped", "no_interest", cls)


def _route_broadcast(broker: "Broker", topics: Sequence[int], raw: Bytes,
                     to_users_only: bool, egress: EgressBatch,
                     users_via_device: bool = False,
                     exclude_brokers: frozenset = frozenset(),
                     interest_cache: Optional[dict] = None,
                     cls: int = flowclass.LIVE) -> None:
    if interest_cache is None:
        users, brokers = broker.connections.get_interested_by_topic(
            list(topics), to_users_only)
    else:
        version = broker.connections.interest_version
        key = (tuple(topics), to_users_only)
        hit = interest_cache.get(key)
        if hit is None or hit[0] != version:
            hit = (version, broker.connections.get_interested_by_topic(
                list(topics), to_users_only))
            interest_cache[key] = hit
        users, brokers = hit[1]
    conns = broker.connections
    if conns.num_shards > 1:
        # sharded data plane: the interest tables span the whole box, so
        # a hit may live on a sibling shard (user) or be reachable only
        # through shard 0's mesh links (broker) — ride the handoff ring
        from pushcdn_tpu.broker import shardring
        local_users = conns.users
        local_brokers = conns.brokers
        for ident in brokers:
            if ident in exclude_brokers:
                continue
            if ident in local_brokers:
                egress.to_broker(ident, raw, cls=cls)
            else:
                link_shard = conns.remote_broker_shard.get(ident)
                if link_shard is not None:
                    egress.to_shard(link_shard, shardring.KIND_BROKER,
                                    ident, raw, cls=cls)
        if not users_via_device:
            for user in users:
                if user in local_users:
                    egress.to_user(user, raw)
                else:
                    shard = conns.remote_user_shard.get(user)
                    if shard is not None:
                        egress.to_shard(shard, shardring.KIND_USER, user,
                                        raw)
                    elif user in conns.parting:
                        # interest rows outlive the eviction through the
                        # parting grace: the chase delivery (see
                        # Connections.remove_user)
                        egress.to_user(user, raw)
        return
    for ident in brokers:
        if ident not in exclude_brokers:
            egress.to_broker(ident, raw, cls=cls)
    if not users_via_device:
        for user in users:
            egress.to_user(user, raw)


async def handle_direct_message(broker: "Broker", recipient: bytes,
                                raw: Bytes, to_user_only: bool) -> None:
    """One-shot direct routing (kept for non-batched callers)."""
    egress = EgressBatch(broker)
    route_direct(broker, recipient, raw, to_user_only, egress)
    await egress.flush()


async def handle_broadcast_message(broker: "Broker", topics: Sequence[int],
                                   raw: Bytes, to_users_only: bool,
                                   users_via_device: bool = False,
                                   exclude_brokers: frozenset = frozenset()
                                   ) -> None:
    """One-shot broadcast fan-out (kept for non-batched callers)."""
    egress = EgressBatch(broker)
    route_broadcast(broker, topics, raw, to_users_only, egress,
                    users_via_device=users_via_device,
                    exclude_brokers=exclude_brokers)
    await egress.flush()


async def _stage_with_backpressure(device, message, raw: Bytes):
    """Stage onto the device plane; FULL results block THIS sender's
    receive loop and retry — the same "block the reader, not the router"
    semantics the byte-pool gives the host path. The wait is unbounded on
    purpose (so is the pool's): if the pump dies it flips ``disabled`` and
    try_stage starts returning INELIGIBLE, which exits the loop."""
    while True:
        result = device.try_stage(message, raw)
        if result != StageResult.FULL:
            return result
        await asyncio.sleep(0.002)


# ---------------------------------------------------------------------------
# user receive loop
# ---------------------------------------------------------------------------

async def user_receive_loop(broker: "Broker", public_key: bytes,
                            connection) -> None:
    """Pump one user's messages until the connection dies or the user is
    kicked (user/handler.rs:104-161). Messages are drained and routed in
    batches: one ``recv_raw_many`` wakeup routes every pending frame, and
    the fan-out goes out as per-peer ``send_raw_many`` batches."""
    from pushcdn_tpu.broker.tasks import cutthrough  # lazy: import cycle
    hook = broker.run_def.user_def.hook
    topics = broker.run_def.topics
    alive = True
    try:
        while alive:
            # Cut-through plane: when eligible (native kernel compiled, no
            # device plane, default hook), whole FrameChunk batches route
            # via one plan call with zero per-frame Python — the scalar
            # body below is the correctness twin (and the path control
            # frames always take).
            cut = cutthrough.acquire(broker, hook)
            if cut is not None:
                items = await connection.recv_frames()
                alive = await cut.route_drain(public_key, items,
                                              is_user=True,
                                              conn=connection)
                continue
            raws = await connection.recv_raw_many()
            metrics_mod.ROUTE_SCALAR_FRAMES.inc(len(raws))
            egress = EgressBatch(broker)
            interest_cache: dict = {}
            # device-eligible (message, raw, pruned_topics) collected during
            # the scan and staged in ONE stage_batch call after it (one
            # native pack per size lane instead of a per-frame ring push)
            stage_items: list = []
            device = broker.device_plane
            try:
                for raw in raws:
                    try:
                        message = deserialize(raw.data)
                    except Error:
                        # malformed frame ⇒ disconnect
                        # (user/handler.rs:106-118)
                        logger.info(
                            "user %s sent malformed frame; disconnecting",
                            mnemonic(public_key))
                        connection.flightrec.record("malformed-frame",
                                                    abnormal=True)
                        ledger_mod.record_fate("dropped", "malformed",
                                               flowclass.CLASS_NONE)
                        alive = False
                        break
                    ledger_mod.note_ingress(_ingress_class(message))
                    result = hook(public_key, message)
                    if result == HookResult.SKIP:
                        continue
                    if result == HookResult.DISCONNECT:
                        alive = False
                        break

                    if isinstance(message, Direct):
                        # device path covers local-recipient delivery (and,
                        # for a mesh-group plane, any recipient in the
                        # group); host path covers the rest
                        if device is not None:
                            stage_items.append((message, raw, None))
                            continue
                        a0 = egress.appended
                        route_direct(broker, message.recipient, raw,
                                     to_user_only=False, egress=egress)
                        _emit_scalar_trace(message, egress, a0)
                    elif isinstance(message, Broadcast):
                        pruned, _bad = topics.prune(message.topics)
                        if pruned:
                            # durable topics (ISSUE 14): retention stamp in
                            # the same synchronous block as the route
                            # decision; a False return means the owning
                            # shard fans out through its ordered drainer
                            durable = broker.durable
                            if durable is not None and not durable.on_publish(
                                    pruned, message, raw,
                                    to_users_only=False):
                                continue
                            if device is not None:
                                stage_items.append((message, raw, pruned))
                                continue
                            a0 = egress.appended
                            route_broadcast(
                                broker, pruned, raw, to_users_only=False,
                                egress=egress,
                                interest_cache=interest_cache,
                                raw_topics=message.topics)
                            _emit_scalar_trace(message, egress, a0)
                    elif isinstance(message, Subscribe):
                        pruned, bad = topics.prune(message.topics)
                        if bad:
                            # unknown topic ⇒ disconnect (subscribe.rs test
                            # behavior: invalid-topic subscriptions kick)
                            alive = False
                            break
                        adm = broker.admission
                        if adm is not None and \
                                not adm.allow_subscribe(connection):
                            # over-rate: drop the mutation, notify typed
                            # through the ordered egress path (ISSUE 7)
                            adm.shed_subscribe(public_key, connection,
                                               egress)
                            continue
                        broker.connections.subscribe_user_to(public_key,
                                                             pruned)
                    elif isinstance(message, Unsubscribe):
                        adm = broker.admission
                        if adm is not None and \
                                not adm.allow_subscribe(connection):
                            adm.shed_subscribe(public_key, connection,
                                               egress)
                            continue
                        pruned, _bad = topics.prune(message.topics)
                        broker.connections.unsubscribe_user_from(public_key,
                                                                 pruned)
                    elif isinstance(message, SubscribeFrom):
                        # durable replay subscribe (ISSUE 14): registration
                        # + ring snapshot + replay enqueue in one
                        # synchronous block (the handover invariant)
                        adm = broker.admission
                        if adm is not None and \
                                not adm.allow_subscribe(connection):
                            adm.shed_subscribe(public_key, connection,
                                               egress)
                            continue
                        durable = broker.durable
                        if durable is None or not durable.handle_subscribe_from(
                                public_key, message, connection):
                            alive = False
                            break
                    else:
                        # users may not send auth or sync messages
                        # post-handshake
                        alive = False
                        break

                # phase 2: batch-stage the collected device-eligible
                # messages, then host-route whatever the device didn't take
                if stage_items:
                    results = device.stage_batch(
                        [(m, r) for m, r, _ in stage_items])
                    for (message, raw, pruned), res in zip(stage_items,
                                                           results):
                        if res == StageResult.FULL:
                            res = await _stage_with_backpressure(
                                device, message, raw)
                        staged = res == StageResult.STAGED
                        if staged:
                            _emit_staged_trace(message)
                        if isinstance(message, Direct):
                            if not staged:
                                a0 = egress.appended
                                route_direct(broker, message.recipient, raw,
                                             to_user_only=False,
                                             egress=egress)
                                _emit_scalar_trace(message, egress, a0)
                        else:
                            # host side: remaining fan-out — all of it when
                            # not staged; only out-of-group/interest
                            # forwarding when the device covers users
                            # (+ group peers over ICI)
                            a0 = egress.appended
                            route_broadcast(
                                broker, pruned, raw, to_users_only=False,
                                egress=egress, users_via_device=staged,
                                exclude_brokers=(
                                    frozenset(
                                        device.covered_broker_idents())
                                    if staged else frozenset()),
                                interest_cache=interest_cache,
                                raw_topics=message.topics)
                            if not staged:
                                _emit_scalar_trace(message, egress, a0)
            finally:
                try:
                    await egress.flush()
                finally:
                    for raw in raws:
                        raw.release()
    except (Error, asyncio.IncompleteReadError):
        pass  # connection died: fall through to removal
    except asyncio.CancelledError:
        raise
    finally:
        # Only deregister if WE are still the registered connection — a
        # same-broker double-connect evicts the old loop (cancelling it)
        # after the new connection has already taken the map slot, and the
        # old loop's cleanup must not remove the new entry.
        if broker.connections.get_user_connection(public_key) is connection:
            broker.connections.remove_user(public_key, reason="receive loop ended")
        broker.update_metrics()


# ---------------------------------------------------------------------------
# broker receive loop
# ---------------------------------------------------------------------------

async def broker_receive_loop(broker: "Broker", identifier: str,
                              connection) -> None:
    """Pump a peer broker's messages (broker/handler.rs:121-193), batched
    the same way as the user loop."""
    from pushcdn_tpu.broker.tasks import cutthrough  # lazy: import cycle
    hook = broker.run_def.broker_def.hook
    topics = broker.run_def.topics
    alive = True
    try:
        while alive:
            # same cut-through seam as the user loop (broker-origin mode:
            # local-users-only broadcast, to_user_only direct)
            cut = cutthrough.acquire(broker, hook)
            if cut is not None:
                items = await connection.recv_frames()
                alive = await cut.route_drain(identifier, items,
                                              is_user=False,
                                              conn=connection)
                continue
            raws = await connection.recv_raw_many()
            metrics_mod.ROUTE_SCALAR_FRAMES.inc(len(raws))
            egress = EgressBatch(broker)
            interest_cache: dict = {}
            stage_items: list = []
            device = broker.device_plane
            # A covers_brokers (mesh-group) plane must NOT re-stage
            # host-forwarded traffic: the origin couldn't stage it, and
            # re-staging would all_gather it back to every shard —
            # duplicate delivery. Host-forwarded frames are delivered
            # locally only, exactly the reference's to_users_only rule.
            single_shard = (device is not None
                            and not device.covers_brokers)
            try:
                for raw in raws:
                    try:
                        message = deserialize(raw.data)
                    except Error:
                        logger.warning(
                            "broker %s sent malformed frame; dropping link",
                            identifier)
                        connection.flightrec.record("malformed-frame",
                                                    abnormal=True)
                        ledger_mod.record_fate("dropped", "malformed",
                                               flowclass.CLASS_NONE)
                        alive = False
                        break
                    ledger_mod.note_ingress(_ingress_class(message),
                                            peer=identifier)
                    result = hook(identifier, message)
                    if result == HookResult.SKIP:
                        continue
                    if result == HookResult.DISCONNECT:
                        alive = False
                        break

                    if isinstance(message, Direct):
                        # deliver to our own user only — never re-forward
                        # (broker/handler.rs:148-153); the single-shard
                        # device path's delivery-iff-owner rule keeps that
                        # invariant
                        if single_shard:
                            stage_items.append((message, raw, None))
                            continue
                        a0 = egress.appended
                        route_direct(broker, message.recipient, raw,
                                     to_user_only=True, egress=egress)
                        _emit_scalar_trace(message, egress, a0)
                    elif isinstance(message, Broadcast):
                        # users only — prevents broadcast loops
                        # (broker/handler.rs:156-161)
                        pruned, _bad = topics.prune(message.topics)
                        if pruned:
                            # mesh-forwarded durable broadcasts are retained
                            # here too, so a user rejoining at THIS broker
                            # replays mesh-wide history (seqs broker-local)
                            durable = broker.durable
                            if durable is not None and not durable.on_publish(
                                    pruned, message, raw,
                                    to_users_only=True):
                                continue
                            if single_shard:
                                stage_items.append((message, raw, pruned))
                                continue
                            a0 = egress.appended
                            route_broadcast(broker, pruned, raw,
                                            to_users_only=True,
                                            egress=egress,
                                            interest_cache=interest_cache,
                                            raw_topics=message.topics)
                            _emit_scalar_trace(message, egress, a0)
                    elif isinstance(message, UserSync):
                        broker.connections.apply_user_sync(message.payload)
                        broker.update_metrics()
                    elif isinstance(message, TopicSync):
                        broker.connections.apply_topic_sync(identifier,
                                                            message.payload)
                    elif isinstance(message, LedgerSync):
                        # peer's conservation balance sheet (ISSUE 20) —
                        # unparseable sheets are ignored, not link-fatal
                        # (monotone snapshots, last writer wins)
                        import json
                        try:
                            sheet = json.loads(bytes(message.payload))
                        except (ValueError, UnicodeDecodeError):
                            sheet = None
                        if sheet is not None:
                            ledger_mod.LEDGER.note_peer_sheet(identifier,
                                                              sheet)
                    else:
                        logger.warning(
                            "broker %s sent unexpected %s; dropping link",
                            identifier, type(message).__name__)
                        alive = False
                        break

                if stage_items:
                    results = device.stage_batch(
                        [(m, r) for m, r, _ in stage_items])
                    for (message, raw, pruned), res in zip(stage_items,
                                                           results):
                        if res == StageResult.FULL:
                            res = await _stage_with_backpressure(
                                device, message, raw)
                        if res == StageResult.STAGED:
                            _emit_staged_trace(message)
                            continue
                        a0 = egress.appended
                        if isinstance(message, Direct):
                            route_direct(broker, message.recipient, raw,
                                         to_user_only=True, egress=egress)
                        else:
                            route_broadcast(broker, pruned, raw,
                                            to_users_only=True,
                                            egress=egress,
                                            interest_cache=interest_cache,
                                            raw_topics=message.topics)
                        _emit_scalar_trace(message, egress, a0)
            finally:
                try:
                    await egress.flush()
                finally:
                    for raw in raws:
                        raw.release()
    except (Error, asyncio.IncompleteReadError):
        pass
    except asyncio.CancelledError:
        raise
    finally:
        # Same guard as the user loop: a replaced link's cancelled loop must
        # not deregister the replacement.
        if broker.connections.get_broker_connection(identifier) is connection:
            broker.connections.remove_broker(identifier, reason="receive loop ended")
        broker.update_metrics()
