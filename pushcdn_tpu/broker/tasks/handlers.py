"""Receive loops + the routing core (the #1 hot path).

Capability parity with cdn-broker/src/tasks/user/handler.rs:26-163 and
tasks/broker/handler.rs:31-272:

- ``user_receive_loop``: per-message recv-raw → deserialize (zero-copy) →
  hook → route ``Direct``/``Broadcast`` to users **and** brokers, or apply
  ``Subscribe``/``Unsubscribe`` locally; an invalid message disconnects the
  user (user/handler.rs:104-161).
- ``broker_receive_loop``: ``Direct`` → deliver to own user only
  (``to_user_only=True``); ``Broadcast`` → local users only (prevents
  re-forward loops); ``UserSync``/``TopicSync`` → CRDT merge
  (broker/handler.rs:121-193).
- ``handle_direct_message`` (broker/handler.rs:197-237): DirectMap lookup →
  self? send-to-user : send-to-broker (suppressed when ``to_user_only``).
- ``handle_broadcast_message`` (broker/handler.rs:240-272): interest query →
  fan-out. The serialized frame is forwarded **verbatim** (one deserialize
  per hop for dispatch; payload bytes shared via refcounted ``Bytes``).

Latency accounting: each frame's pool permit lives from socket-read to
last-fan-out-write; its lifetime feeds the LATENCY histogram
(limiter.AllocationPermit), mirroring the reference's latency proxy.
"""

from __future__ import annotations

import asyncio
import logging
from typing import TYPE_CHECKING, List, Sequence

from pushcdn_tpu.broker.tasks.senders import (
    try_send_to_broker,
    try_send_to_brokers,
    try_send_to_user,
)
from pushcdn_tpu.broker.staging import StageResult
from pushcdn_tpu.proto import metrics as metrics_mod
from pushcdn_tpu.proto.def_ import HookResult
from pushcdn_tpu.proto.error import Error
from pushcdn_tpu.proto.limiter import Bytes
from pushcdn_tpu.proto.message import (
    Broadcast,
    Direct,
    Subscribe,
    TopicSync,
    Unsubscribe,
    UserSync,
    deserialize,
)
from pushcdn_tpu.proto.util import mnemonic

if TYPE_CHECKING:
    from pushcdn_tpu.broker.broker import Broker

logger = logging.getLogger("pushcdn.broker")


# ---------------------------------------------------------------------------
# routing core
# ---------------------------------------------------------------------------

async def handle_direct_message(broker: "Broker", recipient: bytes,
                                raw: Bytes, to_user_only: bool) -> None:
    """One-hop direct routing (broker/handler.rs:197-237)."""
    owner = broker.connections.get_broker_identifier_of_user(recipient)
    if owner is None:
        return  # unknown user: drop
    if owner == broker.connections.identity:
        await try_send_to_user(broker, recipient, raw)
    elif not to_user_only:
        # forward one hop to the owning broker; the remote end delivers
        # with to_user_only=True so it can never bounce back
        await try_send_to_broker(broker, owner, raw)


async def handle_broadcast_message(broker: "Broker", topics: Sequence[int],
                                   raw: Bytes, to_users_only: bool,
                                   users_via_device: bool = False,
                                   exclude_brokers: frozenset = frozenset()
                                   ) -> None:
    """Interest-driven fan-out (broker/handler.rs:240-272).

    ``users_via_device=True`` means the local-user fan-out was staged onto
    the device plane; only the inter-broker forwarding runs on the host.
    ``exclude_brokers`` are peers already covered by the device mesh
    (group members) — interested OUT-of-group brokers still get the frame.
    """
    users, brokers = broker.connections.get_interested_by_topic(
        list(topics), to_users_only)
    for ident in brokers:
        if ident not in exclude_brokers:
            await try_send_to_broker(broker, ident, raw)
    if not users_via_device:
        for user in users:
            await try_send_to_user(broker, user, raw)


async def _stage_with_backpressure(device, message, raw: Bytes):
    """Stage onto the device plane; FULL results block THIS sender's
    receive loop and retry — the same "block the reader, not the router"
    semantics the byte-pool gives the host path. The wait is unbounded on
    purpose (so is the pool's): if the pump dies it flips ``disabled`` and
    try_stage starts returning INELIGIBLE, which exits the loop."""
    while True:
        result = device.try_stage(message, raw)
        if result != StageResult.FULL:
            return result
        await asyncio.sleep(0.002)


# ---------------------------------------------------------------------------
# user receive loop
# ---------------------------------------------------------------------------

async def user_receive_loop(broker: "Broker", public_key: bytes,
                            connection) -> None:
    """Pump one user's messages until the connection dies or the user is
    kicked (user/handler.rs:104-161)."""
    hook = broker.run_def.user_def.hook
    topics = broker.run_def.topics
    try:
        while True:
            raw = await connection.recv_raw()
            try:
                try:
                    message = deserialize(raw.data)
                except Error:
                    # malformed frame ⇒ disconnect (user/handler.rs:106-118)
                    logger.info("user %s sent malformed frame; disconnecting",
                                mnemonic(public_key))
                    break
                result = hook(public_key, message)
                if result == HookResult.SKIP:
                    continue
                if result == HookResult.DISCONNECT:
                    break

                device = broker.device_plane
                if isinstance(message, Direct):
                    # device path covers local-recipient delivery (and, for
                    # a mesh-group plane, any recipient in the group); host
                    # path covers the rest
                    if device is not None:
                        result = await _stage_with_backpressure(
                            device, message, raw)
                        if result == StageResult.STAGED:
                            continue
                    await handle_direct_message(
                        broker, message.recipient, raw, to_user_only=False)
                elif isinstance(message, Broadcast):
                    pruned, _bad = topics.prune(message.topics)
                    if pruned:
                        staged = False
                        if device is not None:
                            result = await _stage_with_backpressure(
                                device, message, raw)
                            staged = result == StageResult.STAGED
                        # host side: remaining fan-out — all of it when not
                        # staged; only out-of-group/interest forwarding when
                        # the device covers users (+ group peers over ICI)
                        await handle_broadcast_message(
                            broker, pruned, raw, to_users_only=False,
                            users_via_device=staged,
                            exclude_brokers=(
                                frozenset(device.covered_broker_idents())
                                if staged else frozenset()))
                elif isinstance(message, Subscribe):
                    pruned, bad = topics.prune(message.topics)
                    if bad:
                        # unknown topic ⇒ disconnect (subscribe.rs test
                        # behavior: invalid-topic subscriptions kick)
                        break
                    broker.connections.subscribe_user_to(public_key, pruned)
                elif isinstance(message, Unsubscribe):
                    pruned, _bad = topics.prune(message.topics)
                    broker.connections.unsubscribe_user_from(public_key, pruned)
                else:
                    # users may not send auth or sync messages post-handshake
                    break
            finally:
                raw.release()
    except (Error, asyncio.IncompleteReadError):
        pass  # connection died: fall through to removal
    except asyncio.CancelledError:
        raise
    finally:
        # Only deregister if WE are still the registered connection — a
        # same-broker double-connect evicts the old loop (cancelling it)
        # after the new connection has already taken the map slot, and the
        # old loop's cleanup must not remove the new entry.
        if broker.connections.get_user_connection(public_key) is connection:
            broker.connections.remove_user(public_key, reason="receive loop ended")
        broker.update_metrics()


# ---------------------------------------------------------------------------
# broker receive loop
# ---------------------------------------------------------------------------

async def broker_receive_loop(broker: "Broker", identifier: str,
                              connection) -> None:
    """Pump a peer broker's messages (broker/handler.rs:121-193)."""
    hook = broker.run_def.broker_def.hook
    topics = broker.run_def.topics
    try:
        while True:
            raw = await connection.recv_raw()
            try:
                try:
                    message = deserialize(raw.data)
                except Error:
                    logger.warning("broker %s sent malformed frame; dropping link",
                                   identifier)
                    break
                result = hook(identifier, message)
                if result == HookResult.SKIP:
                    continue
                if result == HookResult.DISCONNECT:
                    break

                device = broker.device_plane
                # A covers_brokers (mesh-group) plane must NOT re-stage
                # host-forwarded traffic: the origin couldn't stage it, and
                # re-staging would all_gather it back to every shard —
                # duplicate delivery. Host-forwarded frames are delivered
                # locally only, exactly the reference's to_users_only rule.
                single_shard = device is not None and not device.covers_brokers
                if isinstance(message, Direct):
                    # deliver to our own user only — never re-forward
                    # (broker/handler.rs:148-153); the single-shard device
                    # path's delivery-iff-owner rule keeps that invariant
                    if single_shard:
                        result = await _stage_with_backpressure(
                            device, message, raw)
                        if result == StageResult.STAGED:
                            continue
                    await handle_direct_message(
                        broker, message.recipient, raw, to_user_only=True)
                elif isinstance(message, Broadcast):
                    # users only — prevents broadcast loops
                    # (broker/handler.rs:156-161)
                    pruned, _bad = topics.prune(message.topics)
                    if pruned:
                        if single_shard:
                            result = await _stage_with_backpressure(
                                device, message, raw)
                            if result == StageResult.STAGED:
                                continue
                        await handle_broadcast_message(
                            broker, pruned, raw, to_users_only=True)
                elif isinstance(message, UserSync):
                    broker.connections.apply_user_sync(message.payload)
                    broker.update_metrics()
                elif isinstance(message, TopicSync):
                    broker.connections.apply_topic_sync(identifier,
                                                        message.payload)
                else:
                    logger.warning("broker %s sent unexpected %s; dropping link",
                                   identifier, type(message).__name__)
                    break
            finally:
                raw.release()
    except (Error, asyncio.IncompleteReadError):
        pass
    except asyncio.CancelledError:
        raise
    finally:
        # Same guard as the user loop: a replaced link's cancelled loop must
        # not deregister the replacement.
        if broker.connections.get_broker_connection(identifier) is connection:
            broker.connections.remove_broker(identifier, reason="receive loop ended")
        broker.update_metrics()
